"""MiniCluster: real Master + TabletServer objects in one process.

Capability parity with the reference test harness (ref:
integration-tests/mini_cluster.h:101-120 — in-process multi-node cluster on
loopback RPC with ephemeral ports; MiniMaster / MiniTabletServer
tserver/mini_tablet_server.h). This is the primary multi-node test vehicle:
everything uses real sockets, real WALs, real Raft — only the process
boundary is collapsed.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.master.master import Master, MasterOptions
from yugabyte_tpu.tserver.tablet_server import (
    TabletServer, TabletServerOptions)
from yugabyte_tpu.utils.status import Status, StatusError


@dataclass
class MiniClusterOptions:
    num_masters: int = 1
    num_tservers: int = 3
    fs_root: str = "/tmp/ybtpu-minicluster"
    tablet_options_factory: Optional[Callable] = None


class MiniCluster:
    def __init__(self, opts: MiniClusterOptions):
        self.opts = opts
        self.masters: List[Master] = []
        self.tservers: List[TabletServer] = []
        self._clients: List[YBClient] = []

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "MiniCluster":
        master_ids = [f"m{i}" for i in range(self.opts.num_masters)]
        for mid in master_ids:
            self.masters.append(Master(MasterOptions(
                master_id=mid,
                fs_root=os.path.join(self.opts.fs_root, mid),
                master_ids=master_ids)))
        addr_map = {m.master_id: m.address for m in self.masters}
        for m in self.masters:
            m.set_master_addrs(addr_map)
            m.start()
        deadline = time.monotonic() + 30
        while not any(m.catalog.is_leader() for m in self.masters):
            if time.monotonic() > deadline:
                raise StatusError(Status.TimedOut("no master leader"))
            time.sleep(0.01)
        for i in range(self.opts.num_tservers):
            self.add_tablet_server()
        return self

    def add_tablet_server(self) -> TabletServer:
        sid = f"ts{len(self.tservers)}"
        ts = TabletServer(TabletServerOptions(
            server_id=sid,
            fs_root=os.path.join(self.opts.fs_root, sid),
            master_addrs=self.master_addrs(),
            tablet_options_factory=self.opts.tablet_options_factory))
        ts.start()
        self.tservers.append(ts)
        return ts

    def restart_tablet_server(self, index: int) -> TabletServer:
        """Stop and recreate a tserver over the same data dirs (crash
        recovery path: WAL replay + catalog re-registration)."""
        old = self.tservers[index]
        sid, fs_root = old.server_id, old.opts.fs_root
        old.shutdown()
        ts = TabletServer(TabletServerOptions(
            server_id=sid, fs_root=fs_root,
            master_addrs=self.master_addrs(),
            tablet_options_factory=self.opts.tablet_options_factory))
        ts.start()
        self.tservers[index] = ts
        return ts

    def master_addrs(self) -> List[str]:
        return [m.address for m in self.masters]

    def leader_master(self) -> Master:
        for m in self.masters:
            if m.catalog.is_leader():
                return m
        raise StatusError(Status.NotFound("no master leader"))

    def new_client(self) -> YBClient:
        client = YBClient(self.master_addrs())
        self._clients.append(client)
        return client

    # -------------------------------------------------------------- helpers
    def wait_all_replicas_running(self, table_id: str,
                                  timeout_s: float = 30.0) -> None:
        """Block until every tablet of the table has all replicas created
        and a ready leader (the reference's WaitForTabletsRunning)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                locs = self.leader_master().catalog.get_table_locations(
                    table_id)
            except StatusError:
                time.sleep(0.05)
                continue
            hosted = {}
            for ts in self.tservers:
                for tid in ts.tablet_manager.tablet_ids():
                    hosted.setdefault(tid, set()).add(ts.server_id)
            ok = True
            for loc in locs:
                have = hosted.get(loc["tablet_id"], set())
                if not set(s["server_id"] for s in loc["replicas"]) <= have:
                    ok = False
                    break
                if loc["leader"] is None:
                    ok = False
                    break
            if ok:
                return
            time.sleep(0.05)
        raise StatusError(Status.TimedOut(
            f"replicas of {table_id} not all running"))

    def wait_for_table_leaders(self, namespace: str, name: str,
                               timeout_s: float = 30.0) -> List[str]:
        """Deadline-poll until EVERY tablet of `namespace.name` has a
        READY leader; returns the tablet ids.

        The table-level form of wait_for_tablet_leader — the deflake
        primitive for tests that CREATE TABLE (possibly via a query
        layer) and immediately write: on a loaded single-core runner a
        fresh tablet's first election can outlast the client retry
        budget, so the write races the election (the known tier-1
        leadership-timing flake)."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                cat = self.leader_master().catalog
                table = cat.get_table(namespace, name)
                tablet_ids = list(table["tablet_ids"])
                break
            except (StatusError, StopIteration):
                if time.monotonic() > deadline:
                    raise StatusError(Status.TimedOut(
                        f"table {namespace}.{name} not in catalog within "
                        f"{timeout_s}s"))
                time.sleep(0.02)
        for tid in tablet_ids:
            self.wait_for_tablet_leader(
                tid, timeout_s=max(0.1, deadline - time.monotonic()))
        return tablet_ids

    def wait_for_tablet_leader(self, tablet_id: str,
                               timeout_s: float = 30.0,
                               exclude: Optional[set] = None) -> str:
        """Deadline-poll the live tservers' raft state until one reports
        READY leadership for `tablet_id`; returns its server_id.

        This is the deflake primitive for leader-failover tests: on a
        loaded single-core CI machine an election can outlast the
        client's retry budget, so a test that kills a leader and
        immediately writes races the election (the known tier-1 flake).
        Polling actual leader state — instead of a fixed sleep or retry
        exhaustion — makes the wait exactly as long as the election."""
        exclude = exclude or set()
        deadline = time.monotonic() + timeout_s
        while True:
            for ts in self.tservers:
                if ts.server_id in exclude:
                    continue
                try:
                    if tablet_id not in ts.tablet_manager.tablet_ids():
                        continue
                    peer = ts.tablet_manager.get_tablet(tablet_id)
                    if peer.raft.is_leader() and peer.raft.leader_ready():
                        return ts.server_id
                except Exception:
                    continue  # server mid-shutdown/bootstrap: keep polling
            if time.monotonic() > deadline:
                raise StatusError(Status.TimedOut(
                    f"no ready leader for tablet {tablet_id} within "
                    f"{timeout_s}s"))
            time.sleep(0.02)

    def shutdown(self) -> None:
        for c in self._clients:
            c.close()
        for ts in self.tservers:
            ts.shutdown()
        for m in self.masters:
            m.shutdown()
