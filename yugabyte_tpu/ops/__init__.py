from yugabyte_tpu.ops.slabs import KVSlab, pack_kvs, unpack_keys
from yugabyte_tpu.ops.merge_gc import merge_and_gc_device, GCParams
