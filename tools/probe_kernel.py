"""TPU kernel probe: time the Pallas merge-path kernel vs the jnp merge
network on the real chip, at graduated sizes, persisting progressively.

The flagship device kernel (ops/pallas_merge.py — the tournament
merge-path counterpart of the reference's MergingIterator + compaction
filter, ref: src/yb/rocksdb/table/merger.cc:51,
src/yb/docdb/docdb_compaction_filter.cc:74) can only be validated on real
hardware: its Mosaic lowering never executes under interpret-mode tests.
The axon TPU tunnel is intermittent, so this tool is built to be run
OPPORTUNISTICALLY and OFTEN:

  - every intermediate result is flushed to PROBE_TPU.json (repo root)
    the moment it exists — a wedged tunnel or a timeout still leaves
    whatever was measured on disk, committed by the caller;
  - a watchdog (SIGALRM, --budget seconds, default 480) bounds the run;
  - CPU fallback is refused by default: this tool exists to capture TPU
    numbers (--allow-cpu for plumbing tests).

Usage:  python tools/probe_kernel.py [--budget 480] [--shapes 18,20]
Writes: PROBE_TPU.json — platform, device, per-shape first-call (compile)
        and sustained per-job seconds, rows/s, pallas-vs-network
        agreement, and kernel_vs_native (vs the single-core in-memory C++
        merge+GC, the same basis as BENCH kernel_vs_cpu_core).
"""

import argparse
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# CPU plumbing-test runs (--allow-cpu) write a SEPARATE artifact: merging
# interpret-mode rates into PROBE_TPU.json would let CPU numbers drive the
# production TPU impl router (run_merge._load_probe_winners)
OUT = os.path.join(_REPO, "PROBE_TPU.json")

state = {"start": time.strftime("%Y-%m-%d %H:%M:%S"), "done": False}


def _init_artifact(allow_cpu: bool) -> None:
    """MERGE into the existing artifact: probes run opportunistically all
    round (different shapes per invocation) and every TPU datapoint ever
    captured must survive the next run — an overwrite would discard the
    only hardware numbers the project has when a later probe times out
    mid-shape.  Status keys (done/timeout/skipped/note/errors) describe
    one run only and never carry over."""
    global OUT
    if allow_cpu:
        OUT = os.path.join(_REPO, "PROBE_CPU.json")
    try:
        with open(OUT) as f:
            prev = json.load(f)
        for k, v in prev.items():
            if k not in ("start", "done", "timeout", "skipped", "note") \
                    and "error" not in k and "traceback" not in k:
                state.setdefault(k, v)
    except (OSError, ValueError):
        pass


def save():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, OUT)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=480,
                    help="hard wall-clock cap in seconds (SIGALRM)")
    ap.add_argument("--shapes", default="18,20",
                    help="comma-separated log2 row counts to probe")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="probe even when only CPU-JAX is available")
    args = ap.parse_args()
    _init_artifact(args.allow_cpu)

    def on_alarm(_sig, _frm):
        state["timeout"] = True
        save()
        print(json.dumps(state))
        os._exit(2)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(args.budget)
    # SIGALRM only fires between Python bytecodes — a wedged axon tunnel
    # hangs INSIDE native backend init and never returns to the
    # interpreter. A forked watchdog child kills the parent regardless.
    parent = os.getpid()
    watchdog = os.fork()
    if watchdog == 0:
        time.sleep(args.budget + 5)
        try:
            with open(OUT) as f:
                st = json.load(f)
            st["timeout"] = True
            with open(OUT, "w") as f:
                json.dump(st, f, indent=1)
        except OSError:
            pass
        try:
            os.kill(parent, signal.SIGKILL)
        except ProcessLookupError:
            pass
        os._exit(0)
    save()
    try:
        return _probe(args)
    finally:
        try:
            os.kill(watchdog, signal.SIGKILL)  # retire the watchdog child
        except ProcessLookupError:
            pass


def _probe(args):
    t0 = time.time()
    if args.allow_cpu and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # plumbing-test mode: pin CPU BEFORE backend init — the axon
        # sitecustomize force-registers the tunnel TPU and overrides the
        # env var, and a wedged tunnel then hangs jax.devices() forever
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax
    dev = jax.devices()[0]
    platform = dev.platform
    state["backend_init_s"] = round(time.time() - t0, 1)
    if platform != "tpu" and not args.allow_cpu:
        # Do NOT stamp platform/device: overwriting a TPU artifact's
        # platform with "cpu" would disable probe-driven routing
        # (run_merge._load_probe_winners gates on it) even though every
        # preserved datapoint is still a TPU measurement
        state["skipped"] = "no TPU backend (platform=%s)" % platform
        save()
        print(json.dumps(state))
        return 1
    state["device"] = str(dev)
    state["platform"] = platform
    save()
    # like-for-like impl comparison: the chunked-subcompaction wrapper
    # would otherwise engage for the network timing at large shapes while
    # the direct pallas call stays monolithic, contaminating the winner
    # data that drives production routing
    os.environ["YBTPU_MERGE_CHUNK_ROWS"] = "0"

    import numpy as np  # noqa: F401

    from bench import synth_ycsb_runs, _split_runs
    from yugabyte_tpu.ops import pallas_merge, run_merge
    from yugabyte_tpu.ops.merge_gc import GCParams

    cutoff = 10_000_000 << 12
    params = GCParams(cutoff, True)

    def stage(n):
        slab, offsets = synth_ycsb_runs(n, 4, max(1, n // 2))
        runs = _split_runs(slab, offsets)
        return run_merge.stage_runs_from_slabs(runs, dev), slab, offsets

    def time_impl(tag, fn, staged, n):
        t_first = time.time()
        h = fn(staged, params)
        perm, keep, mk = h.result()
        state[f"{tag}_first_s"] = round(time.time() - t_first, 2)
        kept = int(keep.sum())
        state[f"{tag}_kept"] = kept
        save()
        # sustained: pipelined stream slope (k=6 minus k=2 over 4 jobs)
        def run_stream(k):
            ts = time.time()
            hs = [fn(staged, params)]
            for i in range(1, k):
                hs.append(fn(staged, params))
                hs[i - 1].result()
            hs[-1].result()
            return time.time() - ts
        t2 = run_stream(2)
        t6 = run_stream(6)
        per_job = (t6 - t2) / 4 if t6 > t2 else t6 / 6
        state[f"{tag}_sustained_s"] = round(per_job, 3)
        state[f"{tag}_rows_per_sec"] = round(n / per_job, 1)
        save()
        return kept

    # native single-core in-memory merge+GC rate at the same shape — the
    # kernel_vs_cpu_core denominator (native/compaction_baseline.cc)
    def native_rate(slab, offsets, n):
        try:
            from yugabyte_tpu.storage.cpu_baseline import \
                compact_cpu_baseline
            t = time.time()
            compact_cpu_baseline(slab, offsets, cutoff, True)
            best = time.time() - t
            # best-of-3: the denominator swings 2-3x under transient host
            # load (VERDICT r4 weak #3 — pin the baseline); the fastest
            # run is the least-contended estimate of the machine
            for _ in range(2):
                t = time.time()
                compact_cpu_baseline(slab, offsets, cutoff, True)
                best = min(best, time.time() - t)
            return round(n / best, 1)
        except Exception as e:  # noqa: BLE001
            state["native_error"] = repr(e)[:200]
            return 0.0

    shapes = [int(s) for s in args.shapes.split(",") if s]
    for n_log in shapes:
        n = 1 << n_log
        tag = f"n{n_log}"
        try:
            ts = time.time()
            staged, slab, offsets = stage(n)
            jax.block_until_ready(staged.cols_dev)
            state[f"{tag}_stage_s"] = round(time.time() - ts, 1)
            save()
            kp = time_impl(f"{tag}_pallas",
                           pallas_merge.launch_merge_gc_pallas, staged, n)
            os.environ["YBTPU_MERGE_IMPL"] = "network"
            kn = time_impl(f"{tag}_network", run_merge.launch_merge_gc,
                           staged, n)
            os.environ["YBTPU_MERGE_IMPL"] = "auto"
            state[f"{tag}_agree"] = (kp == kn)
            nat = native_rate(slab, offsets, n)
            state[f"{tag}_native_rows_per_sec"] = nat
            if nat > 0:
                state[f"{tag}_pallas_vs_native"] = round(
                    state[f"{tag}_pallas_rows_per_sec"] / nat, 3)
                state[f"{tag}_network_vs_native"] = round(
                    state[f"{tag}_network_rows_per_sec"] / nat, 3)
            save()
            # (no calibration append: production routing learns its own
            # device-vs-native rates live on the bucket-health board —
            # storage/bucket_health.py — so the probe only reports)
        except Exception as e:  # noqa: BLE001
            import traceback
            state[f"{tag}_error"] = repr(e)[:500]
            state[f"{tag}_traceback"] = traceback.format_exc()[-1500:]
            save()
            break

    state["done"] = True
    save()
    print(json.dumps(state))
    return 0


if __name__ == "__main__":
    sys.exit(main())
