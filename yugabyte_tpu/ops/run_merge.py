"""Pre-sorted-run K-way merge + MVCC-GC: the round-3 compaction kernel.

Compaction inputs are NOT random rows — they are K already-sorted runs
(L0 SSTs / flush outputs). The round-2 kernel ignored that and re-sorted
everything with a 7-pass LSD radix (O(passes x sort(N)) where the reference
does an O(N log K) heap merge, ref: rocksdb/table/merger.cc:51). This module
replaces the re-sort with a *bitonic merge network over the pre-sorted runs*:

  - lay the K runs out as [K_pad, m] (each run padded to a common power-of-two
    length m with all-0xFF sentinel columns that sort to the tail; K_pad runs
    padded with all-sentinel runs),
  - merge pairwise, log2(K_pad) levels. One level: concat(A, reverse(B)) is
    bitonic, and log2(2L) half-cleaner stages sort it. Every stage is a
    static reshape + vectorized lexicographic compare-exchange — regular
    HBM-friendly access, no gathers, no data-dependent control flow.
    Total work: O(N log N) *stage-passes of elementwise ops* vs the radix
    path's O(passes) full bitonic SORTS (each internally ~log^2 N stages):
    ~40x fewer compare-exchange stages at K=4, N=4M.
  - the comparator is the internal-key order (key words asc, key_len asc,
    hybrid time desc, write id desc — ops/slabs.py) over the host-pruned
    non-constant columns, with the global index as final tiebreak, making the
    order total and the network deterministic & run-stable.

The merged permutation then feeds the SAME segmented GC filter as every other
path (ops/merge_gc.gc_over_sorted), so survivors are byte-identical to the
radix kernel, the native C++ baseline and the Python model.

Transfer design (the tunnel-attached TPU downloads at ~10 MB/s, 15-30x slower
than uploads — measured round 3): instead of fetching the 4-byte-per-row
permutation (16 MB at 4M rows), the kernel returns ONE packed decision
buffer: per 32 merged positions, a keep-bit word, a make-tombstone word and
ceil(log2 K_pad) source-run-code words (~0.5 byte/row total). Because the
merge consumes each run in order, the host (or the native C++ shell)
reconstructs the exact permutation from the source codes with a trivial
counting pass. This cuts device->host bytes ~10x and is the difference
between the TPU path losing and beating the CPU baseline end-to-end.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_tpu.ops.merge_gc import (
    _ROW_DKL, _ROW_FLAGS, _ROW_HT_HI, _ROW_HT_LO, _ROW_KEY_LEN, _ROW_TTL_HI,
    _ROW_TTL_LO, _ROW_WID, _ROW_WORDS, GCParams, PAD_SENTINEL, StagedCols,
    column_stats, gc_over_sorted, pack_cols, pad_template,
    route_word_mask, pack_bits_u32 as _pack_group_bits)
from yugabyte_tpu.ops.slabs import KVSlab
from yugabyte_tpu.utils import jax_setup  # noqa: F401  (compilation cache)


def _lex_gt(lo, hi, n_rows: int):
    """Strict lexicographic greater-than over the leading axis (u32 rows)."""
    gt = jnp.zeros(lo.shape[1:], dtype=bool)
    eq = jnp.ones(lo.shape[1:], dtype=bool)
    for i in range(n_rows):
        gt = gt | (eq & (lo[i] > hi[i]))
        eq = eq & (lo[i] == hi[i])
    return gt


def merge_network(x, k_pad: int, m: int, pos=None):
    """Bitonic merge tree over [C, k_pad, m] (each run ascending).

    Returns the fully merged [C, k_pad*m]. All C rows form the comparator;
    the LAST row must be a unique tiebreak (the global index) so the
    order is total.

    Stage formulation (profiled on v5e): every half-cleaner runs on the
    FLAT [C, n] array — the partner of position i at stride s is i^s,
    fetched with two lane rotations (jnp.roll) and a parity select
    instead of reshape(..., 2, s) slicing. The reshape form forced a
    tiled-layout copy per stage (~half the merge wall time); rolls keep
    one fixed layout for the whole network. Only the per-level reverse of
    the B runs still reshapes.

    pos must be a RUNTIME int32 iota [k_pad*m] (the caller's jit takes it
    as an operand): written as jnp.arange inside the trace, every stage's
    `pos & s` parity mask is a compile-time constant and XLA folds ~40
    multi-MB literals — at 4M rows that blew the compile past 10 minutes.
    """
    c = x.shape[0]
    n_cmp = c
    n = k_pad * m
    if pos is None:   # convenience for tests; production passes it in
        pos = jnp.arange(n, dtype=jnp.int32)
    z = x.reshape(c, n)
    k, length = k_pad, m
    while k > 1:
        # reverse every odd run: concat(A, reverse(B)) is bitonic
        y = z.reshape(c, k // 2, 2, length)
        z = jnp.concatenate([y[:, :, 0, :], y[:, :, 1, ::-1]],
                            axis=-1).reshape(c, n)
        s = length
        while s >= 1:
            hi_half = (pos & s) != 0
            # partner = z[i ^ s]; XOR never crosses a 2s block, so the
            # roll's wrap-around values are never selected
            p = jnp.where(hi_half[None], jnp.roll(z, s, axis=1),
                          jnp.roll(z, -s, axis=1))
            gt = _lex_gt(z[:n_cmp], p[:n_cmp], n_cmp)   # strict, total
            take_p = jnp.where(hi_half, ~gt, gt)        # lo keeps min
            z = jnp.where(take_p[None], p, z)
            s //= 2
        k //= 2
        length *= 2
    return z




def _merge_gc_runs_impl(cols, cmp_rows, pos,
                        cutoff_hi, cutoff_lo, cutoff_phys_hi, cutoff_phys_lo,
                        k_pad: int, m: int, w: int, n_cmp: int,
                        is_major: bool, retain_deletes: bool,
                        snapshot: bool, lexsort: bool = False):
    """One device program: run-merge + GC + packed decision buffer.

    cols: [8+w, k_pad*m] run-major layout. cmp_rows: int32 [n_cmp] row ids of
    the non-constant compare columns in most-significant-first order (host
    prunes constants; WHICH rows is dynamic so the compile key is only the
    shape tuple). Output: uint32 [N//32, 2+b] packed groups (keep bits,
    make-tombstone bits, b source-code bit-planes), b = log2(k_pad).

    lexsort (static): merge with ONE multi-key `lax.sort` instead of the
    bitonic network. The comparator short-circuits per comparison, so it is
    the clear winner everywhere a real comparison sort runs fast and
    multi-operand sorts compile quickly — i.e. every non-TPU backend (the
    CPU fallback path ran ~15x faster in measurement); on TPU the
    multi-operand sort costs minutes of XLA compile and the network/pallas
    paths stay the default. Both impls produce bit-identical decisions:
    the comparator (pruned rows + global-index tiebreak) is the same total
    order.
    """
    n = k_pad * m
    u32max = jnp.uint32(0xFFFFFFFF)

    # compare matrix: gather the pruned rows, complement the descending ones
    # (ht_hi/ht_lo/write_id), append the global index as total-order tiebreak
    invert = ((cmp_rows >= _ROW_HT_HI) & (cmp_rows <= _ROW_WID))
    cmp = cols[cmp_rows, :] ^ jnp.where(invert, u32max, jnp.uint32(0))[:, None]
    idx = pos.astype(jnp.uint32)

    if k_pad > 1 and lexsort:
        ops = [cmp[i] for i in range(n_cmp)] + [idx]
        perm = jax.lax.sort(ops, num_keys=n_cmp + 1)[-1].astype(jnp.int32)
        s = cols[:, perm]
    elif k_pad > 1:
        x = jnp.concatenate([cmp, idx[None]], axis=0)
        merged = merge_network(x.reshape(n_cmp + 1, k_pad, m), k_pad, m,
                               pos=pos)
        perm = merged[-1].astype(jnp.int32)
        s = cols[:, perm]
    else:
        perm = pos
        s = cols

    keep, make_tomb = gc_over_sorted(
        s, w, cutoff_hi, cutoff_lo, cutoff_phys_hi, cutoff_phys_lo,
        is_major=is_major, retain_deletes=retain_deletes, snapshot=snapshot)
    keep = keep & (s[_ROW_KEY_LEN] != jnp.uint32(PAD_SENTINEL))

    groups = [_pack_group_bits(keep, n), _pack_group_bits(make_tomb, n)]
    b = max(1, (k_pad - 1).bit_length())
    if k_pad > 1:
        src = (perm >> int(m).bit_length() - 1).astype(jnp.uint32)  # run id
        for t in range(b):
            groups.append(_pack_group_bits((src >> t) & 1, n))
    else:
        zeros = jnp.zeros_like(groups[0])
        for _ in range(b):
            groups.append(zeros)
    # perm/keep/make_tomb stay DEVICE-resident: only `packed` is ever
    # downloaded; the others feed the zero-transfer output staging gather
    # (_gather_staged_output) so write-through never re-uploads columns
    return jnp.stack(groups, axis=1), perm, keep, make_tomb


_FUSED_STATICS = ("k_pad", "m", "w", "n_cmp", "is_major", "retain_deletes",
                  "snapshot", "lexsort")

_merge_gc_runs_fused = functools.partial(
    jax.jit, static_argnames=_FUSED_STATICS)(_merge_gc_runs_impl)

# Donated variant for TRANSIENT column buffers (carved subcompaction
# chunks, per-chunk host uploads): XLA reuses the input's HBM for the
# merge scratch instead of holding input + working set live together.
# Never used on buffers that outlive the launch (HBM slab-cache entries,
# the chunked parent matrix that write-through staging gathers from).
_merge_gc_runs_fused_donated = functools.partial(
    jax.jit, static_argnames=_FUSED_STATICS,
    donate_argnums=(0,))(_merge_gc_runs_impl)


class _DonatedBuffer:
    """Poison placeholder installed over StagedRuns.cols_dev once the
    buffer was donated to XLA: any later touch (the write-through gather
    in gather_staged_outputs, a re-dispatch) raises with the launch that
    consumed it instead of silently reading reused HBM."""

    __slots__ = ("_what",)

    def __init__(self, what: str):
        self._what = what

    def _die(self, *_a, **_k):
        raise RuntimeError(
            f"cols_dev was donated to {self._what}: XLA reuses its HBM "
            "in place, so this buffer no longer holds the staged "
            "columns. Launch without donate=True if anything (e.g. "
            "device write-through staging) must read it afterwards.")

    __getattr__ = __getitem__ = __array__ = _die


def _donation_supported() -> bool:
    """Buffer donation is a no-op (with a per-call warning) on the CPU
    backend — only donate where the runtime honors it. Doubles as the
    "H2D really copies" predicate: the CPU backend may alias host numpy
    memory, so staging arrays are only pooled for reuse on tpu/gpu."""
    return jax.default_backend() in ("tpu", "gpu")


def _use_lexsort() -> bool:
    """Merge-impl selector for the fused program's `lexsort` static (see
    _merge_gc_runs_impl): YBTPU_MERGE_LEXSORT=1/0 forces it; auto uses the
    multi-key lax.sort everywhere except TPU (where its compile takes
    minutes and the network/pallas paths win)."""
    env = os.environ.get("YBTPU_MERGE_LEXSORT", "auto").lower()
    if env in ("1", "true", "on"):
        return True
    if env in ("0", "false", "off"):
        return False
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# Shape-bucket lattice: every static piece of the fused program's compile
# key is quantized so one tablet's whole compaction lifetime hits a small
# fixed set of executables (k_pad and m are powers of two by construction;
# w and n_cmp are quantized here), and the persistent compilation cache
# (utils/jax_setup.py) makes each bucket a one-time cost per node.

_CMP_LATTICE = (2, 4, 6, 8, 12, 16, 24, 32)


def quantize_width(w: int) -> int:
    """Key-word width bucket: power of two, >= 4 (matches pack_cols'
    default w_pad so slab-staged and run-staged layouts share buckets)."""
    return 1 << max(2, (w - 1).bit_length() if w > 1 else 1)


def _quantize_cmp(used: List[int]) -> List[int]:
    """Pad the compare schedule to the next lattice point by repeating its
    last row. A duplicated compare row is a no-op for the lexicographic
    comparator (gt/eq are already resolved at the first occurrence), so
    only the static n_cmp changes — onto ~8 values instead of any int."""
    for q in _CMP_LATTICE:
        if len(used) <= q:
            return used + [used[-1]] * (q - len(used))
    return used


_bucket_keys_seen = set()  # guarded-by: _bucket_lock
_bucket_lock = __import__("threading").Lock()


def _record_bucket(key) -> None:
    """Executable-bucket hit/miss counters: a 'miss' is the first launch of
    a (impl, shape, params) bucket in this process — the jit cache compiles
    (or loads from the persistent cache); every later launch is a hit."""
    from yugabyte_tpu.utils.metrics import kernel_metrics
    with _bucket_lock:
        hit = key in _bucket_keys_seen
        if not hit:
            _bucket_keys_seen.add(key)
    if hit:
        kernel_metrics().counter(
            "kernel_compile_bucket_hits_total",
            "kernel launches that reused an already-compiled shape "
            "bucket").increment()
    else:
        kernel_metrics().counter(
            "kernel_compile_bucket_misses_total",
            "first launches of a shape bucket (compile or persistent-"
            "cache load)").increment()


# The shape buckets steady-state universal compaction actually produces:
# 2/4-slot merges of flush-sized (64k-row) through once-compacted (256k-row)
# runs at the default 4-word quantized key width, whose full compare
# schedule (4 words + key_len/ht_hi/ht_lo/write_id) lands on the n_cmp=8
# lattice point.
_PREWARM_SHAPES = (
    (2, 1 << 16, 4, 8),
    (4, 1 << 16, 4, 8),
    (2, 1 << 18, 4, 8),
    (4, 1 << 18, 4, 8),
)


def prewarm_buckets(shapes: Optional[Sequence[Tuple[int, int, int, int]]]
                    = None) -> int:
    """Ahead-of-traffic compile of the common fused-kernel buckets.

    Each (k_pad, m, w, n_cmp) bucket lowers + compiles against
    ShapeDtypeStructs (no device memory touched), populating the
    persistent compilation cache (utils/jax_setup.py) so the first REAL
    compaction of each bucket loads a cached executable instead of paying
    the full XLA compile (107s measured on the tunnel TPU). Run by the
    tserver maintenance manager at startup (flag-gated); returns how many
    executables compiled.

    Coverage matches the committed compile-surface manifest
    (tools/analysis/kernel_manifest.json): BOTH is_major variants per
    shape (minor compactions are the common case — warming only the
    major twin left half the steady surface cold), and on TPU the pallas
    tournament kernel too, with the full unpruned compare schedule —
    auto impl routing launches pallas there, so warming only the jnp
    program cached an executable the TPU path never runs."""
    shapes = tuple(shapes) if shapes is not None else _PREWARM_SHAPES
    lexsort = _use_lexsort()
    donate = _donation_supported()
    fn = _merge_gc_runs_fused_donated if donate else _merge_gc_runs_fused
    on_tpu = jax.default_backend() == "tpu"
    compiled = 0

    def _warm(what: str, lower_fn) -> int:
        try:
            lower_fn().compile()
            return 1
        except Exception as e:  # noqa: BLE001 — prewarm must never block
            import sys as _sys                       # server startup
            print(f"[run_merge] prewarm of {what} failed: {e!r}",
                  file=_sys.stderr, flush=True)
            return 0

    for (k_pad, m, w, n_cmp) in shapes:
        r = _ROW_WORDS + w
        n = k_pad * m
        u32 = jax.ShapeDtypeStruct((), jnp.uint32)
        fused_args = (
            jax.ShapeDtypeStruct((r, n), jnp.uint32),
            jax.ShapeDtypeStruct((n_cmp,), jnp.int32),
            jax.ShapeDtypeStruct((n,), jnp.int32),
            u32, u32, u32, u32)
        for is_major in (True, False):
            got = _warm(
                f"bucket (k_pad={k_pad} m={m} w={w} n_cmp={n_cmp} "
                f"is_major={is_major})",
                lambda: fn.lower(
                    *fused_args, k_pad=k_pad, m=m, w=w, n_cmp=n_cmp,
                    is_major=is_major, retain_deletes=False,
                    snapshot=False, lexsort=lexsort))
            if got:
                _record_bucket(("lexsort" if lexsort else "network",
                                k_pad, m, w, n_cmp, is_major, False,
                                False, donate))
            compiled += got
        # the chained-compaction write-through programs launch right after
        # every merge of this bucket (restage of cache-resident inputs,
        # survivor scan, per-span output gather) — tiny compiles, warmed
        # so the first chained L0->L1->L2 job is entirely cache-hot
        pos_fn = (_survivor_positions_donated if donate
                  else _survivor_positions)
        compiled += _warm(
            f"survivor_positions (n_pad={n})",
            lambda: pos_fn.lower(jax.ShapeDtypeStruct((n,), jnp.bool_)))
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        compiled += _warm(
            f"gather_staged_output (n_pad={n} n_out_pad={m})",
            lambda: _gather_staged_output.lower(
                jax.ShapeDtypeStruct((r, n), jnp.uint32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.bool_),
                i32, i32, n_out_pad=m))
        compiled += _warm(
            f"restage_concat (k_pad={k_pad} m={m} w={w})",
            lambda: _restage_concat.lower(
                tuple(jax.ShapeDtypeStruct((r, m), jnp.uint32)
                      for _ in range(k_pad)),
                jax.ShapeDtypeStruct((k_pad,), jnp.int32),
                w=w, m=m, k_pad=k_pad))
        if not on_tpu:
            continue
        from yugabyte_tpu.ops import pallas_merge
        cmp_rows, n_cmp_full = _cmp_schedule(w, np.zeros(r, dtype=bool))
        cmp_rows_t = tuple(int(x) for x in cmp_rows)
        rp = ((r + 1 + 7) // 8) * 8
        tile = min(pallas_merge.default_tile(rp), m)
        for is_major in (True, False):
            got = _warm(
                f"pallas bucket (k_pad={k_pad} m={m} w={w} "
                f"is_major={is_major})",
                lambda: pallas_merge._pallas_merge_gc_fused.lower(
                    jax.ShapeDtypeStruct((r, n), jnp.uint32),
                    jax.ShapeDtypeStruct((n,), jnp.int32),
                    u32, u32, u32, u32,
                    k_pad=k_pad, m=m, w=w, cmp_rows_t=cmp_rows_t,
                    tile=tile, is_major=is_major, retain_deletes=False,
                    snapshot=False, interpret=False))
            if got:
                _record_bucket(("pallas", k_pad, m, w, n_cmp_full,
                                is_major, False, False))
            compiled += got
    return compiled


@dataclass
class StagedRuns:
    """K sorted runs laid out run-major on device: [8+w, k_pad*m]."""
    cols_dev: object
    m: int                 # per-run padded length (power of two)
    k_pad: int             # run slots (power of two)
    w: int                 # key words
    run_ns: List[int]      # real rows per run (len = real run count)
    cmp_rows: np.ndarray   # pruned compare row ids, MSB-first, + int32
    n_cmp: int
    # greedy run-packing (pack_runs_greedy): slot i's rows map to input
    # rows run_maps[i][slot_position] over the concatenation of the
    # ORIGINAL live runs; None = identity (slot == run)
    run_maps: Optional[List[np.ndarray]] = None

    @property
    def n(self) -> int:
        return int(sum(self.run_ns))

    @property
    def n_pad(self) -> int:
        return self.m * self.k_pad

    @property
    def nbytes(self) -> int:
        return int(self.cols_dev.size) * 4


def _merge_const_stats(per_run: Sequence[Tuple[np.ndarray, np.ndarray]],
                       r: int) -> np.ndarray:
    """Merge per-run (is_const, first_val) column stats into the cross-run
    is_const vector: a row is prunable from the comparator only if it is
    constant WITH THE SAME VALUE across every input — constant-per-run with
    differing values still orders the merge. Vectorized: first values of
    non-constant runs never matter (the all-const mask already excludes
    their rows)."""
    consts = np.stack([c for c, _f in per_run]).astype(bool)
    firsts = np.stack([f for _c, f in per_run]).astype(np.uint32)
    return consts.all(axis=0) & (firsts == firsts[0:1]).all(axis=0)


def _cmp_schedule(w: int, is_const: np.ndarray) -> Tuple[np.ndarray, int]:
    """Most-significant-first compare rows with constants pruned, padded to
    the n_cmp lattice (see _quantize_cmp — n_cmp is a static jit arg).

    Order: key words 0..w-1, key_len, ht_hi, ht_lo, write_id (the merge
    comparator; complements for the descending rows are applied on device).
    """
    full = [_ROW_WORDS + j for j in range(w)] + [
        _ROW_KEY_LEN, _ROW_HT_HI, _ROW_HT_LO, _ROW_WID]
    used = [r for r in full if not is_const[r]]
    if not used:
        used = [_ROW_KEY_LEN]  # degenerate: all constant; any row works
    used = _quantize_cmp(used)
    return np.asarray(used, dtype=np.int32), len(used)


def run_bucket(n: int) -> int:
    """Per-run padded length: power of two, >= 256 (lane-tile friendly)."""
    return 1 << max(8, (n - 1).bit_length() if n > 1 else 1)


def plan_run_packing(run_ns: Sequence[int]) -> Optional[List[List[int]]]:
    """Greedy (first-fit-decreasing) packing of small runs into shared
    m-slots: bins of combined size <= m (the largest run's bucket).

    The run-major layout pads EVERY run to m; a pick of one big run plus
    several small ones wastes most of its padded slots (the pad-waste
    gauges record it). Packing several small runs into one slot cuts the
    slot count — and often k_pad, halving device work. Returns the bins
    (lists of run indices, input order preserved within a bin), or None
    when packing would not shrink k_pad (same padded layout, extra host
    pre-merge for nothing)."""
    k = len(run_ns)
    if k < 2:
        return None
    m = max(run_bucket(n) for n in run_ns)
    order = sorted(range(k), key=lambda i: -run_ns[i])
    bins: List[List[object]] = []          # [free_slots, [run indices]]
    for i in order:
        for b in bins:
            if b[0] >= run_ns[i]:
                b[0] -= run_ns[i]
                b[1].append(i)
                break
        else:
            bins.append([m - run_ns[i], [i]])
    k_pad_orig = 1 << max(0, (k - 1).bit_length())
    k_new = len(bins)
    k_pad_new = 1 << max(0, (k_new - 1).bit_length()) if k_new > 1 else 1
    if k_pad_new >= k_pad_orig:
        return None
    return [sorted(b[1]) for b in bins]


def packed_run_ns(run_ns: Sequence[int]) -> List[int]:
    """Slot sizes after greedy run-packing (the layout-inflation gates
    score the layout that would ACTUALLY be staged)."""
    bins = plan_run_packing(run_ns)
    if bins is None:
        return list(run_ns)
    return [sum(run_ns[i] for i in b) for b in bins]


def _slab_sort_order(slab: KVSlab) -> np.ndarray:
    """Merged order of a concatenated slab under the kernel comparator
    (key words asc, key_len asc, ht desc, write_id desc; stable — ties
    keep concatenation order, matching the kernel's global-index
    tiebreak over the slot layout)."""
    inv = np.uint32(0xFFFFFFFF)
    keys = [slab.write_id ^ inv, slab.ht_lo ^ inv, slab.ht_hi ^ inv,
            slab.key_len.astype(np.uint32)]
    for j in range(slab.width_words - 1, -1, -1):
        keys.append(slab.key_words[:, j])
    return np.lexsort(tuple(keys))


def _gather_slab_keys(slab: KVSlab, order: np.ndarray) -> KVSlab:
    """Key-column gather of a slab (values untouched: staging only reads
    key columns; survivors gather values via the GLOBAL perm later)."""
    from yugabyte_tpu.ops.slabs import ValueArray
    return KVSlab(
        key_words=slab.key_words[order], key_len=slab.key_len[order],
        doc_key_len=slab.doc_key_len[order], ht_hi=slab.ht_hi[order],
        ht_lo=slab.ht_lo[order], write_id=slab.write_id[order],
        flags=slab.flags[order], ttl_ms=slab.ttl_ms[order],
        value_idx=np.arange(len(order), dtype=np.int32),
        values=ValueArray.empty_rows(len(order)))


def pack_runs_greedy(live: Sequence[KVSlab]
                     ) -> Tuple[List[KVSlab], Optional[List[np.ndarray]]]:
    """Apply plan_run_packing to live slabs: bins with >1 run are
    pre-merged on the host (sorted merge of sorted runs — cheap, they are
    the SMALL runs) into one sorted slot slab, with a per-slot map from
    slot position to global input row so the decoded permutation still
    indexes the original input concatenation."""
    from yugabyte_tpu.ops.slabs import concat_slabs
    if os.environ.get("YBTPU_RUN_PACKING", "1") == "0":
        return list(live), None
    bins = plan_run_packing([s.n for s in live])
    if bins is None:
        return list(live), None
    bases = np.concatenate(([0], np.cumsum([s.n for s in live])))
    slot_slabs: List[KVSlab] = []
    run_maps: List[np.ndarray] = []
    for idxs in bins:
        if len(idxs) == 1:
            i = idxs[0]
            slot_slabs.append(live[i])
            run_maps.append(np.arange(bases[i], bases[i] + live[i].n,
                                      dtype=np.int64))
            continue
        cat = concat_slabs([live[i] for i in idxs])
        gidx = np.concatenate([np.arange(bases[i], bases[i] + live[i].n,
                                         dtype=np.int64) for i in idxs])
        order = _slab_sort_order(cat)
        slot_slabs.append(_gather_slab_keys(cat, order))
        run_maps.append(gidx[order])
    from yugabyte_tpu.utils.metrics import kernel_metrics
    kernel_metrics().counter(
        "kernel_run_packing_total",
        "staging calls that packed small runs into shared "
        "m-slots").increment()
    return slot_slabs, run_maps


def stage_runs_from_slabs(slabs: Sequence[KVSlab], device=None,
                          pack_runs: bool = True) -> StagedRuns:
    """Pack K sorted slabs into the run-major layout with ONE upload.

    pack_runs: greedily pack small runs into shared m-slots first
    (pack_runs_greedy) — cuts the pad waste the kernel gauges expose."""
    from yugabyte_tpu.storage.device_cache import host_staging_pool
    live = [s for s in slabs if s.n]
    run_maps = None
    if pack_runs:
        live, run_maps = pack_runs_greedy(live)
    k = len(live)
    k_pad = 1 << max(0, (k - 1).bit_length()) if k > 1 else 1
    m = max(run_bucket(s.n) for s in live)
    w = quantize_width(max(int(s.width_words) for s in live))
    r = _ROW_WORDS + w
    pool = host_staging_pool()
    cols = pool.acquire((r, k_pad * m))
    try:
        cols[:] = pad_template(r)[:, None]
        stats = []
        for i, s in enumerate(live):
            sub, n_s, _, _ = pack_cols(s, n_pad_override=s.n,
                                       w_pad_override=w)
            cols[:, i * m: i * m + n_s] = sub
            stats.append(column_stats(sub, n_s))
        cmp_rows, n_cmp = _cmp_schedule(w, _merge_const_stats(stats, r))
    except BaseException:
        # the upload below never started, so no device buffer can alias
        # these pages on ANY backend — recycle instead of leaking the
        # lease (an unwinding pipeline stage would otherwise degrade the
        # pool to one-shot allocations)
        pool.release(cols)
        raise
    cols_dev = (jax.device_put(cols, device) if device is not None
                else jnp.asarray(cols))
    if _donation_supported():
        # the accelerator H2D copy owns its bytes once the put completes;
        # block for it, then recycle the staging array (the next chunk's
        # stage-A pack reuses these pages instead of allocating). The CPU
        # backend may alias host memory, so there the array just drops —
        # forget() ends the lease without recycling, so the outstanding-
        # lease gauge (the chaos soak's leak detector) still drains.
        jax.block_until_ready(cols_dev)
        pool.release(cols)
    else:
        pool.forget(cols)
    return StagedRuns(cols_dev, m, k_pad, w, [s.n for s in live],
                      cmp_rows, n_cmp, run_maps=run_maps)


# --------------------------------------------------------------------------
# Device-side re-staging (the restage_concat kernel family): cache-resident
# per-SST cols re-laid into merge inputs with ONE cached jitted program per
# shape bucket, instead of a stream of small un-jitted slice/pad/concat ops
# per input per job. Both layouts appear in the compile-surface manifest;
# all inputs are LIVE slab-cache entries, so nothing here may donate.

@functools.partial(jax.jit, static_argnames=("w", "m", "k_pad"))
def _restage_concat(parts, ns, w: int, m: int, k_pad: int):
    """Per-SST staged cols -> the run-major [8+w, k_pad*m] merge layout.

    parts: tuple of device cols matrices [r_i, n_pad_i] (r_i <= 8+w,
    n_pad_i <= m — both lattice-quantized, so the compile key is bounded);
    ns[i] is the real row count of part i. Real rows land at the head of
    slot i, narrow inputs expose their extra word rows as zero, and every
    padding lane (slot tails + the k_pad-k empty slots) carries the pad
    template so it sorts to the tail."""
    r = _ROW_WORDS + w
    pad_col = jnp.asarray(pad_template(r))
    lane = jnp.arange(m, dtype=jnp.int32)
    outs = []
    for i in range(k_pad):
        if i < len(parts):
            cols = parts[i]
            sub = cols[:, jnp.clip(lane, 0, cols.shape[1] - 1)]
            if cols.shape[0] < r:
                sub = jnp.concatenate(
                    [sub, jnp.zeros((r - cols.shape[0], m), jnp.uint32)],
                    axis=0)
            outs.append(jnp.where((lane < ns[i])[None, :], sub,
                                  pad_col[:, None]))
        else:
            outs.append(jnp.broadcast_to(pad_col[:, None], (r, m)))
    return jnp.concatenate(outs, axis=1) if k_pad > 1 else outs[0]


@functools.partial(jax.jit, static_argnames=("w", "n_pad"))
def _concat_staged_fused(parts, ns, w: int, n_pad: int):
    """Per-SST staged cols -> ONE contiguous padded cols matrix [8+w,
    n_pad] (the radix kernel's input layout, storage/device_cache.py
    concat_staged): real rows of every input laid out back to back, tail
    padded with the template."""
    r = _ROW_WORDS + w
    pad_col = jnp.asarray(pad_template(r))
    out = jnp.broadcast_to(pad_col[:, None], (r, n_pad))
    lane = jnp.arange(n_pad, dtype=jnp.int32)
    off = jnp.int32(0)
    for i, cols in enumerate(parts):
        idx = lane - off
        sub = cols[:, jnp.clip(idx, 0, cols.shape[1] - 1)]
        if cols.shape[0] < r:
            sub = jnp.concatenate(
                [sub, jnp.zeros((r - cols.shape[0], n_pad), jnp.uint32)],
                axis=0)
        valid = (idx >= 0) & (idx < ns[i])
        out = jnp.where(valid[None, :], sub, out)
        off = off + ns[i]
    return out


def stage_runs_from_staged(staged_list: Sequence[StagedCols]) -> StagedRuns:
    """Device-side re-layout of per-SST staged cols (HBM slab cache hits)
    into the run-major matrix — no host->device transfer at all, and one
    jitted dispatch (_restage_concat) instead of per-input slice/pad/concat
    chains."""
    live = [s for s in staged_list if s.n]
    k = len(live)
    k_pad = 1 << max(0, (k - 1).bit_length()) if k > 1 else 1
    m = max(run_bucket(s.n) for s in live)
    # staged widths are already pack_cols-quantized; the explicit
    # quantize_width keeps this layout on the lattice even if a caller
    # ever stages an odd width (idempotent on lattice points)
    w = quantize_width(max(s.w for s in live))
    r = _ROW_WORDS + w
    cat = _restage_concat(tuple(s.cols_dev for s in live),
                          jnp.asarray([s.n for s in live], dtype=jnp.int32),
                          w=w, m=m, k_pad=k_pad)
    stats = []
    for s in live:
        c_i = np.zeros(r, dtype=bool)
        f_i = np.zeros(r, dtype=np.uint32)
        rs = min(_ROW_WORDS + s.w, r)
        c_i[rs:] = True                  # implicit zero-pad word rows
        if s.col_const is not None:
            c_i[:rs] = s.col_const[:rs]
            f_i[:rs] = s.col_first[:rs]
        stats.append((c_i, f_i))
    cmp_rows, n_cmp = _cmp_schedule(w, _merge_const_stats(stats, r))
    return StagedRuns(cat, m, k_pad, w, [s.n for s in live], cmp_rows, n_cmp)


class DeviceFaultError(Exception):
    """A device-path failure that survived its retry: the kernel path of
    this job is broken (XLA compile error, HBM OOM, runtime dispatch
    fault). Carries the shape-bucket key so the containment layer
    (storage/compaction.py) can quarantine the bucket before taking the
    byte-identical native fallback."""

    def __init__(self, bucket: Tuple[int, int], cause: BaseException):
        super().__init__(f"device merge failed after retry "
                         f"(bucket k_pad={bucket[0]} m={bucket[1]}): "
                         f"{cause!r}")
        self.bucket = bucket
        self.cause = cause


def _chunk_retry_counter():
    from yugabyte_tpu.utils.metrics import kernel_metrics
    return kernel_metrics().counter(
        "kernel_chunk_retry_total",
        "per-chunk kernel retries after a device fault")


class MergeGCHandle:
    """In-flight merge+GC launch: packed decisions transferring async.

    Pipelining hook: launch job i+1 while job i's (small) decision buffer
    rides the tunnel, so sustained compaction throughput is bounded by
    max(compute, transfer), not their sum.
    """

    def __init__(self, packed_dev, staged: StagedRuns,
                 perm_dev=None, keep_dev=None, mk_dev=None,
                 host_async: bool = True, relaunch=None):
        self._packed_dev = packed_dev
        self._staged = staged
        self._result = None
        # device-resident merge products for zero-transfer output staging
        self._perm_dev = perm_dev
        self._keep_dev = keep_dev
        self._mk_dev = mk_dev
        # retry-once hook: a closure re-dispatching the SAME launch (only
        # set when the input buffer was not donated, so re-reading it is
        # legal) — a transient device fault at download time gets one
        # more attempt before the caller's native fallback
        self._relaunch = relaunch
        if host_async:
            try:
                packed_dev.copy_to_host_async()
            except (AttributeError, NotImplementedError):  # yblint: contained(backend lacks async D2H; result() falls back to the sync download)
                pass
        # (a chunked parent fuses every chunk's packed buffer into ONE
        # device concat + download instead of calling result() per chunk —
        # each separate np.asarray pays a full tunnel round-trip)

    def _download(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        from yugabyte_tpu.utils.metrics import record_pipeline_stage
        import time as _time
        t0 = _time.monotonic()
        packed = np.asarray(self._packed_dev)  # [n_pad//32, 2+b]
        t1 = _time.monotonic()
        out = _decode_packed(packed, self._staged)
        record_pipeline_stage("device", (t1 - t0) * 1e3)
        record_pipeline_stage("host", (_time.monotonic() - t1) * 1e3)
        return out

    def result(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(perm, keep, make_tombstone) host arrays over the merged order.

        perm indexes the CONCATENATION of the live runs in input order
        (padding excluded): merged position i came from input row perm[i].
        Arrays cover exactly the real rows (length n = sum(run_ns)).
        """
        if self._result is not None:
            return self._result
        from yugabyte_tpu.ops import device_faults
        try:
            device_faults.maybe_fault("result")
            self._result = self._download()
        except Exception as e:  # noqa: BLE001 — device-fault containment
            if self._relaunch is None or not device_faults.is_device_fault(e):
                raise
            # one retry of the same launch (jit-cached: re-dispatch is
            # cheap); a second failure surfaces to the caller, which
            # quarantines the bucket and falls back to the native merge
            _chunk_retry_counter().increment()
            from yugabyte_tpu.utils.trace import TRACE
            TRACE("run_merge: device fault at download (%r) — retrying "
                  "the launch once", e)
            self._packed_dev, self._perm_dev, self._keep_dev, \
                self._mk_dev = self._relaunch()
            device_faults.maybe_fault("result")
            self._result = self._download()
        return self._result

    def result_iter(self):
        """Streaming form of result(): yields (perm, keep, make_tombstone)
        once — the single-launch degenerate case of the chunked handle's
        per-chunk stream, so pipeline consumers handle both uniformly."""
        yield self.result()


def _decode_packed(packed: np.ndarray, staged: StagedRuns
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host decode of one launch's packed decision words -> (perm, keep,
    make_tombstone) over the merged order (see MergeGCHandle.result)."""
    n = staged.n
    n_grp = (n + 31) // 32
    grp = packed[:n_grp]
    keep = _unpack_words(grp[:, 0], n)
    mk = _unpack_words(grp[:, 1], n)
    if staged.k_pad == 1:
        if staged.run_maps is not None:
            return staged.run_maps[0][:n].copy(), keep, mk
        return np.arange(n, dtype=np.int64), keep, mk
    b = max(1, (staged.k_pad - 1).bit_length())
    src = np.zeros(n, dtype=np.uint32)
    for t in range(b):
        src |= _unpack_words(grp[:, 2 + t], n).astype(np.uint32) << t
    # reconstruct the permutation: the merge consumes each run in order,
    # so output position i with source run r maps to the next unconsumed
    # row of r. Padding sorts after every real key, so positions [0, n)
    # are exactly the real rows. Packed slots (run_maps) translate slot
    # consumption order to the original input rows.
    perm = np.zeros(n, dtype=np.int64)
    base = np.concatenate(([0], np.cumsum(staged.run_ns)))
    for r_i in range(len(staged.run_ns)):
        sel = src == r_i
        cnt = int(sel.sum())
        if staged.run_maps is not None:
            perm[sel] = staged.run_maps[r_i][:cnt]
        else:
            perm[sel] = base[r_i] + np.arange(cnt, dtype=np.int64)
    return perm, keep, mk


def _unpack_words(words: np.ndarray, n: int) -> np.ndarray:
    from yugabyte_tpu.ops.merge_gc import _unpack_bits
    return _unpack_bits(np.ascontiguousarray(words), n)


def _survivor_positions_impl(keep):
    """Merged positions of all survivors, padded with n_pad-1 (a padding
    row: padding sorts to the tail and is never kept, so n_pad-1 is only a
    real row when NOTHING was padded AND it survived — in which case it is
    a valid filler that sits beyond every real survivor index anyway)."""
    n_pad = keep.shape[0]
    return jnp.nonzero(keep, size=n_pad, fill_value=n_pad - 1)[0]


_survivor_positions = jax.jit(_survivor_positions_impl)

# Donated variant for the CHAINED-buffer handoff: the keep mask is dead
# once its survivor positions are scanned (the span gathers below read
# only perm/mk/pos), so on backends that honor donation XLA reuses its
# HBM in place. The caller (survivor_positions) poisons the handle's
# _keep_dev afterwards so any late reader fails loudly instead of seeing
# reused memory.
_survivor_positions_donated = functools.partial(
    jax.jit, donate_argnums=(0,))(_survivor_positions_impl)


def survivor_positions(handle: "MergeGCHandle"):
    """Device survivor-position scan over a finished merge's keep mask —
    the first half of write-through staging. Donates the keep mask where
    the backend honors donation (it is the last reader)."""
    keep = handle._keep_dev
    if _donation_supported():
        pos = _survivor_positions_donated(keep)
        handle._keep_dev = _DonatedBuffer("_survivor_positions_donated")
    else:
        pos = _survivor_positions(keep)
    return pos


@functools.partial(jax.jit, static_argnames=("n_out_pad",))
def _gather_staged_output(cols, perm, pos_all, mk, start, end,
                          n_out_pad: int):
    """Gather survivors [start, end) of the merged order into a padded
    StagedCols matrix — entirely on device.

    This is the write-through path for the HBM slab cache: compaction
    outputs become the next compaction's inputs WITHOUT ever leaving HBM
    (the tunnel-attached TPU moves ~14 MB/s host<->device — measured round
    3 — so re-uploading ~130 MB of packed output columns per job would
    cost more than the whole native byte shell).

    start/end are traced scalars (no recompile per file split); n_out_pad
    is the static power-of-two bucket. Padding columns are rewritten with
    the pad template so future merges sort them to the tail.
    """
    from yugabyte_tpu.ops.slabs import FLAG_TOMBSTONE
    n_pad = cols.shape[1]
    idx = start + jnp.arange(n_out_pad, dtype=jnp.int32)
    valid = idx < end
    pos = pos_all[jnp.clip(idx, 0, n_pad - 1)]
    src = perm[pos]
    sub = cols[:, src]
    # TTL-expired survivors are rewritten as tombstones by the byte shell;
    # mirror the flag bit the shell sets (native/compaction_engine.cc
    # write_output: fl |= 1) so the staged entry matches the file
    fl = sub[_ROW_FLAGS] | jnp.where(mk[pos] & valid,
                                     jnp.uint32(FLAG_TOMBSTONE),
                                     jnp.uint32(0))
    sub = sub.at[_ROW_FLAGS].set(fl)
    pad_col = jnp.asarray(pad_template(cols.shape[0]))
    return jnp.where(valid[None, :], sub, pad_col[:, None])


def gather_staged_output_span(handle: MergeGCHandle, pos_all,
                              start: int, end: int) -> StagedCols:
    """Stage ONE output file's [start, end) survivor span directly from
    HBM — the per-span half of write-through: called as each
    _StreamingNativeWriter span completes, so the cache entry installs
    under the output file id the moment its SST exists on disk.

    pos_all: the survivor-position scan from survivor_positions(handle),
    computed once per job. Column stats are conservatively absent (every
    column treated as non-constant) to avoid any device->host fetch."""
    from yugabyte_tpu.ops.merge_gc import (bucket_size as _bucket,
                                           build_sort_schedule)
    staged = handle._staged
    r = _ROW_WORDS + staged.w
    n_out = end - start
    n_out_pad = _bucket(n_out)
    sort_rows, n_sort = build_sort_schedule(staged.w, np.zeros(r, dtype=bool))
    cols_out = _gather_staged_output(
        staged.cols_dev, handle._perm_dev, pos_all,
        handle._mk_dev, jnp.int32(start), jnp.int32(end), n_out_pad)
    return StagedCols(cols_out, sort_rows, n_sort, n_out,
                      n_out_pad, staged.w, None, None)


def gather_staged_outputs(handle: MergeGCHandle,
                          ranges: Sequence[Tuple[int, int]]
                          ) -> List[StagedCols]:
    """Stage the output files of a finished merge directly from HBM.

    ranges: per-output-file [start, end) positions in survivor order —
    exactly the spans the byte shell wrote (returned by
    storage/compaction.py _write_native_outputs). Returns one StagedCols
    per file, device-resident, suitable for DeviceSlabCache.put. The
    survivor-position scan (which consumes — donates — the keep mask on
    capable backends) runs once for all files.
    """
    if getattr(handle, "_perm_dev", None) is None \
            and hasattr(handle, "to_parent_products"):
        handle.to_parent_products()   # chunked: rebuild parent-domain arrays
    pos_all = survivor_positions(handle)
    return [gather_staged_output_span(handle, pos_all, start, end)
            for start, end in ranges]


# --------------------------------------------------------------------------
# Chunked subcompactions: bound the compiled shape of arbitrarily large jobs
# (ref: GenSubcompactionBoundaries, rocksdb/db/compaction_job.cc:330 — the
# reference splits one big compaction into key-range subcompactions; here
# each chunk reuses the SAME bucketed executable, so a 4M-row job rides the
# already-compiled 1M-row program instead of paying a fresh multi-minute
# XLA/Mosaic compile that scales with n).
#
# Chunk boundaries are doc-key ROUTE prefixes (first _W_ROUTE_CHUNK words
# masked to doc_key_len — the same order-preserving, doc-atomic routing
# dist_compact.py uses across mesh shards): every entry/version of one
# document shares its route, and encoded doc keys are prefix-free, so the
# route is monotone within each sorted run and a binary search per run
# yields slice bounds that never split a document — the GC segment logic
# never straddles chunks, and chunk concatenation preserves global order.

_W_ROUTE_CHUNK = 4


def _chunk_target_rows() -> int:
    """YBTPU_MERGE_CHUNK_ROWS: target padded rows per chunk launch.
    Values below 1024 (including 0 and negatives) disable chunking — a
    tiny target would explode into one chunk per handful of rows.

    Unset, chunking is on for TPU only. It exists to bound the compiled
    shape (the multi-minute Mosaic/XLA compile scales with n there) and
    to stream decision downloads over the tunnel; on the CPU fallback the
    lexsort impl compiles in seconds at ANY shape, while the chunk
    machinery costs real work — splitter sampling is a synchronous
    device round-trip inside launch and every carve copies the matrix —
    so chunking LOWERED CPU steady throughput ~15% when measured."""
    env = os.environ.get("YBTPU_MERGE_CHUNK_ROWS")
    if env is None:
        return (1 << 20) if jax.default_backend() == "tpu" else 0
    try:
        t = int(env)
    except ValueError:  # yblint: contained(malformed env override falls back to the platform default target)
        return (1 << 20) if jax.default_backend() == "tpu" else 0
    return t if t >= 1024 else 0


def _mask_route_host(words: np.ndarray, dkl: np.ndarray) -> np.ndarray:
    """words [w_route, s] u32, dkl [s] int32 -> doc-key-masked route
    (host wrapper over the shared merge_gc.route_word_mask)."""
    msk = np.asarray(route_word_mask(jnp.asarray(dkl, jnp.int32),
                                     words.shape[0]))
    return words & msk


@functools.partial(jax.jit, static_argnames=("k_pad", "m", "w_route",
                                             "n_iters"))
def _chunk_split_search(cols, run_ns, splitters, k_pad: int, m: int,
                        w_route: int, n_iters: int):
    """First index >= splitter per (run, splitter): [k_pad, n_split].

    Runs are sorted and routes are monotone within a run (see module
    comment), so a vectorized binary search with leading-axis gathers
    suffices; only real lanes (mid < run_n) are ever compared."""
    dkl = cols[_ROW_DKL].astype(jnp.int32)
    n_split = splitters.shape[0]
    runs = jnp.arange(k_pad, dtype=jnp.int32)[:, None]
    lo = jnp.zeros((k_pad, n_split), jnp.int32)
    hi = jnp.broadcast_to(run_ns[:, None], (k_pad, n_split))
    base = runs * m
    wt = cols[_ROW_WORDS:_ROW_WORDS + w_route].T          # [n, w_route]

    def body(_, lh):
        lo, hi = lh
        live = lo < hi
        mid = (lo + hi) >> 1
        idx = base + mid                                   # [k, n_split]
        kw = wt[idx]                                       # [k, ns, w]
        kd = dkl[idx]
        kr = kw & route_word_mask(kd, w_route, leading=False)
        sp = splitters[None, :, :]
        lt = jnp.zeros(kr.shape[:-1], bool)
        eq = jnp.ones(kr.shape[:-1], bool)
        for i in range(w_route):
            lt = lt | (eq & (kr[..., i] < sp[..., i]))
            eq = eq & (kr[..., i] == sp[..., i])
        ge = ~lt
        hi = jnp.where(live & ge, mid, hi)
        lo = jnp.where(live & ~ge, mid + 1, lo)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, n_iters, body, (lo, hi))
    return lo


@functools.partial(jax.jit, static_argnames=("m", "m_c", "k_pad"))
def _carve_chunk(cols, starts, lens, m: int, m_c: int, k_pad: int):
    """Slice each run's [starts[i], starts[i]+lens[i]) rows into a fresh
    run-major [r, k_pad*m_c] matrix, padding the tails.

    A window may poke into the NEXT run's region (harmless: lens masking
    covers it, since starts[i]+lens[i] <= m).  Only the LAST slot can poke
    past the matrix end, where dynamic_slice would clamp and silently
    misalign lane j from starts[i]+j — that slot selects from a small
    [r, 2*m_c] tail extension instead of copying the whole parent."""
    r = cols.shape[0]
    n_pad = k_pad * m
    pad_col = jnp.asarray(pad_template(r))[:, None]
    lane = jnp.arange(m_c, dtype=jnp.int32)[None, :]
    parts = []
    for i in range(k_pad):
        st = i * m + starts[i]
        if i < k_pad - 1:
            seg = jax.lax.dynamic_slice(cols, (0, st), (r, m_c))
        else:
            seg_a = jax.lax.dynamic_slice(
                cols, (0, jnp.minimum(st, n_pad - m_c)), (r, m_c))
            tail_ext = jnp.concatenate(
                [jax.lax.dynamic_slice(cols, (0, n_pad - m_c), (r, m_c)),
                 jnp.tile(pad_col, (1, m_c))], axis=1)
            delta = jnp.maximum(st - (n_pad - m_c), 0)
            seg_b = jax.lax.dynamic_slice(tail_ext, (0, delta), (r, m_c))
            seg = jnp.where(st > n_pad - m_c, seg_b, seg_a)
        parts.append(jnp.where(lane < lens[i], seg, pad_col))
    return jnp.concatenate(parts, axis=1)


class _ChunkedMergeGCHandle:
    """Concatenation of per-chunk merge+GC results in global merged order.

    Chunks are range-partitioned by route, so chunk-order concatenation IS
    the global merged order; per-chunk perms (which index the chunk's own
    live-run concatenation) remap through the slice offsets.

    HBM write-through staging (gather_staged_outputs) works through
    `to_parent_products()`, which uploads the decoded decisions back as
    parent-domain device arrays: ~24 MB at 4M rows, far cheaper than the
    ~130 MB output-column re-upload that skipping write-through would
    cost every subsequent compaction."""

    def __init__(self, handles, metas, staged: StagedRuns,
                 params=None, snapshot: bool = False, carve=None):
        self._handles = handles          # one per chunk, dispatch order
        self._metas = metas              # (starts[k_live], lens[k_live])
        self._staged = staged
        self._result = None
        self._perm_dev = None
        self._keep_dev = None
        self._mk_dev = None
        # re-carve info for per-chunk device-fault retry: the chunk
        # buffers themselves are donated (their HBM is gone after the
        # launch), but the PARENT matrix is intact, so a failed chunk is
        # re-carved from it and re-dispatched once
        self._params = params
        self._snapshot = snapshot
        self._carve = carve              # (starts_full, lens_full, m_c)

    def _result_with_retry(self, i: int):
        """Chunk i's (perm, keep, mk) with ONE device-fault retry: re-carve
        the chunk from the intact parent matrix and re-dispatch. A second
        failure raises DeviceFaultError so the compaction layer can
        quarantine the shape bucket and fall back to the native merge."""
        from yugabyte_tpu.ops import device_faults
        h = self._handles[i]
        try:
            return h.result()
        except Exception as e:  # noqa: BLE001 — device-fault containment
            if self._carve is None or not device_faults.is_device_fault(e):
                raise
            _chunk_retry_counter().increment()
            from yugabyte_tpu.utils.trace import TRACE
            TRACE("run_merge: chunk %d device fault (%r) — re-carving "
                  "and retrying once", i, e)
            staged = self._staged
            starts, lens, m_c = self._carve[i]
            k_live = len(staged.run_ns)
            try:
                carved = _carve_chunk(
                    staged.cols_dev, jnp.asarray(starts),
                    jnp.asarray(lens), staged.m, m_c, staged.k_pad)
                sub = StagedRuns(carved, m_c, staged.k_pad, staged.w,
                                 [int(x) for x in lens[:k_live]],
                                 staged.cmp_rows, staged.n_cmp)
                h2 = launch_merge_gc(sub, self._params,
                                     snapshot=self._snapshot,
                                     host_async=False, donate=True)
                out = h2.result()
            except Exception as e2:  # noqa: BLE001 — retry exhausted
                raise DeviceFaultError(
                    (staged.k_pad, staged.m), e2) from e2
            self._handles[i] = h2   # memoized passes reuse the good run
            return out

    def _chunk_results(self):
        """Per-chunk (perm, keep, mk) host tuples — via ONE fused device
        concat + host transfer of every chunk's packed decisions (each
        separate np.asarray pays a full tunnel round trip: ~0.15s x
        chunks x jobs dominated the e2e steady profile). Any failure
        degrades to the per-chunk path, which preserves the pallas ->
        network fallback semantics."""
        hs = self._handles
        from yugabyte_tpu.ops import device_faults
        if os.environ.get("YBTPU_FUSED_DOWNLOAD", "1") == "0" \
                or device_faults.armed_count():
            # armed fault injection takes the per-chunk path, where the
            # injection sites and the re-carve retry live — the fused
            # concat would bypass both
            return [self._result_with_retry(i) for i in range(len(hs))]
        try:
            import time as _time
            from yugabyte_tpu.utils.metrics import record_pipeline_stage
            devs = [h._packed_dev for h in hs]
            if len({d.shape[1] for d in devs}) == 1:
                rows = [d.shape[0] for d in devs]
                t0 = _time.monotonic()
                cat = np.asarray(jnp.concatenate(devs, axis=0))
                t1 = _time.monotonic()
                record_pipeline_stage("device", (t1 - t0) * 1e3)
                out, off = [], 0
                for h, r in zip(hs, rows):
                    out.append(_decode_packed(cat[off:off + r], h._staged))
                    off += r
                record_pipeline_stage("host",
                                      (_time.monotonic() - t1) * 1e3)
                return out
        except Exception as e:  # noqa: BLE001 — degrade, never fail here
            import sys as _sys
            print(f"[run_merge] fused chunk download failed — using the "
                  f"per-chunk path: {e!r}", file=_sys.stderr, flush=True)
        return [self._result_with_retry(i) for i in range(len(hs))]

    def _remap_perm(self, p: np.ndarray, starts: np.ndarray,
                    lens: np.ndarray) -> np.ndarray:
        """Chunk-local perm (over the chunk's slot concatenation) ->
        global input-row indices, through the slice offsets and — when the
        slots were greedily packed — the per-slot run_maps."""
        staged = self._staged
        k_live = len(staged.run_ns)
        lb = np.concatenate(([0], np.cumsum(lens)))
        run_of = np.searchsorted(lb[1:], p, side="right")
        slot_pos = p - lb[run_of] + starts[run_of]
        if staged.run_maps is None:
            grb = np.concatenate(([0], np.cumsum(staged.run_ns)))
            return grb[:k_live][run_of] + slot_pos
        out = np.empty(len(p), dtype=np.int64)
        for r_i in range(k_live):
            selr = run_of == r_i
            if selr.any():
                out[selr] = staged.run_maps[r_i][slot_pos[selr]]
        return out

    def result(self):
        if self._result is not None:
            return self._result
        perms, keeps, mks = [], [], []
        for (p, keep, mk), (starts, lens) in zip(self._chunk_results(),
                                                 self._metas):
            perms.append(self._remap_perm(p, starts, lens))
            keeps.append(keep)
            mks.append(mk)
        self._result = (np.concatenate(perms), np.concatenate(keeps),
                        np.concatenate(mks))
        return self._result

    def result_iter(self):
        """Stream per-chunk (perm, keep, make_tombstone) — the stage-C
        hand-off of the compaction pipeline. Chunks are range-partitioned
        by route, so chunk-order concatenation IS the global merged order:
        the consumer (storage/compaction.py's streaming SST writer) can
        write chunk i's survivors while chunks i+1.. still compute or
        ride the link. All pending packed buffers start their async D2H
        up front; the full result is memoized so a later result() call
        pays nothing extra."""
        if self._result is not None:
            yield self._result
            return
        for h in self._handles:
            pd = getattr(h, "_packed_dev", None)
            if pd is not None:
                try:
                    pd.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass
        perms, keeps, mks = [], [], []
        for i, (starts, lens) in enumerate(self._metas):
            p, keep, mk = self._result_with_retry(i)
            perm_g = self._remap_perm(p, starts, lens)
            perms.append(perm_g)
            keeps.append(keep)
            mks.append(mk)
            yield perm_g, keep, mk
        self._result = (np.concatenate(perms), np.concatenate(keeps),
                        np.concatenate(mks))

    def to_parent_products(self) -> None:
        """Build the parent-domain device arrays gather_staged_outputs
        needs (perm over the PADDED run-major layout, keep/mk padded to
        n_pad) from the decoded host results."""
        if self._perm_dev is not None:
            return
        staged = self._staged
        perm, keep, mk = self.result()
        grb = np.concatenate(([0], np.cumsum(staged.run_ns)))
        run_of = np.searchsorted(grb[1:], perm, side="right")
        perm_pad = (run_of.astype(np.int64) * staged.m
                    + (perm - grb[run_of]))
        n_pad = staged.n_pad
        pp = np.zeros(n_pad, dtype=np.int32)
        pp[:len(perm_pad)] = perm_pad
        kp = np.zeros(n_pad, dtype=bool)
        kp[:len(keep)] = keep
        mp = np.zeros(n_pad, dtype=bool)
        mp[:len(mk)] = mk
        dev = getattr(staged.cols_dev, "device", None)
        put = (lambda a: jax.device_put(a, dev)) if dev is not None \
            else jnp.asarray
        self._perm_dev = put(pp)
        self._keep_dev = put(kp)
        self._mk_dev = put(mp)


def _launch_chunked(staged: StagedRuns, params: GCParams, snapshot: bool,
                    target: int):
    """Split one staged job into route-partitioned chunk launches.

    Returns a handle, or None when chunking cannot help (chunk bucket
    would not shrink below the parent's m) — the caller then launches the
    single big program as before."""
    k_live = len(staged.run_ns)
    if k_live < 1 or staged.n == 0:
        return None
    m, k_pad, w = staged.m, staged.k_pad, staged.w
    w_route = min(_W_ROUTE_CHUNK, w)
    nc = max(2, -(-staged.n // max(1, target // 2)))
    n_split = nc - 1
    run_ns_arr = np.zeros(k_pad, dtype=np.int32)
    run_ns_arr[:k_live] = staged.run_ns

    # --- splitters from host-side strided samples (tiny download) -------
    s_per = 256
    idx = []
    for i, rn in enumerate(staged.run_ns):
        if rn > 0:
            idx.append(i * m + (np.arange(s_per, dtype=np.int64) * rn)
                       // s_per)
    idx = np.concatenate(idx)
    words = np.asarray(staged.cols_dev[
        _ROW_WORDS:_ROW_WORDS + w_route][:, idx])
    dkl = np.asarray(staged.cols_dev[_ROW_DKL][idx]).astype(np.int32)
    routes = _mask_route_host(words, dkl).T          # [s, w_route]
    order = np.lexsort(tuple(routes[:, i]
                             for i in range(w_route - 1, -1, -1)))
    routes = routes[order]
    q = (np.arange(1, nc, dtype=np.int64) * len(routes)) // nc
    splitters = routes[q]                            # [n_split, w_route]

    bounds = np.asarray(_chunk_split_search(
        staged.cols_dev, jnp.asarray(run_ns_arr), jnp.asarray(splitters),
        k_pad, m, w_route, int(m).bit_length() + 1))
    bounds = np.concatenate(
        [np.zeros((k_pad, 1), np.int32), bounds,
         run_ns_arr[:, None]], axis=1)               # [k_pad, nc+1]
    bounds = np.maximum.accumulate(bounds, axis=1)

    lens_all = np.diff(bounds, axis=1)               # [k_pad, nc]
    m_c = run_bucket(int(lens_all.max()))
    if m_c >= m:
        return None                                  # no shape win: skew
    handles, metas, carve = [], [], []
    for c in range(nc):
        starts = bounds[:, c].astype(np.int32)
        lens = lens_all[:, c].astype(np.int32)
        if int(lens.sum()) == 0:
            continue                                 # duplicate splitter
        carved = _carve_chunk(staged.cols_dev, jnp.asarray(starts),
                              jnp.asarray(lens), m, m_c, k_pad)
        sub = StagedRuns(carved, m_c, k_pad, w,
                         [int(x) for x in lens[:k_live]],
                         staged.cmp_rows, staged.n_cmp)
        # host_async=False: the parent handle fuses all chunks' packed
        # buffers into one concat + download; per-chunk async D2H would
        # move the same bytes twice over the tunnel. donate=True: the
        # carved matrix is transient (only this launch reads it), so XLA
        # reuses its HBM in place instead of holding chunk input + merge
        # working set live together
        handles.append(launch_merge_gc(sub, params, snapshot=snapshot,
                                       host_async=False, donate=True))
        metas.append((starts[:k_live].astype(np.int64),
                      lens[:k_live].astype(np.int64)))
        carve.append((starts, lens, m_c))
    if not handles:
        return None
    return _ChunkedMergeGCHandle(handles, metas, staged,
                                 params=params, snapshot=snapshot,
                                 carve=carve)


_probe_winners = None  # guarded-by: _probe_lock
_probe_lock = __import__("threading").Lock()


def _load_probe_winners() -> dict:
    """Measured per-shape impl winners from tools/probe_kernel.py's
    artifact (real-TPU sustained rates).  The probe showed neither impl
    dominates across shapes, so auto routes by the nearest measured size
    instead of by architecture faith.  Initialized once under _probe_lock
    (concurrent compaction threads race the first launch; the unlocked
    check-then-set here used to let two threads build it concurrently and
    one publish a half-filled dict)."""
    global _probe_winners
    with _probe_lock:
        if _probe_winners is not None:
            return _probe_winners
        winners = {}
        path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "PROBE_TPU.json")
        try:
            import json as _json
            with open(path) as f:
                d = _json.load(f)
            if d.get("platform") == "tpu":
                for k, v in d.items():
                    if k.endswith("_pallas_rows_per_sec"):
                        lg = int(k[1:].split("_")[0])
                        net = d.get(f"n{lg}_network_rows_per_sec")
                        if net:
                            winners[lg] = \
                                "pallas" if v > net else "network"
        except (OSError, ValueError, KeyError):  # yblint: contained(absent/corrupt probe artifact means no measured winners — auto impl choice falls back to its default)
            pass
        _probe_winners = winners
        return _probe_winners


def _pick_impl(staged: StagedRuns) -> str:
    """Merge strategy: YBTPU_MERGE_IMPL = auto|pallas|network.

    auto on TPU: the winner measured by the real-hardware probe at the
    nearest shape (PROBE_TPU.json), defaulting to the pallas merge-path
    tournament (ops/pallas_merge.py) when unprobed — it replaces ~log^2
    full-array compare-exchange stages + a giant lane gather with log2(K)
    streaming level passes.  The jnp network on every other backend
    (pallas interpret mode is far too slow for the production CPU
    fallback path).
    """
    impl = os.environ.get("YBTPU_MERGE_IMPL", "auto")
    if impl == "network" or staged.k_pad < 2:
        return "network"
    from yugabyte_tpu.ops import pallas_merge
    if not pallas_merge.supported(staged):
        if impl == "pallas":
            import sys as _sys
            print(f"[run_merge] YBTPU_MERGE_IMPL=pallas requested but "
                  f"preconditions fail (k_pad={staged.k_pad} m={staged.m} "
                  f"w={staged.w}) — using the jnp network instead",
                  file=_sys.stderr, flush=True)
        return "network"
    if impl == "pallas":
        return "pallas"
    import jax as _jax
    if _jax.default_backend() != "tpu":
        return "network"
    winners = _load_probe_winners()
    if winners:
        lg = max(1, staged.n_pad).bit_length() - 1
        nearest = min(winners, key=lambda w: abs(w - lg))
        return winners[nearest]
    return "pallas"


# Deliberately unannotated latch bool: False->True exactly once, torn
# reads impossible for a bool, and a racy read only costs one extra
# pallas attempt that fails the same way.
_pallas_broken = False  # set on the first Mosaic lowering/runtime failure


def _fallback_counter(name: str, help: str):
    from yugabyte_tpu.utils.metrics import kernel_metrics
    return kernel_metrics().counter(name, help)


class _PallasFallbackHandle:
    """Wraps a pallas launch so a lazy compile/runtime failure (surfacing
    at .result()) degrades to the jnp network instead of killing the
    caller — the first real-TPU run of the kernel must never take the
    whole bench/compaction down with it."""

    def __init__(self, inner, staged, params, snapshot):
        self._inner = inner
        self._args = (staged, params, snapshot)
        self._effective = None   # set by result(): the handle that ran

    def result(self):
        global _pallas_broken
        try:
            out = self._inner.result()
            self._effective = self._inner
            return out
        except Exception as e:  # noqa: BLE001 — lowering/launch failure
            import sys as _sys
            _pallas_broken = True
            _fallback_counter(
                "kernel_pallas_fallback_total",
                "pallas merge failures degraded to the jnp "
                "network").increment()
            print(f"[run_merge] pallas kernel failed at result() — "
                  f"falling back to the jnp network for this process: "
                  f"{e!r}", file=_sys.stderr, flush=True)
            staged, params, snapshot = self._args
            self._effective = launch_merge_gc(staged, params,
                                              snapshot=snapshot)
            return self._effective.result()

    def result_iter(self):
        """Explicit (not via __getattr__): the inner handle's iterator
        would bypass the fallback try/except around .result()."""
        yield self.result()

    def __getattr__(self, name):
        # delegate device-resident merge products (_staged, _perm_dev,
        # _keep_dev, _mk_dev) to whichever handle actually produced the
        # result, so HBM write-through staging (gather_staged_outputs)
        # works through the fallback wrapper
        return getattr(self._effective if self._effective is not None
                       else self._inner, name)


def launch_merge_gc(staged: StagedRuns, params: GCParams,
                    snapshot: bool = False,
                    host_async: bool = True,
                    donate: bool = False) -> MergeGCHandle:
    """donate: the caller promises staged.cols_dev is TRANSIENT (a carved
    subcompaction chunk or a per-chunk pipeline upload that nothing reads
    after this launch) — the fused program then donates it so XLA reuses
    its HBM for the merge scratch. Never set for slab-cache entries or a
    chunked parent matrix (write-through staging gathers from those)."""
    global _pallas_broken
    from yugabyte_tpu.utils.metrics import (kernel_metrics,
                                            record_kernel_dispatch)
    record_kernel_dispatch("kernel_run_merge", staged.n, staged.n_pad)
    target = _chunk_target_rows()
    if (target and staged.k_pad >= 2 and staged.n_pad > target
            and staged.m >= 512):
        # bound the compiled shape: subcompaction chunks reuse the
        # already-compiled bucket executable (see _launch_chunked)
        h = _launch_chunked(staged, params, snapshot, target)
        if h is not None:
            kernel_metrics().counter(
                "kernel_chunked_launch_total",
                "merge jobs split into route-partitioned chunk "
                "launches").increment()
            return h
    # device-fault injection site "dispatch" (ops/device_faults.py): a
    # real XLA compile failure surfaces here, synchronously, per leaf
    # launch (each chunk of a chunked job passes through this point);
    # the bucket lets a "slow" nemesis throttle one shape bucket only
    from yugabyte_tpu.ops import device_faults
    device_faults.maybe_fault("dispatch", bucket=(staged.k_pad, staged.m))
    explicit = os.environ.get("YBTPU_MERGE_IMPL", "auto") == "pallas"
    if (not _pallas_broken or explicit) and _pick_impl(staged) == "pallas":
        from yugabyte_tpu.ops import pallas_merge
        try:
            h = pallas_merge.launch_merge_gc_pallas(staged, params,
                                                    snapshot=snapshot,
                                                    host_async=host_async)
        except Exception as e:  # noqa: BLE001 — trace/compile failure
            if explicit:
                raise
            import sys as _sys
            _pallas_broken = True
            _fallback_counter(
                "kernel_pallas_fallback_total",
                "pallas merge failures degraded to the jnp "
                "network").increment()
            print(f"[run_merge] pallas kernel failed to launch — using "
                  f"the jnp network for this process: {e!r}",
                  file=_sys.stderr, flush=True)
        else:
            kernel_metrics().counter(
                "kernel_pallas_merge_total",
                "merges launched on the pallas kernel").increment()
            _record_bucket(("pallas", staged.k_pad, staged.m, staged.w,
                            staged.n_cmp, params.is_major_compaction,
                            params.retain_deletes, snapshot))
            return h if explicit else _PallasFallbackHandle(
                h, staged, params, snapshot)
    kernel_metrics().counter(
        "kernel_network_merge_total",
        "merges launched on the jnp bitonic network").increment()
    cutoff = params.history_cutoff_ht
    cutoff_phys = cutoff >> 12
    lexsort = _use_lexsort()
    use_donate = donate and _donation_supported()
    fn = _merge_gc_runs_fused_donated if use_donate else _merge_gc_runs_fused
    _record_bucket(("lexsort" if lexsort else "network", staged.k_pad,
                    staged.m, staged.w, staged.n_cmp,
                    params.is_major_compaction, params.retain_deletes,
                    snapshot, use_donate))
    # runtime iota operand: see merge_network's pos docstring (compile-
    # time constant folding of per-stage parity masks)
    def _dispatch():
        pos = jnp.arange(staged.n_pad, dtype=jnp.int32)
        return fn(
            staged.cols_dev, jnp.asarray(staged.cmp_rows), pos,
            jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
            jnp.uint32(cutoff_phys >> 20),
            jnp.uint32(cutoff_phys & 0xFFFFF),
            k_pad=staged.k_pad, m=staged.m, w=staged.w,
            n_cmp=staged.n_cmp,
            is_major=params.is_major_compaction,
            retain_deletes=params.retain_deletes, snapshot=snapshot,
            lexsort=lexsort)

    packed, perm, keep, mk = _dispatch()
    if use_donate:
        # the dispatch above consumed cols_dev (XLA reuses its HBM);
        # poison it in the handle's staged copy so a later read — e.g.
        # gather_staged_outputs write-through on a handle that was
        # wrongly launched donated — fails loudly instead of staging
        # garbage into the slab cache. Decode only needs the metadata.
        import dataclasses as _dc
        staged = _dc.replace(
            staged, cols_dev=_DonatedBuffer("_merge_gc_runs_fused_donated"))
    # non-donated launches keep a relaunch closure: the input buffer is
    # intact, so a device fault at download time gets one re-dispatch
    # before the caller's native fallback (chunked jobs instead re-carve
    # from the parent in _ChunkedMergeGCHandle._result_with_retry)
    return MergeGCHandle(packed, staged, perm, keep, mk,
                         host_async=host_async,
                         relaunch=None if use_donate else _dispatch)


def merge_and_gc_runs(slabs: Sequence[KVSlab], params: GCParams, device=None,
                      staged: Optional[StagedRuns] = None,
                      snapshot: bool = False
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Blocking wrapper: stage (if needed), run, decode.

    Drop-in for ops/merge_gc.merge_and_gc_device when the caller knows the
    run structure — which every real caller (compaction over SSTs, scans
    over memtable+SSTs) does. Guards: empty input returns empty arrays; a
    heavily skewed run-size mix (where padding every run to the largest
    bucket would inflate device work/memory beyond 2x the radix path's
    single bucket) falls back to the radix kernel.
    """
    import os as _os
    import time as _time
    from yugabyte_tpu.utils.metrics import kernel_metrics
    if staged is None:
        live = [s for s in slabs if s.n]
        if not live:
            z = np.zeros(0, dtype=np.int64)
            zb = np.zeros(0, dtype=bool)
            return z, zb, zb
        if (run_layout_inflation([s.n for s in live]) > 2.0
                or _os.environ.get("YBTPU_FORCE_RADIX", "").lower()
                not in ("", "0", "false")):
            from yugabyte_tpu.ops.merge_gc import merge_and_gc_device
            from yugabyte_tpu.ops.slabs import concat_slabs
            kernel_metrics().counter(
                "kernel_radix_fallback_total",
                "run-merges routed to the radix re-sort (skewed run "
                "layout or forced)").increment()
            merged = concat_slabs(live)
            perm, keep, mk = merge_and_gc_device(merged, params,
                                                 device=device)
            real = perm < merged.n
            return perm[real].astype(np.int64), keep[real], mk[real]
        staged = stage_runs_from_slabs(live, device)
    t0 = _time.monotonic()
    out = launch_merge_gc(staged, params, snapshot=snapshot).result()
    kernel_metrics().histogram(
        "kernel_run_merge_duration_ms",
        "run-merge launch-to-decisions wall time").increment(
        (_time.monotonic() - t0) * 1e3)
    return out


def run_layout_inflation(run_ns: Sequence[int]) -> float:
    """Padded-slot inflation of the run-major layout vs one radix bucket.

    k_pad * max(run_bucket) over bucket_size(sum): >1 means the bitonic
    path touches that many more slots than the radix re-sort would. Skewed
    picks (one huge base run + tiny L0s) can inflate ~K x; callers fall
    back to the radix kernel past 2x.
    """
    from yugabyte_tpu.ops.merge_gc import bucket_size
    k = len(run_ns)
    k_pad = 1 << max(0, (k - 1).bit_length()) if k > 1 else 1
    m = max(run_bucket(n) for n in run_ns)
    return (k_pad * m) / bucket_size(int(sum(run_ns)))
