"""DocDB key/value encoding tests.

Modeled on the reference's docdb/doc_key-test.cc: roundtrips plus the
*ordering* invariants the LSM depends on (memcmp order == semantic order).
"""

import random

import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.doc_key import (
    DocKey, SubDocKey, PrimitiveValue, zero_encode, zero_decode, split_key_and_ht)
from yugabyte_tpu.docdb.value import Value, decode_control_fields


class TestZeroEncoding:
    def test_roundtrip_with_nuls(self):
        for raw in [b"", b"abc", b"\x00", b"a\x00b\x00\x00c", bytes(range(256))]:
            enc = zero_encode(raw)
            dec, pos = zero_decode(enc, 0)
            assert dec == raw
            assert pos == len(enc)

    def test_order_preserving(self):
        samples = [b"", b"\x00", b"\x00\x00", b"a", b"a\x00", b"ab", b"b"]
        encoded = [zero_encode(s) for s in samples]
        assert sorted(encoded) == [zero_encode(s) for s in sorted(samples)]


class TestPrimitiveValue:
    @pytest.mark.parametrize("v", [None, True, False, 0, -1, 42, -(2**40), 2**40,
                                   3.14, -2.71, 0.0, "hello", "", b"\x00\xff"])
    def test_roundtrip(self, v):
        buf = bytearray()
        PrimitiveValue.encode(v, buf)
        out, pos = PrimitiveValue.decode(bytes(buf), 0)
        assert out == v
        assert pos == len(buf)

    def test_int_order_preserving(self):
        vals = [-(2**40), -65536, -1, 0, 1, 65535, 2**40]
        encs = []
        for v in vals:
            buf = bytearray()
            PrimitiveValue.encode(v, buf)
            encs.append(bytes(buf))
        # int32s order among themselves; int64s among themselves
        i32 = [e for e in encs if e[0] == ord("H")]
        i64 = [e for e in encs if e[0] == ord("I")]
        assert i32 == sorted(i32)
        assert i64 == sorted(i64)

    def test_double_order_preserving(self):
        vals = sorted([-1e300, -1.5, -1e-300, 0.0, 1e-300, 2.5, 1e300])
        encs = []
        for v in vals:
            buf = bytearray()
            PrimitiveValue.encode(float(v), buf)
            encs.append(bytes(buf))
        assert encs == sorted(encs)

    def test_string_order_preserving(self):
        vals = sorted(["", "a", "a\x00", "ab", "b", "ba"])
        encs = []
        for v in vals:
            buf = bytearray()
            PrimitiveValue.encode(v, buf)
            encs.append(bytes(buf))
        assert encs == sorted(encs)


class TestDocKey:
    def test_roundtrip_hash(self):
        dk = DocKey(hash_components=("user1",), range_components=(42, "msg"))
        enc = dk.encode()
        dec, pos = DocKey.decode(enc)
        assert pos == len(enc)
        assert dec.hash_components == ("user1",)
        assert dec.range_components == (42, "msg")

    def test_roundtrip_range_only(self):
        dk = DocKey(range_components=("k1", 7))
        dec, pos = DocKey.decode(dk.encode())
        assert dec.range_components == ("k1", 7)
        assert dec.hash_components == ()

    def test_prefix_sorts_first(self):
        # DocKey(a) must sort before DocKey(a, b): kGroupEnd is the lowest tag.
        shorter = DocKey(range_components=("a",)).encode()
        longer = DocKey(range_components=("a", "b")).encode()
        assert shorter < longer


class TestSubDocKey:
    def test_roundtrip_with_ht(self):
        dht = DocHybridTime(HybridTime.from_micros(1000), 3)
        sdk = SubDocKey(DocKey(hash_components=("u",), range_components=(1,)),
                        subkeys=(("col", 2),), doc_ht=dht)
        enc = sdk.encode()
        dec = SubDocKey.decode(enc)
        assert dec.doc_ht == dht
        assert dec.subkeys == (("col", 2),)
        assert dec.doc_key.range_components == (1,)

    def test_ht_descending_within_key(self):
        """Same logical key, later write -> sorts FIRST (MVCC layout invariant)."""
        dk = DocKey(range_components=("k",))
        old = SubDocKey(dk, (), DocHybridTime(HybridTime.from_micros(100), 0)).encode()
        new = SubDocKey(dk, (), DocHybridTime(HybridTime.from_micros(200), 0)).encode()
        assert new < old

    def test_fewer_subkeys_sort_first(self):
        dk = DocKey(range_components=("k",))
        ht = DocHybridTime(HybridTime.from_micros(100), 0)
        shallow = SubDocKey(dk, (), ht).encode()
        deep = SubDocKey(dk, (("col", 1),), ht).encode()
        assert shallow < deep

    def test_split_key_and_ht(self):
        dht = DocHybridTime(HybridTime.from_micros(555), 9)
        sdk = SubDocKey(DocKey(range_components=("z",)), (("col", 0),), dht)
        enc = sdk.encode()
        prefix, ht = split_key_and_ht(enc)
        assert ht == dht
        assert prefix == sdk.encode(include_ht=False)


class TestValue:
    def test_roundtrips(self):
        for v in [Value(primitive=42), Value(primitive="s", ttl_ms=5000),
                  Value.tombstone(), Value(is_object=True),
                  Value(primitive=1.5, merge_flags=1, ttl_ms=100)]:
            assert Value.decode(v.encode()) == v

    def test_control_fields_peek(self):
        v = Value(primitive="payload", ttl_ms=7777, merge_flags=1)
        mf, ttl, off = decode_control_fields(v.encode())
        assert mf == 1 and ttl == 7777
        assert off == 5 + 9  # merge flags + ttl sections


class TestRandomizedOrdering:
    def test_memcmp_order_matches_semantic_order(self):
        """Fuzz: encoded byte order == (doc_key, subkeys, -ht) tuple order.

        Mirrors the randomized model-check approach of
        docdb/randomized_docdb-test.cc.
        """
        rng = random.Random(1234)
        items = []
        for _ in range(300):
            dk = DocKey(range_components=(rng.choice(["a", "b", "c"]), rng.randint(0, 3)))
            subkeys = (("col", rng.randint(0, 2)),) if rng.random() < 0.7 else ()
            ht = DocHybridTime(HybridTime.from_micros(rng.randint(1, 50)), rng.randint(0, 3))
            sem = (dk.encode(), SubDocKey(dk, subkeys).encode(include_ht=False),
                   -ht.ht.value, -ht.write_id)
            items.append((SubDocKey(dk, subkeys, ht).encode(), sem))
        by_bytes = sorted(i[0] for i in items)
        by_sem = [i[0] for i in sorted(items, key=lambda i: i[1])]
        assert by_bytes == by_sem
