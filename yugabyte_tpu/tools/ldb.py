"""ldb: inspect one DB directory (ref: rocksdb/tools/ldb_cmd.cc).

    python -m yugabyte_tpu.tools.ldb scan     --db <dir> [--limit N]
    python -m yugabyte_tpu.tools.ldb get      --db <dir> --key <hex>
    python -m yugabyte_tpu.tools.ldb manifest --db <dir>
    python -m yugabyte_tpu.tools.ldb verify   --db <dir>

Read-only: opens the manifest + SSTs in place (a live DB's files are
immutable once written, so inspecting a running tablet's dir is safe).
`verify` deep-checks every live SST (block CRCs + footer + index/bloom
consistency — the background scrubber's core) and exits non-zero on
corruption.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _open_readers(db_dir: str):
    import os

    from yugabyte_tpu.storage.sst import SSTReader
    from yugabyte_tpu.storage.version_set import VersionSet
    versions = VersionSet(db_dir)
    versions.recover()
    readers = []
    for fm in versions.live_files():
        path = os.path.join(db_dir, f"{fm.file_id:06d}.sst")
        readers.append((fm, SSTReader(path)))
    return versions, readers


def cmd_manifest(db_dir: str, out) -> int:
    versions, readers = _open_readers(db_dir)
    fr = versions.flushed_frontier
    print(f"db:               {db_dir}", file=out)
    print(f"next_file_id:     {versions.next_file_id}", file=out)
    if fr is not None:
        print(f"flushed_frontier: op_id={fr.op_id_max} "
              f"ht_max={fr.ht_max}", file=out)
    print(f"live files:       {len(readers)}", file=out)
    for fm, r in readers:
        print(f"  {fm.file_id:06d}.sst entries={r.props.n_entries} "
              f"bytes={r.props.data_size}", file=out)
        r.close()
    return 0


def cmd_scan(db_dir: str, limit: int, out) -> int:
    from yugabyte_tpu.tools.sst_dump import describe_entry
    _versions, readers = _open_readers(db_dir)
    shown = 0
    try:
        streams = []
        for _fm, r in readers:
            streams.append(r.iter_entries())
        # merged view is for inspection: show per-file streams in file
        # order (ldb scan shows raw, unresolved entries the same way)
        for (fm, _r), stream in zip(readers, streams):
            for key_prefix, dht, value, flags in stream:
                if shown >= limit:
                    return 0
                print(f"[{fm.file_id:06d}] "
                      f"{describe_entry(key_prefix, dht, value, flags)}",
                      file=out)
                shown += 1
        return 0
    finally:
        for _fm, r in readers:
            r.close()


def cmd_get(db_dir: str, key_hex: str, out) -> int:
    from yugabyte_tpu.ops.slabs import _doc_key_len
    from yugabyte_tpu.tools.sst_dump import describe_entry
    want = bytes.fromhex(key_hex)
    try:
        doc_key = want[: _doc_key_len(want)]
    except Exception:  # noqa: BLE001 — undecodable key: no bloom skip
        doc_key = None
    _versions, readers = _open_readers(db_dir)
    found = 0
    try:
        for fm, r in readers:
            if doc_key is not None and not r.may_contain_doc(doc_key):
                continue  # bloom proves the doc key is absent here
            for key_prefix, dht, value, flags in r.iter_entries():
                if key_prefix == want:
                    print(f"[{fm.file_id:06d}] "
                          f"{describe_entry(key_prefix, dht, value, flags)}",
                          file=out)
                    found += 1
        print(f"{found} version(s)", file=out)
        return 0 if found else 1
    finally:
        for _fm, r in readers:
            r.close()


def cmd_verify(db_dir: str, out) -> int:
    """Deep-check every live SST of the DB; exit 1 on any corruption."""
    import os

    from yugabyte_tpu.storage.integrity import verify_sst
    from yugabyte_tpu.storage.version_set import VersionSet
    versions = VersionSet(db_dir)
    versions.recover()
    bad = 0
    files = 0
    for fm in versions.live_files():
        path = os.path.join(db_dir, f"{fm.file_id:06d}.sst")
        rep = verify_sst(path)
        files += 1
        status = "OK" if rep.ok else f"{len(rep.errors)} error(s)"
        print(f"  {fm.file_id:06d}.sst blocks={rep.n_blocks} "
              f"bytes={rep.bytes_verified}: {status}", file=out)
        for err in rep.errors:
            print(f"    CORRUPT: {err}", file=out)
        if not rep.ok:
            bad += 1
    print(f"verify: {files} file(s), "
          + ("all OK" if bad == 0 else f"{bad} corrupt"), file=out)
    return 0 if bad == 0 else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="ldb")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("scan", "get", "manifest", "verify"):
        p = sub.add_parser(name)
        p.add_argument("--db", required=True)
        if name == "scan":
            p.add_argument("--limit", type=int, default=100)
        if name == "get":
            p.add_argument("--key", required=True, help="full subdoc key, hex")
    args = ap.parse_args(argv)
    if args.cmd == "manifest":
        return cmd_manifest(args.db, sys.stdout)
    if args.cmd == "scan":
        return cmd_scan(args.db, args.limit, sys.stdout)
    if args.cmd == "verify":
        return cmd_verify(args.db, sys.stdout)
    return cmd_get(args.db, args.key, sys.stdout)


if __name__ == "__main__":
    sys.exit(main())
