"""Device SST block codec: decode/encode block bytes on the accelerator.

Closes the byte shell around the compaction kernel (ROADMAP item 2): the
merge+GC kernel runs at 3.5M rows/s but end-to-end compaction ran at
~0.53x native because every job still paid the HOST byte codec — threaded
`decode_block` + `pack_cols` on ingest (stage A) and per-row block encode
on output (stage C).  This module moves the column transforms themselves
into two manifest-disciplined kernel families (the LUDA staging shape:
decode -> device compute -> encode as one offloaded chain):

  - `_block_decode_fused`: raw (CRC-checked, uncompressed) block bodies
    upload as ONE padded uint32 word matrix plus per-entry offset
    vectors; the kernel gathers key words (big-endian swap), widens the
    u16/u8 metadata arrays and splits TTL into the 20/32-bit microsecond
    limbs — producing the staged cols matrix `pack_cols` would have
    built, bit for bit, without materializing a decoded row on the host.
    Values never upload: they are zero-copy slices of the same raw body
    (block_format.raw_block_values) — the LSM-OPD direction of operating
    on block bytes directly.

  - `_block_encode_fused`: a gathered survivor-span cols matrix (already
    on device from the write-through gather) transforms into the exact
    on-disk column encodings — entry-major byteswapped key slab, packed
    u16 length pairs, packed u8 flags, raw TTL limbs — so the host
    writer only splices value bytes, stamps headers + CRC and writes the
    file (`encode_span`), killing the per-row encode work.

CRC stays host-side by design: zlib.crc32 is memory-bandwidth C over
bytes the host touches anyway (corrupt blocks surface typed
Status.Corruption BEFORE any upload, never wrong bytes), while the
per-entry transform work — the measured wall — runs on device.
`YBTPU_DEVICE_CODEC=0` disables both families (the compaction job then
takes the native byte shell exactly as before); device faults at the
dispatch/result sites quarantine the job's shape bucket and complete
byte-identically via the native merge, like every other kernel family.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_tpu.ops.merge_gc import (
    _ROW_WORDS, PAD_SENTINEL, StagedCols, bucket_size, build_sort_schedule)
from yugabyte_tpu.storage import block_format
from yugabyte_tpu.utils import jax_setup  # noqa: F401  (compilation cache)


class BlockCodecUnsupported(Exception):
    """The device codec cannot run this job (host byte shell takes it)."""


def codec_enabled() -> bool:
    """YBTPU_DEVICE_CODEC=0 disables both codec families (the documented
    fallback knob, next to YBTPU_PIPELINE)."""
    return os.environ.get("YBTPU_DEVICE_CODEC", "1").lower() \
        not in ("0", "false", "off")


def codec_metrics():
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    e = ROOT_REGISTRY.entity("server", "storage")
    return {
        "decode_blocks": e.counter(
            "compaction_block_decode_device_total",
            "SST blocks decoded into staged cols by the device codec "
            "(the host decode_block loop these replace counts in "
            "sst_block_decode_total)"),
        "encode_blocks": e.counter(
            "compaction_block_encode_device_total",
            "output SST blocks whose column bytes were assembled by the "
            "device codec"),
        "encode_fallbacks": e.counter(
            "compaction_block_encode_fallback_total",
            "device-native compactions that wrote outputs through the "
            "native shell encode instead of the device codec (codec "
            "disabled, all inputs run-cached, or mid-job fault)"),
    }


def _bswap32(x):
    """Big-endian key bytes <-> the uint32 key-word convention of
    ops/slabs.py (a little-endian u32 view of the raw bytes needs one
    byte swap each way)."""
    return (((x & jnp.uint32(0xFF)) << jnp.uint32(24))
            | ((x & jnp.uint32(0xFF00)) << jnp.uint32(8))
            | ((x >> jnp.uint32(8)) & jnp.uint32(0xFF00))
            | (x >> jnp.uint32(24)))


def _block_decode_impl(cols_in, n):
    """Raw block columns -> the staged cols matrix, on device.

    The host splits each CRC-checked body into its CONTIGUOUS column
    regions, laid straight into the cols layout (pure memcpy-class
    slicing + u16/u8 widening, no per-entry work — see
    decode_file_to_staged); the kernel does the per-entry transforms:
    big-endian key byteswap, the TTL ms -> 20/32-bit-microsecond limb
    split, and the column stats.  Deliberately gather- and
    transpose-free: every op is elementwise, so the program is fast on
    both the CPU fallback and the TPU (1-D lane gathers run ~180MB/s
    there; this layout avoids them entirely) and the donated twin can
    reuse the input HBM in place.  No static args — the compile key is
    the (n_pad, w_pad) shape bucket.

      cols_in: u32 [8+w_pad, n_pad] — the pack_cols row layout, except
            rows 6..7 carry the RAW (lo, hi) limbs of the i64
            millisecond TTL and rows 8+ carry the little-endian raw key
            words (zero beyond each entry's real stride; the host
            pre-fills the pad template beyond n: sentinel lens, 0xFF
            keys — 0xFF is bswap-invariant and sorts last)

    Returns (cols [8+w_pad, n_pad], is_const [R], first [R]) — cols plus
    the column stats stage_slab computes, so the host never downloads
    the matrix."""
    n_pad = cols_in.shape[1]
    lane = jnp.arange(n_pad, dtype=jnp.int32)
    valid = lane < n

    t_lo = cols_in[6]
    t_hi = cols_in[7]
    # ttl_us = ttl_ms * 1000 in two u32 limbs, then the 20/32 split
    # pack_cols writes (int64-free: 16-bit partial products + carry)
    k1000 = jnp.uint32(1000)
    a0 = t_lo & jnp.uint32(0xFFFF)
    a1 = t_lo >> jnp.uint32(16)
    p0 = a0 * k1000
    p1 = a1 * k1000
    add = (p1 & jnp.uint32(0xFFFF)) << jnp.uint32(16)
    us_lo = p0 + add
    carry = (us_lo < add).astype(jnp.uint32)
    us_hi = (p1 >> jnp.uint32(16)) + t_hi * k1000 + carry
    ttl_hi_col = (us_lo >> jnp.uint32(20)) | (us_hi << jnp.uint32(12))
    ttl_lo_col = us_lo & jnp.uint32(0xFFFFF)

    cols = jnp.concatenate(
        [cols_in[:6], ttl_hi_col[None], ttl_lo_col[None],
         _bswap32(cols_in[_ROW_WORDS:])], axis=0)
    first = cols[:, 0]
    is_const = jnp.all((cols == first[:, None]) | (~valid)[None, :],
                       axis=1)
    return cols, is_const, first


_block_decode_fused = jax.jit(_block_decode_impl)

# Donated variant: the uploaded raw column buffers are TRANSIENT
# (nothing reads them after the decode — values were sliced host-side),
# so on backends that honor donation XLA reuses the key matrix's HBM for
# the cols output instead of holding both live together.
_block_decode_fused_donated = functools.partial(
    jax.jit, donate_argnums=(0,))(_block_decode_impl)


def _block_encode_impl(cols):
    """Gathered survivor-span cols -> the on-disk column encodings.

    Input is the write-through span gather (ops/run_merge.
    gather_staged_output_span — tombstone flags already OR'd on device);
    NEVER donated: the same buffer installs into the slab cache after
    the span's SST hits disk.  Outputs (all u32, sliced/viewed by the
    host assembler `encode_span`):
      keys  [n_pad, w_pad]  entry-major byteswapped key words
      kl2 / dkl2 [n_pad/2]  packed u16 pairs (little-endian)
      ht_hi / ht_lo / wid [n_pad]
      fl4   [n_pad/4]       packed u8 quads
      ttl   [2, n_pad]      the 20/32 microsecond limbs (host divides
                            back to i64 milliseconds — exact, the limbs
                            were ms*1000)"""
    from yugabyte_tpu.ops.point_read import (_FNV_OFFSET_HI,
                                             _FNV_OFFSET_LO,
                                             _mul64_by_prime)
    kl = cols[0]
    dkl = cols[1]
    w_pad = cols.shape[0] - _ROW_WORDS
    keys = _bswap32(cols[_ROW_WORDS:]).T
    kl2 = (kl[0::2] & jnp.uint32(0xFFFF)) | (kl[1::2] << jnp.uint32(16))
    dkl2 = (dkl[0::2] & jnp.uint32(0xFFFF)) | (dkl[1::2] << jnp.uint32(16))
    fl = cols[5] & jnp.uint32(0xFF)
    fl4 = (fl[0::4] | (fl[1::4] << jnp.uint32(8))
           | (fl[2::4] << jnp.uint32(16)) | (fl[3::4] << jnp.uint32(24)))
    ttl = jnp.stack([cols[6], cols[7]], axis=0)
    # doc-key bloom hashes ride the same dispatch: FNV-1a over the first
    # doc_key_len bytes of each key (storage/bloom.fnv64_masked's exact
    # limb arithmetic via the point-read device twin) — the base-file
    # bloom build needs them anyway and the host pass was the single
    # most expensive piece of the host encode
    n_pad = cols.shape[1]
    h_hi = jnp.full((n_pad,), jnp.uint32(_FNV_OFFSET_HI))
    h_lo = jnp.full((n_pad,), jnp.uint32(_FNV_OFFSET_LO))
    dkl_i = dkl.astype(jnp.int32)
    for j in range(w_pad * 4):
        word = cols[_ROW_WORDS + j // 4]
        byte = (word >> jnp.uint32(8 * (3 - (j % 4)))) & jnp.uint32(0xFF)
        active = dkl_i > j
        nhi, nlo = _mul64_by_prime(h_hi, h_lo ^ byte)
        h_hi = jnp.where(active, nhi, h_hi)
        h_lo = jnp.where(active, nlo, h_lo)
    return (keys, kl2, dkl2, cols[2], cols[3], cols[4], fl4, ttl,
            h_hi, h_lo)


_block_encode_fused = jax.jit(_block_encode_impl)


# ---------------------------------------------------------------------------
# Host side: raw-file parsing (CRC + zero-copy values), upload staging,
# and the output-block assembler.
# ---------------------------------------------------------------------------


@dataclass
class RawFileBlocks:
    """One SST data file parsed at the raw-block level: CRC-checked
    bodies ready for upload, values as zero-copy slices — no column
    decode happened and none of the sst_block_decode_total /
    compaction_ingest_decode_total counters moved."""
    n: int                       # total entries
    w: int                       # real key words (max stride/4)
    counts: np.ndarray           # int64 [B]
    strides_w: np.ndarray        # int64 [B]
    bodies: List[np.ndarray]     # uint8 fixed regions (keys + metadata)
    # per-block ZERO-COPY value rows (views over the raw bodies): the
    # decode path never materializes them — the compaction job concats
    # every input's parts ONCE when stage C starts gathering survivors
    value_parts: List[object]

    @property
    def values(self):
        """This file's value rows as one ValueArray (lazy concat —
        only the single-file callers pay it)."""
        from yugabyte_tpu.ops.slabs import ValueArray
        return (ValueArray.concat(self.value_parts) if self.value_parts
                else ValueArray.empty_rows(0))


def parse_raw_file(raw: bytes, handles: Sequence[Tuple[int, int, int]]
                   ) -> RawFileBlocks:
    """Split one data file's bytes into CRC-checked raw block regions.

    Corruption surfaces here, typed, BEFORE anything uploads or any
    value byte is trusted — the codec twin of the native shell's
    prepare()-time checks."""
    counts: List[int] = []
    strides_w: List[int] = []
    bodies: List[np.ndarray] = []
    vals: List[object] = []
    mv = memoryview(raw)   # zero-copy block/body slicing
    for off, size, _cnt in handles:
        n_b, stride, body = block_format.split_raw_block(
            mv[off: off + size])
        counts.append(n_b)
        strides_w.append(stride // 4)
        bodies.append(np.frombuffer(
            body, dtype=np.uint8,
            count=block_format.fixed_region_bytes(n_b, stride)))
        vals.append(block_format.raw_block_values(n_b, stride, body))
    return RawFileBlocks(
        n=int(sum(counts)),
        w=max([int(s) for s in strides_w], default=1),
        counts=np.asarray(counts, dtype=np.int64),
        strides_w=np.asarray(strides_w, dtype=np.int64),
        bodies=bodies,
        value_parts=vals)


def _quantize_width(w: int) -> int:
    # pack_cols' width formula (== run_merge.quantize_width): decoded
    # staging must land on the same bucket as host staging
    return 1 << max(2, (w - 1).bit_length() if w > 1 else 1)


def decode_file_to_staged(rfb: RawFileBlocks, device=None) -> StagedCols:
    """Upload one file's raw fixed regions and decode them on device into
    the StagedCols matrix stage_slab would have produced (bit-identical;
    differential-tested in tests/test_block_codec.py)."""
    import time as _time
    from yugabyte_tpu.ops import device_faults
    from yugabyte_tpu.ops.run_merge import _donation_supported
    from yugabyte_tpu.utils.metrics import (record_kernel_dispatch,
                                            record_pipeline_stage)
    n = rfb.n
    if n == 0:
        raise BlockCodecUnsupported("empty file has nothing to stage")
    t0 = _time.monotonic()
    n_pad = bucket_size(n)
    from yugabyte_tpu.storage.bucket_health import health_board
    _board = health_board()
    if not _board.allow_device("block_decode", (1, n_pad)):
        # parked bucket (recent fault / sticky mismatch): the caller's
        # BlockCodecUnsupported handling takes the native byte shell
        raise BlockCodecUnsupported("decode bucket parked by the "
                                    "health board")
    w_pad = _quantize_width(rfb.w)
    # Per-block CONTIGUOUS region slices laid straight into ONE buffer
    # in the cols layout.  All memcpy-class (vectorized widening of the
    # u16/u8 regions included): the per-entry transform work (byteswap,
    # TTL limb math, stats) happens in the kernel.
    cols_in = np.zeros((_ROW_WORDS + w_pad, n_pad), dtype=np.uint32)
    cols_in[0, n:] = np.uint32(0xFFFFFFFF)   # PAD_SENTINEL key_len
    cols_in[1, n:] = np.uint32(0xFFFFFFFF)   # PAD_SENTINEL doc_key_len
    cols_in[_ROW_WORDS:, n:] = np.uint32(0xFFFFFFFF)   # pad keys: last
    pos = 0
    for n_b, sw, body in zip(rfb.counts, rfb.strides_w, rfb.bodies):
        n_b = int(n_b)
        sw = int(sw)
        sl = slice(pos, pos + n_b)
        ks = n_b * sw * 4                      # key-slab bytes
        kv = np.frombuffer(body, dtype="<u4",
                           count=n_b * sw).reshape(n_b, sw)
        cols_in[_ROW_WORDS: _ROW_WORDS + sw, sl] = kv.T
        cols_in[0, sl] = np.frombuffer(body, dtype="<u2", count=n_b,
                                       offset=ks)
        cols_in[1, sl] = np.frombuffer(body, dtype="<u2", count=n_b,
                                       offset=ks + 2 * n_b)
        cols_in[2, sl] = np.frombuffer(body, dtype="<u4", count=n_b,
                                       offset=ks + 4 * n_b)
        cols_in[3, sl] = np.frombuffer(body, dtype="<u4", count=n_b,
                                       offset=ks + 8 * n_b)
        cols_in[4, sl] = np.frombuffer(body, dtype="<u4", count=n_b,
                                       offset=ks + 12 * n_b)
        cols_in[5, sl] = np.frombuffer(body, dtype=np.uint8, count=n_b,
                                       offset=ks + 16 * n_b)
        # the ttl region is 8*n bytes at a possibly-odd alignment: read
        # through an aligned u8 copy, then de-interleave the i64 limbs
        t = np.frombuffer(body, dtype=np.uint8, count=8 * n_b,
                          offset=ks + 17 * n_b).copy().view("<u4")
        cols_in[6, sl] = t[0::2]
        cols_in[7, sl] = t[1::2]
        pos += n_b

    device_faults.maybe_fault("dispatch")
    donate = _donation_supported()
    fn = _block_decode_fused_donated if donate else _block_decode_fused

    def _dispatch():
        # fresh uploads each dispatch: the donated variant consumed the
        # previous input matrix, but the host array is intact
        ci = (jax.device_put(cols_in, device) if device is not None
              else jnp.asarray(cols_in))
        return fn(ci, jnp.int32(n))

    cols, is_const_d, first_d = _dispatch()
    try:
        device_faults.maybe_fault("result")
        is_const = np.asarray(is_const_d)
        first = np.asarray(first_d)
    except Exception as e:  # noqa: BLE001 — device-fault containment
        if not device_faults.is_device_fault(e):
            raise
        # one retry of the same (jit-cached) launch, like the merge
        # handle's relaunch; a second failure takes the native fallback
        from yugabyte_tpu.ops.run_merge import _chunk_retry_counter
        from yugabyte_tpu.utils.trace import TRACE
        _chunk_retry_counter().increment()
        TRACE("block_codec: device fault at decode download (%r) — "
              "retrying the launch once", e)
        try:
            cols, is_const_d, first_d = _dispatch()
            device_faults.maybe_fault("result")
            is_const = np.asarray(is_const_d)
            first = np.asarray(first_d)
        except Exception as e2:  # noqa: BLE001 — post-retry containment
            if device_faults.is_device_fault(e2):
                # retry exhausted: park the decode bucket before the
                # fault unwinds to the job-level native fallback
                _board.record_fault(
                    "block_decode", (1, n_pad),
                    reason=f"decode {type(e2).__name__}: {e2}")
            raise
    sort_rows, n_sort = build_sort_schedule(w_pad, is_const)
    record_kernel_dispatch("kernel_block_decode", n, n_pad,
                           (_time.monotonic() - t0) * 1e3)
    record_pipeline_stage("decode", (_time.monotonic() - t0) * 1e3)
    _board.record_device("block_decode", (1, n_pad), n,
                         _time.monotonic() - t0)
    codec_metrics()["decode_blocks"].increment(len(rfb.bodies))
    return StagedCols(cols, sort_rows, n_sort, n, n_pad, w_pad,
                      is_const, first)


def encode_span(st: StagedCols, n_rows: int, w_out: int, values,
                block_entries: int, compress: bool):
    """Assemble the finished block bytes of one survivor span.

    st: the span's gathered cols (device); n_rows real rows; w_out the
    output key stride in words (max real input stride — the native
    shell's rule, so files stay byte-identical); values: the span's
    host-side value rows (tombstone rewrite already applied).
    Returns (blocks, index_items, bloom_hashes, first_key, last_key) in
    the exact write_base_file vocabulary."""
    import time as _time
    import zlib as _zlib
    from yugabyte_tpu.ops import device_faults
    from yugabyte_tpu.storage.bucket_health import health_board
    from yugabyte_tpu.utils.metrics import (record_kernel_dispatch,
                                            record_pipeline_stage)
    _board = health_board()
    if not _board.allow_device("block_encode", (1, st.n_pad)):
        # parked encode bucket: the job unwinds its partial outputs and
        # re-runs through the native byte shell, byte-identically
        raise BlockCodecUnsupported("encode bucket parked by the "
                                    "health board")
    t0 = _time.monotonic()
    device_faults.maybe_fault("dispatch")

    def _download():
        # device-side row slicing before the D2H: only the real rows and
        # the real output stride cross the link, not the pad tail
        (keys_d, kl2, dkl2, ht_hi_d, ht_lo_d, wid_d, fl4, ttl_d,
         h_hi_d, h_lo_d) = _block_encode_fused(st.cols_dev)
        device_faults.maybe_fault("result")
        return (np.asarray(keys_d[:n_rows, :w_out]),
                np.asarray(kl2[: (n_rows + 1) // 2]),
                np.asarray(dkl2[: (n_rows + 1) // 2]),
                np.asarray(ht_hi_d[:n_rows]),
                np.asarray(ht_lo_d[:n_rows]),
                np.asarray(wid_d[:n_rows]),
                np.asarray(fl4[: (n_rows + 3) // 4]),
                np.asarray(ttl_d[:, :n_rows]),
                np.asarray(h_hi_d[:n_rows]),
                np.asarray(h_lo_d[:n_rows]))

    try:
        outs = _download()
    except Exception as e:  # noqa: BLE001 — device-fault containment
        if not device_faults.is_device_fault(e):
            raise
        # retry-once: the span cols are NOT donated (the write-through
        # install reads them after this), so re-dispatch is legal
        from yugabyte_tpu.ops.run_merge import _chunk_retry_counter
        from yugabyte_tpu.utils.trace import TRACE
        _chunk_retry_counter().increment()
        TRACE("block_codec: device fault at encode download (%r) — "
              "retrying the launch once", e)
        try:
            outs = _download()
        except Exception as e2:  # noqa: BLE001 — post-retry containment
            if device_faults.is_device_fault(e2):
                _board.record_fault(
                    "block_encode", (1, st.n_pad),
                    reason=f"encode {type(e2).__name__}: {e2}")
            raise
    keys, kl2, dkl2, ht_hi, ht_lo, wid, fl4, ttl, h_hi, h_lo = outs
    keys_u8 = keys.view(np.uint8).reshape(n_rows, w_out * 4)
    kl = kl2.view("<u2")[:n_rows]
    dkl = dkl2.view("<u2")[:n_rows]
    fl = fl4.view(np.uint8)[:n_rows]
    # ttl rows are [hi20, lo] — the pack_cols 20/32 microsecond split
    ttl_us = ((ttl[0].astype(np.uint64) << np.uint64(20))
              | ttl[1].astype(np.uint64))
    ttl_ms = (ttl_us // np.uint64(1000)).astype("<i8")

    hashes = (h_hi.astype(np.uint64) << np.uint64(32)) \
        | h_lo.astype(np.uint64)

    def key_at(i: int) -> bytes:
        return keys_u8[i, : int(kl[i])].tobytes()

    blocks: List[bytes] = []
    index_items: List[Tuple[bytes, int, int, int]] = []
    data_off = 0
    voffs = values.offsets
    for s in range(0, n_rows, block_entries):
        e = min(s + block_entries, n_rows)
        vo = (voffs[s: e + 1] - voffs[s]).astype("<u4")
        body = b"".join([
            keys_u8[s:e].tobytes(),
            kl[s:e].tobytes(), dkl[s:e].tobytes(),
            ht_hi[s:e].tobytes(), ht_lo[s:e].tobytes(),
            wid[s:e].tobytes(), fl[s:e].tobytes(),
            ttl_ms[s:e].tobytes(), vo.tobytes(),
            values.data[voffs[s]: voffs[e]].tobytes(),
        ])
        raw_len = len(body)
        bflags = 0
        stored = body
        if compress:
            c = _zlib.compress(body, 1)
            if len(c) < raw_len:
                stored = c
                bflags = 1
        header = block_format._HEADER.pack(
            block_format.BLOCK_MAGIC, e - s, w_out * 4, bflags,
            len(stored), raw_len)
        crc = _zlib.crc32(header[4:] + stored)
        blk = header + stored + np.uint32(crc).tobytes()
        blocks.append(blk)
        index_items.append((key_at(e - 1), data_off, len(blk), e - s))
        data_off += len(blk)
    first_key = key_at(0) if n_rows else b""
    last_key = key_at(n_rows - 1) if n_rows else b""
    record_kernel_dispatch("kernel_block_encode", n_rows, st.n_pad,
                           (_time.monotonic() - t0) * 1e3)
    record_pipeline_stage("encode", (_time.monotonic() - t0) * 1e3)
    _board.record_device("block_encode", (1, st.n_pad), n_rows,
                         _time.monotonic() - t0)
    codec_metrics()["encode_blocks"].increment(len(blocks))
    return blocks, index_items, hashes, first_key, last_key


# ---------------------------------------------------------------------------
# Prewarm (PrewarmKernelsOp folds this into the startup compile pass)
# ---------------------------------------------------------------------------

# (n_pad, w_pad) lattice the manifest declares: the flush-sized and
# once-compacted row buckets of _PREWARM_SHAPES at the default key width
_PREWARM_DECODE = ((1 << 16, 4), (1 << 18, 4))


def prewarm_block_codec() -> int:
    """Ahead-of-traffic compile of the codec buckets (mirrors
    run_merge.prewarm_buckets; called by PrewarmKernelsOp)."""
    from yugabyte_tpu.ops.run_merge import _donation_supported
    compiled = 0

    def _warm(what, lower_fn):
        nonlocal compiled
        try:
            lower_fn().compile()
            compiled += 1
        except Exception as e:  # noqa: BLE001 — prewarm must never block
            import sys as _sys                       # server startup
            print(f"[block_codec] prewarm of {what} failed: {e!r}",
                  file=_sys.stderr, flush=True)

    sdt = jax.ShapeDtypeStruct
    donate = _donation_supported()
    fn = _block_decode_fused_donated if donate else _block_decode_fused
    for n_pad, w_pad in _PREWARM_DECODE:
        _warm(f"block_decode (n_pad={n_pad} w_pad={w_pad})",
              lambda: fn.lower(*decode_avals(n_pad, w_pad)))
        _warm(f"block_encode (n_pad={n_pad} w_pad={w_pad})",
              lambda: _block_encode_fused.lower(
                  sdt((_ROW_WORDS + w_pad, n_pad), jnp.uint32)))
    return compiled


def decode_avals(n_pad: int, w_pad: int):
    """The decode program's abstract arg shapes for one (n_pad, w_pad)
    bucket — shared by prewarm and the manifest generator so they can
    never drift apart."""
    sdt = jax.ShapeDtypeStruct
    return (sdt((_ROW_WORDS + w_pad, n_pad), jnp.uint32),
            sdt((), jnp.int32))
