"""CQL binary protocol v4 server: the network face Cassandra drivers speak.

Capability parity with the reference's cqlserver (ref: src/yb/yql/cql/
cqlserver/cql_server.h:58 — socket server; cql_processor.h:63 — per
connection processor; cql_service.cc — shared prepared-statement cache):
STARTUP/OPTIONS/QUERY/PREPARE/EXECUTE/BATCH/REGISTER over real v4 frames,
one thread per connection, statements executed by the shared YCQL
parser/executor (yql/cql/parser.py, executor.py).

Prepared statements: PREPARE parses once, infers each bind marker's type
from the target table's schema (the metadata a driver uses to encode
EXECUTE values), and caches under an MD5 id, like the reference's
prepared-statement cache.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.transaction import TransactionManager
from yugabyte_tpu.common.schema import DataType
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.yql.cql import parser as P
from yugabyte_tpu.yql.cql import wire as W
from yugabyte_tpu.yql.cql.executor import QLProcessor, ResultSet
from yugabyte_tpu.utils import ybsan


def infer_marker_types(stmt, processor: QLProcessor) -> List[DataType]:
    """Bind-marker types in statement order, from the table schema (the
    reference's analyzer types markers the same way, ql/ptree pt_bind_var).
    """
    def table_schema(ks, name):
        return processor._table(ks, name).schema

    def where_types(schema, where):
        out = []
        for c, _op, v in where:
            if isinstance(v, list):       # col IN (?, 'x', ?) markers
                out.extend(schema.column(c).type for x in v
                           if x is P.MARKER)
            elif v is P.MARKER:
                out.append(schema.column(c).type)
        return out

    def value_marker_types(col_type, v):
        """Markers in a value position, including ones nested inside
        builtin calls — INSERT ... VALUES (?, textasblob(?)) binds two.
        A marker that is a FUNCTION ARGUMENT is typed by the function's
        parameter (textasblob takes STRING even into a BLOB column),
        falling back to the column type only when overloads disagree."""
        from yugabyte_tpu.yql import bfunc
        if v is P.MARKER:
            return [col_type]
        if isinstance(v, P.FuncCall):
            out = []
            for i, a in enumerate(v.args):
                if a is P.MARKER:
                    out.append(bfunc.marker_arg_type(v.name, i) or col_type)
                else:
                    out.extend(value_marker_types(col_type, a))
            return out
        return []

    def select_item_types(schema, items):
        from yugabyte_tpu.yql import bfunc
        out: List[DataType] = []
        for it in (items or []):
            if isinstance(it, P.FuncCall):
                # ambiguous markers (ANY-typed params, e.g. coalesce)
                # fall back to a sibling COLUMN argument's type — the
                # marker almost always stands in for that column's value
                sibling = None
                for a in it.args:
                    if isinstance(a, P.ColumnRef):
                        try:
                            sibling = schema.column(a.name).type
                        except Exception:
                            sibling = None
                        break
                for i, a in enumerate(it.args):
                    if a is P.MARKER:
                        out.append(bfunc.marker_arg_type(it.name, i)
                                   or sibling or DataType.STRING)
                    elif isinstance(a, P.FuncCall):
                        out.extend(select_item_types(schema, [a]))
        return out

    def _marker_in_collection(v) -> bool:
        if v is P.MARKER:
            return True
        if isinstance(v, (list, tuple, set, frozenset)):
            return any(x is P.MARKER for x in v)
        if isinstance(v, dict):
            return any(k is P.MARKER or x is P.MARKER
                       for k, x in v.items())
        return False

    if isinstance(stmt, P.Insert):
        schema = table_schema(stmt.keyspace, stmt.table)
        out = []
        for c, v in zip(stmt.columns, stmt.values):
            if schema.column(c).collection is not None \
                    and _marker_in_collection(v):
                raise StatusError(Status.NotSupported(
                    "bind markers in collection values: inline the "
                    "literal"))
            out.extend(value_marker_types(schema.column(c).type, v))
        return out
    if isinstance(stmt, P.Update):
        schema = table_schema(stmt.keyspace, stmt.table)
        out = []
        for c, v in stmt.assignments:
            base = c[0] if isinstance(c, tuple) else c
            is_coll = schema.column(base).collection is not None
            in_rhs = (v[1] if isinstance(v, tuple) and len(v) == 2
                      and v[0] in ("__append__", "__remove__") else v)
            if is_coll and (_marker_in_collection(in_rhs)
                            or (isinstance(c, tuple)
                                and c[1] is P.MARKER)):
                raise StatusError(Status.NotSupported(
                    "bind markers in collection values: inline the "
                    "literal"))
            if v is P.MARKER:
                out.append(schema.column(base).type)
        # LWT IF-clause markers bind after the WHERE markers in statement
        # order (UPDATE ... WHERE k = ? IF v = ?); conditions share the
        # (col, op, value) shape where_types walks
        return (out + where_types(schema, stmt.where)
                + where_types(schema, stmt.conditions))
    if isinstance(stmt, P.Delete):
        schema = table_schema(stmt.keyspace, stmt.table)
        for c in stmt.columns or ():
            if isinstance(c, tuple) and c[1] is P.MARKER:
                raise StatusError(Status.NotSupported(
                    "bind markers in collection element deletes: inline "
                    "the literal"))
        return (where_types(schema, stmt.where)
                + where_types(schema, stmt.conditions))
    if isinstance(stmt, P.Select):
        ks = stmt.keyspace or processor._keyspace
        if ks in ("system", "system_schema"):
            # vtables have no client-side schema object; their WHERE
            # predicates are all text-typed (keyspace_name/table_name/...)
            out = []
            for _c, _op, v in stmt.where:
                if isinstance(v, list):
                    out.extend(DataType.STRING for x in v if x is P.MARKER)
                elif v is P.MARKER:
                    out.append(DataType.STRING)
            return out
        schema = table_schema(stmt.keyspace, stmt.table)
        # select-list markers precede WHERE markers in statement order
        return select_item_types(schema, stmt.columns) + \
            where_types(schema, stmt.where)
    if isinstance(stmt, P.Transaction):
        out: List[DataType] = []
        for s in stmt.statements:
            out.extend(infer_marker_types(s, processor))
        return out
    return []


class _Prepared:
    def __init__(self, text: str, types: List[DataType],
                 keyspace: Optional[str]):
        self.text = text
        self.types = types
        # keyspace-scoped id: the same unqualified text prepared under two
        # keyspaces must not collide (their marker types can differ)
        self.id = hashlib.md5(
            (keyspace or "").encode() + b"\x00" + text.encode()).digest()


class _Connection:
    def __init__(self, server: "CQLBinaryServer", sock: socket.socket):
        self._server = server
        self._sock = sock
        self._processor = QLProcessor(server.client, server.txn_manager,
                                      local_addr=(server.host, server.port))
        self._lock = threading.Lock()  # serialize writes (async streams)

    # ------------------------------------------------------------- sending
    def _send(self, stream: int, opcode: int, body: bytes = b"") -> None:
        with self._lock:
            self._sock.sendall(
                W.frame(W.VERSION_RESPONSE, stream, opcode, body))

    def _send_error(self, stream: int, code: int, msg: str) -> None:
        self._send(stream, W.OP_ERROR, W.error_body(code, msg))

    def _send_rows(self, stream: int, rs: ResultSet) -> None:
        ks, tbl = rs.source
        cols = [(ks, tbl, name, rs.types[i] if i < len(rs.types)
                 and rs.types[i] is not None else _infer_type(rs, i))
                for i, name in enumerate(rs.columns)]
        out = [struct.pack(">i", W.RESULT_ROWS),
               W.rows_metadata(cols, paging_state=rs.paging_state),
               struct.pack(">i", len(rs.rows))]
        for row in rs.rows:
            for i, v in enumerate(row):
                out.append(W.w_bytes(W.encode_value(v, cols[i][3])))
        self._send(stream, W.OP_RESULT, b"".join(out))

    def _send_void(self, stream: int) -> None:
        self._send(stream, W.OP_RESULT, struct.pack(">i", W.RESULT_VOID))

    # -------------------------------------------------------------- serving
    def serve(self) -> None:
        try:
            while True:
                try:
                    version, stream, opcode, body = W.read_frame(self._sock)
                except (ConnectionError, OSError):
                    return
                if version != W.VERSION_REQUEST:
                    self._send_error(stream, W.ERR_PROTOCOL,
                                     f"unsupported version {version:#x}")
                    return
                try:
                    self._dispatch(stream, opcode, W.Reader(body))
                except StatusError as e:
                    self._send_error(stream, _err_code(e), str(e))
                except (ValueError, KeyError, struct.error) as e:
                    self._send_error(stream, W.ERR_INVALID, str(e))
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def _dispatch(self, stream: int, opcode: int, r: W.Reader) -> None:
        if opcode == W.OP_STARTUP:
            r.string_map()  # CQL_VERSION etc. — any v4 dialect accepted
            self._send(stream, W.OP_READY)
        elif opcode == W.OP_OPTIONS:
            self._send(stream, W.OP_SUPPORTED, W.w_string_multimap(
                {"CQL_VERSION": ["3.4.4"], "COMPRESSION": []}))
        elif opcode == W.OP_REGISTER:
            r.string_list()  # event registration accepted; no events yet
            self._send(stream, W.OP_READY)
        elif opcode == W.OP_QUERY:
            query = r.long_string()
            params, page_size, paging_state = self._read_query_params(
                r, types=None, types_provider=lambda: self._marker_types(
                    query))
            self._run(stream, query, params, page_size, paging_state)
        elif opcode == W.OP_PREPARE:
            text = r.long_string()
            stmt = P.parse(text)
            prep = _Prepared(text, infer_marker_types(stmt,
                                                      self._processor),
                             self._processor._keyspace)
            self._server.prepared[prep.id] = prep
            # v4 Prepared result: id, bind-marker metadata (flags=0,
            # n columns, pk_count=0, per-marker ks/table/name/type),
            # then empty result metadata
            marker_meta = [struct.pack(">i", 0),
                           struct.pack(">i", len(prep.types)),
                           struct.pack(">i", 0)]
            for i, t in enumerate(prep.types):
                marker_meta += [W.w_string(""), W.w_string(""),
                                W.w_string(f"p{i}"),
                                struct.pack(">H", W.cql_type_of(t))]
            self._send(stream, W.OP_RESULT, b"".join(
                [struct.pack(">i", W.RESULT_PREPARED),
                 W.w_short_bytes(prep.id)] + marker_meta
                + [W.rows_metadata([])]))
        elif opcode == W.OP_EXECUTE:
            pid = r.short_bytes()
            prep = self._server.prepared.get(pid)
            if prep is None:
                self._send_error(stream, W.ERR_UNPREPARED,
                                 "unprepared statement")
                return
            params, page_size, paging_state = self._read_query_params(
                r, types=prep.types)
            self._run(stream, prep.text, params, page_size, paging_state)
        elif opcode == W.OP_BATCH:
            self._run_batch(stream, r)
        else:
            self._send_error(stream, W.ERR_PROTOCOL,
                             f"unsupported opcode {opcode:#x}")

    def _marker_types(self, query: str) -> List[DataType]:
        """Bind-marker types for an unprepared QUERY with values: parse the
        text and type the markers against the schema (same inference the
        PREPARE path uses; drivers send raw bytes either way)."""
        try:
            return infer_marker_types(P.parse(query), self._processor)
        except (StatusError, ValueError, KeyError):
            return []

    def _read_query_params(self, r: W.Reader,
                           types: Optional[List[DataType]],
                           types_provider=None):
        """Returns (bind values, page_size, paging_state)."""
        r.u16()  # consistency — single-partition linearizable regardless
        flags = r.u8()
        params: List = []
        page_size = None
        paging_state = None
        if flags & 0x01:  # values
            if types is None and types_provider is not None:
                types = types_provider()
            if flags & 0x40:
                # named values would need named markers to bind correctly;
                # binding them positionally silently swaps columns, so
                # refuse (drivers use positional values by default)
                raise ValueError("named bind values are not supported")
            n = r.u16()
            for i in range(n):
                raw = r.bytes_()
                dt = (types[i] if types is not None and i < len(types)
                      else DataType.STRING)
                params.append(W.decode_value(raw, dt))
        if flags & 0x04:
            page_size = r.i32()
            if page_size is not None and page_size <= 0:
                page_size = None
        if flags & 0x08:
            paging_state = r.bytes_()
        if flags & 0x10:
            r.u16()   # serial consistency
        if flags & 0x20:
            r.i64()   # default timestamp
        return params, page_size, paging_state

    def _run(self, stream: int, text: str, params: List,
             page_size: Optional[int] = None,
             paging_state: Optional[bytes] = None) -> None:
        stmt_head = text.lstrip()[:6].upper()
        rs = self._processor.execute(text, params, page_size=page_size,
                                     paging_state=paging_state)
        if stmt_head.startswith("USE"):
            self._send(stream, W.OP_RESULT,
                       struct.pack(">i", W.RESULT_SET_KEYSPACE)
                       + W.w_string(self._processor._keyspace or ""))
        elif rs.columns:
            self._send_rows(stream, rs)
        elif stmt_head.startswith(("CREATE", "DROP", "ALTER")):
            # SCHEMA_CHANGE result (change_type, target, options)
            self._send(stream, W.OP_RESULT,
                       struct.pack(">i", W.RESULT_SCHEMA_CHANGE)
                       + W.w_string("CREATED") + W.w_string("TABLE")
                       + W.w_string(self._processor._keyspace or "")
                       + W.w_string(""))
        else:
            self._send_void(stream)

    def _run_batch(self, stream: int, r: W.Reader) -> None:
        r.u8()  # batch type (logged/unlogged/counter)
        n = r.u16()
        for _ in range(n):
            kind = r.u8()
            if kind == 0:
                text = r.long_string()
                types: Optional[List[DataType]] = self._marker_types(text)
            else:
                prep = self._server.prepared.get(r.short_bytes())
                if prep is None:
                    self._send_error(stream, W.ERR_UNPREPARED,
                                     "unprepared statement in batch")
                    return
                text, types = prep.text, prep.types
            nvals = r.u16()
            params = []
            for i in range(nvals):
                raw = r.bytes_()
                dt = (types[i] if types is not None and i < len(types)
                      else DataType.STRING)
                params.append(W.decode_value(raw, dt))
            self._processor.execute(text, params)
        r.u16()  # consistency
        self._send_void(stream)


def _infer_type(rs: ResultSet, col: int) -> DataType:
    for row in rs.rows:
        v = row[col]
        if v is None:
            continue
        if isinstance(v, bool):
            return DataType.BOOL
        if isinstance(v, int):
            return DataType.INT64
        if isinstance(v, float):
            return DataType.DOUBLE
        if isinstance(v, bytes):
            return DataType.BINARY
        return DataType.STRING
    return DataType.STRING


def _err_code(e: StatusError) -> int:
    name = e.status.code.name
    if name == "INVALID_ARGUMENT":
        return W.ERR_INVALID
    if name == "ALREADY_PRESENT":
        return W.ERR_ALREADY_EXISTS
    if name == "NOT_SUPPORTED":
        return W.ERR_SYNTAX
    return W.ERR_SERVER


@ybsan.shadow(_shutdown=ybsan.SINGLE_WRITER)
class CQLBinaryServer:
    """Thread-per-connection CQL v4 endpoint (default port 9042 in the
    reference; ephemeral here unless given)."""

    def __init__(self, client: YBClient, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = client
        self.txn_manager = TransactionManager(client)
        self.prepared: Dict[bytes, _Prepared] = {}
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._shutdown = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="cql-accept")
        self._accept_thread.start()
        TRACE("cql binary server listening on %s:%d", self.host, self.port)

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            conn = _Connection(self, sock)
            threading.Thread(target=conn.serve, daemon=True,
                             name="cql-conn").start()

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass
