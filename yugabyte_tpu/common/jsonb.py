"""JSONB document support shared by the YCQL and YSQL layers.

The reference serializes jsonb to a binary sorted-key format
(ref: src/yb/common/jsonb.h:33-66) so documents compare deterministically
and keys binary-search. Our storage form keeps the same properties with
canonical compact JSON text: object keys sorted, no whitespace — equal
documents always store byte-identical. Path navigation (-> / ->>)
mirrors common/jsonb.cc ApplyJsonbOperators: missing keys, out-of-range
indexes and scalar mismatches yield NULL, never an error.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence


def canonicalize(v) -> str:
    """Validate + canonicalize a jsonb input value to storage text.

    Accepts json text (the normal literal path) or an already-materialized
    python value (bound params arriving through a wire codec).
    Raises ValueError on malformed json / unsupported input type.
    """
    if isinstance(v, (dict, list, int, float, bool)) or v is None:
        return json.dumps(v, sort_keys=True, separators=(",", ":"),
                          allow_nan=False)
    if not isinstance(v, str):
        raise ValueError(
            f"jsonb value must be a json text literal, "
            f"got {type(v).__name__}")
    # spec-strict: NaN/Infinity are not JSON (PG rejects them with 22P02)
    # and NaN would break the canonical-equality guarantee (NaN != NaN)
    doc = json.loads(v, parse_constant=_reject_constant)
    return json.dumps(doc, sort_keys=True, separators=(",", ":"),
                      allow_nan=False)


def _reject_constant(name: str):
    raise ValueError(f"{name} is not valid JSON")


def navigate(stored: Optional[str], path: Sequence, as_text: bool):
    """Apply a -> / ->> chain over stored canonical json text.

    path holds object keys (str) and array indexes (int); as_text marks a
    trailing ->> (unquote strings / stringify scalars). Returns None for
    any miss (PG + reference jsonb operator semantics)."""
    if stored is None:
        return None
    try:
        doc = json.loads(stored)
    except ValueError:
        return None
    for step in path:
        if isinstance(step, int) and not isinstance(step, bool):
            if not isinstance(doc, list) or not (-len(doc) <= step
                                                 < len(doc)):
                return None
            doc = doc[step]
        else:
            if not isinstance(doc, dict) or step not in doc:
                return None
            doc = doc[step]
    if as_text:
        if doc is None:
            return None
        if isinstance(doc, bool):
            return "true" if doc else "false"
        if isinstance(doc, (dict, list)):
            return json.dumps(doc, sort_keys=True, separators=(",", ":"))
        return str(doc)
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))
