"""YBTransaction + TransactionManager: the client side of distributed
transactions.

Capability parity with the reference (ref: src/yb/client/transaction.h:59 —
a transaction picks a status tablet, registers, heartbeats while live,
attaches its metadata to every data op, tracks touched tablets, and commits
or aborts through the coordinator; transaction_manager.h:36 — lazily
ensures the `system.transactions` status table exists and load-balances
transactions across its tablets).

Isolation: snapshot isolation. The coordinator assigns the read point at
transaction start; every read snapshots there and every write conflict-
checks against it, so the transaction sees one consistent snapshot and
fails (TransactionError, retryable) on write-write races.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.common.hybrid_time import HybridTime
from yugabyte_tpu.common.wire import doc_key_to_wire, row_from_wire, \
    write_op_to_wire
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp
from yugabyte_tpu.docdb.intents import TransactionMetadata
from yugabyte_tpu.rpc.messenger import RemoteError
from yugabyte_tpu.tserver.transaction_coordinator import (
    SYSTEM_NAMESPACE, TRANSACTIONS_TABLE, TXN_STATUS_SCHEMA)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils import lock_rank
from yugabyte_tpu.utils.status import Code, Status, StatusError

flags.define_flag("txn_client_heartbeat_ms", 2000,
                  "client-side transaction heartbeat period")


class TransactionError(StatusError):
    """Conflict or expiry; the whole transaction should be retried."""

    def __init__(self, msg: str):
        super().__init__(Status.TryAgain(msg))


class TransactionManager:
    """ref client/transaction_manager.h:36"""

    def __init__(self, client: YBClient, num_status_tablets: int = 2):
        self._client = client
        self._num_status_tablets = num_status_tablets
        self._status_table: Optional[YBTable] = None
        self._lock = threading.Lock()

    def status_table(self) -> YBTable:
        with self._lock:
            if self._status_table is not None:
                return self._status_table
            try:
                self._client.create_namespace(SYSTEM_NAMESPACE)
            except RemoteError as e:
                if e.status.code != Code.ALREADY_PRESENT:
                    raise
            try:
                table = self._client.create_table(
                    SYSTEM_NAMESPACE, TRANSACTIONS_TABLE, TXN_STATUS_SCHEMA,
                    num_tablets=self._num_status_tablets)
            except RemoteError as e:
                if e.status.code != Code.ALREADY_PRESENT:
                    raise
                table = self._client.open_table(SYSTEM_NAMESPACE,
                                                TRANSACTIONS_TABLE)
            self._status_table = table
            return table

    def begin(self) -> "YBTransaction":
        return YBTransaction(self._client, self)


class YBTransaction:
    """ref client/transaction.h:59"""

    def __init__(self, client: YBClient, manager: TransactionManager):
        self._client = client
        self._manager = manager
        self.txn_id = uuid.uuid4().bytes
        status_table = manager.status_table()
        dk = DocKey(hash_components=(self.txn_id,))
        pk = status_table.partition_key_for(dk)
        self._status_tablet = client.meta_cache.lookup_tablet(
            status_table.table_id, pk)
        self._status_table = status_table
        resp = self._status_call("txn_create")
        self.read_ht: int = resp["read_ht"]
        self._participants: Dict[str, str] = {}  # tablet_id -> addr hint
        self._state = "pending"  # guarded-by: _lock
        self._stmt_seq = 0  # guarded-by: _lock; IntraTxnWriteId statement slots (see write())
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "client.txn._lock")
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"txn-hb-{self.txn_id.hex()[:8]}")
        self._hb_thread.start()

    def _next_stmt_seq(self) -> int:
        with self._lock:
            self._stmt_seq += 1
            return self._stmt_seq

    # ------------------------------------------------------------- plumbing
    def _status_call(self, mth: str, **args):
        return self._client._tablet_call(
            self._status_table, self._status_tablet, mth,
            txn_id=self.txn_id, **args)

    def _heartbeat_loop(self) -> None:
        period = flags.get_flag("txn_client_heartbeat_ms") / 1000.0
        while not self._hb_stop.wait(period):
            try:
                self._status_call("txn_heartbeat")
            except RemoteError as e:
                if e.status.code in (Code.EXPIRED, Code.ABORTED,
                                     Code.ILLEGAL_STATE):
                    return  # txn resolved; ops will surface the state
                # transient (leader move etc.): keep beating
            except StatusError:
                continue  # retry-exhaustion during failover: keep beating

    def _meta(self) -> TransactionMetadata:
        return TransactionMetadata(self.txn_id,
                                   self._status_tablet.tablet_id,
                                   read_ht=self.read_ht)

    def _check_pending(self) -> None:
        with self._lock:
            if self._state != "pending":
                raise TransactionError(f"transaction is {self._state}")

    # -------------------------------------------------------------- data ops
    def write(self, table: YBTable, ops: Sequence[QLWriteOp],
              _depth: int = 0) -> None:
        """Write provisional records. Ops are grouped by destination
        tablet internally (the session batcher's grouping, ref
        client/batcher.cc) — one write RPC per tablet touched; callers
        may pass any mix of keys. A tablet split between lookup and RPC
        re-routes by key like YBClient.write does."""
        self._check_pending()
        # IntraTxnWriteId base: each write CALL gets the next statement
        # slot (65536 kv pairs per statement), so a later statement's
        # intents sort ABOVE an earlier one's at the shared commit hybrid
        # time (ref docdb/intent.h IntraTxnWriteId; the collection-marker
        # shadowing bug this fixes: INSERT marker wid > UPDATE element
        # wid made the element invisible). Stable across retries of this
        # call.
        write_id_base = self._next_stmt_seq() << 16
        groups: dict = {}
        for op in ops:
            pk = table.partition_key_for(op.doc_key)
            tablet = self._client.meta_cache.lookup_tablet(
                table.table_id, pk)
            groups.setdefault(tablet.tablet_id, (tablet, pk, []))[2] \
                .append(op)
        for tablet, pk, group in groups.values():
            # Record the participant BEFORE issuing the write: on a
            # timeout or unknown outcome the intents may exist on the
            # tablet anyway, and commit/abort must notify every tablet
            # that may hold them — otherwise orphaned intents are never
            # applied or cleaned up. A spurious participant (write never
            # landed) costs one no-op notification.
            self._participants.setdefault(tablet.tablet_id,
                                          tablet.leader_addr())
            try:
                self._client._tablet_call(
                    table, tablet, "write", refresh_key=pk,
                    ops=[write_op_to_wire(op) for op in group],
                    txn=self._meta().to_wire(),
                    txn_write_id_base=write_id_base,
                    schema_version=table.schema_version)
            except RemoteError as e:
                if e.extra.get("txn_conflict"):
                    raise TransactionError(e.status.message) from e
                if (e.extra.get("tablet_split")
                        or e.extra.get("wrong_tablet")) and _depth < 8:
                    # stale routing (split landed between lookup and
                    # RPC): refresh and re-group this group's ops by key
                    import time as _time
                    _time.sleep(0.15 * (_depth + 1))
                    self._client.meta_cache.invalidate(table.table_id)
                    self.write(table, group, _depth=_depth + 1)
                    continue
                raise

    def read_row(self, table: YBTable, doc_key: DocKey,
                 projection: Optional[Sequence[str]] = None):
        """Snapshot read at the transaction's read point, seeing its own
        provisional writes."""
        self._check_pending()
        pk = table.partition_key_for(doc_key)
        tablet = self._client.meta_cache.lookup_tablet(table.table_id, pk)
        w = self._client._tablet_call(
            table, tablet, "read_row", refresh_key=pk,
            doc_key=doc_key_to_wire(doc_key), read_ht=self.read_ht,
            projection=list(projection) if projection else None,
            txn_id=self.txn_id, schema_version=table.schema_version)
        return row_from_wire(w)

    # ------------------------------------------------------------ resolution
    def commit(self) -> HybridTime:
        self._check_pending()
        self._hb_stop.set()
        participants = [[tid, addr]
                        for tid, addr in self._participants.items()]
        try:
            resp = self._status_call("txn_commit",
                                     participants=participants)
        except RemoteError as e:
            with self._lock:
                self._state = "aborted"
            if e.status.code in (Code.EXPIRED, Code.ABORTED):
                raise TransactionError(e.status.message) from e
            raise
        with self._lock:
            self._state = "committed"
        return HybridTime(resp["commit_ht"])

    def abort(self) -> None:
        self._hb_stop.set()
        with self._lock:
            if self._state != "pending":
                return
            self._state = "aborted"
        participants = [[tid, addr]
                        for tid, addr in self._participants.items()]
        try:
            self._status_call("txn_abort", participants=participants)
        except StatusError:
            pass  # expiry will clean up

    def __enter__(self) -> "YBTransaction":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.commit()
        else:
            self.abort()
