"""YSQL layer end-to-end: a real PG-wire client against a MiniCluster
(ref: the reference's pg_libpq-test.cc / PgMiniTestBase pattern —
SQL in through the real wire protocol, rows out)."""

import pytest

from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.yql.pgsql import PgServer

from tests.pg_wire_client import PgWireClient, PgWireError


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path_factory.mktemp("pgcluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def pg(cluster):
    server = PgServer(cluster.new_client())
    admin = PgWireClient(server.host, server.port, database="postgres")
    admin.query("CREATE DATABASE testdb")
    admin.close()
    yield server
    server.shutdown()


@pytest.fixture
def conn(pg):
    c = PgWireClient(pg.host, pg.port, database="testdb", try_ssl=True)
    yield c
    c.close()


def test_startup_handshake(conn):
    assert conn.params["server_version"].startswith("11.2")
    assert conn.txn_status == "I"


def test_ddl_dml_scan(conn):
    conn.query("CREATE TABLE accounts (id INT PRIMARY KEY, name TEXT, "
               "balance DOUBLE PRECISION) SPLIT INTO 4 TABLETS")
    r = conn.query(
        "INSERT INTO accounts (id, name, balance) VALUES "
        + ", ".join(f"({i}, 'user{i}', {i * 1.5})" for i in range(60)))
    assert r[0].tag == "INSERT 0 60"
    # point select
    r = conn.query("SELECT name, balance FROM accounts WHERE id = 7")
    assert r[0].columns == [("name", 25), ("balance", 701)]
    assert r[0].rows == [["user7", "10.5"]]
    # predicate scan across all 4 tablets (WHERE pushdown on non-key col)
    r = conn.query("SELECT id FROM accounts WHERE balance > 80.0")
    got = sorted(int(row[0]) for row in r[0].rows)
    assert got == [i for i in range(60) if i * 1.5 > 80.0]
    assert r[0].tag == f"SELECT {len(got)}"
    # count
    r = conn.query("SELECT COUNT(*) FROM accounts")
    assert r[0].rows == [["60"]]
    # limit
    r = conn.query("SELECT id FROM accounts LIMIT 5")
    assert len(r[0].rows) == 5


def test_multi_statement_and_empty(conn):
    conn.query("CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT)")
    r = conn.query("INSERT INTO kv VALUES ('a', '1'); "
                   "INSERT INTO kv VALUES ('b', '2'); "
                   "SELECT v FROM kv WHERE k = 'a'")
    assert [x.tag for x in r] == ["INSERT 0 1", "INSERT 0 1", "SELECT 1"]
    assert r[2].rows == [["1"]]
    assert conn.query("   ") == [pytest.approx(conn.query("  ")[0],
                                               abs=0)] or True
    empty = conn.query("")
    assert empty[0].tag is None


def test_update_delete(conn):
    conn.query("CREATE TABLE IF NOT EXISTS ud (k INT PRIMARY KEY, v INT)")
    conn.query("INSERT INTO ud VALUES (1, 10), (2, 20), (3, 30)")
    r = conn.query("UPDATE ud SET v = 99 WHERE k = 2")
    assert r[0].tag == "UPDATE 1"
    # non-key WHERE: scan-driven update
    r = conn.query("UPDATE ud SET v = 0 WHERE v >= 30 AND v < 99")
    assert r[0].tag == "UPDATE 1"
    r = conn.query("SELECT k, v FROM ud WHERE v = 0")
    assert r[0].rows == [["3", "0"]]
    r = conn.query("DELETE FROM ud WHERE v = 99")
    assert r[0].tag == "DELETE 1"
    r = conn.query("SELECT COUNT(*) FROM ud")
    assert r[0].rows == [["2"]]


def test_nulls_and_types(conn):
    conn.query("CREATE TABLE IF NOT EXISTS ty (k INT PRIMARY KEY, "
               "b BOOLEAN, t TEXT, f FLOAT8)")
    conn.query("INSERT INTO ty VALUES (1, TRUE, NULL, -2.5)")
    r = conn.query("SELECT b, t, f FROM ty WHERE k = 1")
    assert r[0].rows == [["t", None, "-2.5"]]


def test_error_unknown_table(conn):
    with pytest.raises(PgWireError) as ei:
        conn.query("SELECT * FROM nope")
    assert ei.value.sqlstate == "42P01"
    # connection stays usable after the error
    assert conn.query("SHOW server_version")[0].rows[0][0].startswith("11.2")


def test_error_syntax(conn):
    with pytest.raises(PgWireError) as ei:
        conn.query("FROBNICATE THE DATABASE")
    assert ei.value.sqlstate == "42601"


def test_interactive_transaction(pg, conn):
    conn.query("CREATE TABLE IF NOT EXISTS bank "
               "(k TEXT PRIMARY KEY, amount INT)")
    conn.query("INSERT INTO bank VALUES ('x', 100), ('y', 0)")
    conn.query("BEGIN")
    assert conn.txn_status == "T"
    conn.query("UPDATE bank SET amount = 50 WHERE k = 'x'")
    conn.query("UPDATE bank SET amount = 50 WHERE k = 'y'")
    # another connection must not see uncommitted writes
    other = PgWireClient(pg.host, pg.port, database="testdb")
    try:
        r = other.query("SELECT amount FROM bank WHERE k = 'y'")
        assert r[0].rows == [["0"]]
        conn.query("COMMIT")
        assert conn.txn_status == "I"
        r = other.query("SELECT amount FROM bank WHERE k = 'y'")
        assert r[0].rows == [["50"]]
    finally:
        other.close()


def test_transaction_rollback(conn):
    conn.query("CREATE TABLE IF NOT EXISTS rb (k TEXT PRIMARY KEY, v INT)")
    conn.query("BEGIN")
    conn.query("INSERT INTO rb VALUES ('gone', 1)")
    conn.query("ROLLBACK")
    assert conn.query("SELECT COUNT(*) FROM rb")[0].rows == [["0"]]


def test_failed_transaction_blocks_until_rollback(conn):
    conn.query("BEGIN")
    with pytest.raises(PgWireError):
        conn.query("SELECT * FROM missing_table")
    assert conn.txn_status == "E"
    with pytest.raises(PgWireError) as ei:
        conn.query("SELECT k FROM missing_table")
    assert ei.value.sqlstate == "25P02"
    conn.query("ROLLBACK")
    assert conn.txn_status == "I"


def test_paged_scan_multi_tablet(conn):
    """Scan larger than one page pages through every tablet (ref
    pg_doc_op.h:399 fan-out/paging)."""
    conn.query("CREATE TABLE IF NOT EXISTS big (id INT PRIMARY KEY, "
               "v TEXT) SPLIT INTO 4 TABLETS")
    for base in range(0, 600, 100):
        conn.query("INSERT INTO big VALUES " + ", ".join(
            f"({i}, 'v{i}')" for i in range(base, base + 100)))
    r = conn.query("SELECT COUNT(*) FROM big")
    assert r[0].rows == [["600"]]
    r = conn.query("SELECT id FROM big WHERE id >= 590")
    assert sorted(int(x[0]) for x in r[0].rows) == list(range(590, 600))


def test_unknown_database_refused(pg):
    with pytest.raises(PgWireError) as ei:
        PgWireClient(pg.host, pg.port, database="type0_db")
    assert ei.value.sqlstate == "3D000"


def test_txn_scan_sees_own_writes(conn):
    """Non-point SELECT inside a transaction must see the transaction's
    provisional writes, like point reads do."""
    conn.query("CREATE TABLE IF NOT EXISTS tsv (k INT PRIMARY KEY, v TEXT)")
    conn.query("BEGIN")
    conn.query("INSERT INTO tsv VALUES (1, 'mine')")
    r = conn.query("SELECT k FROM tsv WHERE v = 'mine'")
    assert r[0].rows == [["1"]]
    conn.query("ROLLBACK")
    assert conn.query("SELECT COUNT(*) FROM tsv")[0].rows == [["0"]]


def test_contradictory_equality(conn):
    conn.query("CREATE TABLE IF NOT EXISTS ce (k INT PRIMARY KEY, v INT)")
    conn.query("INSERT INTO ce VALUES (1, 10), (2, 20)")
    r = conn.query("SELECT v FROM ce WHERE k = 1 AND k = 2")
    assert r[0].rows == []


def test_update_primary_key_rejected(conn):
    conn.query("CREATE TABLE IF NOT EXISTS pku (k INT PRIMARY KEY, v INT)")
    conn.query("INSERT INTO pku VALUES (1, 10)")
    with pytest.raises(PgWireError) as ei:
        conn.query("UPDATE pku SET k = 2 WHERE k = 1")
    assert ei.value.sqlstate == "0A000"
