"""Disk-fault injection + background-error containment tests (ref:
rocksdb/db/fault_injection_test.cc FaultInjectionTest; tablet FAILED
state containment in the reference's tablet_peer.cc / ts_tablet_manager).

Covers the whole containment chain: FaultInjectionEnv semantics, DB
background-error parking (degraded read-only, clean abort, retry), WAL
append failures failing the replicate, the tablet FAILED state with
retryable write rejection + maintenance-manager backoff recovery, and
dropped-fsync crash recovery yielding exactly the synced prefix.
"""

import os
import time

import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.consensus.log import Log, LogEntry, LogReader
from yugabyte_tpu.consensus.transport import LocalTransport
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.storage.db import DB, DBOptions
from yugabyte_tpu.utils import env as env_mod
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.env import FaultError, FaultInjectionEnv
from yugabyte_tpu.utils.status import Code, StatusError


@pytest.fixture()
def fenv():
    fi = env_mod.enable_fault_injection(env_mod.Env())
    yield fi
    env_mod.set_env(env_mod.Env())


def _key(i):
    return SubDocKey(DocKey(range_components=(f"r{i:04d}",)),
                     (("col", 0),)).encode(include_ht=False)


def _items(lo, hi):
    return [(_key(i), DocHybridTime(HybridTime((i + 1) << 12), 0),
             Value(primitive=f"v{i}").encode()) for i in range(lo, hi)]


def wait_for(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timeout: {msg}"
        time.sleep(0.02)


# ------------------------------------------------------------- env semantics
class TestFaultInjectionEnv:
    def test_pread_and_read_file_faults(self, fenv, tmp_path):
        p = str(tmp_path / "f")
        fenv.write_file(p, b"payload-bytes")
        fenv.set_fault("read", count=1)
        with pytest.raises(FaultError):
            fenv.read_file(p)
        assert fenv.read_file(p) == b"payload-bytes"  # count exhausted
        fenv.set_fault("read", path_filter="other")
        r = fenv.open_random(p)
        assert r.pread(7, 0) == b"payload"  # filter does not match
        fenv.set_fault("read", path_filter="f")
        with pytest.raises(FaultError):
            r.pread(7, 0)
        r.close()

    def test_enospc_and_short_append(self, fenv, tmp_path):
        p = str(tmp_path / "a")
        f = fenv.open_append(p)
        f.append(b"good")
        fenv.set_fault("enospc", count=1)
        with pytest.raises(OSError) as ei:
            f.append(b"never")
        import errno
        assert ei.value.errno == errno.ENOSPC
        fenv.set_fault("append_short", count=1)
        with pytest.raises(FaultError):
            f.append(b"12345678")  # half lands: a torn write
        f.flush()
        f.close()
        assert fenv.read_file(p) == b"good1234"

    def test_dropped_fsync_crash_loses_exactly_unsynced_tail(
            self, fenv, tmp_path):
        p = str(tmp_path / "wal-000001")
        f = fenv.open_append(p)
        f.append(b"SYNCED")
        f.flush(fsync=True)
        fenv.set_drop_fsyncs(True)
        f.append(b"-UNSYNCED")
        f.flush(fsync=True)  # lying disk: claims success
        f.close()
        assert fenv.read_file(p) == b"SYNCED-UNSYNCED"  # visible pre-crash
        fenv.simulate_crash()
        assert open(p, "rb").read() == b"SYNCED"  # exactly the synced prefix

    def test_crash_removes_never_synced_files(self, fenv, tmp_path):
        fenv.set_drop_fsyncs(True)
        p1 = str(tmp_path / "new-append")
        f = fenv.open_append(p1)
        f.append(b"x" * 100)
        f.flush(fsync=True)
        f.close()
        p2 = str(tmp_path / "whole")
        fenv.write_file(p2, b"whole-file")
        fenv.simulate_crash()
        assert not os.path.exists(p1)
        assert not os.path.exists(p2)

    def test_whole_file_overwrite_reverts_to_synced_content(
            self, fenv, tmp_path):
        p = str(tmp_path / "base.sst")
        fenv.write_file(p, b"generation-1")
        fenv.set_drop_fsyncs(True)
        fenv.write_file(p, b"generation-2-unsynced")
        fenv.simulate_crash()
        assert open(p, "rb").read() == b"generation-1"

    def test_stacks_over_encrypted_env(self, tmp_path):
        pytest.importorskip("cryptography")
        import secrets
        keys = env_mod.UniverseKeys()
        keys.add("uk", secrets.token_bytes(32))
        fi = FaultInjectionEnv(env_mod.EncryptedEnv(keys))
        assert fi.encrypted
        p = str(tmp_path / "enc")
        fi.write_file(p, b"secret-data")
        assert open(p, "rb").read()[:8] == b"YBENCv1\x00"
        assert fi.read_file(p) == b"secret-data"
        fi.set_fault("read")
        with pytest.raises(FaultError):
            fi.read_file(p)

    def test_no_faults_passthrough_sst_byte_identical(self, fenv, tmp_path):
        """The CPU SST path through an (un-armed) FaultInjectionEnv must
        produce byte-identical files to the plain Env — the wrapper adds
        failure modes, never byte drift."""
        dirs = {}
        for name in ("via_fault", "via_plain"):
            if name == "via_plain":
                env_mod.set_env(env_mod.Env())
            db = DB(str(tmp_path / name), DBOptions(auto_compact=False))
            db.write_batch(_items(0, 50))
            db.flush()
            db.close()
            dirs[name] = tmp_path / name
        a, b = (sorted(p.name for p in dirs[n].iterdir()
                       if ".sst" in p.name) for n in dirs)
        assert a == b and a
        for fn in a:
            assert (dirs["via_fault"] / fn).read_bytes() == \
                (dirs["via_plain"] / fn).read_bytes(), fn


# --------------------------------------------------- DB background error slot
class TestDBBackgroundError:
    def test_flush_error_parks_db_readonly_then_recovers(
            self, fenv, tmp_path):
        db = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
        db.write_batch(_items(0, 30))
        db.flush()
        assert db.n_live_files == 1
        db.write_batch(_items(30, 60))
        fenv.set_fault("enospc", path_filter=".sst")
        assert db.flush() is None  # contained, not raised
        assert db.background_error is not None
        # version set untouched; no partial SST files on disk
        assert db.n_live_files == 1
        leftovers = [n for n in os.listdir(str(tmp_path / "db"))
                     if ".sst" in n]
        assert len(leftovers) == 2  # base + data of the installed SST only
        # degraded READ-ONLY: reads serve (memtable restored), writes
        # reject retryably
        assert db.get(_key(45)) is not None
        assert db.get(_key(10)) is not None
        with pytest.raises(StatusError) as ei:
            db.write_batch(_items(60, 61))
        assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
        # flush attempts while parked are no-ops
        assert db.flush() is None
        # fault persists -> retry fails and re-parks
        assert not db.retry_background_work()
        assert db.background_error is not None
        # fault clears -> retry recovers, parked rows flush
        fenv.clear_faults()
        assert db.retry_background_work()
        assert db.background_error is None
        assert db.n_live_files == 2
        db.write_batch(_items(60, 70))
        assert db.get(_key(65)) is not None
        db.close()
        # restart: everything readable (manifest consistent throughout)
        db2 = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
        for i in (0, 29, 30, 59):
            assert db2.get(_key(i)) is not None, i
        db2.close()

    def test_compaction_error_keeps_inputs_live_then_recovers(
            self, fenv, tmp_path):
        db = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
        for lo in range(0, 120, 30):
            db.write_batch(_items(lo, lo + 30))
            db.flush()
        assert db.n_live_files == 4
        fenv.set_fault("enospc", path_filter=".sst")
        db.compact_all()  # contained
        assert db.background_error is not None
        assert db.n_live_files == 4  # inputs still the live version
        for i in (0, 45, 119):
            assert db.get(_key(i)) is not None, i
        fenv.clear_faults()
        assert db.retry_background_work()
        db.compact_all()
        assert db.n_live_files == 1
        for i in (0, 45, 119):
            assert db.get(_key(i)) is not None, i
        db.close()

    def test_dropped_fsync_crash_rolls_manifest_back_with_sst(
            self, fenv, tmp_path):
        """Acceptance (a), storage half: with fsyncs dropped, a crash after
        a 'successful' flush must not leave a manifest that references
        vanished SST bytes — recovery sees the pre-flush version set (the
        synced prefix) and no phantom records."""
        d = str(tmp_path / "db")
        db = DB(d, DBOptions(auto_compact=False))
        db.write_batch(_items(0, 20))
        db.flush()  # durable generation
        fenv.set_drop_fsyncs(True)
        db.write_batch(_items(20, 40))
        db.flush()  # claims success; nothing actually durable
        assert db.n_live_files == 2
        db.close()
        fenv.simulate_crash()
        db2 = DB(d, DBOptions(auto_compact=False))
        assert db2.n_live_files == 1  # exactly the synced flush
        for i in range(0, 20):
            assert db2.get(_key(i)) is not None, i
        for i in range(20, 40):
            assert db2.get(_key(i)) is None, i  # no phantom rows
        db2.close()


# ------------------------------------------------------- WAL append failures
class TestWalAppendFailure:
    def test_append_sync_raises_and_log_seals(self, fenv, tmp_path):
        log = Log(str(tmp_path / "wal"))
        log.append_sync([LogEntry(1, 1, b"ok")])
        fenv.set_fault("append", path_filter="wal-")
        with pytest.raises(OSError):
            log.append_sync([LogEntry(1, 2, b"fails")])
        assert log.io_error is not None
        # sealed: even after the fault clears, appends keep failing (the
        # segment may hold a torn record; recovery is a re-open)
        fenv.clear_faults()
        with pytest.raises(OSError):
            log.append_sync([LogEntry(1, 3, b"still fails")])
        log.close()
        # replay yields exactly the pre-failure prefix
        entries = list(LogReader(str(tmp_path / "wal")).read_all())
        assert [e.index for e in entries] == [1]

    def test_torn_append_recovers_to_record_boundary(self, fenv, tmp_path):
        log = Log(str(tmp_path / "wal"))
        log.append_sync([LogEntry(1, i, f"p{i}".encode() * 50)
                         for i in range(1, 6)])
        fenv.set_fault("append_short", path_filter="wal-", count=1)
        with pytest.raises(OSError):
            log.append_sync([LogEntry(1, 6, b"torn" * 100)])
        log.close()
        fenv.clear_faults()
        # the torn half-record is dropped by the crc rule at replay
        entries = list(LogReader(str(tmp_path / "wal")).read_all())
        assert [e.index for e in entries] == [1, 2, 3, 4, 5]
        # and a fresh Log over the same dir rewrites the tail cleanly
        log2 = Log(str(tmp_path / "wal"))
        log2.append_sync([LogEntry(1, 6, b"retried")])
        log2.close()
        entries = list(LogReader(str(tmp_path / "wal")).read_all())
        assert [e.index for e in entries] == [1, 2, 3, 4, 5, 6]


# --------------------------------------------------- tablet FAILED state e2e
SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.INT64)],
    num_hash_key_columns=0, num_range_key_columns=1)


def _op(k, v):
    return QLWriteOp(WriteOpKind.INSERT, DocKey(range_components=(k,)),
                     {"v": v})


def _elect(peer, timeout=30.0):
    deadline = time.monotonic() + timeout
    window = 2.0
    while time.monotonic() < deadline:
        peer.raft.start_election(ignore_lease=True)
        attempt_end = min(time.monotonic() + window, deadline)
        while time.monotonic() < attempt_end:
            if peer.raft.is_leader():
                return
            time.sleep(0.005)
        window *= 2
    raise TimeoutError("no leader")


@pytest.fixture()
def manager(fenv, tmp_path):
    from yugabyte_tpu.common.wire import schema_to_wire
    from yugabyte_tpu.tserver.ts_tablet_manager import TSTabletManager
    flags.set_flag("raft_heartbeat_interval_ms", 15)
    mgr = TSTabletManager("ts0", str(tmp_path / "ts0"), LocalTransport())
    mgr.create_tablet("t1", "tbl1", schema_to_wire(SCHEMA), ["ts0"])
    peer = mgr.get_tablet("t1")
    _elect(peer)
    wait_for(lambda: peer.raft.leader_ready(), msg="leader ready")
    yield mgr
    flags.reset_flag("raft_heartbeat_interval_ms")
    mgr.shutdown()


class TestTabletFailedState:
    def test_flush_fault_fails_tablet_writes_reject_reads_drain(
            self, fenv, manager):
        """Acceptance (b): injected flush error -> DB degraded read-only ->
        tablet FAILED -> retryable write rejection while reads drain ->
        heartbeat report carries the state -> backoff retry recovers ->
        writes succeed again."""
        from yugabyte_tpu.tablet.tablet_peer import (STATE_FAILED,
                                                     STATE_RUNNING)
        from yugabyte_tpu.tserver.maintenance_manager import (
            MaintenanceManager)
        peer = manager.get_tablet("t1")
        for i in range(20):
            peer.write([_op(f"k{i:03d}", i)])
        fenv.set_fault("enospc", path_filter=".sst")
        peer.tablet.flush()  # contained: parks the regular DB
        assert peer.tablet.regular_db.background_error is not None
        assert peer.state == STATE_FAILED
        # report carries the state for the master's load balancer
        report = {t["tablet_id"]: t for t in manager.generate_report()}
        assert report["t1"]["state"] == STATE_FAILED
        # writes reject retryably, tagged for the client's replica walk
        with pytest.raises(StatusError) as ei:
            peer.write([_op("rejected", 1)])
        assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
        assert ei.value.extra.get("tablet_failed")
        # reads drain
        row = peer.read_row(DocKey(range_components=("k003",)))
        assert row is not None and row.to_dict(SCHEMA)["v"] == 3
        # maintenance-manager recovery with capped backoff
        flags.set_flag("background_error_retry_initial_s", 0.02)
        try:
            mm = MaintenanceManager(
                peers_fn=manager.peers,
                recover_fn=lambda p: manager.recover_failed_tablet(
                    p.tablet_id))
            assert mm.run_once() == "recover:t1"  # fault still armed
            assert peer.state == STATE_FAILED
            sched = mm._recover_backoff["t1"]
            assert sched.failures == 1
            fenv.clear_faults()
            wait_for(sched.ready, msg="backoff window")
            assert mm.run_once() == "recover:t1"
            assert peer.state == STATE_RUNNING
            assert peer.tablet.regular_db.background_error is None
        finally:
            flags.reset_flag("background_error_retry_initial_s")
        # parked rows flushed; writes flow again; nothing lost
        peer.write([_op("after", 99)])
        for k, v in [("k000", 0), ("k019", 19), ("after", 99)]:
            row = peer.read_row(DocKey(range_components=(k,)))
            assert row is not None and row.to_dict(SCHEMA)["v"] == v, k

    def test_wal_failure_fails_replicate_and_rebootstrap_recovers(
            self, fenv, manager):
        """A WAL append fault fails the in-flight replicate (fate-unknown,
        not a silent torn write), seals the log, FAILs the tablet, and
        recover_failed_tablet re-bootstraps it back to RUNNING with every
        acked row intact."""
        from yugabyte_tpu.tablet.tablet_peer import (STATE_FAILED,
                                                     STATE_RUNNING)
        from yugabyte_tpu.consensus.raft import OperationOutcomeUnknown
        peer = manager.get_tablet("t1")
        for i in range(10):
            peer.write([_op(f"w{i:02d}", i)])
        fenv.set_fault("append", path_filter="wal-")
        # fate-unknown, raised FAST (well under the timeout): the entry is
        # in leader memory and a follower majority could still commit it
        t0 = time.monotonic()
        with pytest.raises(OperationOutcomeUnknown):
            peer.write([_op("doomed", -1)], timeout_s=30.0)
        assert time.monotonic() - t0 < 10.0
        wait_for(lambda: peer.state == STATE_FAILED, msg="peer FAILED")
        assert peer.log.io_error is not None
        # in-place recovery cannot fix a sealed WAL...
        assert not peer.try_recover()
        fenv.clear_faults()
        assert not peer.try_recover()
        # ...but a re-bootstrap can
        assert manager.recover_failed_tablet("t1")
        peer2 = manager.get_tablet("t1")
        assert peer2 is not peer and peer2.state == STATE_RUNNING
        _elect(peer2)
        wait_for(lambda: peer2.raft.leader_ready(), msg="leader ready")
        for i in range(10):
            row = peer2.read_row(DocKey(range_components=(f"w{i:02d}",)))
            assert row is not None and row.to_dict(SCHEMA)["v"] == i, i
        peer2.write([_op("fresh", 7)])
        assert peer2.read_row(
            DocKey(range_components=("fresh",))).to_dict(SCHEMA)["v"] == 7

    def test_dropped_wal_fsyncs_crash_recovers_synced_prefix(
            self, fenv, tmp_path):
        """Acceptance (a), WAL half: acked writes whose fsyncs were
        silently dropped vanish at the crash; recovery replays exactly the
        synced prefix — no torn or phantom records."""
        from yugabyte_tpu.common.wire import schema_to_wire
        from yugabyte_tpu.tserver.ts_tablet_manager import TSTabletManager
        flags.set_flag("raft_heartbeat_interval_ms", 15)
        try:
            mgr = TSTabletManager("tsA", str(tmp_path / "tsA"),
                                  LocalTransport())
            mgr.create_tablet("tw", "tblw", schema_to_wire(SCHEMA), ["tsA"])
            peer = mgr.get_tablet("tw")
            _elect(peer)
            wait_for(lambda: peer.raft.leader_ready(), msg="leader ready")
            for i in range(10):
                peer.write([_op(f"s{i:02d}", i)])  # durable era
            fenv.set_drop_fsyncs(True, path_filter="wal-")
            for i in range(10, 20):
                peer.write([_op(f"s{i:02d}", i)])  # acked by a lying disk
            mgr.shutdown()
            fenv.simulate_crash()
            # every surviving WAL record parses cleanly (no torn tail
            # surprises beyond the crc rule)
            mgr2 = TSTabletManager("tsA", str(tmp_path / "tsA"),
                                   LocalTransport())
            assert mgr2.open_existing() == 1
            peer2 = mgr2.get_tablet("tw")
            _elect(peer2)
            wait_for(lambda: peer2.raft.leader_ready(), msg="leader ready")
            for i in range(10):
                row = peer2.read_row(
                    DocKey(range_components=(f"s{i:02d}",)))
                assert row is not None, i  # synced prefix intact
            for i in range(10, 20):
                row = peer2.read_row(
                    DocKey(range_components=(f"s{i:02d}",)))
                assert row is None, i  # unsynced suffix is gone, not torn
            mgr2.shutdown()
        finally:
            flags.reset_flag("raft_heartbeat_interval_ms")


# ----------------------------------------------- master-side FAILED handling
class TestMasterSideFailedReplicas:
    def test_ts_manager_tracks_failed_and_lb_flags_them(self):
        from yugabyte_tpu.master.catalog_manager import TSManager
        from yugabyte_tpu.master.load_balancer import ClusterLoadBalancer
        tsm = TSManager()
        tsm.heartbeat("ts0", "h:1", [
            {"tablet_id": "ta", "state": "RUNNING"},
            {"tablet_id": "tb", "state": "FAILED"}])
        assert tsm.get("ts0").failed_tablets == {"tb"}

        class _Cat:
            ts_manager = tsm
        lb = ClusterLoadBalancer(_Cat(), messenger=None)
        assert lb._reported_failed("ts0", "tb")
        assert not lb._reported_failed("ts0", "ta")
        assert not lb._reported_failed("ts-unknown", "tb")
        # a later healthy report clears the flag
        tsm.heartbeat("ts0", "h:1", [
            {"tablet_id": "ta", "state": "RUNNING"},
            {"tablet_id": "tb", "state": "RUNNING"}])
        assert not lb._reported_failed("ts0", "tb")
