"""Automatic tablet splitting through the full stack (ref:
integration-tests/tablet-split-itest.cc; master tablet_split_manager.cc;
tablet/operations/split_operation.cc)."""

import time

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


def wait_for(cond, timeout=40, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timeout: {msg}"
        time.sleep(0.05)


@pytest.fixture
def cluster(tmp_path):
    flags.set_flag("replication_factor", 3)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path / "cluster"))).start()
    yield c
    c.shutdown()


N_ROWS = 80


def test_split_end_to_end(cluster):
    client = cluster.new_client()
    client.create_namespace("db")
    table = client.create_table("db", "t", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    cluster.wait_for_table_leaders("db", "t")  # don't race the election
    for i in range(N_ROWS):
        client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk(f"k{i:03d}"),
                                       {"v": f"v{i}"})])
    parent = client.meta_cache.tablets(table.table_id)[0]
    master = cluster.leader_master()
    children = master.catalog.split_tablet(parent.tablet_id)
    assert len(children) == 2

    # Master adopts the children and retires the parent.
    def split_settled():
        locs = master.catalog.get_table_locations(table.table_id)
        ids = [l["tablet_id"] for l in locs]
        return (sorted(ids) == sorted(children)
                and all(l["leader"] for l in locs)
                and not master.catalog.has_tablet(parent.tablet_id))

    wait_for(split_settled, msg="children adopted + parent retired")

    # Children partitions tile the parent's range.
    locs = master.catalog.get_table_locations(table.table_id)
    assert locs[0]["partition"]["start"] == b""
    assert locs[0]["partition"]["end"] == locs[1]["partition"]["start"]
    assert locs[1]["partition"]["end"] == b""

    # Every row readable after the split (routing through children).
    client.meta_cache.invalidate(table.table_id)
    for i in range(N_ROWS):
        row = client.read_row(table, dk(f"k{i:03d}"))
        assert row is not None, f"k{i:03d} lost by split"
        assert row.columns[SCHEMA.column_id("v")] == f"v{i}"

    # Scans see each row exactly once (bounds clamp the shared files).
    rows = list(client.scan(table, page_size=16))
    keys = sorted(r.doc_key.hash_components[0] for r in rows)
    assert keys == sorted(f"k{i:03d}" for i in range(N_ROWS))

    # Writes keep working, now routed to the children.
    for i in range(N_ROWS, N_ROWS + 10):
        client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk(f"k{i:03d}"),
                                       {"v": f"v{i}"})])
    rows = list(client.scan(table, page_size=64))
    assert len(rows) == N_ROWS + 10

    # Parent replicas are torn down on the tservers.
    def parent_gone():
        return all(parent.tablet_id not in ts.tablet_manager.tablet_ids()
                   for ts in cluster.tservers)
    wait_for(parent_gone, msg="parent replicas deleted")


def test_write_during_split_is_rerouted(cluster):
    client = cluster.new_client()
    client.create_namespace("db2")
    table = client.create_table("db2", "t", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    cluster.wait_for_table_leaders("db2", "t")  # don't race the election
    session_keys = [f"a{i:03d}" for i in range(40)]
    for k in session_keys:
        client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk(k),
                                       {"v": "pre"})])
    parent = client.meta_cache.tablets(table.table_id)[0]
    cluster.leader_master().catalog.split_tablet(parent.tablet_id)
    # Immediately write through the STALE meta cache: the client must chase
    # the split (regroup by child) without surfacing an error.
    for k in session_keys:
        client.write(table, [QLWriteOp(WriteOpKind.UPDATE, dk(k),
                                       {"v": "post"})])
    for k in session_keys:
        row = client.read_row(table, dk(k))
        assert row is not None and \
            row.columns[SCHEMA.column_id("v")] == "post"
