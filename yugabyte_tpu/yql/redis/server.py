"""Redis-compatible server over the doc store.

Capability parity with the reference (ref: src/yb/yql/redis/redisserver/ —
redis_service.cc command dispatch, redis_commands.cc command table,
redis_rpc.cc RESP framing; data modeled in DocDB via redis_operation.cc).
Data model here:

- strings: table `redis.strings` — key BINARY (hash pk) -> value BINARY
- hashes:  table `redis.hashes`  — (key BINARY hash pk, field BINARY range)
           -> value BINARY; one redis hash = one document family sharing a
           hash bucket, so HGETALL is a single-tablet prefix scan (the same
           layout trick as the reference's subdocument encoding).

Counters (INCR/DECR) run as snapshot-isolated transactions with conflict
retry, giving the reference's per-key atomicity. TTLs ride the doc store's
native value TTLs (SET ... EX / SETEX / EXPIRE-as-rewrite).
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.client.transaction import (
    TransactionError, TransactionManager)
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.rpc.messenger import RemoteError
from yugabyte_tpu.utils.status import Code, StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.yql.redis import resp
from yugabyte_tpu.utils import ybsan

REDIS_KEYSPACE = "redis"

STR_SCHEMA = Schema(
    columns=[ColumnSchema("key", DataType.BINARY),
             ColumnSchema("value", DataType.BINARY)],
    num_hash_key_columns=1)

HASH_SCHEMA = Schema(
    columns=[ColumnSchema("key", DataType.BINARY),
             ColumnSchema("field", DataType.BINARY),
             ColumnSchema("value", DataType.BINARY)],
    num_hash_key_columns=1, num_range_key_columns=1)


@ybsan.shadow(_shutdown=ybsan.SINGLE_WRITER,
              _conns=ybsan.SINGLE_WRITER)
class RedisServer:
    def __init__(self, client: YBClient, bind_host: str = "127.0.0.1",
                 port: int = 0, num_tablets: int = 4):
        self._client = client
        self._txns = TransactionManager(client)
        self._ensure_tables(num_tablets)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((bind_host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()
        self._shutdown = False
        self._conns: List[socket.socket] = []
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="redis-accept").start()

    def _ensure_tables(self, num_tablets: int) -> None:
        try:
            self._client.create_namespace(REDIS_KEYSPACE)
        except (StatusError, RemoteError) as e:
            if getattr(e, "status", None) and \
                    e.status.code != Code.ALREADY_PRESENT:
                raise
        for name, schema in (("strings", STR_SCHEMA),
                             ("hashes", HASH_SCHEMA)):
            try:
                self._client.create_table(REDIS_KEYSPACE, name, schema,
                                          num_tablets=num_tablets)
            except (StatusError, RemoteError) as e:
                if getattr(e, "status", None) and \
                        e.status.code != Code.ALREADY_PRESENT:
                    raise
        self._strings = self._client.open_table(REDIS_KEYSPACE, "strings")
        self._hashes = self._client.open_table(REDIS_KEYSPACE, "hashes")
        self._val_str = STR_SCHEMA.column_id("value")
        self._val_hash = HASH_SCHEMA.column_id("value")

    # --------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn, _peer = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,), daemon=True,
                             name="redis-conn").start()

    def _serve(self, conn: socket.socket) -> None:
        reader = resp.Reader(conn)
        try:
            while True:
                cmd = reader.read_command()
                if cmd is None:
                    return
                if not cmd:
                    continue
                name = cmd[0].decode("utf-8", "replace").upper()
                handler = getattr(self, f"cmd_{name.lower()}", None)
                try:
                    if handler is None:
                        out = resp.error(f"unknown command '{name}'")
                    else:
                        out = handler(cmd[1:])
                    if name == "QUIT":
                        conn.sendall(out)
                        return
                except (StatusError, RemoteError) as e:
                    out = resp.error(str(e))
                except IndexError:
                    out = resp.error(
                        f"wrong number of arguments for '{name.lower()}'")
                except (ValueError, TypeError) as e:
                    out = resp.error(str(e))
                conn.sendall(out)
        except (ConnectionError, resp.ProtocolError, OSError):
            pass
        finally:
            reader.close()
            conn.close()

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._listener.close()
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass

    # -------------------------------------------------------------- helpers
    @staticmethod
    def _str_key(key: bytes) -> DocKey:
        return DocKey(hash_components=(key,))

    @staticmethod
    def _hash_key(key: bytes, field: bytes) -> DocKey:
        return DocKey(hash_components=(key,), range_components=(field,))

    def _get(self, key: bytes) -> Optional[bytes]:
        row = self._client.read_row(self._strings, self._str_key(key))
        return None if row is None else row.columns.get(self._val_str)

    def _set(self, key: bytes, value: bytes,
             ttl_ms: Optional[int] = None) -> None:
        self._client.write(self._strings, [QLWriteOp(
            WriteOpKind.INSERT, self._str_key(key), {"value": value},
            ttl_ms=ttl_ms)])

    def _hash_fields(self, key: bytes):
        """All (field, value) of one redis hash: single-tablet prefix scan
        over the shared hash bucket."""
        dk = DocKey(hash_components=(key,))
        encoded = dk.encode()
        prefix = encoded[:-1]  # open the range group: all fields follow
        pk = self._hashes.partition_key_for(dk)
        for row in self._client.scan_key_range(
                self._hashes, pk, prefix, prefix + b"\xff"):
            if row.doc_key.hash_components != (key,):
                continue
            yield (row.doc_key.range_components[0],
                   row.columns.get(self._val_hash))

    # ------------------------------------------------------------- commands
    def cmd_ping(self, args):
        return resp.bulk(args[0]) if args else resp.simple("PONG")

    def cmd_echo(self, args):
        return resp.bulk(args[0])

    def cmd_quit(self, args):
        return resp.simple("OK")

    def cmd_select(self, args):
        return resp.simple("OK")

    def cmd_command(self, args):
        return resp.array([])

    def cmd_config(self, args):
        return resp.array([])

    def cmd_set(self, args):
        if len(args) < 2:
            return resp.error("wrong number of arguments for 'set'")
        key, value = args[0], args[1]
        ttl_ms = None
        i = 2
        while i < len(args):
            opt = args[i].upper()
            if opt == b"EX":
                ttl_ms = int(args[i + 1]) * 1000
                i += 2
            elif opt == b"PX":
                ttl_ms = int(args[i + 1])
                i += 2
            else:
                return resp.error(f"unsupported SET option {opt!r}")
        self._set(key, value, ttl_ms)
        return resp.simple("OK")

    def cmd_setex(self, args):
        self._set(args[0], args[2], int(args[1]) * 1000)
        return resp.simple("OK")

    def cmd_get(self, args):
        return resp.bulk(self._get(args[0]))

    def cmd_mset(self, args):
        if len(args) % 2:
            return resp.error("wrong number of arguments for 'mset'")
        for i in range(0, len(args), 2):
            self._set(args[i], args[i + 1])
        return resp.simple("OK")

    def cmd_mget(self, args):
        return resp.array([resp.bulk(self._get(k)) for k in args])

    def _key_exists(self, key: bytes) -> bool:
        if self._get(key) is not None:
            return True
        return next(iter(self._hash_fields(key)), None) is not None

    def cmd_exists(self, args):
        return resp.integer(sum(1 for k in args if self._key_exists(k)))

    def cmd_del(self, args):
        n = 0
        for key in args:
            if self._get(key) is not None:
                self._client.write(self._strings, [QLWriteOp(
                    WriteOpKind.DELETE_ROW, self._str_key(key))])
                n += 1
            fields = list(self._hash_fields(key))
            if fields:
                self._client.write(self._hashes, [
                    QLWriteOp(WriteOpKind.DELETE_ROW,
                              self._hash_key(key, f))
                    for f, _v in fields])
                n += 1
        return resp.integer(n)

    cmd_unlink = cmd_del

    def cmd_expire(self, args):
        value = self._get(args[0])
        if value is None:
            return resp.integer(0)
        self._set(args[0], value, int(args[1]) * 1000)
        return resp.integer(1)

    def cmd_ttl(self, args):
        # TTLs are enforced by the doc store; remaining time is not
        # surfaced through the row API (reference returns it from the
        # value's control fields) — report "no expiry info".
        return resp.integer(-1 if self._get(args[0]) is not None else -2)

    def _txn_rmw(self, body, cmd_name: str):
        """Atomic read-modify-write: run body(txn) in a distributed txn
        with conflict retries — the single-key atomicity redis commands
        guarantee on a thread-per-connection server (ref: the reference
        routes YEDIS RMW commands through the same write path)."""
        for _ in range(16):
            txn = self._txns.begin()
            try:
                out = body(txn)
                txn.commit()
                return out
            except TransactionError:
                txn.abort()
            except BaseException:
                # e.g. non-integer value: abort, or the heartbeating txn
                # would pin its intents.
                txn.abort()
                raise
        return resp.error(f"{cmd_name} conflict retries exhausted")

    def _incr_by(self, key: bytes, delta: int):
        def body(txn):
            row = txn.read_row(self._strings, self._str_key(key))
            cur = 0
            if row is not None:
                raw = row.columns.get(self._val_str) or b"0"
                cur = int(raw)
            new = cur + delta
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.INSERT, self._str_key(key),
                {"value": str(new).encode()})])
            return resp.integer(new)
        return self._txn_rmw(body, "INCR")

    def cmd_append(self, args):
        def body(txn):
            row = txn.read_row(self._strings, self._str_key(args[0]))
            cur = b"" if row is None \
                else (row.columns.get(self._val_str) or b"")
            new = cur + args[1]
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.INSERT, self._str_key(args[0]),
                {"value": new})])
            return resp.integer(len(new))
        return self._txn_rmw(body, "APPEND")

    def cmd_strlen(self, args):
        v = self._get(args[0])
        return resp.integer(0 if v is None else len(v))

    def cmd_setnx(self, args):
        def body(txn):
            if txn.read_row(self._strings,
                            self._str_key(args[0])) is not None:
                return resp.integer(0)
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.INSERT, self._str_key(args[0]),
                {"value": args[1]})])
            return resp.integer(1)
        return self._txn_rmw(body, "SETNX")

    def cmd_getset(self, args):
        def body(txn):
            row = txn.read_row(self._strings, self._str_key(args[0]))
            old = None if row is None else row.columns.get(self._val_str)
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.INSERT, self._str_key(args[0]),
                {"value": args[1]})])
            return resp.bulk(old)
        return self._txn_rmw(body, "GETSET")

    def cmd_getdel(self, args):
        def body(txn):
            row = txn.read_row(self._strings, self._str_key(args[0]))
            old = None if row is None else row.columns.get(self._val_str)
            if old is not None:
                txn.write(self._strings, [QLWriteOp(
                    WriteOpKind.DELETE_ROW, self._str_key(args[0]))])
            return resp.bulk(old)
        return self._txn_rmw(body, "GETDEL")

    def cmd_getrange(self, args):
        v = self._get(args[0])
        if not v:
            return resp.bulk(b"")
        start, end = int(args[1]), int(args[2])
        # redis clamps both indexes into [0, len-1] after negative
        # adjustment; an inverted range is empty
        if start < 0:
            start = max(0, len(v) + start)
        if end < 0:
            end = max(0, len(v) + end)
        end = min(end, len(v) - 1)
        if start > end:
            return resp.bulk(b"")
        return resp.bulk(v[start:end + 1])

    def cmd_setrange(self, args):
        offset, patch = int(args[1]), args[2]
        if offset < 0:
            return resp.error("offset is out of range")

        def body(txn):
            row = txn.read_row(self._strings, self._str_key(args[0]))
            v = None if row is None else row.columns.get(self._val_str)
            if not patch:
                # empty patch never creates a key (redis semantics)
                return resp.integer(0 if v is None else len(v))
            v = v or b""
            if len(v) < offset:
                v = v + b"\x00" * (offset - len(v))
            new = v[:offset] + patch + v[offset + len(patch):]
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.INSERT, self._str_key(args[0]),
                {"value": new})])
            return resp.integer(len(new))
        return self._txn_rmw(body, "SETRANGE")

    def cmd_persist(self, args):
        def body(txn):
            row = txn.read_row(self._strings, self._str_key(args[0]))
            v = None if row is None else row.columns.get(self._val_str)
            if v is None:
                return resp.integer(0)
            # rewrite without TTL control field, atomically vs SET races
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.INSERT, self._str_key(args[0]),
                {"value": v})])
            return resp.integer(1)
        return self._txn_rmw(body, "PERSIST")

    def cmd_type(self, args):
        if self._get(args[0]) is not None:
            return resp.simple("string")
        if next(iter(self._hash_fields(args[0])), None) is not None:
            return resp.simple("hash")
        return resp.simple("none")

    def _txn_hash_fields(self, txn, key: bytes):
        """(field, value) pairs of a hash read THROUGH the transaction:
        the discovery scan is snapshot-only, so each found field is
        re-read via txn.read_row to lay a read intent — a concurrent
        write to any copied field conflicts and retries the txn. Fields
        ADDED concurrently with the scan can still be missed (no range
        read intents at this layer); the reference closes that with
        weak-read intents on the whole hash bucket."""
        out = []
        for f, _v in list(self._hash_fields(key)):
            row = txn.read_row(self._hashes, self._hash_key(key, f))
            if row is not None:
                out.append((f, row.columns.get(self._val_hash)))
        return out

    def _clear_key(self, txn, key: bytes) -> None:
        """Remove every representation of `key` (string row + hash
        fields) inside txn — RENAME fully replaces the destination."""
        if txn.read_row(self._strings, self._str_key(key)) is not None:
            txn.write(self._strings, [QLWriteOp(
                WriteOpKind.DELETE_ROW, self._str_key(key))])
        for f, _v in self._txn_hash_fields(txn, key):
            txn.write(self._hashes, [QLWriteOp(
                WriteOpKind.DELETE_ROW, self._hash_key(key, f))])

    def cmd_rename(self, args):
        src, dst = args[0], args[1]

        def body(txn):
            row = txn.read_row(self._strings, self._str_key(src))
            v = None if row is None else row.columns.get(self._val_str)
            # a key can carry BOTH representations; move them together
            fields = self._txn_hash_fields(txn, src)
            if v is None and not fields:
                return resp.error("no such key")
            if src == dst:
                return resp.simple("OK")  # successful no-op
            self._clear_key(txn, dst)
            if v is not None:
                txn.write(self._strings, [
                    QLWriteOp(WriteOpKind.INSERT, self._str_key(dst),
                              {"value": v}),
                    QLWriteOp(WriteOpKind.DELETE_ROW,
                              self._str_key(src))])
            if fields:
                txn.write(self._hashes, [
                    QLWriteOp(WriteOpKind.INSERT,
                              self._hash_key(dst, f), {"value": val})
                    for f, val in fields] + [
                    QLWriteOp(WriteOpKind.DELETE_ROW,
                              self._hash_key(src, f))
                    for f, _v in fields])
            return resp.simple("OK")
        return self._txn_rmw(body, "RENAME")

    def cmd_incr(self, args):
        return self._incr_by(args[0], 1)

    def cmd_incrby(self, args):
        return self._incr_by(args[0], int(args[1]))

    def cmd_decr(self, args):
        return self._incr_by(args[0], -1)

    def cmd_decrby(self, args):
        return self._incr_by(args[0], -int(args[1]))

    # --------------------------------------------------------------- hashes
    def cmd_hset(self, args):
        if len(args) < 3 or len(args) % 2 == 0:
            return resp.error("wrong number of arguments for 'hset'")
        key = args[0]
        added = 0
        ops = []
        for i in range(1, len(args), 2):
            field, value = args[i], args[i + 1]
            if self._client.read_row(self._hashes,
                                     self._hash_key(key, field)) is None:
                added += 1
            ops.append(QLWriteOp(WriteOpKind.INSERT,
                                 self._hash_key(key, field),
                                 {"value": value}))
        self._client.write(self._hashes, ops)
        return resp.integer(added)

    cmd_hmset = cmd_hset

    def cmd_hget(self, args):
        row = self._client.read_row(self._hashes,
                                    self._hash_key(args[0], args[1]))
        return resp.bulk(None if row is None
                         else row.columns.get(self._val_hash))

    def cmd_hmget(self, args):
        key = args[0]
        out = []
        for field in args[1:]:
            row = self._client.read_row(self._hashes,
                                        self._hash_key(key, field))
            out.append(resp.bulk(None if row is None
                                 else row.columns.get(self._val_hash)))
        return resp.array(out)

    def cmd_hdel(self, args):
        key = args[0]
        n = 0
        for field in args[1:]:
            if self._client.read_row(self._hashes,
                                     self._hash_key(key, field)) is not None:
                self._client.write(self._hashes, [QLWriteOp(
                    WriteOpKind.DELETE_ROW, self._hash_key(key, field))])
                n += 1
        return resp.integer(n)

    def cmd_hgetall(self, args):
        out = []
        for field, value in self._hash_fields(args[0]):
            out.append(resp.bulk(field))
            out.append(resp.bulk(value))
        return resp.array(out)

    def cmd_hlen(self, args):
        return resp.integer(sum(1 for _ in self._hash_fields(args[0])))

    def cmd_hexists(self, args):
        row = self._client.read_row(self._hashes,
                                    self._hash_key(args[0], args[1]))
        return resp.integer(0 if row is None else 1)

    def cmd_hkeys(self, args):
        return resp.array([resp.bulk(f)
                           for f, _v in self._hash_fields(args[0])])

    def cmd_hvals(self, args):
        return resp.array([resp.bulk(v)
                           for _f, v in self._hash_fields(args[0])])

    def cmd_hstrlen(self, args):
        row = self._client.read_row(self._hashes,
                                    self._hash_key(args[0], args[1]))
        v = None if row is None else row.columns.get(self._val_hash)
        return resp.integer(0 if v is None else len(v))

    def cmd_hincrby(self, args):
        key, field, delta = args[0], args[1], int(args[2])

        def body(txn):
            row = txn.read_row(self._hashes, self._hash_key(key, field))
            cur = 0
            if row is not None:
                cur = int(row.columns.get(self._val_hash) or b"0")
            new = cur + delta
            txn.write(self._hashes, [QLWriteOp(
                WriteOpKind.INSERT, self._hash_key(key, field),
                {"value": str(new).encode()})])
            return resp.integer(new)
        return self._txn_rmw(body, "HINCRBY")

    def cmd_hsetnx(self, args):
        def body(txn):
            if txn.read_row(self._hashes,
                            self._hash_key(args[0],
                                           args[1])) is not None:
                return resp.integer(0)
            txn.write(self._hashes, [QLWriteOp(
                WriteOpKind.INSERT, self._hash_key(args[0], args[1]),
                {"value": args[2]})])
            return resp.integer(1)
        return self._txn_rmw(body, "HSETNX")

    # ----------------------------------------------------------------- misc
    def _all_keys(self):
        keys = {row.doc_key.hash_components[0]
                for row in self._client.scan(self._strings)}
        keys.update(row.doc_key.hash_components[0]
                    for row in self._client.scan(self._hashes))
        return keys

    def cmd_keys(self, args):
        if args and args[0] not in (b"*",):
            return resp.error("only KEYS * is supported")
        return resp.array([resp.bulk(k) for k in sorted(self._all_keys())])

    def cmd_dbsize(self, args):
        return resp.integer(len(self._all_keys()))

    def cmd_flushall(self, args):
        for row in self._client.scan(self._strings):
            self._client.write(self._strings, [QLWriteOp(
                WriteOpKind.DELETE_ROW,
                DocKey(hash_components=row.doc_key.hash_components))])
        for row in self._client.scan(self._hashes):
            self._client.write(self._hashes, [QLWriteOp(
                WriteOpKind.DELETE_ROW, row.doc_key)])
        return resp.simple("OK")

    cmd_flushdb = cmd_flushall
