"""Per-op serve-path latency attribution: the LatencyBudget.

A `LatencyBudget` rides one batched-write or multi_read op alongside the
existing trace context (utils/trace.py) and splits the op's measured
end-to-end wall time into named, disjoint stages:

  batched write : client_queue -> wire_encode -> [wire_transfer] ->
                  rpc_queue -> raft_replicate (-> wal_fsync -> apply)
                  -> server_other
  multi_read    : wire_encode -> [wire_transfer] -> rpc_queue ->
                  device_dispatch | host_fallback -> row_assembly ->
                  server_other

The carrier is a contextvar, exactly like the trace span stack, so the
client batcher, the RPC messenger, raft, the WAL appender and the
storage layer all record into the same object without any plumbing
through intermediate signatures. Two sites cross threads and carry the
budget explicitly instead: `Log.append_async` (the WAL appender thread
records the group-commit fsync slice) and raft's `_budget_by_index` map
(the commit worker records the apply slice), both mirroring how the
trace context already crosses the same boundaries.

Server-side stages cross the wire back to the client: the RPC response
carries a `lat` stage map (rpc/codec.py::LAT_HEADER_KEY) that
`Messenger.call` merges into the caller's budget, so the CLIENT-side
end-to-end histogram decomposes into SERVER-side stages. Two residual
stages telescope the decomposition closed: `server_other` (handler wall
minus the measured server stages) and `wire_transfer` (end-to-end minus
everything measured anywhere) — which is why the named stages sum to
the measured e2e by construction (>=90% asserted in
tests/test_telemetry.py; the clamp to >=0 under cross-thread clock
slack is the only way to lose mass).

Lock-free by design (acceptance: ZERO new locks on the hot path): every
mutation is a single dict-item write under the GIL, and each stage has
exactly one writer thread. Aggregation into the `serve_path` histograms
(which carry trace-id exemplars for /servez -> /tracez click-through)
happens once per op at finalize time, off the per-stage hot path.
"""

from __future__ import annotations

import contextvars
import time
from typing import Dict, Optional

from yugabyte_tpu.utils import metrics as _metrics
from yugabyte_tpu.utils import ybsan

OP_WRITE = "write"
OP_MULTI_READ = "multi_read"

# Stage names (the vocabulary /servez and the README document).
STAGE_CLIENT_QUEUE = "client_queue"      # op waited in the session batcher
STAGE_WIRE_ENCODE = "wire_encode"        # request frame encode + socket send
STAGE_WIRE_TRANSFER = "wire_transfer"    # residual: link + response decode
STAGE_RPC_QUEUE = "rpc_queue"            # inbound service-pool queue wait
STAGE_RAFT_REPLICATE = "raft_replicate"  # replicate wall minus fsync/apply
STAGE_WAL_FSYNC = "wal_fsync"            # group-commit fsync slice
STAGE_APPLY = "apply"                    # committed-entry apply (row encode)
STAGE_SERVER_OTHER = "server_other"      # residual: handler wall minus above
STAGE_DEVICE_DISPATCH = "device_dispatch"  # fused point-read kernel path
STAGE_HOST_FALLBACK = "host_fallback"    # native per-key read path
STAGE_ROW_ASSEMBLY = "row_assembly"      # winner-row flat-row assembly

# Literal per-(op, stage) histogram names: kept literal (not composed)
# so the metric-names lint pass covers every family of the attribution
# namespace at its construction site.
_WRITE_STAGE_HISTOGRAMS = {
    STAGE_CLIENT_QUEUE: "serve_path_write_client_queue_ms",
    STAGE_WIRE_ENCODE: "serve_path_write_wire_encode_ms",
    STAGE_WIRE_TRANSFER: "serve_path_write_wire_transfer_ms",
    STAGE_RPC_QUEUE: "serve_path_write_rpc_queue_ms",
    STAGE_RAFT_REPLICATE: "serve_path_write_raft_replicate_ms",
    STAGE_WAL_FSYNC: "serve_path_write_wal_fsync_ms",
    STAGE_APPLY: "serve_path_write_apply_ms",
    STAGE_SERVER_OTHER: "serve_path_write_server_other_ms",
}
_READ_STAGE_HISTOGRAMS = {
    STAGE_WIRE_ENCODE: "serve_path_multi_read_wire_encode_ms",
    STAGE_WIRE_TRANSFER: "serve_path_multi_read_wire_transfer_ms",
    STAGE_RPC_QUEUE: "serve_path_multi_read_rpc_queue_ms",
    STAGE_DEVICE_DISPATCH: "serve_path_multi_read_device_dispatch_ms",
    STAGE_HOST_FALLBACK: "serve_path_multi_read_host_fallback_ms",
    STAGE_ROW_ASSEMBLY: "serve_path_multi_read_row_assembly_ms",
    STAGE_SERVER_OTHER: "serve_path_multi_read_server_other_ms",
}
_E2E_HISTOGRAMS = {
    OP_WRITE: "serve_path_write_e2e_ms",
    OP_MULTI_READ: "serve_path_multi_read_e2e_ms",
}
_STAGE_TABLES = {
    OP_WRITE: _WRITE_STAGE_HISTOGRAMS,
    OP_MULTI_READ: _READ_STAGE_HISTOGRAMS,
}


@ybsan.shadow(stages=ybsan.SINGLE_WRITER_PER_KEY)
class LatencyBudget:
    """One op's wall clock, split into named disjoint stage slices.

    `stages` maps stage name -> accumulated milliseconds. Mutations are
    single dict-item writes (GIL-atomic) with one writer thread per
    stage — no lock, by acceptance-criteria design. `trace_id` is the
    op's root trace id, stamped where the wire encode happens (the
    trace context is live there) and attached as the e2e histogram
    exemplar at finalize.
    """

    __slots__ = ("op", "t0", "stages", "trace_id")

    def __init__(self, op: str, t0: Optional[float] = None):
        self.op = op
        self.t0 = time.monotonic() if t0 is None else t0
        self.stages: Dict[str, float] = {}
        self.trace_id: Optional[str] = None

    def record(self, stage: str, ms: float) -> None:
        if ms <= 0.0:
            return
        cur = self.stages.get(stage)
        self.stages[stage] = ms if cur is None else cur + ms

    def merge(self, stage_map) -> None:
        """Fold a wire-carried stage map (the response's `lat` value)
        into this budget. Wire data: tolerate any malformed entry."""
        if not isinstance(stage_map, dict):
            return
        for k, v in stage_map.items():
            if isinstance(k, str) and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                self.record(k, float(v))

    def elapsed_ms(self) -> float:
        return (time.monotonic() - self.t0) * 1e3

    def measured_ms(self) -> float:
        return sum(self.stages.values())

    def to_wire(self) -> Dict[str, float]:
        return {k: round(v, 4) for k, v in self.stages.items()}


_BUDGET_VAR: "contextvars.ContextVar[Optional[LatencyBudget]]" = \
    contextvars.ContextVar("ybtpu_latency_budget", default=None)


def current_budget() -> Optional[LatencyBudget]:
    return _BUDGET_VAR.get()


def record_stage(stage: str, ms: float) -> None:
    """Record into the ambient budget, if any. The no-budget fast path
    is one contextvar read + an is-None check."""
    b = _BUDGET_VAR.get()
    if b is not None:
        b.record(stage, ms)


def use_budget(budget: Optional[LatencyBudget]):
    """Install `budget` as the ambient budget; returns the reset token.
    (The server handler path, which must NOT finalize — the budget's
    stage map rides the response back to the owning client.)"""
    return _BUDGET_VAR.set(budget)


def clear_budget(token) -> None:
    _BUDGET_VAR.reset(token)


class budget_scope:
    """Client-side scope: installs a fresh LatencyBudget for the with
    block and, on SUCCESSFUL exit, closes the decomposition and feeds
    the serve_path histograms. A failed op (exception propagating)
    records nothing — its wall time includes retry/timeout semantics
    the stage vocabulary does not describe."""

    __slots__ = ("budget", "_token")

    def __init__(self, op: str, t0: Optional[float] = None):
        self.budget = LatencyBudget(op, t0)

    def __enter__(self) -> LatencyBudget:
        self._token = _BUDGET_VAR.set(self.budget)
        return self.budget

    def __exit__(self, exc_type, exc, tb):
        _BUDGET_VAR.reset(self._token)
        if exc_type is None:
            finalize_budget(self.budget)
        return False


_STAGE_HELP = ("serve-path attribution: milliseconds this op spent in "
               "the stage (see README 'Telemetry timebase')")


def finalize_budget(budget: LatencyBudget) -> None:
    """Close the decomposition (wire_transfer residual) and aggregate
    the budget into the per-stage serve_path histograms; the e2e
    observation carries the op's trace id as exemplar."""
    table = _STAGE_TABLES.get(budget.op)
    if table is None:
        return
    e2e = budget.elapsed_ms()
    if e2e <= 0.0:
        return
    residual = e2e - budget.measured_ms()
    if residual > 0.0:
        budget.record(STAGE_WIRE_TRANSFER, residual)
    ent = _metrics.serve_path_metrics()
    for stage, ms in budget.stages.items():
        name = table.get(stage)
        if name is not None:
            ent.histogram(name, _STAGE_HELP).increment(ms)
    ent.histogram(_E2E_HISTOGRAMS[budget.op],
                  "serve-path attribution: measured end-to-end op wall "
                  "time; sums the per-stage histograms within clamp "
                  "slack").increment(e2e, exemplar=budget.trace_id)


def serve_path_attribution_page() -> Dict[str, object]:
    """The /servez attribution block: per op, the e2e summary plus each
    stage's share of total e2e time (percentages computed from the
    histogram sums, so they answer 'where did the path's time go' over
    the server's lifetime) with trace-id exemplars on e2e."""
    ent = _metrics.serve_path_metrics()
    out: Dict[str, object] = {}
    for op, table in _STAGE_TABLES.items():
        e2e_h = ent.histogram(_E2E_HISTOGRAMS[op])
        e2e = e2e_h.snapshot_dict()
        total = float(e2e.get("sum") or 0.0)
        stages = {}
        for stage, name in table.items():
            h = ent.histogram(name, _STAGE_HELP)
            snap = h.snapshot_dict()
            snap.pop("exemplars", None)
            snap["pct_of_e2e"] = (round(100.0 * float(snap["sum"]) / total, 2)
                                  if total > 0 else 0.0)
            stages[stage] = snap
        out[op] = {"e2e": e2e, "stages": stages}
    return out
