"""Builtin function registry (yql/bfunc.py) — the bfql/bfpg equivalent.

Covers resolution with exact + implicit-widening signatures (ref
bfql/bfql.cc FindOpcodeByType), the conversion families from
bfql/directory.cc, and the YCQL wiring: builtins in SELECT lists,
INSERT value expressions, and the writetime() metadata marker.
"""

import struct

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.yql import bfunc


# ------------------------------------------------------------- registry

def test_resolution_exact_and_widening():
    d = bfunc.resolve("length", [DataType.STRING])
    assert d.ret_type == DataType.INT32
    # abs has INT64 and DOUBLE overloads: exact first
    assert bfunc.resolve("abs", [DataType.INT64]).ret_type == DataType.INT64
    assert bfunc.resolve("abs", [DataType.DOUBLE]).ret_type == DataType.DOUBLE
    # INT32 widens into the INT64 overload
    assert bfunc.resolve("abs", [DataType.INT32]).ret_type == DataType.INT64
    # FLOAT widens into DOUBLE
    assert bfunc.resolve("abs", [DataType.FLOAT]).ret_type == DataType.DOUBLE
    with pytest.raises(bfunc.NoSuchFunction):
        bfunc.resolve("nope", [])
    with pytest.raises(bfunc.NoSuchFunction):
        bfunc.resolve("length", [DataType.INT64])


def test_scalar_functions():
    assert bfunc.evaluate("upper", ["abc"])[0] == "ABC"
    assert bfunc.evaluate("lower", ["AbC"])[0] == "abc"
    assert bfunc.evaluate("length", ["hello"])[0] == 5
    assert bfunc.evaluate("substr", ["hello", 2, 3])[0] == "ell"
    assert bfunc.evaluate("abs", [-7])[0] == 7
    assert bfunc.evaluate("ceil", [1.2])[0] == 2.0
    assert bfunc.evaluate("coalesce", [None, None, 3, 4])[0] == 3
    assert bfunc.evaluate("nullif", [5, 5])[0] is None
    assert bfunc.evaluate("nullif", [5, 6])[0] == 5
    assert bfunc.evaluate("greatest", [1, 9, 4])[0] == 9
    assert bfunc.evaluate("least", [None, 9, 4])[0] == 4
    # null propagation
    assert bfunc.evaluate("upper", [None], [DataType.STRING])[0] is None


def test_blob_conversions_roundtrip():
    b, t = bfunc.evaluate("intasblob", [7], [DataType.INT32])
    assert t == DataType.BINARY and b == struct.pack(">i", 7)
    assert bfunc.evaluate("blobasint", [b])[0] == 7
    b, _ = bfunc.evaluate("textasblob", ["hi"])
    assert bfunc.evaluate("blobastext", [b])[0] == "hi"
    b, _ = bfunc.evaluate("doubleasblob", [2.5])
    assert bfunc.evaluate("blobasdouble", [b])[0] == 2.5
    b, _ = bfunc.evaluate("booleanasblob", [True], [DataType.BOOL])
    assert bfunc.evaluate("blobasboolean", [b])[0] is True


def test_volatile_time_functions():
    v1, t = bfunc.evaluate("now", [])
    assert t == DataType.TIMESTAMP and v1 > 1_500_000_000 * 10**6
    u1, _ = bfunc.evaluate("uuid", [])
    u2, _ = bfunc.evaluate("uuid", [])
    assert u1 != u2
    assert bfunc.evaluate("tounixtimestamp", [v1],
                          [DataType.TIMESTAMP])[0] == v1 // 1000


def test_arithmetic_operators():
    assert bfunc.evaluate("+", [2, 3], [DataType.INT64, DataType.INT64])[0] == 5
    assert bfunc.evaluate("*", [2.5, 4.0],
                          [DataType.DOUBLE, DataType.DOUBLE])[0] == 10.0
    v, t = bfunc.evaluate("/", [7, 2], [DataType.INT64, DataType.INT64])
    assert v == 3.5 and t == DataType.DOUBLE
    assert bfunc.evaluate("||", ["a", "b"],
                          [DataType.STRING, DataType.STRING])[0] == "ab"


def test_marker_functions_refuse_direct_eval():
    with pytest.raises(bfunc.NoSuchFunction):
        bfunc.evaluate("writetime", ["x"], [bfunc.ANY])


def test_cast():
    assert bfunc.evaluate("cast", [7, None],
                          [DataType.INT32, DataType.INT64])[0] == 7
    v, t = bfunc.evaluate("cast", [7, None],
                          [DataType.INT32, DataType.DOUBLE])
    assert v == 7.0 and t == DataType.DOUBLE


# ----------------------------------------------------------- CQL wiring

@pytest.fixture(scope="module")
def ql(tmp_path_factory):
    import jax  # noqa: F401 — conftest pins the CPU platform
    from yugabyte_tpu.integration.mini_cluster import (
        MiniCluster, MiniClusterOptions)
    from yugabyte_tpu.yql.cql.executor import QLProcessor
    from yugabyte_tpu.utils import flags
    old_rf = flags.get_flag("replication_factor")
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path_factory.mktemp("bfunc_cluster")))).start()
    proc = QLProcessor(c.new_client())
    proc.execute("CREATE KEYSPACE ks")
    proc.execute("USE ks")
    proc.execute("CREATE TABLE t (k text, v text, n bigint, "
                 "PRIMARY KEY ((k)))")
    yield proc
    c.shutdown()
    flags.set_flag("replication_factor", old_rf)


def test_cql_builtin_in_select(ql):
    ql.execute("INSERT INTO t (k, v, n) VALUES ('a', 'Hello', -4)")
    rs = ql.execute("SELECT upper(v), length(v), abs(n) FROM t WHERE k = 'a'")
    assert rs.columns == ["upper(v)", "length(v)", "abs(n)"]
    assert rs.rows == [["HELLO", 5, 4]]


def test_cql_builtin_in_insert_values(ql):
    ql.execute("INSERT INTO t (k, v) VALUES ('u', uuid())")
    rs = ql.execute("SELECT v, length(v) FROM t WHERE k = 'u'")
    assert rs.rows[0][1] == 36   # canonical uuid text length


def test_cql_writetime(ql):
    ql.execute("INSERT INTO t (k, v) VALUES ('w', 'x')")
    rs = ql.execute("SELECT writetime(v) FROM t WHERE k = 'w'")
    assert rs.columns == ["writetime(v)"]
    wt = rs.rows[0][0]
    assert isinstance(wt, int) and wt > 1_500_000_000 * 10**6


def test_cql_nested_call(ql):
    ql.execute("INSERT INTO t (k, v) VALUES ('n', 'abc')")
    rs = ql.execute("SELECT upper(substr(v, 1, 2)) FROM t WHERE k = 'n'")
    assert rs.rows == [["AB"]]


def test_cql_unknown_function_rejected(ql):
    from yugabyte_tpu.utils.status import StatusError
    with pytest.raises(StatusError):
        ql.execute("SELECT frobnicate(v) FROM t WHERE k = 'a'")


# ------------------------------------------------------------ PG wiring

def test_pg_scalar_functions(tmp_path):
    """SELECT upper(name), length(name) through the real PG wire server."""
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from pg_wire_client import PgWireClient
    from yugabyte_tpu.integration.mini_cluster import (
        MiniCluster, MiniClusterOptions)
    from yugabyte_tpu.yql.pgsql.server import PgServer
    from yugabyte_tpu.utils import flags
    old_rf = flags.get_flag("replication_factor")
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path / "fs"))).start()
    try:
        server = PgServer(c.new_client())
        conn = PgWireClient(server.host, server.port, database="postgres")
        conn.query("CREATE TABLE people (id bigint PRIMARY KEY, "
                   "name text, score double precision)")
        conn.query("INSERT INTO people (id, name, score) "
                   "VALUES (1, 'Ada', -2.5)")
        res = conn.query(
            "SELECT upper(name), length(name), abs(score) FROM people "
            "WHERE id = 1")[0]
        assert [c0 for c0, _o in res.columns] == ["upper", "length", "abs"]
        assert res.rows == [["ADA", "3", "2.5"]]
        # nested + coalesce over a NULL column
        conn.query("INSERT INTO people (id, name) VALUES (2, NULL)")
        res2 = conn.query(
            "SELECT coalesce(name, 'unknown') FROM people WHERE id = 2")[0]
        assert res2.rows == [["unknown"]]
        conn.close()
        server.shutdown()
    finally:
        c.shutdown()
        flags.set_flag("replication_factor", old_rf)


def test_literal_reachable_conversions():
    """Plain literals (INT64/DOUBLE inferred) reach the narrow decls."""
    assert bfunc.evaluate("intasblob", [7])[0] == struct.pack(">i", 7)
    assert bfunc.evaluate("floatasblob", [1.5])[0] == struct.pack(">f", 1.5)
    with pytest.raises(bfunc.EvalError):
        bfunc.evaluate("intasblob", [1 << 40])


def test_eval_error_wrapped():
    with pytest.raises(bfunc.EvalError):
        bfunc.evaluate("blobasint", [b"xx"])   # wrong length -> struct.error


def test_cql_runtime_error_is_status_not_crash(ql):
    from yugabyte_tpu.utils.status import StatusError
    ql.execute("INSERT INTO t (k, v, n) VALUES ('e', 'z', 1)")
    with pytest.raises(StatusError):
        ql.execute("SELECT greatest(v, n) FROM t WHERE k = 'e'")
    # the processor is still usable afterwards
    rs = ql.execute("SELECT v FROM t WHERE k = 'e'")
    assert rs.rows == [["z"]]


def test_cql_select_list_marker_binds(ql):
    """'?' inside a select-list builtin binds positionally (before WHERE
    markers, matching statement-text order)."""
    ql.execute("INSERT INTO t (k, v) VALUES ('m', NULL)")
    rs = ql.execute("SELECT coalesce(v, ?) FROM t WHERE k = ?",
                    ("dflt", "m"))
    assert rs.rows == [["dflt"]]


def test_prepared_marker_types_inside_func_args(ql):
    """Markers that are function ARGUMENTS are typed by the function's
    parameter, not the target column (textasblob(?) binds a STRING even
    into a BLOB column)."""
    from yugabyte_tpu.yql.cql import parser as P
    from yugabyte_tpu.yql.cql.binary_server import infer_marker_types
    ql.execute("CREATE TABLE tb (k text, b blob, PRIMARY KEY ((k)))")
    stmt = P.parse("INSERT INTO tb (k, b) VALUES (?, textasblob(?))")
    types = infer_marker_types(stmt, ql)
    assert types == [DataType.STRING, DataType.STRING]
    # and executing with the string param produces the encoded blob
    ql.execute("INSERT INTO tb (k, b) VALUES (?, textasblob(?))",
               ("x", "payload"))
    rs = ql.execute("SELECT b FROM tb WHERE k = 'x'")
    assert rs.rows == [[b"payload"]]


# --------------------------------------------------- system vtables (YCQL)

def test_system_local_and_peers(ql):
    rs = ql.execute("SELECT * FROM system.local")
    assert rs.rows and dict(zip(rs.columns, rs.rows[0]))["key"] == "local"
    rs = ql.execute("SELECT peer, data_center FROM system.peers")
    assert rs.columns == ["peer", "data_center"]   # RF1: no peers rows


def test_system_schema_tables_and_columns(ql):
    rs = ql.execute("SELECT keyspace_name, table_name FROM "
                    "system_schema.tables WHERE keyspace_name = 'ks'")
    names = [r[1] for r in rs.rows]
    assert "t" in names
    rs = ql.execute("SELECT column_name, kind, type FROM "
                    "system_schema.columns WHERE table_name = 't'")
    cols = {r[0]: (r[1], r[2]) for r in rs.rows}
    assert cols["k"][0] == "partition_key"
    assert cols["v"] == ("regular", "string")
    rs = ql.execute("SELECT keyspace_name FROM system_schema.keyspaces")
    assert ["ks"] in rs.rows


def test_system_select_star_empty_still_has_columns(ql):
    rs = ql.execute("SELECT * FROM system.peers")
    assert rs.columns == ["peer", "rpc_address", "data_center", "rack",
                          "tokens"]
    assert rs.rows == []
    rs = ql.execute("SELECT * FROM system_schema.tables "
                    "WHERE keyspace_name = 'does_not_exist'")
    assert rs.columns and rs.rows == []


def test_i32_cast_overflow_raises():
    """ADVICE r3: narrowing casts must use the same overflow policy as the
    checked intasblob companion — no silent truncation."""
    import pytest as _pytest
    from yugabyte_tpu.yql.bfunc import EvalError, resolve
    from yugabyte_tpu.common.schema import DataType
    fn = resolve("cast", [DataType.INT64, DataType.INT32])
    assert fn.fn(5, None) == 5
    with _pytest.raises(EvalError):
        fn.fn(1 << 40, None)
