"""Runtime lock-order tracker: acquisition edges + cycle detection.

The static lock-discipline pass (tools/analysis) proves accesses happen
under the right lock; it cannot prove locks are acquired in a consistent
ORDER. This module records the actual acquisition graph at runtime and
fails when it contains a cycle — the classic deadlock precondition (ref:
the reference's yb::RWC lock-rank debugging and absl's deadlock
detector).

Usage — wrap a lock at construction:

    self._lock = lock_rank.tracked(threading.Lock(), "raft._lock")
    self._durable_lock = lock_rank.tracked(threading.Lock(),
                                           "raft._durable_lock")

`tracked()` is a NO-OP passthrough in production: tracking is enabled
only under pytest (or YBTPU_LOCK_RANK=1), so the hot paths pay nothing
outside tests. When enabled, each acquire records edges
(every-currently-held-lock -> acquired-lock) into a process-global
graph; a NEW edge triggers an incremental cycle check whose result is
latched into `violations()` (raising inside arbitrary daemon threads
would vanish — the tier-1 test asserts `assert_no_cycles()` instead).

All tracked locks sharing one `name` are one graph node: per-instance
locks of the same class/field (e.g. every tablet's raft lock) collapse
to their rank, which is exactly the granularity deadlock ordering is
defined over.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from yugabyte_tpu.utils import ybsan as _ybsan

_edges_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}          # name -> set of names acquired
                                          # while `name` was held
_edge_sites: Dict[Tuple[str, str], str] = {}
_violations: List[str] = []
_races: List[str] = []                    # latched ybsan race reports
_held = threading.local()


def _count_violation(counter_name: str) -> None:
    """Export the latched-violation counters to ROOT_REGISTRY so soaks
    can assert zero (`lock_rank_violations_total`, `ybsan_races_total`).
    Lazy import: lock_rank must stay importable before metrics."""
    from yugabyte_tpu.utils import metrics
    metrics.ROOT_REGISTRY.entity("server", "sanitizer").counter(
        counter_name,
        "latched concurrency-violation reports (lock-order cycles / "
        "ybsan races) observed by this process").increment()


def enabled() -> bool:
    env = os.environ.get("YBTPU_LOCK_RANK")
    if env is not None:
        return env not in ("", "0", "false", "off")
    return "pytest" in sys.modules


def tracked(lock, name: str):
    """Wrap `lock` for order tracking; passthrough when tracking is off."""
    if not enabled():
        return lock
    return TrackedLock(lock, name)


class TrackedLock:
    """Duck-types threading.Lock (acquire/release/context manager), so it
    also works as the inner lock of a threading.Condition. Non-blocking
    probe acquires (Condition._is_owned's `acquire(False)`) that fail do
    not record edges or held state."""

    __slots__ = ("_lock", "name", "ybsan_vc")

    def __init__(self, lock, name: str):
        self._lock = lock
        self.name = name
        self.ybsan_vc = None   # per-instance vector clock (ybsan armed)

    # -------------------------------------------------- lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            _record_acquire(self.name)
            _ybsan.lock_acquired(self)
        return got

    def release(self) -> None:
        _record_release(self.name)
        _ybsan.lock_releasing(self)   # publish BEFORE the lock drops
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _held_stack() -> List[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _local_seen() -> Set[Tuple[str, str]]:
    seen = getattr(_held, "seen", None)
    if seen is None:
        seen = _held.seen = set()
    return seen


def _record_acquire(name: str) -> None:
    stack = _held_stack()
    seen = _local_seen()
    for holder in stack:
        edge = (holder, name)
        if holder == name or edge in seen:
            continue
        seen.add(edge)
        with _edges_lock:
            known = _edges.setdefault(holder, set())
            if name in known:
                continue
            known.add(name)
            _edge_sites[edge] = threading.current_thread().name
            cycle = _find_cycle_unlocked()
            if cycle is not None:
                _violations.append(
                    "[lock-rank/lock-order-cycle] "
                    + " -> ".join(cycle)
                    + f"\n  closing edge {holder} -> {name} on thread "
                    + threading.current_thread().name + "\n"
                    + _ybsan.format_stack(_ybsan.capture_stack(skip=2)))
                _count_violation("lock_rank_violations_total")
    stack.append(name)


def _record_release(name: str) -> None:
    stack = _held_stack()
    # release order may not be LIFO (rare but legal): drop the last
    # matching entry
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


def _find_cycle_unlocked() -> Optional[List[str]]:
    """DFS over the edge graph; returns one cycle as a node list (first
    node repeated at the end) or None. Caller holds _edges_lock."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    parent: Dict[str, str] = {}

    def dfs(u: str) -> Optional[List[str]]:
        color[u] = GRAY
        for v in sorted(_edges.get(u, ())):
            c = color.get(v, WHITE)
            if c == GRAY:
                path = [v, u]
                cur = u
                while cur != v:
                    cur = parent[cur]
                    path.append(cur)
                path.reverse()
                return path
            if c == WHITE:
                parent[v] = u
                found = dfs(v)
                if found is not None:
                    return found
        color[u] = BLACK
        return None

    for node in sorted(_edges):
        if color.get(node, WHITE) == WHITE:
            found = dfs(node)
            if found is not None:
                return found
    return None


# ------------------------------------------------------------- inspection
def edges() -> Dict[str, Set[str]]:
    with _edges_lock:
        return {k: set(v) for k, v in _edges.items()}


def find_cycle() -> Optional[List[str]]:
    with _edges_lock:
        return _find_cycle_unlocked()


def record_race(report: str) -> None:
    """Latch a ybsan race report into the merged violation list (called
    by tools/sanitizer when armed). Same stack format as the cycle
    reports — `violations()` is ONE vocabulary for both failure kinds,
    and `ybsan_races_total` lets soaks assert zero without parsing."""
    with _edges_lock:
        _races.append(report)
    _count_violation("ybsan_races_total")


def cycle_violations() -> List[str]:
    with _edges_lock:
        return list(_violations)


def race_violations() -> List[str]:
    with _edges_lock:
        return list(_races)


def violations() -> List[str]:
    """The merged latched violation report: lock-order cycles AND ybsan
    race reports, in one shared `[pass/code] headline + indented stack`
    format."""
    with _edges_lock:
        return list(_violations) + list(_races)


def assert_no_cycles() -> None:
    """Fail (AssertionError) if any acquisition-order CYCLE was ever
    observed in this process — wired into tier-1 via tests/test_yblint.py.
    (Race reports gate separately through the ybsan session gate, which
    is baseline-aware; a justified benign race must not fail tier-1.)"""
    with _edges_lock:
        problems = list(_violations)
        cycle = _find_cycle_unlocked()
    if cycle is not None and not problems:
        problems.append("[lock-rank/lock-order-cycle] "
                        + " -> ".join(cycle))
    assert not problems, "\n".join(problems)


def reset() -> None:
    """Clear the global graph (unit tests seeding artificial cycles)."""
    with _edges_lock:
        _edges.clear()
        _edge_sites.clear()
        _violations.clear()
        _races.clear()
    # thread-local caches of other threads expire naturally: a stale
    # `seen` entry only suppresses re-recording an edge that reset()
    # just dropped, so tests use fresh lock names instead
    _held.stack = []
    _held.seen = set()
