"""Builtin function registry shared by the YCQL and YSQL front ends.

Capability parity with the reference's bfql/bfpg libraries (ref:
src/yb/bfql/directory.cc kBFDirectory — a declarative table of
{cpp_name, ql_name, return_type, argument_types}; resolution walks the
table matching name + signature with implicit numeric widening, ref
bfql/bfql.cc FindOpcodeByType / IsImplicitlyConvertible). The reference
generates stable OPCODEs from table order for wire compatibility; this
registry is in-process (both query layers run in the same server), so
decls are resolved by name+signature and called directly.

Declared families (ref bfql/directory.cc + bfpg/directory.cc):
  - numeric casts (the ConvertXToY matrix)
  - CQL blob conversions (typeasblob / blobastype)
  - time functions (now, currenttimestamp, totimestamp, tounixtimestamp,
    dateof, uuid)
  - arithmetic operators (+ - * / %) and string concatenation (||)
  - scalar SQL functions (length, upper, lower, substr, abs, ceil,
    floor, round, coalesce, nullif, greatest, least)
  - server-side markers writetime/ttl (evaluated by the executor from
    row metadata, like the reference's TSOpcode routing)
"""

from __future__ import annotations

import math
import struct
import time
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from yugabyte_tpu.common.schema import DataType

# Sentinel types (ref directory.cc ANYTYPE / TYPEARGS)
ANY = "ANY"

_NUMERIC = (DataType.INT32, DataType.INT64, DataType.FLOAT, DataType.DOUBLE)
# implicit widening order (ref IsImplicitlyConvertible's numeric chain)
_WIDEN_RANK = {DataType.INT32: 0, DataType.INT64: 1,
               DataType.FLOAT: 2, DataType.DOUBLE: 3}


class BFError(Exception):
    """Base for builtin-function failures; front ends catch this and
    answer with a protocol error instead of dropping the connection."""


class NoSuchFunction(BFError):
    pass


class EvalError(BFError):
    pass


@dataclass(frozen=True)
class BFDecl:
    """One builtin declaration (ref bfql/bfdecl.h BFDecl)."""
    cpp_name: str
    ql_name: str
    ret_type: object                     # DataType or ANY
    arg_types: Tuple[object, ...]        # DataTypes / ANY; last may be ...
    fn: Optional[Callable]               # None = executor-evaluated marker
    variadic: bool = False
    volatile: bool = False               # re-evaluate per call (now, uuid)


_REGISTRY: Dict[str, List[BFDecl]] = {}


def declare(cpp_name: str, ql_name: str, ret_type, arg_types,
            fn, variadic: bool = False, volatile: bool = False) -> None:
    decl = BFDecl(cpp_name, ql_name.lower(), ret_type, tuple(arg_types),
                  fn, variadic, volatile)
    _REGISTRY.setdefault(decl.ql_name, []).append(decl)


def is_builtin(ql_name: str) -> bool:
    return ql_name.lower() in _REGISTRY


def marker_arg_type(ql_name: str, arg_index: int):
    """Type of a bind marker sitting at arg_index of a builtin call, if
    every overload agrees on it (prepared-statement metadata for
    INSERT ... VALUES (textasblob(?))). None = ambiguous/unknown."""
    types = set()
    for d in _REGISTRY.get(ql_name.lower(), []):
        want = d.arg_types
        if d.variadic and arg_index >= len(want):
            t = want[-1]
        elif arg_index < len(want):
            t = want[arg_index]
        else:
            continue
        types.add(t)
    if len(types) == 1:
        t = types.pop()
        return None if t is ANY else t
    return None


def _convertible(have, want) -> bool:
    if want is ANY or have is None or have == want:
        return True
    if have in _WIDEN_RANK and want in _WIDEN_RANK:
        return _WIDEN_RANK[have] <= _WIDEN_RANK[want]
    return False


def resolve(ql_name: str, arg_types: Sequence[object]) -> BFDecl:
    """Find the declaration for name+signature (ref FindOpcodeByType):
    exact match wins; otherwise the first overload every argument is
    implicitly convertible to."""
    cands = _REGISTRY.get(ql_name.lower())
    if not cands:
        raise NoSuchFunction(f"unknown function {ql_name!r}")

    def sig_ok(d: BFDecl, exact: bool) -> bool:
        want = list(d.arg_types)
        if d.variadic:
            if len(arg_types) < len(want) - 1:
                return False
            want = want[:-1] + [want[-1]] * (len(arg_types) - len(want) + 1)
        elif len(want) != len(arg_types):
            return False
        for have, w in zip(arg_types, want):
            if exact:
                if not (w is ANY or have is None or have == w):
                    return False
            elif not _convertible(have, w):
                return False
        return True

    for d in cands:
        if sig_ok(d, exact=True):
            return d

    def cost(d: BFDecl) -> int:
        # minimal total widening distance wins (INT32 prefers the INT64
        # overload of abs over DOUBLE); ANY slots cost more than any
        # concrete conversion so typed overloads take priority
        want = list(d.arg_types)
        if d.variadic:
            want = want[:-1] + [want[-1]] * (len(arg_types) - len(want) + 1)
        total = 0
        for have, w in zip(arg_types, want):
            if w is ANY or have is None:
                total += 10
            elif have != w:
                total += _WIDEN_RANK[w] - _WIDEN_RANK[have]
        return total

    viable = [d for d in cands if sig_ok(d, exact=False)]
    if viable:
        return min(viable, key=cost)
    raise NoSuchFunction(
        f"no overload of {ql_name!r} accepts "
        f"({', '.join(getattr(t, 'value', str(t)) for t in arg_types)})")


def evaluate(ql_name: str, args: Sequence[object],
             arg_types: Optional[Sequence[object]] = None):
    """Resolve + call. Returns (value, ret_type). Marker decls (fn=None,
    e.g. writetime/ttl) must be handled by the executor and raise here."""
    if arg_types is None:
        arg_types = [infer_type(a) for a in args]
    d = resolve(ql_name, arg_types)
    if d.fn is None:
        raise NoSuchFunction(
            f"{ql_name} requires row metadata (executor-evaluated)")
    try:
        return d.fn(*args), d.ret_type
    except BFError:
        raise
    except Exception as e:
        # a raw TypeError/struct.error escaping here would kill the wire
        # connection thread instead of producing a protocol error
        raise EvalError(f"{ql_name}: {e}")


def infer_type(v) -> Optional[object]:
    if v is None:
        return None
    if isinstance(v, bool):
        return DataType.BOOL
    if isinstance(v, int):
        return DataType.INT64
    if isinstance(v, float):
        return DataType.DOUBLE
    if isinstance(v, str):
        return DataType.STRING
    if isinstance(v, (bytes, bytearray)):
        return DataType.BINARY
    return ANY


# ---------------------------------------------------------------- casts
def _to_i32(x):
    v = int(x)
    if not -(1 << 31) <= v < (1 << 31):
        # same overflow policy as the checked intasblob companion below —
        # a silent narrow here would store a different number than written
        raise EvalError(f"cast: {v} out of int32 range")
    return v


def _num_cast(target):
    if target == DataType.INT32:
        return lambda x, _t=None: None if x is None else _to_i32(x)
    if target == DataType.INT64:
        return lambda x, _t=None: None if x is None else int(x)
    return lambda x, _t=None: None if x is None else float(x)


for _src in _NUMERIC:
    for _dst in _NUMERIC:
        if _src != _dst:
            # second argument is the target-type witness, exactly like the
            # reference's {"ConvertI8ToI16", "cast", "", INT16,
            # {INT8, INT16}} rows (directory.cc:74)
            declare(f"Convert{_src.name}To{_dst.name}", "cast", _dst,
                    (_src, _dst), _num_cast(_dst))

# ---------------------------------------------- CQL blob conversions
_BLOB_PACK = {
    ("varcharasblob", DataType.STRING): lambda s: s.encode(),
    ("textasblob", DataType.STRING): lambda s: s.encode(),
    ("booleanasblob", DataType.BOOL): lambda b: bytes([1 if b else 0]),
    ("intasblob", DataType.INT32): lambda v: struct.pack(">i", int(v)),
    ("bigintasblob", DataType.INT64): lambda v: struct.pack(">q", int(v)),
    ("floatasblob", DataType.FLOAT): lambda v: struct.pack(">f", float(v)),
    ("doubleasblob", DataType.DOUBLE): lambda v: struct.pack(">d", float(v)),
    ("timestampasblob", DataType.TIMESTAMP):
        lambda v: struct.pack(">q", int(v)),
}
for (_name, _src), _f in _BLOB_PACK.items():
    declare(f"Convert_{_name}", _name, DataType.BINARY, (_src,),
            (lambda f: lambda x: None if x is None else f(x))(_f))
# literal reachability: infer_type maps every int literal to INT64 and
# every float to DOUBLE, and resolution only WIDENS — so the INT32/FLOAT/
# TIMESTAMP-arg rows above would never match a plain literal. Companion
# overloads (with range checks where narrowing) keep intasblob(7) legal.


def _checked_i32(v):
    v = int(v)
    if not -(1 << 31) <= v < (1 << 31):
        raise EvalError(f"intasblob: {v} out of int32 range")
    return struct.pack(">i", v)


declare("ConvertI64ToBlobAsI32", "intasblob", DataType.BINARY,
        (DataType.INT64,), lambda v: None if v is None else _checked_i32(v))
declare("ConvertDoubleToBlobAsFloat", "floatasblob", DataType.BINARY,
        (DataType.DOUBLE,),
        lambda v: None if v is None else struct.pack(">f", float(v)))
declare("ConvertI64ToBlobAsTimestamp", "timestampasblob", DataType.BINARY,
        (DataType.INT64,),
        lambda v: None if v is None else struct.pack(">q", int(v)))

_BLOB_UNPACK = {
    ("blobasvarchar", DataType.STRING): lambda b: b.decode(),
    ("blobastext", DataType.STRING): lambda b: b.decode(),
    ("blobasboolean", DataType.BOOL): lambda b: b != b"\x00",
    ("blobasint", DataType.INT32): lambda b: struct.unpack(">i", b)[0],
    ("blobasbigint", DataType.INT64): lambda b: struct.unpack(">q", b)[0],
    ("blobasfloat", DataType.FLOAT): lambda b: struct.unpack(">f", b)[0],
    ("blobasdouble", DataType.DOUBLE): lambda b: struct.unpack(">d", b)[0],
    ("blobastimestamp", DataType.TIMESTAMP):
        lambda b: struct.unpack(">q", b)[0],
}
for (_name, _dst), _f in _BLOB_UNPACK.items():
    declare(f"Convert_{_name}", _name, _dst, (DataType.BINARY,),
            (lambda f: lambda x: None if x is None else f(x))(_f))

# ------------------------------------------------------- time / uuid
# DIVERGENCE from Cassandra: now() returns a TIMESTAMP (micros since
# epoch), not a version-1 timeuuid — this framework has no TIMEUUID wire
# type, so schemas using now() for timeuuid columns must declare them as
# timestamp.  dateof()/tounixtimestamp() below are consistent with this
# (they accept the timestamp directly).
declare("NowTimeUuid", "now", DataType.TIMESTAMP, (),
        lambda: int(time.time() * 1e6), volatile=True)
declare("GetCurrentTimestamp", "currenttimestamp", DataType.TIMESTAMP, (),
        lambda: int(time.time() * 1e6), volatile=True)
declare("GetUuid", "uuid", DataType.STRING, (),
        lambda: str(_uuid.uuid4()), volatile=True)
declare("ConvertToTimestamp", "totimestamp", DataType.TIMESTAMP,
        (DataType.TIMESTAMP,), lambda x: x)
declare("ConvertToUnixTimestamp", "tounixtimestamp", DataType.INT64,
        (DataType.TIMESTAMP,),
        lambda x: None if x is None else int(x) // 1000)
declare("ConvertTimeuuidToTimestamp", "dateof", DataType.TIMESTAMP,
        (DataType.TIMESTAMP,), lambda x: x)
# literal-reachability companions (int literals infer INT64, which does
# not widen into TIMESTAMP)
declare("ConvertI64ToTimestamp", "totimestamp", DataType.TIMESTAMP,
        (DataType.INT64,), lambda x: None if x is None else int(x))
declare("ConvertI64ToUnixTimestamp", "tounixtimestamp", DataType.INT64,
        (DataType.INT64,), lambda x: None if x is None else int(x) // 1000)
declare("DateOfI64", "dateof", DataType.TIMESTAMP,
        (DataType.INT64,), lambda x: None if x is None else int(x))

# ----------------------------------------------- arithmetic operators
_ARITH = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
          "*": lambda a, b: a * b, "%": lambda a, b: a % b}
for _op, _f in _ARITH.items():
    declare(f"Op{_op}", _op, DataType.INT64,
            (DataType.INT64, DataType.INT64),
            (lambda f: lambda a, b: None if a is None or b is None
             else f(int(a), int(b)))(_f))
    declare(f"Op{_op}D", _op, DataType.DOUBLE,
            (DataType.DOUBLE, DataType.DOUBLE),
            (lambda f: lambda a, b: None if a is None or b is None
             else f(float(a), float(b)))(_f))
declare("OpDivide", "/", DataType.DOUBLE, (DataType.DOUBLE, DataType.DOUBLE),
        lambda a, b: None if a is None or b is None else float(a) / float(b))
declare("ConcatStrStr", "||", DataType.STRING,
        (DataType.STRING, DataType.STRING),
        lambda a, b: None if a is None or b is None else str(a) + str(b))
declare("OpPlusStr", "+", DataType.STRING,
        (DataType.STRING, DataType.STRING),
        lambda a, b: None if a is None or b is None else str(a) + str(b))

# -------------------------------------------------- scalar functions
declare("StringLength", "length", DataType.INT32, (DataType.STRING,),
        lambda s: None if s is None else len(s))
declare("StringLower", "lower", DataType.STRING, (DataType.STRING,),
        lambda s: None if s is None else s.lower())
declare("StringUpper", "upper", DataType.STRING, (DataType.STRING,),
        lambda s: None if s is None else s.upper())
declare("StringTrim", "trim", DataType.STRING, (DataType.STRING,),
        lambda s: None if s is None else s.strip())
declare("SubStr", "substr", DataType.STRING,
        (DataType.STRING, DataType.INT64, DataType.INT64),
        lambda s, start, n: None if s is None
        else s[max(0, int(start) - 1): max(0, int(start) - 1) + int(n)])
declare("Abs", "abs", DataType.DOUBLE, (DataType.DOUBLE,),
        lambda x: None if x is None else abs(x))
declare("AbsI", "abs", DataType.INT64, (DataType.INT64,),
        lambda x: None if x is None else abs(int(x)))
declare("Ceil", "ceil", DataType.DOUBLE, (DataType.DOUBLE,),
        lambda x: None if x is None else float(math.ceil(x)))
declare("Floor", "floor", DataType.DOUBLE, (DataType.DOUBLE,),
        lambda x: None if x is None else float(math.floor(x)))
declare("Round", "round", DataType.DOUBLE, (DataType.DOUBLE,),
        # half-away-from-zero like PG/CQL, not Python's banker's rounding
        lambda x: None if x is None
        else float(math.floor(x + 0.5) if x >= 0 else math.ceil(x - 0.5)))
declare("Coalesce", "coalesce", ANY, (ANY, ANY), variadic=True,
        fn=lambda *xs: next((x for x in xs if x is not None), None))
declare("NullIf", "nullif", ANY, (ANY, ANY),
        lambda a, b: None if a == b else a)
declare("Greatest", "greatest", ANY, (ANY, ANY), variadic=True,
        fn=lambda *xs: max((x for x in xs if x is not None), default=None))
declare("Least", "least", ANY, (ANY, ANY), variadic=True,
        fn=lambda *xs: min((x for x in xs if x is not None), default=None))

# --------------------------------------- executor-evaluated markers
# (ref bfql TSOpcode::kWriteTime / kTtl: the tserver fills these from
# the entry's DocHybridTime / TTL — our executors read Row metadata)
declare("WriteTime", "writetime", DataType.INT64, (ANY,), None)
declare("TTL", "ttl", DataType.INT32, (ANY,), None)
