"""TabletServer: process object tying messenger, tablet manager, heartbeater.

Capability parity with the reference bringup (ref: src/yb/tserver/
tablet_server.h:71, tablet_server_main.cc:310 — Messenger + RpcServer start,
TSTabletManager::Init reopening local tablets, Heartbeater::Start). One
TabletServer per process in production; MiniCluster runs several in-process
on loopback ports (ref integration-tests/mini_cluster.h).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from yugabyte_tpu.common.hybrid_time import HybridClock
from yugabyte_tpu.rpc.consensus_service import RpcTransport
from yugabyte_tpu.rpc.messenger import Messenger
from yugabyte_tpu.tablet.tablet import TabletOptions
from yugabyte_tpu.tserver.heartbeater import Heartbeater
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.tserver.tablet_service import TabletServiceImpl
from yugabyte_tpu.tserver.ts_tablet_manager import TSTabletManager
from yugabyte_tpu.utils.metrics import MetricRegistry

TABLET_SERVICE = "tserver"


@dataclass
class TabletServerOptions:
    server_id: str
    fs_root: str
    master_addrs: List[str] = field(default_factory=list)
    bind_host: str = "127.0.0.1"
    port: int = 0
    tablet_options_factory: Optional[Callable[[], TabletOptions]] = None
    webserver_port: Optional[int] = 0  # None disables; 0 = ephemeral


class TabletServer:
    def __init__(self, opts: TabletServerOptions):
        self.opts = opts
        self.server_id = opts.server_id
        os.makedirs(opts.fs_root, exist_ok=True)
        self.clock = HybridClock()
        self.metrics = MetricRegistry()
        self.messenger = Messenger(f"ts-{opts.server_id}",
                                   bind_host=opts.bind_host, port=opts.port,
                                   metrics=self.metrics)
        # server_id -> host:port map for consensus peer resolution; seeded
        # with ourselves, refreshed by every heartbeat response.
        self._addr_map: Dict[str, str] = {opts.server_id: self.address}
        self._addr_lock = threading.Lock()
        self.transport = RpcTransport(self.messenger, self._resolve_peer)
        # The server-wide execution context is the DEFAULT tablet-options
        # source: every hosted tablet shares one compaction pool, device
        # handle, HBM slab cache and block cache (ref: db_impl.cc:201-440
        # shared PriorityThreadPool; a custom factory overrides for tests).
        self.exec_context = None
        tablet_options_factory = opts.tablet_options_factory
        if tablet_options_factory is None:
            from yugabyte_tpu.tserver.server_context import (
                ServerExecutionContext)
            self.exec_context = ServerExecutionContext(metrics=self.metrics)
            tablet_options_factory = self.exec_context.tablet_options
        self.tablet_manager = TSTabletManager(
            opts.server_id, opts.fs_root, self.transport, clock=self.clock,
            tablet_options_factory=tablet_options_factory,
            metrics=self.metrics, messenger=self.messenger)
        from yugabyte_tpu.tserver.transaction_coordinator import (
            TransactionCoordinator)
        self.coordinator = TransactionCoordinator(
            leader_resolver=self.lookup_tablet_leader,
            messenger=self.messenger)
        self.tablet_manager.status_resolver = self.resolve_txn_status
        self.service = TabletServiceImpl(self.tablet_manager,
                                         addr_updater=self.update_addr_map,
                                         coordinator=self.coordinator,
                                         client_provider=self.local_client,
                                         overload_provider=lambda:
                                         self.overloadz())
        self.messenger.register_service(TABLET_SERVICE, self.service)
        self.heartbeater = Heartbeater(
            self.messenger, opts.master_addrs, opts.server_id, self.address,
            report_provider=self.tablet_manager.generate_report,
            on_response=self._handle_heartbeat_response)
        # Server-wide memory arbitration: global memstore limit + cache GC
        # under one tracker tree (ref: tserver/tablet_memory_manager.h:39).
        from yugabyte_tpu.tserver.tablet_memory_manager import (
            TabletMemoryManager)
        from yugabyte_tpu.utils.mem_tracker import root_tracker
        self.memory_manager = TabletMemoryManager(
            peers_fn=self._tablet_peers,
            block_cache=(self.exec_context.block_cache
                         if self.exec_context is not None else None),
            metric_entity=self.metrics.entity("server", "memory"),
            server_id=opts.server_id)
        # Scored background-op scheduling: flush/log-GC/compact ranked by
        # (ram anchored, log bytes retained, perf debt) — the automatic
        # WAL-GC trigger (ref tablet/maintenance_manager.cc FindBestOp).
        from yugabyte_tpu.tserver.maintenance_manager import (
            MaintenanceManager)
        self.maintenance_manager = MaintenanceManager(
            peers_fn=self._tablet_peers,
            metric_entity=self.metrics.entity("server", "maintenance"),
            # full recovery path: in-place background-error retry first,
            # then re-bootstrap (sealed WAL) via the tablet manager
            recover_fn=lambda peer: self.tablet_manager
            .recover_failed_tablet(peer.tablet_id))
        if self.exec_context is not None:
            # one-shot startup compile of the common compaction-kernel
            # shape buckets (flag-gated; no-op for device="native")
            prewarm = self.exec_context.prewarm_op()
            if prewarm is not None:
                self.maintenance_manager.register_op(prewarm)
        # at-rest integrity scrubber (interval-gated; leader tablets also
        # run the cross-replica digest exchange after a clean local scrub)
        from yugabyte_tpu.tserver.maintenance_manager import ScrubTabletsOp
        self._digest_strikes: Dict = {}  # (tablet, server) -> consecutive
        #                                  mismatches; _addr_lock guards
        self.scrub_op = ScrubTabletsOp(
            peers_fn=self._tablet_peers,
            digest_check=self._scrub_digest_check)
        self.maintenance_manager.register_op(self.scrub_op)
        self.webserver = None
        if opts.webserver_port is not None:
            from yugabyte_tpu.server.webserver import Webserver
            self.webserver = Webserver(self.metrics, opts.bind_host,
                                       opts.webserver_port)
            self.webserver.register_json("/status", self._status_page)
            self.webserver.register_json(
                "/tablets", self.tablet_manager.generate_report)
            self.webserver.register_json(
                "/memz", lambda: root_tracker().tree_json())
            # observability endpoints (ref /rpcz rpc/rpcz_store.cc,
            # /tracez + /threadz from util/debug-util.cc). /tracez groups
            # spans by trace_id so multi-hop requests read as one tree.
            from yugabyte_tpu.utils import trace as trace_mod
            self.webserver.register_json("/rpcz", self.messenger.rpcz)
            self.webserver.register_json("/tracez", trace_mod.tracez_page)
            self.webserver.register_json("/threadz", trace_mod.threadz)
            # /compactionz: per-DB flush/compaction stats incl. running
            # write amplification (the GetProperty("rocksdb.stats")
            # analogue, ref rocksdb/db/internal_stats.cc)
            self.webserver.register_json("/compactionz", self.compactionz)
            # /integrityz: shadow-verification + scrub + quarantine state
            # (the data-integrity loop's single pane of glass)
            self.webserver.register_json("/integrityz", self.integrityz)
            # /servez: the batched serve path — group-commit write
            # batching, client-batch coalescing and follower-read
            # vouch accounting (ROADMAP item 1)
            self.webserver.register_json("/servez", self.servez)
            # /healthz: the bucket-health board — per-(kernel family,
            # bucket) state, measured rates, probe history and the
            # transition log (storage/bucket_health.py)
            self.webserver.register_json("/healthz", self.healthz)
            # /timeseriesz: the telemetry timebase — per-metric ring-
            # buffer history with rates + sparklines, self-scraped by
            # the in-process sampler (utils/timeseries.py)
            self.webserver.register_json("/timeseriesz", self.timeseriesz)

    def _tablet_peers(self):
        return self.tablet_manager.peers()

    def healthz(self) -> dict:
        """Liveness (`status: ok`, what probes key on) plus the
        bucket-health board's single pane of glass: per-key state +
        rates + probe history, the state histogram, open quarantine
        windows and the recent transition log."""
        from yugabyte_tpu.storage.bucket_health import health_board
        return {"status": "ok", "server_id": self.server_id,
                "bucket_health": health_board().snapshot()}

    def timeseriesz(self) -> dict:
        """The in-process time-series store: per-metric raw window,
        rate-over-window and sparkline downsample, plus the store's
        meta block (memory bound, sampler overhead, drop counts)."""
        from yugabyte_tpu.utils.timeseries import timeseries_store
        page = timeseries_store().page()
        page["server_id"] = self.server_id
        return page

    def _health_board_path(self) -> str:
        from yugabyte_tpu.utils import flags as _flags
        return _flags.get_flag("bucket_health_path") or os.path.join(
            self.opts.fs_root, "bucket_health.json")

    def compactionz(self) -> dict:
        """Flush/compaction stats per hosted tablet DB + server totals."""
        tablets = []
        totals = {"flush_bytes_written": 0, "compaction_bytes_read": 0,
                  "compaction_bytes_written": 0, "versions_gcd": 0,
                  "tombstones_written": 0}
        for peer in self.tablet_manager.peers():
            tablet = getattr(peer, "tablet", None)
            if tablet is None:
                continue
            entry = {"tablet_id": peer.tablet_id}
            for part in ("regular", "intents"):
                db = getattr(tablet, f"{part}_db", None)
                if db is None:
                    continue
                stats = db.compaction_stats.to_dict()
                entry[part] = stats
                for k in totals:
                    totals[k] += stats.get(k, 0)
            tablets.append(entry)
        ingested = totals["flush_bytes_written"]
        totals["write_amplification"] = round(
            (ingested + totals["compaction_bytes_written"]) / ingested,
            3) if ingested else 0.0
        # where offloaded-compaction wall time went (host decode/pack vs
        # device compute+transfer vs native output I/O) plus the shape-
        # bucket executable reuse — the pipeline-stall view of the page
        from yugabyte_tpu.utils.metrics import (kernel_metrics,
                                                pipeline_stage_totals)
        ke = kernel_metrics()
        pipeline = {f"stage_{k}_ms": round(v, 1)
                    for k, v in pipeline_stage_totals().items()}
        pipeline["compile_bucket_hits"] = ke.counter(
            "kernel_compile_bucket_hits_total",
            "kernel launches that reused an already-compiled shape "
            "bucket").value()
        pipeline["compile_bucket_misses"] = ke.counter(
            "kernel_compile_bucket_misses_total",
            "first launches of a shape bucket (compile or persistent-"
            "cache load)").value()
        # device block codec (ops/block_codec.py): blocks decoded/encoded
        # on device vs jobs that wrote through the native shell encode
        from yugabyte_tpu.ops.block_codec import codec_metrics
        cm = codec_metrics()
        pipeline["compaction_block_decode_device_total"] = \
            cm["decode_blocks"].value()
        pipeline["compaction_block_encode_device_total"] = \
            cm["encode_blocks"].value()
        pipeline["compaction_block_encode_fallback_total"] = \
            cm["encode_fallbacks"].value()
        # device-fault containment: shape buckets parked native-only
        # after a kernel-path fault (timed decay), plus how often the
        # mid-job native fallback and the per-chunk retry actually fired
        from yugabyte_tpu.storage.compaction import (
            _storage_fallback_counter)
        from yugabyte_tpu.storage.offload_policy import bucket_quarantine
        device_faults = {
            "quarantined_buckets": bucket_quarantine().snapshot(),
            "native_fallbacks": _storage_fallback_counter().value(),
            "chunk_retries": ke.counter(
                "kernel_chunk_retry_total",
                "per-chunk kernel retries after a device fault").value(),
        }
        # batched point reads: batch/bloom-skip/learned-index/fallback
        # counters for the device serve path (ops/point_read.py)
        from yugabyte_tpu.ops.point_read import point_read_snapshot
        # query pushdown: fused filtered/aggregating scan counters —
        # hits and per-reason fallbacks, per-bucket dispatches, and the
        # blocks-decoded-per-scan histogram (ops/scan.pushdown_snapshot)
        from yugabyte_tpu.ops.scan import pushdown_snapshot
        out = {"server_id": self.server_id, "totals": totals,
               "pipeline": pipeline, "device_faults": device_faults,
               "point_reads": point_read_snapshot(),
               "scans": pushdown_snapshot(),
               "tablets": tablets}
        # HBM residency: the multi-level resident set behind the chained
        # L0->L1->L2 compaction path — per-level entries/bytes, pins and
        # eviction pressure (storage/device_cache.py snapshot)
        ctx = self.exec_context
        if ctx is not None and ctx.device_cache is not None:
            out["device_cache"] = ctx.device_cache.snapshot()
        # mesh-sharded compaction pool: queue depth, per-tablet
        # queued/running, packed-slot occupancy and the measured
        # per-bucket aggregate rates the scheduler routes by
        if ctx is not None and getattr(ctx, "compaction_pool", None) \
                is not None:
            out["pool"] = ctx.compaction_pool.snapshot()
        return out

    def servez(self) -> dict:
        """Serve-path state: group-commit write batching (one raft
        replicate / WAL fsync per batch), batched point-read counters,
        per-replica follower-read vouch status, and the overload block
        (bounded RPC queue + per-tablet write-pressure state)."""
        from yugabyte_tpu.ops.point_read import point_read_snapshot
        from yugabyte_tpu.utils.latency import serve_path_attribution_page
        from yugabyte_tpu.utils.metrics import serve_path_snapshot
        tablets = []
        for peer in self.tablet_manager.peers():
            tablets.append({
                "tablet_id": peer.tablet_id,
                "role": peer.raft.observed_state()[0].value,
                "vouched": peer.is_vouched(),
                "vouch_read_ht": peer._vouch_read_ht,
            })
        return {"server_id": self.server_id,
                "serve_path": serve_path_snapshot(),
                # per-stage latency attribution: where a batched write /
                # multi_read spends its end-to-end wall, as percentages
                # of the e2e histogram (utils/latency.py)
                "attribution": serve_path_attribution_page(),
                "point_reads": point_read_snapshot(),
                "overload": self.overloadz(),
                "tablets": tablets}

    def overloadz(self) -> dict:
        """The overload block: every shedding layer's live state — the
        messenger's bounded service queue (depth, overflow/expired
        counters, measured retry_after hint), the server-wide memstore
        tracker, and each hosted tablet's write-pressure state machine
        (tablet/admission.py). Served inside /servez and over the
        `overload_status` RPC (bench scraping on external clusters)."""
        from yugabyte_tpu.utils import flags as _flags
        from yugabyte_tpu.utils.metrics import serve_path_metrics
        mm = self.memory_manager
        tracker = mm.memstore_tracker
        m = serve_path_metrics()
        pressure = []
        for peer in self.tablet_manager.peers():
            admission = getattr(getattr(peer, "tablet", None),
                                "admission", None)
            if admission is not None:
                pressure.append(admission.snapshot())
        return {
            "rpc": self.messenger.overload_snapshot(),
            "memstore": {
                "consumption_bytes": tracker.consumption(),
                "limit_bytes": tracker.limit,
                "reject_fraction": _flags.get_flag(
                    "memstore_reject_fraction"),
            },
            "write_throttle_rejections_total": m.counter(
                "write_throttle_rejections_total",
                "writes rejected retryably by the write-pressure "
                "state machine").value(),
            "write_pressure": pressure,
        }

    def integrityz(self) -> dict:
        """Data-integrity state: shadow-verify sampling + mismatch
        counters, scrubber totals, quarantined files, and per-tablet
        scrub timestamps / corruption flags."""
        from yugabyte_tpu.storage import integrity
        tablets = []
        for peer in self.tablet_manager.peers():
            tablets.append({
                "tablet_id": peer.tablet_id,
                "state": peer.state,
                "failed_corrupt": bool(getattr(peer, "failed_corrupt",
                                               False)),
                "scrub": dict(getattr(peer, "scrub_state", None) or {}),
            })
        return {"server_id": self.server_id,
                "shadow_verify": integrity.shadow_snapshot(),
                "resident_digest": integrity.resident_digest_snapshot(),
                "scrub": integrity.scrub_snapshot(),
                "quarantined_files": integrity.quarantined_files(),
                "tablets": tablets}

    def _scrub_digest_check(self, peer) -> int:
        """Leader-driven cross-replica digest exchange for one tablet
        (reuses the checksum_tablet RPC): every follower's visibility-
        resolved digest at one pinned read time must match the leader's.
        A follower that mismatches ``--scrub_replica_fail_after``
        CONSECUTIVE rounds is marked FAILED+corrupt through
        mark_tablet_failed, and the master rebuilds it from a healthy
        peer — the repair arm for divergence that byte-level CRCs cannot
        see. Returns the mismatches seen this round."""
        from yugabyte_tpu.storage.integrity import (
            replica_mismatch_counter)
        from yugabyte_tpu.utils import flags as _flags
        from yugabyte_tpu.utils.trace import TRACE
        tablet_id = peer.tablet_id
        if not peer.raft.is_leader():
            return 0
        read_ht = peer.tablet.read_time(None).value
        try:
            local = self.service.checksum_tablet(tablet_id, read_ht)
        except StatusError as e:
            TRACE("scrub digest: local checksum of %s failed: %s",
                  tablet_id, e)
            return 0
        mismatches = 0
        fail_after = int(_flags.get_flag("scrub_replica_fail_after"))
        for pid in peer.raft.config.peer_ids:
            sid = pid.split("/", 1)[0]
            if sid == self.server_id:
                continue
            addr = self._resolve_peer(pid)
            if addr is None:
                continue
            key = (tablet_id, sid)
            try:
                remote = self.messenger.call(
                    addr, "tserver", "checksum_tablet", timeout_s=30.0,
                    tablet_id=tablet_id, read_ht=read_ht)
            except StatusError as e:
                # unreachable / mid-repair follower: not divergence
                # evidence — reset its strike count and move on
                TRACE("scrub digest: checksum of %s on %s failed: %s",
                      tablet_id, sid, e)
                with self._addr_lock:
                    self._digest_strikes.pop(key, None)
                continue
            if remote["checksum"] == local["checksum"]:
                with self._addr_lock:
                    self._digest_strikes.pop(key, None)
                # matching digest = follower-read license: the replica's
                # resolved rows provably agree with the leader's at
                # read_ht, so bounded-staleness reads may land there
                # until the vouch TTL lapses (ROADMAP item 1 safety rail)
                try:
                    self.messenger.call(
                        addr, "tserver", "vouch_tablet", timeout_s=10.0,
                        tablet_id=tablet_id, read_ht=read_ht)
                except StatusError as e:
                    # vouch is an optimization, never correctness: an
                    # unreachable follower just stays unvouched and keeps
                    # refusing follower reads until the next clean round
                    TRACE("scrub digest: vouch of %s on %s failed: %s",
                          tablet_id, sid, e)
                continue
            mismatches += 1
            replica_mismatch_counter().increment()
            with self._addr_lock:
                strikes = self._digest_strikes.get(key, 0) + 1
                self._digest_strikes[key] = strikes
            TRACE("scrub digest: %s on %s diverges from leader "
                  "(%#x != %#x; strike %d/%d)", tablet_id, sid,
                  remote["checksum"], local["checksum"], strikes,
                  fail_after)
            if strikes >= fail_after:
                with self._addr_lock:
                    self._digest_strikes.pop(key, None)
                try:
                    self.messenger.call(
                        addr, "tserver", "mark_tablet_failed",
                        timeout_s=10.0, tablet_id=tablet_id,
                        reason=(f"scrub digest divergence from leader "
                                f"{self.server_id} at read_ht={read_ht}"),
                        corrupt=True)
                except StatusError as e:
                    TRACE("scrub digest: failing %s on %s failed "
                          "(retried next scrub round): %s", tablet_id,
                          sid, e)
        return mismatches

    def _status_page(self) -> dict:
        if self.exec_context is not None:
            self.exec_context.refresh_metrics()
        return {"server_id": self.server_id, "rpc_address": self.address,
                "num_tablets": len(self.tablet_manager.tablet_ids())}

    @property
    def address(self) -> str:
        return self.messenger.address

    def _resolve_peer(self, peer_id: str) -> Optional[str]:
        server_id = peer_id.split("/", 1)[0]
        with self._addr_lock:
            return self._addr_map.get(server_id)

    def _handle_heartbeat_response(self, resp: dict) -> None:
        with self._addr_lock:
            self._addr_map.update(resp.get("addr_map") or {})
        for tablet_id in resp.get("tablets_to_delete") or []:
            self.tablet_manager.delete_tablet(tablet_id)
        self._reconcile_pollers(resp.get("replication") or [])
        self.tablet_manager.apply_history_retention(
            resp.get("history_retention"))
        for upd in resp.get("schema_updates") or []:
            try:
                self.tablet_manager.alter_tablet_schema(
                    upd["tablet_id"], upd["schema"], upd["version"])
            except StatusError:
                pass  # tablet moved/deleted since the report
        keys = resp.get("universe_keys")
        if keys:
            self._apply_universe_keys(keys)

    def _apply_universe_keys(self, keys) -> None:
        """Encryption at rest: the master ships the key registry via
        heartbeats; once keys exist, every NEW storage file this process
        writes is encrypted (old plaintext files stay readable)."""
        from yugabyte_tpu.utils import env as env_mod
        known = getattr(self, "_universe_key_ids", set())
        ids = {m["key_id"] for m in keys}
        if ids == known:
            return
        reg = env_mod.UniverseKeys()
        for m in keys:
            reg.add(m["key_id"], bytes.fromhex(m["key"]),
                    make_latest=bool(m.get("latest")))
        env_mod.enable_encryption(reg)
        self._universe_key_ids = ids

    # ------------------------------------------------------------- xCluster
    def _reconcile_pollers(self, specs) -> None:
        """Start/stop xCluster pollers per the master's heartbeat piggyback
        (ref: cdc_consumer.cc reconciling pollers from the consumer
        registry)."""
        from yugabyte_tpu.cdc.poller import XClusterPoller
        if not hasattr(self, "_pollers"):
            self._pollers = {}
        want = {(s["replication_id"], s["tablet_id"]): s for s in specs}
        with self._addr_lock:
            if getattr(self, "_shutting_down", False):
                return  # a late heartbeat must not resurrect pollers
            for key in list(self._pollers):
                if key not in want:
                    self._pollers.pop(key).stop()
            for key, s in want.items():
                if key not in self._pollers:
                    self._pollers[key] = XClusterPoller(
                        self, s["replication_id"], s["tablet_id"],
                        s["source_master_addrs"], s["src_table"],
                        s["src_namespace"], s["checkpoint"]).start()

    def report_replication_checkpoint(self, replication_id: str,
                                      tablet_id: str, index: int) -> None:
        client = self.local_client()
        if client is not None:
            try:
                client._master_call("update_replication_checkpoint",
                                    replication_id=replication_id,
                                    tablet_id=tablet_id, index=index)
            except StatusError:
                pass  # retried on the next progress report

    def update_addr_map(self, addr_map: Dict[str, str]) -> None:
        with self._addr_lock:
            self._addr_map.update(addr_map)

    def local_client(self):
        """Lazily built YBClient for tserver-initiated cluster ops (index
        backfill writes; the reference's tservers likewise embed a client,
        ref tserver/tablet_server.cc client_future). Shares this server's
        messenger."""
        with self._addr_lock:
            client = getattr(self, "_local_client", None)
            if client is None and self.opts.master_addrs:
                from yugabyte_tpu.client.client import YBClient
                client = YBClient(self.opts.master_addrs,
                                  messenger=self.messenger)
                self._local_client = client
            return client

    # ------------------------------------------------ transaction plumbing
    def lookup_tablet_leader(self, tablet_id: str) -> Optional[str]:
        """Best-effort leader address for any tablet in the cluster: local
        raft state first, then the master's leader map."""
        from yugabyte_tpu.utils.status import StatusError
        try:
            peer = self.tablet_manager.get_tablet(tablet_id)
            if peer.raft.is_leader():
                return self.address
            hint = peer.raft.leader_hint()
            if hint:
                addr = self._resolve_peer(hint)
                if addr:
                    return addr
        except StatusError:
            pass
        for maddr in self.opts.master_addrs:
            try:
                return self.messenger.call(maddr, "master",
                                           "get_tablet_leader",
                                           timeout_s=3.0,
                                           tablet_id=tablet_id)
            except StatusError:
                continue
        return None

    def resolve_txn_status(self, status_tablet: str, txn_id: bytes,
                           read_ht: Optional[int] = None) -> dict:
        """Status resolver wired into every hosted data tablet (ref
        TransactionStatusResolver). Conservative on any failure: a pending
        answer never exposes uncommitted data. read_ht (the reader's
        snapshot) floors any later commit above it via the coordinator's
        clock."""
        from yugabyte_tpu.utils.status import StatusError
        try:
            peer = self.tablet_manager.get_tablet(status_tablet)
            if peer.raft.is_leader():
                return self.coordinator.status(peer, txn_id, read_ht)
        except StatusError:
            pass
        addr = self.lookup_tablet_leader(status_tablet)
        if addr is None:
            return {"status": "pending", "commit_ht": None}
        try:
            return self.messenger.call(addr, "tserver", "txn_status",
                                       timeout_s=5.0,
                                       tablet_id=status_tablet,
                                       txn_id=txn_id,
                                       observing_read_ht=read_ht)
        except StatusError:
            return {"status": "pending", "commit_ht": None}

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "TabletServer":
        # Encryption-at-rest keys must be available BEFORE bootstrap reads
        # any (possibly encrypted) WAL/SST: fetch the registry from a
        # master first (unavailable masters: proceed; heartbeats retrofit
        # the keys, and encrypted tablets simply cannot serve until then).
        self._fetch_universe_keys()
        # restore the bucket-health board before any tablet opens: open
        # quarantine windows and sticky mismatch marks must gate the very
        # first post-restart compaction (rates re-learn from scratch)
        from yugabyte_tpu.storage.bucket_health import health_board
        health_board().load(self._health_board_path())
        self.tablet_manager.open_existing()
        self.memory_manager.init()
        self.maintenance_manager.init()
        # telemetry timebase: register this server's scrape sources on
        # the process store and ref-count the sampler thread up. The
        # sources take their own snapshots — the serve path never sees
        # the store's lock.
        from yugabyte_tpu.utils.timeseries import timeseries_store
        ts = timeseries_store()
        ts.register_registry(f"server.{self.server_id}", self.metrics)
        ts.register_source(f"overload.{self.server_id}",
                           self._overload_series)
        ts.register_source(f"context.{self.server_id}",
                           self._context_series)
        ts.start()
        self._timeseries_started = True
        if self.opts.master_addrs:
            # Register before serving so the master knows our address by the
            # time it places tablets here.
            self.heartbeater.heartbeat_now()
            self.heartbeater.start()
        return self

    def _fetch_universe_keys(self, deadline_s: float = 10.0) -> None:
        import time as _time
        if not self.opts.master_addrs:
            return
        # only insist on keys when local files actually need them
        need = self._has_encrypted_files()
        deadline = _time.monotonic() + deadline_s
        while _time.monotonic() < deadline:
            for addr in self.opts.master_addrs:
                try:
                    keys = self.messenger.call(addr, "master",
                                               "get_universe_keys",
                                               timeout_s=3.0)
                except Exception:  # noqa: BLE001 — master still starting
                    continue
                if keys:
                    self._apply_universe_keys(keys)
                    return
                if not need:
                    # a keyless universe answered: nothing to wait for
                    return
                # an empty reply in an encrypted universe (e.g. a master
                # without the sidecar): keep asking — bootstrap without
                # keys cannot read the local data
            _time.sleep(0.3)
        if need:
            from yugabyte_tpu.utils.trace import TRACE
            TRACE("ts %s: encrypted files present but no universe keys "
                  "obtained; encrypted tablets will fail closed",
                  self.server_id)

    def _has_encrypted_files(self) -> bool:
        from yugabyte_tpu.utils.env import looks_encrypted
        for dirpath, _dirs, files in os.walk(self.opts.fs_root):
            for f in files:
                if f.startswith("wal-") or ".sst" in f:
                    if looks_encrypted(os.path.join(dirpath, f)):
                        return True
        return False

    def _overload_series(self) -> dict:
        """Flat numeric series of the overload block (queue depth,
        shed counters, memstore consumption) for the time-series
        sampler."""
        snap = self.overloadz()
        out = {}
        for k, v in (snap.get("rpc") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"rpc.{k}"] = float(v)
        mem = snap.get("memstore") or {}
        out["memstore.consumption_bytes"] = float(
            mem.get("consumption_bytes") or 0)
        out["memstore.limit_bytes"] = float(mem.get("limit_bytes") or 0)
        out["write_throttle_rejections.total"] = float(
            snap.get("write_throttle_rejections_total") or 0)
        return out

    def _context_series(self) -> dict:
        """Flat numeric series of the shared execution context: HBM
        device-cache residency and compaction-pool queue state."""
        ctx = self.exec_context
        out = {}
        if ctx is None:
            return out
        if ctx.device_cache is not None:
            for k, v in ctx.device_cache.snapshot().items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"device_cache.{k}"] = float(v)
        pool = getattr(ctx, "compaction_pool", None)
        if pool is not None:
            for k, v in pool.snapshot().items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"pool.{k}"] = float(v)
        return out

    def shutdown(self) -> None:
        if getattr(self, "_timeseries_started", False):
            self._timeseries_started = False
            from yugabyte_tpu.utils.timeseries import timeseries_store
            timeseries_store().stop()
        with self._addr_lock:
            self._shutting_down = True
            pollers = list(getattr(self, "_pollers", {}).values())
        for p in pollers:
            p.stop()
        self.heartbeater.stop()
        self.transport.batcher.stop()
        self.memory_manager.shutdown()
        self.maintenance_manager.shutdown()
        if self.webserver is not None:
            self.webserver.shutdown()
        self.tablet_manager.shutdown()
        # persist the bucket-health board after the last compaction has
        # drained (durable facts only: states, faults, quarantine
        # windows, mismatch reasons — rates restart as WARMING)
        from yugabyte_tpu.storage.bucket_health import health_board
        health_board().save(self._health_board_path())
        if self.exec_context is not None:
            self.exec_context.shutdown()
        self.messenger.shutdown()
