"""YSQL executor: SQL statements -> document operations (the pggate role).

Capability parity with the reference's pggate + pgsql doc operations
(ref: yql/pggate/pggate.h:84 PgApiImpl, pg_doc_op.h:399 PgDocReadOp
request fan-out/paging, pg_session.h:113 op buffering,
docdb/pgsql_operation.cc:729/:366 read/write ops). Per-connection state
(current database, open interactive transaction) lives in PgSession; reads
push WHERE conjunctions down to the tservers (tablet_service.scan filters,
the ybgate-pushdown role) and page across tablets via the client library.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.client.transaction import TransactionError, \
    TransactionManager
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.common.wire import row_matches
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils.status import Code, Status, StatusError
from yugabyte_tpu.yql import index_maintenance as IM
from yugabyte_tpu.yql.pgsql import parser as P

# framework DataType -> PostgreSQL type OID (pg_type.h)
PG_OIDS = {
    DataType.INT64: 20, DataType.INT32: 23, DataType.DOUBLE: 701,
    DataType.FLOAT: 700, DataType.STRING: 25, DataType.BOOL: 16,
    DataType.BINARY: 17, DataType.TIMESTAMP: 1184,
}


class PgResult:
    def __init__(self, tag: str, columns: Optional[List[Tuple[str, int]]] = None,
                 rows: Optional[List[List[object]]] = None):
        self.tag = tag                       # CommandComplete tag
        self.columns = columns               # [(name, type_oid)] or None
        self.rows = rows or []


class PgError(StatusError):
    def __init__(self, status: Status, sqlstate: str = "XX000"):
        super().__init__(status)
        self.sqlstate = sqlstate


_SQLSTATE = {
    Code.INVALID_ARGUMENT: "42601",   # syntax_error
    Code.NOT_FOUND: "42P01",          # undefined_table
    Code.ALREADY_PRESENT: "42P07",    # duplicate_table
    Code.NOT_SUPPORTED: "0A000",      # feature_not_supported
    Code.TRY_AGAIN: "40001",          # serialization_failure
}


def _pg_error(e: StatusError) -> PgError:
    return PgError(e.status, _SQLSTATE.get(e.status.code, "XX000"))


class PgSession:
    """One connection's executor state (ref pg_session.h:113)."""

    def __init__(self, client: YBClient, txn_manager: TransactionManager,
                 database: str = "postgres"):
        self._client = client
        self._txn_manager = txn_manager
        self.database = database
        self._tables: Dict[str, Tuple[YBTable, float]] = {}  # TTL'd cache
        self._txn = None
        self.txn_failed = False
        # PG connects to an EXISTING database; only the default one is
        # auto-created (the initdb role). Unknown names fail with 3D000
        # instead of silently materializing a typo'd namespace.
        if database == "postgres":
            try:
                client.create_namespace(database)
            except StatusError as e:
                if e.status.code != Code.ALREADY_PRESENT:
                    raise
        elif database not in client.list_namespaces():
            raise PgError(Status.NotFound(
                f'database "{database}" does not exist'), "3D000")

    # -------------------------------------------------------------- status
    @property
    def in_txn(self) -> bool:
        return self._txn is not None

    def transaction_status(self) -> str:
        if self.txn_failed:
            return "E"
        return "T" if self._txn is not None else "I"

    # ------------------------------------------------------------- execute
    def execute(self, sql: str) -> List[PgResult]:
        try:
            stmts = P.parse_script(sql)
        except StatusError as e:
            raise _pg_error(e) from e
        out = []
        for stmt in stmts:
            if self.txn_failed and not (
                    isinstance(stmt, P.TxnControl)
                    and stmt.kind in ("commit", "rollback")):
                raise PgError(Status.IllegalState(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block"), "25P02")
            try:
                out.append(self._execute_stmt(stmt))
            except PgError:
                self._fail_txn()
                raise
            except TransactionError as e:
                self._fail_txn()
                raise PgError(e.status, "40001") from e
            except StatusError as e:
                self._fail_txn()
                raise _pg_error(e) from e
        return out

    def _fail_txn(self) -> None:
        if self._txn is not None:
            self.txn_failed = True

    def close(self) -> None:
        if self._txn is not None:
            try:
                self._txn.abort()
            except StatusError:
                pass
            self._txn = None

    # ----------------------------------------------------------- dispatch
    def _execute_stmt(self, stmt: P.Statement) -> PgResult:
        if isinstance(stmt, P.CreateDatabase):
            self._client.create_namespace(stmt.name)
            return PgResult("CREATE DATABASE")
        if isinstance(stmt, P.DropDatabase):
            raise PgError(Status.NotSupported("DROP DATABASE"), "0A000")
        if isinstance(stmt, P.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, P.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, P.DropTable):
            try:
                self._client.delete_table(self.database, stmt.name)
            except StatusError as e:
                if not (stmt.if_exists
                        and e.status.code == Code.NOT_FOUND):
                    raise
            self._tables.pop(stmt.name, None)
            return PgResult("DROP TABLE")
        if isinstance(stmt, P.Insert):
            return self._insert(stmt)
        if isinstance(stmt, P.Select):
            return self._select(stmt)
        if isinstance(stmt, P.Update):
            return self._update(stmt)
        if isinstance(stmt, P.Delete):
            return self._delete(stmt)
        if isinstance(stmt, P.TxnControl):
            return self._txn_control(stmt)
        if isinstance(stmt, P.Show):
            value = {"server_version": "11.2 (yugabyte-tpu)",
                     "transaction_isolation": "repeatable read"}.get(
                         stmt.name.lower(), "")
            return PgResult("SHOW", [(stmt.name, 25)], [[value]])
        raise PgError(Status.NotSupported(str(type(stmt))), "0A000")

    # ---------------------------------------------------------------- DDL
    def _create_table(self, stmt: P.CreateTable) -> PgResult:
        cols_by_name = dict(stmt.columns)
        unknown = [k for k in stmt.pk if k not in cols_by_name]
        if unknown:
            raise PgError(Status.InvalidArgument(
                f"primary key columns not defined: {unknown}"), "42703")
        # YSQL default: first PK column hash-partitions, the rest are
        # range components (ref: YSQL PRIMARY KEY (a HASH, b ASC) default)
        ordered = stmt.pk + [n for n, _t in stmt.columns if n not in stmt.pk]
        columns = [ColumnSchema(n, DataType[cols_by_name[n]])
                   for n in ordered]
        schema = Schema(columns=columns, num_hash_key_columns=1,
                        num_range_key_columns=len(stmt.pk) - 1)
        try:
            self._client.create_table(self.database, stmt.name, schema,
                                      num_tablets=stmt.num_tablets)
        except StatusError as e:
            if not (stmt.if_not_exists
                    and e.status.code == Code.ALREADY_PRESENT):
                raise
        return PgResult("CREATE TABLE")

    def _create_index(self, stmt: P.CreateIndex) -> PgResult:
        index_name = stmt.index_name or f"{stmt.table}_{stmt.column}_idx"
        try:
            self._client.create_index(self.database, stmt.table, index_name,
                                      stmt.column)
        except StatusError as e:
            if not (stmt.if_not_exists
                    and e.status.code == Code.ALREADY_PRESENT):
                raise
        self._tables.pop(stmt.table, None)  # refresh the index list
        return PgResult("CREATE INDEX")

    def _table(self, name: str) -> YBTable:
        """TTL'd table-handle cache: index DDL from other sessions becomes
        visible within the schema-propagation window (see
        yql/cql/executor.py _table)."""
        import time as _time
        from yugabyte_tpu.utils import flags as _flags
        ttl = _flags.get_flag("table_cache_ttl_ms") / 1000.0
        now = _time.monotonic()
        entry = self._tables.get(name)
        if entry is not None and now - entry[1] < ttl:
            return entry[0]
        t = self._client.open_table(self.database, name)
        self._tables[name] = (t, now)
        return t

    # ---------------------------------------------------------------- DML
    def _write(self, table: YBTable, ops: List[QLWriteOp]) -> None:
        if self._txn is not None:
            self._txn.write(table, ops)
        else:
            self._client.write(table, ops)

    def _run_statement_txn(self, body, deadline_s: float = 30.0):
        """Statement-level atomicity: a multi-row UPDATE/DELETE can neither
        partially apply nor clobber a concurrent writer between its scan
        and its writes (see index_maintenance.run_in_implicit_txn)."""
        return IM.run_in_implicit_txn(self._txn_manager, self._txn, body,
                                      deadline_s)

    def _insert(self, stmt: P.Insert) -> PgResult:
        table = self._table(stmt.table)
        schema = table.schema
        columns = stmt.columns or [c.name for c in schema.columns]
        key_names = [c.name for c in schema.hash_columns] + \
            [c.name for c in schema.range_columns]
        ops = []
        for row in stmt.rows:
            if len(row) != len(columns):
                raise PgError(Status.InvalidArgument(
                    "INSERT has more expressions than target columns"),
                    "42601")
            bound = dict(zip(columns, row))
            missing = [k for k in key_names if k not in bound]
            if missing:
                raise PgError(Status.InvalidArgument(
                    f"null value in primary key columns {missing}"),
                    "23502")
            dk = DocKey(
                hash_components=tuple(bound[c.name]
                                      for c in schema.hash_columns),
                range_components=tuple(bound[c.name]
                                       for c in schema.range_columns))
            values = {c: v for c, v in bound.items() if c not in key_names}
            ops.append(QLWriteOp(WriteOpKind.INSERT, dk, values))
        if table.indexes:
            # indexed table: route through a (possibly implicit) transaction
            # maintaining every index (yql/index_maintenance.py)
            def body(txn):
                for op in ops:
                    IM.txn_write_with_indexes(txn, table, op, self._table)
            self._run_statement_txn(body)
            return PgResult(f"INSERT 0 {len(ops)}")
        # batch per destination tablet: one write RPC per tablet touched
        # (ref pg_session.h:222 RunAsync buffering + batcher grouping)
        groups: Dict[str, List[QLWriteOp]] = {}
        for op in ops:
            pk = table.partition_key_for(op.doc_key)
            tid = self._client.meta_cache.lookup_tablet(
                table.table_id, pk).tablet_id
            groups.setdefault(tid, []).append(op)
        for group in groups.values():
            self._write(table, group)
        return PgResult(f"INSERT 0 {len(ops)}")

    # ------------------------------------------------------------- SELECT
    def _split_where(self, table: YBTable,
                     where: List[Tuple[str, str, object]]):
        """-> (doc_key or None, pushdown filters). A full primary key
        (all components bound by equality) becomes a point read; anything
        else is pushed down to the tserver scan (ref ybgate pushdown).

        Exactly ONE equality predicate per key column is consumed into the
        doc key; duplicates (e.g. `id = 1 AND id = 2`) stay in the residual
        and are re-checked against the fetched row, so contradictory
        conjunctions correctly return nothing."""
        schema = table.schema
        key_names = [c.name for c in schema.hash_columns] + \
            [c.name for c in schema.range_columns]
        eq: Dict[str, object] = {}
        consumed: set = set()
        for i, (c, op, v) in enumerate(where):
            if op == "=" and c in key_names and c not in eq:
                eq[c] = v
                consumed.add(i)
        if all(k in eq for k in key_names):
            dk = DocKey(
                hash_components=tuple(eq[c.name]
                                      for c in schema.hash_columns),
                range_components=tuple(eq[c.name]
                                       for c in schema.range_columns))
            residual = [f for i, f in enumerate(where) if i not in consumed]
            return dk, residual
        return None, list(where)

    def _select(self, stmt: P.Select) -> PgResult:
        table = self._table(stmt.table)
        schema = table.schema
        known = {c.name for c in schema.columns}
        out_cols = stmt.columns or [c.name for c in schema.columns]
        for c in out_cols + [f[0] for f in stmt.where]:
            if c not in known:
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")
        col_desc = [(c, PG_OIDS[schema.column(c).type]) for c in out_cols]
        dk, filters = self._split_where(table, stmt.where)
        rows_out: List[List[object]] = []
        if dk is not None:
            if self._txn is not None:
                row = self._txn.read_row(table, dk)
            else:
                row = self._client.read_row(table, dk)
            it = [] if row is None else [row]
            for row in it:
                d = row.to_dict(schema)
                if row_matches(d, filters):
                    rows_out.append([d.get(c) for c in out_cols])
        else:
            # Index-accelerated path: a readable secondary index on an
            # equality predicate replaces the full scan. Skipped inside a
            # transaction block: index_lookup's reads would escape the txn
            # snapshot/overlay (the scan path pins both).
            residual: List = []
            picked = (IM.choose_index(table, [tuple(f) for f in filters])
                      if self._txn is None else None)
            if picked is not None:
                idx, value, residual = picked
                idx_table = self._table(idx.index_name)
                rows = IM.index_lookup(self._client, table, idx_table,
                                       idx, value)
            else:
                rows = self._scan(table, filters)
            count = 0
            for row in rows:
                d = row.to_dict(schema)
                if residual and not row_matches(d, residual):
                    continue
                rows_out.append([d.get(c) for c in out_cols])
                count += 1
                if stmt.limit is not None and count >= stmt.limit:
                    break
        if stmt.count_star:
            return PgResult("SELECT 1", [("count", 20)], [[len(rows_out)]])
        if stmt.limit is not None:
            rows_out = rows_out[: stmt.limit]
        return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)

    # ------------------------------------------------------ UPDATE/DELETE
    def _scan(self, table: YBTable, filters):
        """Paged multi-tablet scan; inside a transaction it pins the txn
        snapshot AND passes the txn id so the scan sees the transaction's
        own provisional writes (same overlay point reads use)."""
        read_ht = None
        txn_id = None
        if self._txn is not None:
            from yugabyte_tpu.common.hybrid_time import HybridTime
            read_ht = HybridTime(self._txn.read_ht)
            txn_id = self._txn.txn_id
        return self._client.scan(table, read_ht=read_ht,
                                 filters=filters or None, txn_id=txn_id)

    def _target_keys(self, table: YBTable,
                     where: List[Tuple[str, str, object]], txn=None):
        """Doc keys matching WHERE: point lookup for a full key, pushed-
        down scan otherwise (PG semantics: UPDATE/DELETE take any WHERE).
        With `txn`, reads pin that transaction's snapshot + overlay."""
        from yugabyte_tpu.common.hybrid_time import HybridTime
        schema = table.schema
        txn = txn or self._txn
        dk, filters = self._split_where(table, where)
        if dk is not None and not filters:
            return [dk]
        if dk is not None:
            row = (txn.read_row(table, dk) if txn
                   else self._client.read_row(table, dk))
            if row is None:
                return []
            d = row.to_dict(schema)
            return [dk] if row_matches(d, filters) else []
        if txn is not None:
            rows = self._client.scan(table, read_ht=HybridTime(txn.read_ht),
                                     filters=filters or None,
                                     txn_id=txn.txn_id)
        else:
            rows = self._scan(table, filters)
        return [row.doc_key for row in rows]

    def _update(self, stmt: P.Update) -> PgResult:
        table = self._table(stmt.table)
        schema = table.schema
        key_names = {c.name for c in schema.hash_columns} | \
            {c.name for c in schema.range_columns}
        bad = [c for c, _v in stmt.assignments if c in key_names]
        if bad:
            # a PK update is a row move (delete+insert); not supported
            raise PgError(Status.NotSupported(
                f"cannot update primary key column(s) {bad}"), "0A000")
        dk, filters = self._split_where(table, stmt.where)
        if (dk is not None and not filters and not table.indexes
                and self._txn is None):
            # point update, no indexes: the single-shard fast path is
            # already atomic
            self._write(table, [QLWriteOp(WriteOpKind.UPDATE, dk,
                                          dict(stmt.assignments))])
            return PgResult("UPDATE 1")

        def body(txn):
            keys = self._target_keys(table, stmt.where, txn)
            for k in keys:
                IM.txn_write_with_indexes(
                    txn, table,
                    QLWriteOp(WriteOpKind.UPDATE, k,
                              dict(stmt.assignments)), self._table)
            return len(keys)

        n = self._run_statement_txn(body)
        return PgResult(f"UPDATE {n}")

    def _delete(self, stmt: P.Delete) -> PgResult:
        table = self._table(stmt.table)
        dk, filters = self._split_where(table, stmt.where)
        if (dk is not None and not filters and not table.indexes
                and self._txn is None):
            self._write(table, [QLWriteOp(WriteOpKind.DELETE_ROW, dk)])
            return PgResult("DELETE 1")

        def body(txn):
            keys = self._target_keys(table, stmt.where, txn)
            for k in keys:
                IM.txn_write_with_indexes(
                    txn, table, QLWriteOp(WriteOpKind.DELETE_ROW, k),
                    self._table)
            return len(keys)

        n = self._run_statement_txn(body)
        return PgResult(f"DELETE {n}")

    # ------------------------------------------------------- transactions
    def _txn_control(self, stmt: P.TxnControl) -> PgResult:
        if stmt.kind == "begin":
            if self._txn is None:
                self._txn = self._txn_manager.begin()
            return PgResult("BEGIN")
        if stmt.kind == "commit":
            txn, self._txn = self._txn, None
            failed, self.txn_failed = self.txn_failed, False
            if txn is None:
                return PgResult("COMMIT")
            if failed:
                txn.abort()
                return PgResult("ROLLBACK")
            txn.commit()
            return PgResult("COMMIT")
        txn, self._txn = self._txn, None
        self.txn_failed = False
        if txn is not None:
            txn.abort()
        return PgResult("ROLLBACK")


