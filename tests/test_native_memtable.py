"""Differential tests: NativeMemTable (C++ arena, native/memtable_arena.cc)
must match the Python MemTable on random workloads — ordering, dict
overwrite semantics, point_get seek semantics, packed/slab exports.
ref: src/yb/rocksdb/db/memtable.cc (arena + skiplist memtable)."""

import random

import numpy as np
import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.storage.memtable import (MemTable, NativeMemTable,
                                           make_internal_key,
                                           native_memtable_available)

pytestmark = pytest.mark.skipif(not native_memtable_available(),
                                reason="no native toolchain")


def _dht(us, w=0):
    return DocHybridTime(HybridTime.from_micros(us), w)


def _rand_items(rng, n, key_space, with_dups=True):
    items = []
    for _ in range(n):
        k = b"Skey%06d\x00\x00!" % rng.randrange(key_space)
        ht = _dht(rng.randrange(1, 5000), rng.randrange(3))
        v = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 24)))
        items.append((k, ht, v))
    if with_dups and items:
        # exact (key, dht) duplicates across batches: latest value wins
        k, ht, _ = items[rng.randrange(len(items))]
        items.append((k, ht, b"winner"))
    return items


def _fill_both(rng, n=400):
    py, nat = MemTable(), NativeMemTable()
    for _ in range(4):
        batch = _rand_items(rng, n // 4, key_space=64)
        py.add_batch(batch)
        nat.add_batch(batch)
    one = _rand_items(rng, 1, key_space=64, with_dups=False)[0]
    py.add(*one)
    nat.add(*one)
    return py, nat


def test_iteration_matches_python():
    rng = random.Random(11)
    py, nat = _fill_both(rng)
    assert list(nat.iter_from(b"")) == list(py.iter_from(b""))
    assert nat.n_entries == py.n_entries
    # mid-stream seek
    keys = [k for k, _ in py.iter_from(b"")]
    seek = keys[len(keys) // 2]
    assert list(nat.iter_from(seek)) == list(py.iter_from(seek))


def test_point_get_matches_python():
    rng = random.Random(12)
    py, nat = _fill_both(rng)
    for i in range(64):
        prefix = b"Skey%06d\x00\x00!" % i
        seek = make_internal_key(prefix, _dht(10**9))
        assert nat.point_get(seek, prefix) == py.point_get(seek, prefix)


def test_to_packed_matches_python():
    rng = random.Random(13)
    py, nat = _fill_both(rng)
    pk, pko, pht, pwid, pv, pvo = py.to_packed()
    nk, nko, nht, nwid, nv, nvo = nat.to_packed()
    assert pk == nk and pv == nv
    np.testing.assert_array_equal(pko, nko)
    np.testing.assert_array_equal(pvo, nvo)
    np.testing.assert_array_equal(pht, nht)
    np.testing.assert_array_equal(pwid, nwid)


def test_to_slab_matches_python():
    from yugabyte_tpu.docdb.value import Value
    rng = random.Random(14)
    py, nat = MemTable(), NativeMemTable()
    for i in range(200):
        k = b"Skey%06d\x00\x00!" % rng.randrange(50)
        ht = _dht(rng.randrange(1, 3000), rng.randrange(2))
        v = Value(primitive=rng.randrange(1000)).encode() \
            if rng.random() < 0.8 else Value.tombstone().encode()
        py.add(k, ht, v)
        nat.add(k, ht, v)
    a, b = py.to_slab(), nat.to_slab()
    assert a.n == b.n
    for i in range(a.n):
        assert a.key_bytes(i) == b.key_bytes(i)
        assert a.doc_ht(i) == b.doc_ht(i)
    np.testing.assert_array_equal(a.flags, b.flags)


def test_add_columns_equals_add_batch():
    rng = random.Random(15)
    items = _rand_items(rng, 300, key_space=40)
    a, b = NativeMemTable(), NativeMemTable()
    a.add_batch(items)
    b.add_columns([k for k, _d, _v in items],
                  np.array([d.ht.value for _k, d, _v in items],
                           dtype=np.uint64),
                  np.array([d.write_id for _k, d, _v in items],
                           dtype=np.uint32),
                  [v for _k, _d, v in items])
    assert list(a.iter_from(b"")) == list(b.iter_from(b""))


def test_iteration_survives_concurrent_add():
    rng = random.Random(16)
    nat = NativeMemTable()
    nat.add_batch(_rand_items(rng, 100, key_space=50, with_dups=False))
    it = nat.iter_from(b"")
    first = [next(it) for _ in range(10)]
    nat.add_batch(_rand_items(rng, 100, key_space=50, with_dups=False))
    rest = list(it)
    got = [k for k, _ in first + rest]
    assert got == sorted(set(got)), "iterator tore under concurrent add"
