from yugabyte_tpu.integration.mini_cluster import MiniCluster, MiniClusterOptions

__all__ = ["MiniCluster", "MiniClusterOptions"]
