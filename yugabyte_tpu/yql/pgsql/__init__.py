"""YSQL: the SQL query layer, served over the PostgreSQL wire protocol.

The reference's flagship API is a PostgreSQL 11 fork whose executor calls
into DocDB through pggate (ref: src/postgres + src/yb/yql/pggate,
ybc_pggate.h:430 YBCPgExecSelect, pg_doc_op.h:399 fan-out/paging). This
framework replaces the forked-Postgres approach with a self-contained
TPU-native SQL layer: a PG-wire v3 server (server.py), a SQL-subset parser
(parser.py), and an executor playing the pggate role (executor.py) —
statement -> document operations over the client library, with WHERE
pushdown to the tservers and paged multi-tablet scans.
"""

from yugabyte_tpu.yql.pgsql.server import PgServer  # noqa: F401
