"""Shared server-process infrastructure (ref: src/yb/server —
RpcAndWebServerBase, webserver, path handlers)."""

from yugabyte_tpu.server.webserver import Webserver

__all__ = ["Webserver"]
