"""MiniCluster integration tests: DDL, routed writes/reads, scan,
leader failover, tserver restart (ref: the reference exercises these in
client/ql-*-test.cc and integration-tests/ over mini_cluster.h)."""

import time

import pytest

from yugabyte_tpu.client.session import YBSession
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[
        ColumnSchema("k", DataType.STRING),
        ColumnSchema("v", DataType.STRING),
        ColumnSchema("n", DataType.INT64),
    ],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 3)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("minicluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def table(cluster):
    client = cluster.new_client()
    client.create_namespace("db")
    table = client.create_table("db", "kv", SCHEMA, num_tablets=4)
    cluster.wait_all_replicas_running(table.table_id)
    # READY-leader deadline poll: module tests write immediately
    cluster.wait_for_table_leaders("db", "kv")
    return table


def test_ddl_and_listing(cluster, table):
    client = cluster.new_client()
    tables = client.list_tables("db")
    assert [t["name"] for t in tables] == ["kv"]
    ts = client.list_tservers()
    assert len(ts) == 3 and all(t["alive"] for t in ts)
    # open_table returns a usable handle
    t2 = client.open_table("db", "kv")
    assert t2.table_id == table.table_id
    assert len(client.meta_cache.tablets(table.table_id)) == 4


def test_write_read_roundtrip(cluster, table):
    client = cluster.new_client()
    for i in range(40):
        client.write(table, [QLWriteOp(
            WriteOpKind.INSERT, dk(f"key{i}"),
            {"v": f"val{i}", "n": i})])
    for i in (0, 7, 39):
        row = client.read_row(table, dk(f"key{i}"))
        assert row is not None
        assert row.columns[SCHEMA.column_id("v")] == f"val{i}"
        assert row.columns[SCHEMA.column_id("n")] == i
    assert client.read_row(table, dk("missing")) is None


def test_ops_span_multiple_tablets(cluster, table):
    """Keys hash across tablets; every tablet leader served some writes."""
    counts = {}
    client = cluster.new_client()
    for i in range(40):
        pk = table.partition_key_for(dk(f"key{i}"))
        t = client.meta_cache.lookup_tablet(table.table_id, pk)
        counts[t.tablet_id] = counts.get(t.tablet_id, 0) + 1
    assert len(counts) >= 3  # 40 uniform keys over 4 tablets


def test_session_batching(cluster, table):
    client = cluster.new_client()
    session = YBSession(client)
    for i in range(60):
        session.apply(table, QLWriteOp(
            WriteOpKind.INSERT, dk(f"batch{i}"), {"v": f"b{i}", "n": i}))
    assert session.flush() == 60
    for i in (0, 31, 59):
        row = client.read_row(table, dk(f"batch{i}"))
        assert row is not None and row.columns[SCHEMA.column_id("v")] == f"b{i}"


def test_scan_all_tablets(cluster, table):
    client = cluster.new_client()
    rows = list(client.scan(table, page_size=16))
    keys = {r.doc_key.hash_components[0] for r in rows}
    assert {f"key{i}" for i in range(40)} <= keys
    assert {f"batch{i}" for i in range(60)} <= keys


def test_update_delete(cluster, table):
    client = cluster.new_client()
    client.write(table, [QLWriteOp(
        WriteOpKind.INSERT, dk("mut"), {"v": "v1", "n": 1})])
    client.write(table, [QLWriteOp(
        WriteOpKind.UPDATE, dk("mut"), {"v": "v2"})])
    row = client.read_row(table, dk("mut"))
    assert row.columns[SCHEMA.column_id("v")] == "v2"
    assert row.columns[SCHEMA.column_id("n")] == 1  # untouched column
    client.write(table, [QLWriteOp(WriteOpKind.DELETE_ROW, dk("mut"))])
    assert client.read_row(table, dk("mut")) is None


def test_tablet_leader_failover(cluster, table):
    """Kill the tserver leading some tablet; writes to it still succeed
    after the remaining replicas elect a new leader."""
    client = cluster.new_client()
    client.write(table, [QLWriteOp(
        WriteOpKind.INSERT, dk("failover-probe"), {"v": "pre", "n": 0})])
    pk = table.partition_key_for(dk("failover-probe"))
    tablet = client.meta_cache.lookup_tablet(table.table_id, pk,
                                             refresh=True)
    victim_idx = next(i for i, ts in enumerate(cluster.tservers)
                      if ts.server_id == tablet.leader)
    victim_id = cluster.tservers[victim_idx].server_id
    cluster.tservers[victim_idx].shutdown()
    # Deadline-poll for the new leader instead of racing the election
    # against the client's retry budget (the known tier-1 timing flake on
    # loaded single-core CI: the election can outlast the retries).
    cluster.wait_for_tablet_leader(tablet.tablet_id,
                                   exclude={victim_id})
    client.write(table, [QLWriteOp(
        WriteOpKind.INSERT, dk("failover-probe"), {"v": "post", "n": 1})])
    row = client.read_row(table, dk("failover-probe"))
    assert row.columns[SCHEMA.column_id("v")] == "post"
    # Restore cluster for subsequent tests (same data dirs).
    cluster.restart_tablet_server(victim_idx)


def test_tserver_restart_recovers_data(cluster, table):
    """Full stop + restart of a tserver: WAL replay brings its replicas
    back; reads still see every row."""
    client = cluster.new_client()
    client.write(table, [QLWriteOp(
        WriteOpKind.INSERT, dk("durable"), {"v": "kept", "n": 5})])
    cluster.restart_tablet_server(0)
    row = client.read_row(table, dk("durable"))
    assert row is not None and row.columns[SCHEMA.column_id("v")] == "kept"


def test_delete_table_cleans_replicas(cluster):
    client = cluster.new_client()
    t = client.create_table("db", "ephemeral", SCHEMA, num_tablets=2)
    cluster.wait_all_replicas_running(t.table_id)
    tablet_ids = {x.tablet_id for x in client.meta_cache.tablets(t.table_id)}
    client.delete_table("db", "ephemeral")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        hosted = {tid for ts in cluster.tservers
                  for tid in ts.tablet_manager.tablet_ids()}
        if not (tablet_ids & hosted):
            break
        time.sleep(0.1)
    assert not (tablet_ids & {tid for ts in cluster.tservers
                              for tid in ts.tablet_manager.tablet_ids()})
