"""NemesisController: scheduled fault windows over a running MiniCluster.

The Jepsen-style driver for the chaos layer: it binds a MiniCluster's
endpoints into the process-global nemesis rule table (rpc/nemesis.py) so
fault rules can be written in server ids ("ts0", "m0"), and exposes the
fault vocabulary chaos tests compose into windows:

  - network: symmetric/one-way partitions, probabilistic drops, latency
    and duplicate delivery on any (src, dst) server pair; leader
    partition by tablet id;
  - process: tserver crash (shutdown) + restart over the same data dirs
    (WAL replay / remote-bootstrap recovery underneath);
  - storage/device: ENOSPC via utils/env.FaultInjectionEnv and device
    faults via ops/device_faults — armed per window.

`run_window` applies one fault, holds it for the window, heals, and
waits for convergence; `capture_terms`/`check_terms_monotonic` and
`wait_all_healthy` are the invariant probes the soak asserts between
windows (every acknowledged write readable, raft terms monotonic, all
tablets RUNNING, no leaked staging leases).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from yugabyte_tpu.rpc import nemesis
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE


class NemesisController:
    """Owns the installed fault-rule table for one MiniCluster."""

    def __init__(self, cluster, seed: int = 0):
        self.cluster = cluster
        self.rules = nemesis.install(seed=seed)
        self.refresh_endpoints()

    # --------------------------------------------------------------- naming
    def refresh_endpoints(self) -> None:
        """(Re)bind wire addresses and messenger names to server ids —
        call after any tserver restart (ephemeral ports change)."""
        for m in self.cluster.masters:
            self.rules.register_endpoint(m.address, m.master_id)
            self.rules.register_endpoint(m.messenger.name, m.master_id)
        for ts in self.cluster.tservers:
            self.rules.register_endpoint(ts.address, ts.server_id)
            self.rules.register_endpoint(ts.messenger.name, ts.server_id)

    def close(self) -> None:
        self.rules.heal()
        nemesis.uninstall()

    # --------------------------------------------------------------- faults
    def partition(self, a: str, b: str, one_way: bool = False) -> None:
        TRACE("nemesis: partition %s %s %s", a,
              "->" if one_way else "<->", b)
        self.rules.partition(a, b, one_way=one_way)

    def isolate(self, server_id: str) -> None:
        TRACE("nemesis: isolate %s", server_id)
        self.rules.isolate(server_id)

    def drop(self, src: str, dst: str, prob: float,
             response: bool = False) -> None:
        self.rules.drop(src, dst, prob, response=response)

    def latency(self, src: str, dst: str, delay_s: float,
                jitter_s: float = 0.0) -> None:
        self.rules.latency(src, dst, delay_s, jitter_s=jitter_s)

    def duplicate(self, src: str, dst: str, prob: float) -> None:
        self.rules.duplicate(src, dst, prob)

    def heal(self) -> None:
        TRACE("nemesis: heal")
        self.rules.heal()

    def partition_leader(self, tablet_id: str,
                         timeout_s: float = 30.0) -> str:
        """Partition the tablet's current raft leader from every OTHER
        tserver (client and master links stay up, so writes keep
        arriving at a leader that can no longer commit — the classic
        stale-leader window). Returns the partitioned server id."""
        leader = self.cluster.wait_for_tablet_leader(tablet_id,
                                                     timeout_s=timeout_s)
        for ts in self.cluster.tservers:
            if ts.server_id != leader:
                self.partition(leader, ts.server_id)
        TRACE("nemesis: partitioned leader %s of tablet %s",
              leader, tablet_id)
        return leader

    def kill_tserver(self, index: int):
        """Crash-stop a tserver (no graceful drain of its tablets: the
        cluster must survive the loss, not be told about it)."""
        ts = self.cluster.tservers[index]
        TRACE("nemesis: killing tserver %s", ts.server_id)
        ts.shutdown()
        return ts

    def restart_tserver(self, index: int):
        """Restart a killed tserver over the same data dirs (WAL replay +
        catalog re-registration) and rebind its new endpoints."""
        ts = self.cluster.restart_tablet_server(index)
        self.refresh_endpoints()
        return ts

    # --------------------------------------------------------- fault windows
    def run_window(self, apply_fault, duration_s: float,
                   heal_after: bool = True) -> None:
        """One scheduled fault window: apply, hold, heal."""
        apply_fault()
        time.sleep(duration_s)
        if heal_after:
            self.heal()

    # ------------------------------------------------------------ invariants
    def capture_terms(self) -> Dict[Tuple[str, str], int]:
        """(server_id, tablet_id) -> current raft term, across live
        tservers; tablets mid-shutdown are skipped."""
        terms: Dict[Tuple[str, str], int] = {}
        for ts in self.cluster.tservers:
            try:
                for tid in ts.tablet_manager.tablet_ids():
                    peer = ts.tablet_manager.get_tablet(tid)
                    terms[(ts.server_id, tid)] = int(
                        peer.raft.current_term)
            except Exception:  # yblint: contained(server mid-restart during capture: probe skips it; the next capture sees it again)
                continue
        return terms

    @staticmethod
    def check_terms_monotonic(before: Dict[Tuple[str, str], int],
                              after: Dict[Tuple[str, str], int]) -> None:
        """Raft safety probe: a peer's term never regresses across a
        fault window (a regression means state was lost or forked)."""
        for key, t0 in before.items():
            t1 = after.get(key)
            if t1 is not None and t1 < t0:
                raise AssertionError(
                    f"raft term regressed on {key}: {t0} -> {t1}")

    def wait_all_healthy(self, table_id: str,
                         timeout_s: float = 60.0) -> None:
        """Block until every replica of the table is created, RUNNING
        (not FAILED) and has a ready leader — the end-of-cycle
        convergence bar of the chaos soak."""
        from yugabyte_tpu.tablet.tablet_peer import STATE_FAILED
        deadline = time.monotonic() + timeout_s
        self.cluster.wait_all_replicas_running(
            table_id, timeout_s=timeout_s)
        while True:
            failed: List[str] = []
            for ts in self.cluster.tservers:
                try:
                    for tid in ts.tablet_manager.tablet_ids():
                        peer = ts.tablet_manager.get_tablet(tid)
                        if peer.state == STATE_FAILED:
                            failed.append(f"{ts.server_id}/{tid}")
                except Exception:  # yblint: contained(server mid-restart: re-probed until the deadline)
                    failed.append(f"{ts.server_id}/?")
            if not failed:
                return
            if time.monotonic() > deadline:
                raise StatusError(Status.TimedOut(
                    f"tablets still unhealthy after heal: {failed}"))
            time.sleep(0.1)
