"""Consensus layer: segmented WAL + Raft replication + leader leases.

TPU-native re-expression of src/yb/consensus (RaftConsensus, Log,
PeerMessageQueue, LeaderElection). The WAL doubles as Raft storage exactly
like the reference (ref: consensus/log.h:104-113) — there is no separate
RocksDB WAL; the Raft index becomes the storage frontier.
"""

from yugabyte_tpu.consensus.log import Log, LogEntry, LogReader
from yugabyte_tpu.consensus.raft import (
    NotLeader, OperationOutcomeUnknown, OpId, RaftConsensus, RaftConfig,
    ReplicationAborted, ReplicationTimedOut, Role)
from yugabyte_tpu.consensus.transport import LocalTransport
