"""Tier-2 schedule-perturbation harness: re-run the most concurrent
tier-1 suites under hostile interleavings with ybsan armed.

Each seed runs a subprocess pytest with `YBSAN=1 YBSAN_PERTURB=1`:
sync_point.hit() injects seeded preemption sleeps and the switch
interval shrinks to 10us, so thread schedules that CI timing would
never produce get exercised. Exit code 0 requires BOTH every suite's
own assertions (acked writes stay durable, failovers converge) AND the
armed session gate (zero unbaselined race reports).

tests/test_ybsan.py is deliberately absent from the suite list — its
positive fixtures are races by construction.
"""

import os
import subprocess
import sys

import pytest

_SUITES = [
    "tests/test_bucket_health.py",
    "tests/test_compaction_pool.py",
    "tests/test_multi_raft_and_compression.py",
    "tests/test_consensus.py",
]

_SEEDS = [1, 2, 3]


@pytest.mark.slow
@pytest.mark.parametrize("seed", _SEEDS)
def test_schedule_fuzz_seed(seed):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               YBSAN="1",
               YBSAN_PERTURB="1",
               YBSAN_PERTURB_SEED=str(seed))
    r = subprocess.run(
        [sys.executable, "-m", "pytest", *_SUITES, "-q", "-m", "not slow",
         "-p", "no:cacheprovider", "-p", "no:randomly"],
        env=env, capture_output=True, text=True, timeout=540,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, (
        f"seed {seed}: perturbed armed run failed (rc={r.returncode})\n"
        + r.stdout[-4000:] + "\n" + r.stderr[-4000:])
