"""YSQL executor: SQL statements -> document operations (the pggate role).

Capability parity with the reference's pggate + pgsql doc operations
(ref: yql/pggate/pggate.h:84 PgApiImpl, pg_doc_op.h:399 PgDocReadOp
request fan-out/paging, pg_session.h:113 op buffering,
docdb/pgsql_operation.cc:729/:366 read/write ops). Per-connection state
(current database, open interactive transaction) lives in PgSession; reads
push WHERE conjunctions down to the tservers (tablet_service.scan filters,
the ybgate-pushdown role) and page across tablets via the client library.
"""

from __future__ import annotations

import datetime
import threading
import time
from typing import Dict, List, Optional, Tuple

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.client.transaction import TransactionError, \
    TransactionManager
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.common.wire import row_matches
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils.status import Code, Status, StatusError
from yugabyte_tpu.yql import index_maintenance as IM
from yugabyte_tpu.yql.pgsql import parser as P

# framework DataType -> PostgreSQL type OID (pg_type.h)
PG_OIDS = {
    DataType.INT64: 20, DataType.INT32: 23, DataType.DOUBLE: 701,
    DataType.FLOAT: 700, DataType.STRING: 25, DataType.BOOL: 16,
    # 1114 = timestamp WITHOUT time zone: matches the offset-less text
    # pg_micros_text emits (1184/timestamptz clients would expect '+00')
    DataType.BINARY: 17, DataType.TIMESTAMP: 1114,
    DataType.JSONB: 3802,
}

_EPOCH = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def pg_timestamp_micros(text: str) -> int:
    """'YYYY-MM-DD[ HH:MM[:SS[.ffffff]]][+HH[:MM]]' -> epoch micros.
    Timezone-less input is read as UTC (the session default; the reference
    stores timestamptz normalized to UTC, ref src/postgres timestamptz_in)."""
    import re
    text = text.strip()
    # Python < 3.11 fromisoformat accepts only 3- or 6-digit fractional
    # seconds while PG accepts 1-6 ('12:00:00.25'): zero-pad to 6 first.
    m = re.match(r"^(.*[T ]\d{2}:\d{2}:\d{2})\.(\d{1,6})(.*)$", text)
    if m:
        text = f"{m.group(1)}.{m.group(2).ljust(6, '0')}{m.group(3)}"
    try:
        dt = datetime.datetime.fromisoformat(text)
    except ValueError:
        raise PgError(Status.InvalidArgument(
            f'invalid input syntax for type timestamp: "{text}"'), "22007")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return int((dt - _EPOCH) / datetime.timedelta(microseconds=1))


def pg_micros_text(micros: int) -> str:
    """Epoch micros -> PG text output ('YYYY-MM-DD HH:MM:SS[.ffffff]')."""
    dt = _EPOCH + datetime.timedelta(microseconds=micros)
    out = dt.strftime("%Y-%m-%d %H:%M:%S")
    if dt.microsecond:
        out += f".{dt.microsecond:06d}".rstrip("0")
    return out


def pg_coerce(col_type: Optional[DataType], v: object) -> object:
    """Coerce a literal to the column's storage type at the statement
    boundary (the ybgate equivalent of PG's input-function coercion):
    timestamp text -> epoch micros, int literal -> double for NUMERIC/
    DOUBLE columns, integral float -> int for bigint columns."""
    if v is None or col_type is None:
        return v
    if isinstance(v, (list, tuple)):
        if len(v) == 2 and v[0] == "__expr__":  # expression sentinel
            return v
        return type(v)(pg_coerce(col_type, x) for x in v)
    if col_type == DataType.TIMESTAMP and isinstance(v, str):
        return pg_timestamp_micros(v)
    if col_type == DataType.JSONB:
        from yugabyte_tpu.common import jsonb
        try:
            return jsonb.canonicalize(v)
        except ValueError as e:
            raise PgError(Status.InvalidArgument(
                f"invalid input syntax for type json: {e}"), "22P02")
    if col_type == DataType.DOUBLE and isinstance(v, int) \
            and not isinstance(v, bool):
        return float(v)
    if col_type in (DataType.INT64, DataType.INT32) \
            and isinstance(v, float) and v.is_integer():
        return int(v)
    return v


class PgResult:
    def __init__(self, tag: str, columns: Optional[List[Tuple[str, int]]] = None,
                 rows: Optional[List[List[object]]] = None,
                 row_iter=None):
        self.tag = tag                       # CommandComplete tag
        self.columns = columns               # [(name, type_oid)] or None
        self.rows = rows or []
        # Lazy alternative to `rows` for portal execution: an iterator the
        # server pulls max_rows at a time (Execute row limit + Portal-
        # Suspended; ref the PG backend's ExecutorRun count semantics).
        # When set, `rows` is empty and the tag is composed by the server
        # as "SELECT <total>" on portal completion.
        self.row_iter = row_iter


class PgError(StatusError):
    def __init__(self, status: Status, sqlstate: str = "XX000"):
        super().__init__(status)
        self.sqlstate = sqlstate


_SQLSTATE = {
    Code.INVALID_ARGUMENT: "42601",   # syntax_error
    Code.NOT_FOUND: "42P01",          # undefined_table
    Code.ALREADY_PRESENT: "42P07",    # duplicate_table
    Code.NOT_SUPPORTED: "0A000",      # feature_not_supported
    Code.TRY_AGAIN: "40001",          # serialization_failure
}


def _pg_error(e: StatusError) -> PgError:
    return PgError(e.status, _SQLSTATE.get(e.status.code, "XX000"))


def _page_rows(rows_out, stmt):
    """OFFSET before LIMIT (PG evaluation order)."""
    off = getattr(stmt, "offset", 0) or 0
    if off:
        rows_out = rows_out[off:]
    if stmt.limit is not None:
        rows_out = rows_out[: stmt.limit]
    return rows_out


def _group_cols(group_by):
    """GROUP BY as a list: None -> [], str -> [c], tuple -> [c1, c2...]."""
    if not group_by:
        return []
    return list(group_by) if isinstance(group_by, tuple) else [group_by]


def _map_group_by(group_by, fn):
    """Apply fn over each group column, preserving the None/str/tuple
    shape contract."""
    if not group_by:
        return group_by
    if isinstance(group_by, tuple):
        return tuple(fn(c) for c in group_by)
    return fn(group_by)


def _project_group_output(stmt, col_desc, rows_out):
    """Reorder/subset the aggregate output to the SELECT list (PG allows
    any subset/order of the group columns; aggregates keep their
    positions after them). Raises 42803 for non-grouped columns."""
    gcols = _group_cols(stmt.group_by)
    sel = stmt.columns
    if not sel or list(sel) == gcols:
        return col_desc, rows_out
    if not set(sel) <= set(gcols):
        raise PgError(Status.InvalidArgument(
            "non-aggregated columns must appear in GROUP BY"), "42803")
    idx = [gcols.index(c) for c in sel] \
        + list(range(len(gcols), len(col_desc)))
    return ([col_desc[i] for i in idx],
            [[r[i] for i in idx] for r in rows_out])


def _dedup_rows(rows_out):
    """First-occurrence dedup preserving order (SELECT DISTINCT applied
    after projection, like PG's unique node over the sorted/plain path)."""
    seen = set()
    out = []
    for r in rows_out:
        key = tuple(r)
        if key not in seen:
            seen.add(key)
            out.append(r)
    return out


class _Cursor:
    """One DECLARE'd cursor (the PG portal): a lazy row iterator, its
    column headers, the WITH HOLD flag, and whether the remaining rows
    were already persisted (PG's PersistHoldablePortal at commit)."""

    __slots__ = ("columns", "it", "hold", "materialized")

    def __init__(self, columns, it, hold: bool):
        self.columns = columns
        self.it = it
        self.hold = hold
        self.materialized = False

    def materialize(self) -> None:
        """Drain the lazy scan into memory; idempotent. Must run while the
        creating transaction's snapshot is still valid."""
        if not self.materialized:
            self.it = iter(list(self.it))
            self.materialized = True


class PgSession:
    """One connection's executor state (ref pg_session.h:113)."""

    def __init__(self, client: YBClient, txn_manager: TransactionManager,
                 database: str = "postgres"):
        self._client = client
        self._txn_manager = txn_manager
        self.database = database
        self._tables: Dict[str, Tuple[YBTable, float]] = {}  # TTL'd cache
        self._txn = None
        self.txn_failed = False
        # bumped at every transaction boundary; suspended portals created
        # under an older epoch are invalid (see server._execute_portal)
        self.txn_epoch = 0
        # DECLARE'd cursors; non-hold cursors die at transaction end,
        # WITH HOLD survive (materialized at the creating txn's commit)
        self._cursors: Dict[str, _Cursor] = {}
        # SQL-level PREPARE registry (ref: PG commands/prepare.c) —
        # session-scoped, separate from the wire protocol's named
        # statements
        self._prepared: Dict[str, object] = {}
        self._view_depth = 0  # stacked-view recursion guard
        # per-statement view materialization memo (cleared at each
        # top-level execute entry)
        self._view_memo: Dict[str, tuple] = {}
        # PG connects to an EXISTING database; only the default one is
        # auto-created (the initdb role). Unknown names fail with 3D000
        # instead of silently materializing a typo'd namespace.
        if database == "postgres":
            try:
                client.create_namespace(database)
            except StatusError as e:
                if e.status.code != Code.ALREADY_PRESENT:
                    raise
        elif database not in client.list_namespaces():
            raise PgError(Status.NotFound(
                f'database "{database}" does not exist'), "3D000")

    # -------------------------------------------------------------- status
    @property
    def in_txn(self) -> bool:
        return self._txn is not None

    def transaction_status(self) -> str:
        if self.txn_failed:
            return "E"
        return "T" if self._txn is not None else "I"

    # ------------------------------------------------------------- execute
    def execute(self, sql: str) -> List[PgResult]:
        try:
            stmts = P.parse_script(sql)
        except StatusError as e:
            raise _pg_error(e) from e
        out = []
        for stmt in stmts:
            self._view_memo.clear()  # each statement runs views afresh
            if self.txn_failed and not (
                    isinstance(stmt, P.TxnControl)
                    and stmt.kind in ("commit", "rollback")):
                raise PgError(Status.IllegalState(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block"), "25P02")
            try:
                out.append(self._execute_stmt(stmt))
            except PgError:
                self._fail_txn()
                raise
            except TransactionError as e:
                self._fail_txn()
                raise PgError(e.status, "40001") from e
            except StatusError as e:
                self._fail_txn()
                raise _pg_error(e) from e
        return out

    def _fail_txn(self) -> None:
        if self._txn is not None:
            self.txn_failed = True

    def close(self) -> None:
        if self._txn is not None:
            try:
                self._txn.abort()
            except StatusError:
                pass
            self._txn = None

    def execute_bound(self, stmt: P.Statement, params: List[object],
                      stream: bool = False) -> PgResult:
        """Extended-query-protocol execution: one pre-parsed statement with
        $n placeholders bound to `params` (ref: the reference's PG backend
        exec_bind_message/exec_execute_message path).

        stream=True (portal execution): an eligible SELECT — no
        aggregation/ordering, which need the full match set — returns a
        PgResult with row_iter instead of rows, so Execute row limits pull
        incrementally and a suspended portal holds no materialized
        result."""
        self._view_memo.clear()  # each statement runs views afresh
        bound = P.bind_params(stmt, params)
        if self.txn_failed and not (
                isinstance(bound, P.TxnControl)
                and bound.kind in ("commit", "rollback")):
            raise PgError(Status.IllegalState(
                "current transaction is aborted, commands ignored until "
                "end of transaction block"), "25P02")
        if stream and isinstance(bound, P.Select):
            try:
                streamed = self._select_stream(bound)
            except PgError:
                self._fail_txn()
                raise
            except StatusError as e:
                self._fail_txn()
                raise _pg_error(e) from e
            if streamed is not None:
                return streamed
        try:
            return self._execute_stmt(bound)
        except PgError:
            self._fail_txn()
            raise
        except TransactionError as e:
            self._fail_txn()
            raise PgError(e.status, "40001") from e
        except StatusError as e:
            self._fail_txn()
            raise _pg_error(e) from e

    def param_types(self, stmt: P.Statement) -> List[Optional[DataType]]:
        """DataType per $n placeholder (1-based, None where unknown):
        the analysis that types bind variables against the schema."""
        pairs = P.collect_param_columns(stmt)
        if not pairs:
            return []
        n = max(i for i, _c in pairs)
        out: List[Optional[DataType]] = [None] * n
        table_name = getattr(stmt, "table", None)
        schema = None
        if table_name:
            try:
                schema = self._table(table_name).schema
            except StatusError:
                schema = None
        for idx, col in pairs:
            if col == "__limit__":
                out[idx - 1] = DataType.INT64
            elif schema is None:
                continue
            elif isinstance(col, tuple) and col[0] == "pos":
                # INSERT without a column list: the placeholder's position
                # WITHIN ITS ROW picks the target column
                if col[1] < len(schema.columns):
                    out[idx - 1] = schema.columns[col[1]].type
            elif isinstance(col, str):
                try:
                    out[idx - 1] = schema.column(col).type
                except KeyError:
                    pass
        return out

    def describe_columns(self, stmt: P.Statement
                         ) -> Optional[List[Tuple[str, int]]]:
        """RowDescription for a statement BEFORE execution (the extended
        protocol's Describe), or None for row-less statements."""
        if isinstance(stmt, P.ExecuteStmt):
            # Describe of EXECUTE answers for the prepared inner
            # statement; unknown names error here, like PG (26000)
            inner = self._prepared.get(stmt.name)
            if inner is None:
                raise PgError(Status.NotFound(
                    f'prepared statement "{stmt.name}" does not exist'),
                    "26000")
            return self.describe_columns(inner)
        if isinstance(stmt, (P.Insert, P.Update, P.Delete)) \
                and stmt.returning:
            # RETURNING produces rows: Describe must announce them or
            # the later DataRows violate the protocol
            schema = self._table(stmt.table).schema
            return self._returning_cols(schema, stmt.returning)[1]
        if not isinstance(stmt, (P.Select, P.Show)):
            return None
        if isinstance(stmt, P.Show):
            return [(stmt.name, 25)]
        if getattr(stmt, "table", None) is None and stmt.scalar_items:
            # FROM-less scalar SELECT (`SELECT 1`): there is no table to
            # look up — compile the scalar items over an empty schema,
            # exactly as _select does at execution time (this used to fall
            # through to the virtual-table lookup and raise
            # AttributeError on None.lower())
            col_desc, _rows = self._project_scalar(
                stmt.scalar_items, Schema(columns=[]), [])
            return col_desc
        vt = self._virtual_table_rows(stmt.table)
        if vt is not None:
            cols, _rows = vt
            by_name = dict(cols)
            if stmt.count_star:
                return [("count", 20)]
            if stmt.aggregates or stmt.group_by:
                desc, _ = self._aggregate(stmt,
                                          lambda c: by_name.get(c, 25), [])
                desc, _rows = _project_group_output(stmt, desc, [])
                return desc
            out_cols = stmt.columns or [c for c, _o in cols]
            return [(c, by_name.get(c, 25)) for c in out_cols]
        if stmt.count_star:
            return [("count", 20)]
        schema = self._table(stmt.table).schema
        if stmt.aggregates or stmt.group_by:
            desc, _ = self._aggregate(
                stmt, lambda c: PG_OIDS[schema.column(c).type], [])
            desc, _rows = _project_group_output(stmt, desc, [])
            return desc
        out_cols = stmt.columns or [c.name for c in schema.columns
                                    if not c.dropped]
        return [(c, PG_OIDS[schema.column(c).type]) for c in out_cols]

    # ----------------------------------------------------------- dispatch
    def _execute_stmt(self, stmt: P.Statement) -> PgResult:
        if isinstance(stmt, P.CreateDatabase):
            self._client.create_namespace(stmt.name)
            return PgResult("CREATE DATABASE")
        if isinstance(stmt, P.DropDatabase):
            raise PgError(Status.NotSupported("DROP DATABASE"), "0A000")
        if isinstance(stmt, P.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, P.CreateSequence):
            try:
                self._client.create_sequence(
                    self.database, stmt.name, start=stmt.start,
                    if_not_exists=stmt.if_not_exists)
            except StatusError as e:
                if e.status.code != Code.ALREADY_PRESENT:
                    raise _pg_error(e) from e
                if not stmt.if_not_exists:
                    raise PgError(Status.AlreadyPresent(
                        f'sequence "{stmt.name}" already exists'),
                        "42P07") from e
            return PgResult("CREATE SEQUENCE")
        if isinstance(stmt, P.DropSequence):
            try:
                self._client.drop_sequence(self.database, stmt.name,
                                           if_exists=stmt.if_exists)
            except StatusError as e:
                if e.status.code != Code.NOT_FOUND:
                    raise _pg_error(e) from e
                if not stmt.if_exists:
                    raise PgError(Status.NotFound(
                        f'sequence "{stmt.name}" does not exist'),
                        "42P01") from e
            return PgResult("DROP SEQUENCE")
        if isinstance(stmt, P.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, P.DropTable):
            owned_seqs = []
            try:
                t = self._table(stmt.name)
                owned_seqs = [c.default_seq for c in t.schema.columns
                              if c.default_seq]
            except StatusError:
                pass
            try:
                self._client.delete_table(self.database, stmt.name)
            except StatusError as e:
                if not (stmt.if_exists
                        and e.status.code == Code.NOT_FOUND):
                    raise
            for seq in owned_seqs:  # PG drops owned sequences with the table
                self._client.drop_sequence(self.database, seq,
                                           if_exists=True)
            self._tables.pop(stmt.name, None)
            return PgResult("DROP TABLE")
        if isinstance(stmt, P.Explain):
            return self._explain(stmt)
        if isinstance(stmt, P.Truncate):
            return self._truncate(stmt)
        if isinstance(stmt, P.CreateView):
            # defining SELECT already validated by the parser; a view may
            # not shadow an existing table (the catalog checks too)
            try:
                self._client.create_view(self.database, stmt.name,
                                         stmt.sql, stmt.or_replace)
            except StatusError as e:
                raise _pg_error(e) from e
            return PgResult("CREATE VIEW")
        if isinstance(stmt, P.DropView):
            try:
                self._client.drop_view(self.database, stmt.name,
                                       stmt.if_exists)
            except StatusError as e:
                raise _pg_error(e) from e
            return PgResult("DROP VIEW")
        if isinstance(stmt, P.PrepareStmt):
            if stmt.name in self._prepared:
                raise PgError(Status.AlreadyPresent(
                    f'prepared statement "{stmt.name}" already exists'),
                    "42P05")
            self._prepared[stmt.name] = stmt.stmt
            return PgResult("PREPARE")
        if isinstance(stmt, P.ExecuteStmt):
            inner = self._prepared.get(stmt.name)
            if inner is None:
                raise PgError(Status.NotFound(
                    f'prepared statement "{stmt.name}" does not exist'),
                    "26000")
            need = P.max_param_idx(inner)
            if len(stmt.params) != need:
                raise PgError(Status.InvalidArgument(
                    f'wrong number of parameters for prepared statement '
                    f'"{stmt.name}": expected {need}, '
                    f'got {len(stmt.params)}'), "42601")
            return self._execute_stmt(P.bind_params(inner, stmt.params))
        if isinstance(stmt, P.DeallocateStmt):
            if stmt.name is None:
                self._prepared.clear()
            elif self._prepared.pop(stmt.name, None) is None:
                raise PgError(Status.NotFound(
                    f'prepared statement "{stmt.name}" does not exist'),
                    "26000")
            return PgResult("DEALLOCATE")
        if isinstance(stmt, P.Insert):
            return self._insert(stmt)
        if isinstance(stmt, (P.Select, P.UnionSelect)):
            return self._select(stmt)
        if isinstance(stmt, P.Update):
            return self._update(stmt)
        if isinstance(stmt, P.Delete):
            return self._delete(stmt)
        if isinstance(stmt, P.TxnControl):
            return self._txn_control(stmt)
        if isinstance(stmt, P.Show):
            value = {"server_version": "11.2 (yugabyte-tpu)",
                     "transaction_isolation": "repeatable read"}.get(
                         stmt.name.lower(), "")
            return PgResult("SHOW", [(stmt.name, 25)], [[value]])
        if isinstance(stmt, P.AlterTable):
            return self._alter_table(stmt)
        if isinstance(stmt, P.DeclareCursor):
            return self._declare_cursor(stmt)
        if isinstance(stmt, P.FetchCursor):
            return self._fetch_cursor(stmt)
        if isinstance(stmt, P.CloseCursor):
            if stmt.name not in self._cursors:
                raise PgError(Status.InvalidArgument(
                    f'cursor "{stmt.name}" does not exist'), "34000")
            del self._cursors[stmt.name]
            return PgResult("CLOSE CURSOR")
        raise PgError(Status.NotSupported(str(type(stmt))), "0A000")

    # -------------------------------------------------------------- ALTER
    def _alter_table(self, stmt: P.AlterTable) -> PgResult:
        """Online ADD/DROP COLUMN riding the master's versioned schema
        change (catalog_manager.alter_table; ref the PG ALTER TABLE path
        landing in CatalogManager::AlterTable)."""
        try:
            # parser carries DataType NAMES ("INT32"); the master's wire
            # takes enum values ("int32")
            for _c, t in stmt.add_columns:
                if t == "SERIAL":
                    raise PgError(Status.NotSupported(
                        "ALTER TABLE ADD COLUMN ... SERIAL"), "0A000")
            self._client.alter_table(
                self.database, stmt.table,
                add_columns=[(c, DataType[t].value)
                             for c, t in stmt.add_columns],
                drop_columns=stmt.drop_columns)
        except StatusError as e:
            raise _pg_error(e) from e
        self._tables.pop(stmt.table, None)   # next use sees the new schema
        return PgResult("ALTER TABLE")

    # ------------------------------------------------------------ cursors
    def _declare_cursor(self, stmt: P.DeclareCursor) -> PgResult:
        """DECLARE ... CURSOR FOR SELECT: the cursor holds a lazy iterator
        (streaming plan where eligible), pulled by FETCH in page-sized
        bites (ref the PG portal machinery these map onto)."""
        if stmt.name in self._cursors:
            raise PgError(Status.InvalidArgument(
                f'cursor "{stmt.name}" already exists'), "42P03")
        streamed = self._select_stream(stmt.select)
        if streamed is None:
            materialized = self._select(stmt.select)
            streamed = PgResult(materialized.tag, materialized.columns,
                                row_iter=iter(materialized.rows))
        cur = _Cursor(streamed.columns, streamed.row_iter, stmt.hold)
        if stmt.hold and self._txn is None:
            # autocommit: the implicit transaction around DECLARE ends
            # with the statement — persist the holdable portal NOW, as PG
            # does at the end of the creating transaction, so later writes
            # never leak into the held result set
            cur.materialize()
        self._cursors[stmt.name] = cur
        return PgResult("DECLARE CURSOR")

    def _fetch_cursor(self, stmt: P.FetchCursor) -> PgResult:
        cur = self._cursors.get(stmt.name)
        if cur is None:
            raise PgError(Status.InvalidArgument(
                f'cursor "{stmt.name}" does not exist'), "34000")
        rows = []
        while stmt.count is None or len(rows) < stmt.count:
            try:
                rows.append(next(cur.it))
            except StopIteration:
                break
        return PgResult(f"FETCH {len(rows)}", cur.columns, rows)

    # ---------------------------------------------------------------- DDL
    def _create_table(self, stmt: P.CreateTable) -> PgResult:
        cols_by_name = dict(stmt.columns)
        unknown = [k for k in stmt.pk if k not in cols_by_name]
        if unknown:
            raise PgError(Status.InvalidArgument(
                f"primary key columns not defined: {unknown}"), "42703")
        # YSQL default: first PK column hash-partitions, the rest are
        # range components (ref: YSQL PRIMARY KEY (a HASH, b ASC) default)
        ordered = stmt.pk + [n for n, _t in stmt.columns if n not in stmt.pk]
        columns = []
        serial_seqs = []
        for n in ordered:
            t = cols_by_name[n]
            if t == "SERIAL":
                # SERIAL = INT64 + implicit sequence default (ref: PG
                # pg_attrdef nextval('<table>_<col>_seq'))
                seq = f"{stmt.name}_{n}_seq"
                serial_seqs.append(seq)
                columns.append(ColumnSchema(n, DataType.INT64,
                                            default_seq=seq))
            else:
                if t == "JSONB" and n in stmt.pk:
                    # no order-preserving key encoding for documents
                    # (PG likewise has no jsonb btree opclass by default)
                    raise PgError(Status.InvalidArgument(
                        f'column "{n}" of type jsonb cannot be a '
                        f'primary key'), "42P16")
                columns.append(ColumnSchema(n, DataType[t]))
        schema = Schema(columns=columns, num_hash_key_columns=1,
                        num_range_key_columns=len(stmt.pk) - 1)
        try:
            self._client.create_table(self.database, stmt.name, schema,
                                      num_tablets=stmt.num_tablets)
        except StatusError as e:
            if not (stmt.if_not_exists
                    and e.status.code == Code.ALREADY_PRESENT):
                raise
            return PgResult("CREATE TABLE")
        # owned sequences AFTER a successful create (a failed table
        # create must not leave orphans); DROP TABLE drops them, so a
        # recreated table restarts at 1 (PG owned-sequence semantics)
        for seq in serial_seqs:
            self._client.create_sequence(self.database, seq,
                                         if_not_exists=True)
        return PgResult("CREATE TABLE")

    def _create_index(self, stmt: P.CreateIndex) -> PgResult:
        index_name = stmt.index_name \
            or f"{stmt.table}_{'_'.join(stmt.columns)}_idx"
        try:
            self._client.create_index(self.database, stmt.table,
                                      index_name, list(stmt.columns))
        except StatusError as e:
            if not (stmt.if_not_exists
                    and e.status.code == Code.ALREADY_PRESENT):
                raise
        self._tables.pop(stmt.table, None)  # refresh the index list
        return PgResult("CREATE INDEX")

    def _view_rows(self, name: str):
        """Resolve `name` as a view: run its stored defining SELECT and
        surface the result like a virtual table, so the outer SELECT's
        WHERE / ORDER BY / aggregates / LIMIT compose on top (ref: PG
        expands views through the rewriter; here the view body executes
        and the outer query filters). Views are NOT resolvable as JOIN
        operands (the join planner binds base tables only).
        Returns (columns [(name, oid)], row dicts) or None."""
        # base tables shadow nothing: only consult the view catalog when
        # the name is not a table (table handles are cached, so the
        # common path stays RPC-free)
        try:
            self._table(name)
            return None
        except (PgError, StatusError):
            pass
        try:
            sql = self._client.get_view(self.database, name)
        except StatusError:
            return None
        if sql is None:
            return None
        cached = self._view_memo.get(name)
        if cached is not None:
            return cached
        if self._view_depth >= 8:
            raise PgError(Status.InvalidArgument(
                f'infinite recursion detected in view "{name}"'),
                "42P17")
        from yugabyte_tpu.yql.pgsql.parser import PgParser
        inner = PgParser(sql).parse_one()
        self._view_depth += 1
        try:
            res = self._select(inner)
        finally:
            self._view_depth -= 1
        rows = res.rows if res.row_iter is None else list(res.row_iter)
        names = [n for n, _o in (res.columns or [])]
        out = (list(res.columns or []),
               [dict(zip(names, r)) for r in rows])
        # memoized for the remainder of THIS statement only: the
        # stream-check, plan and execution paths all consult
        # _virtual_table_rows, and the view body must run once per
        # statement (volatile functions, cost)
        self._view_memo[name] = out
        return out

    def _table(self, name: str) -> YBTable:
        """TTL'd table-handle cache: index DDL from other sessions becomes
        visible within the schema-propagation window (see
        yql/cql/executor.py _table)."""
        import time as _time
        from yugabyte_tpu.utils import flags as _flags
        ttl = _flags.get_flag("table_cache_ttl_ms") / 1000.0
        now = _time.monotonic()
        entry = self._tables.get(name)
        if entry is not None and now - entry[1] < ttl:
            return entry[0]
        t = self._client.open_table(self.database, name)
        self._tables[name] = (t, now)
        return t

    # ---------------------------------------------------------------- DML
    def _write(self, table: YBTable, ops: List[QLWriteOp]) -> None:
        if self._txn is not None:
            self._txn.write(table, ops)
        else:
            self._client.write(table, ops)

    def _run_statement_txn(self, body, deadline_s: float = 30.0):
        """Statement-level atomicity: a multi-row UPDATE/DELETE can neither
        partially apply nor clobber a concurrent writer between its scan
        and its writes (see index_maintenance.run_in_implicit_txn)."""
        return IM.run_in_implicit_txn(self._txn_manager, self._txn, body,
                                      deadline_s)

    @staticmethod
    def _returning_cols(schema, returning):
        """Resolve a RETURNING list to (bare column names, col_desc),
        raising 42703 for unknown refs. Called BEFORE the write so a bad
        RETURNING clause fails the whole statement without mutating
        anything (PG statement atomicity); '*' expands to all live
        columns, qualified refs resolve by the bare name."""
        if "*" in returning:
            cols = [c.name for c in schema.columns if not c.dropped]
        else:
            cols = [c.split(".")[-1] for c in returning]
        col_desc = []
        for c in cols:
            try:
                col_desc.append((c, PG_OIDS[schema.column(c).type]))
            except KeyError:
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")
        return cols, col_desc

    def _returning_result(self, tag: str, table, returning,
                          dicts) -> PgResult:
        """RETURNING projection over the written rows (ref: PG
        ExecProcessReturning)."""
        cols, col_desc = self._returning_cols(table.schema, returning)
        return PgResult(tag, col_desc,
                        [[d.get(c) for c in cols] for d in dicts])

    def _insert(self, stmt: P.Insert) -> PgResult:
        table = self._table(stmt.table)
        schema = table.schema
        if stmt.returning:
            self._returning_cols(schema, stmt.returning)  # fail pre-write
        columns = stmt.columns or [c.name for c in schema.columns]
        key_names = [c.name for c in schema.hash_columns] + \
            [c.name for c in schema.range_columns]
        ops = []
        # one sequence_next(cache=N) per SERIAL column for the WHOLE
        # multi-row INSERT (one master RPC, not one per row; PG caches
        # sequence blocks the same way)
        serial_fill: Dict[str, List[int]] = {}
        written: List[dict] = []
        for c in schema.columns:
            if c.default_seq is None or c.name in columns:
                continue  # column bound explicitly: no default draw
            n_missing = len(stmt.rows)
            base = self._client.sequence_next(
                self.database, c.default_seq, cache=n_missing)
            serial_fill[c.name] = list(range(base, base + n_missing))
        for row in stmt.rows:
            if len(row) != len(columns):
                raise PgError(Status.InvalidArgument(
                    "INSERT has more expressions than target columns"),
                    "42601")
            bound = dict(zip(columns, row))
            for c in list(bound):
                v = bound[c]
                if isinstance(v, tuple) and len(v) == 2 \
                        and v[0] == "__nextval__":
                    v = self._client.sequence_next(self.database, v[1])
                try:
                    bound[c] = pg_coerce(schema.column(c).type, v)
                except KeyError:
                    raise PgError(Status.InvalidArgument(
                        f'column "{c}" does not exist'), "42703")
            # SERIAL defaults: omitted columns draw from the statement's
            # pre-allocated block (ref: PG ExecEvalNextValueExpr)
            for c in schema.columns:
                if c.default_seq is not None and c.name not in bound:
                    fill = serial_fill.get(c.name)
                    bound[c.name] = (fill.pop(0) if fill else
                                     self._client.sequence_next(
                                         self.database, c.default_seq))
            missing = [k for k in key_names if k not in bound]
            if missing:
                raise PgError(Status.InvalidArgument(
                    f"null value in primary key columns {missing}"),
                    "23502")
            dk = DocKey(
                hash_components=tuple(bound[c.name]
                                      for c in schema.hash_columns),
                range_components=tuple(bound[c.name]
                                       for c in schema.range_columns))
            values = {c: v for c, v in bound.items() if c not in key_names}
            ops.append(QLWriteOp(WriteOpKind.INSERT, dk, values))
            written.append(bound)
        if stmt.on_conflict is not None:
            return self._insert_on_conflict(stmt, table, ops, written,
                                            key_names)
        if table.indexes:
            # indexed table: route through a (possibly implicit) transaction
            # maintaining every index (yql/index_maintenance.py)
            def body(txn):
                for op in ops:
                    IM.txn_write_with_indexes(txn, table, op, self._table)
            self._run_statement_txn(body)
            if stmt.returning:
                return self._returning_result(
                    f"INSERT 0 {len(ops)}", table, stmt.returning, written)
            return PgResult(f"INSERT 0 {len(ops)}")
        # batch per destination tablet: one write RPC per tablet touched
        # (ref pg_session.h:222 RunAsync buffering + batcher grouping)
        groups: Dict[str, List[QLWriteOp]] = {}
        for op in ops:
            pk = table.partition_key_for(op.doc_key)
            tid = self._client.meta_cache.lookup_tablet(
                table.table_id, pk).tablet_id
            groups.setdefault(tid, []).append(op)
        for group in groups.values():
            self._write(table, group)
        if stmt.returning:
            return self._returning_result(
                f"INSERT 0 {len(ops)}", table, stmt.returning, written)
        return PgResult(f"INSERT 0 {len(ops)}")

    def _insert_on_conflict(self, stmt: P.Insert, table, ops, written,
                            key_names) -> PgResult:
        """INSERT ... ON CONFLICT upsert (ref: PG ExecOnConflictUpdate /
        ExecOnConflictNothing, nodeModifyTable.c). Conflicts are primary-
        key conflicts — the only uniqueness constraint this layer
        enforces; a conflict target, when given, must name the PK. Runs
        as a read-check-write statement transaction."""
        schema = table.schema
        mode, target, assigns = stmt.on_conflict
        if target is not None and set(target) != set(key_names):
            raise PgError(Status.InvalidArgument(
                "there is no unique or exclusion constraint matching "
                "the ON CONFLICT specification"), "42P10")
        for c, v in assigns:
            if c in key_names:
                raise PgError(Status.NotSupported(
                    f"ON CONFLICT DO UPDATE cannot modify key "
                    f"column {c}"), "0A000")
            if not self._has_column(schema, c):
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")
            if isinstance(v, tuple) and len(v) == 2 \
                    and v[0] == "__excluded__" \
                    and not self._has_column(schema, v[1]):
                raise PgError(Status.InvalidArgument(
                    f'column excluded.{v[1]} does not exist'), "42703")
        # SET col = <expression over the EXISTING row> compiles once
        expr_fns = {c: self._compile_row_expr(v[1], schema)[1]
                    for c, v in assigns
                    if isinstance(v, tuple) and len(v) == 2
                    and v[0] == "__expr__"}

        def body(txn):
            n = 0
            touched = []
            seen_keys = set()
            for op, bound in zip(ops, written):
                enc = op.doc_key.encode()
                existing = txn.read_row(table, op.doc_key)
                if existing is None:
                    IM.txn_write_with_indexes(txn, table, op,
                                              self._table,
                                              old_row_dict={})
                    n += 1
                    touched.append(bound)
                    seen_keys.add(enc)
                    continue
                if mode == "nothing":
                    continue
                if enc in seen_keys:
                    # PG: one statement may not affect a row twice
                    raise PgError(Status.InvalidArgument(
                        "ON CONFLICT DO UPDATE command cannot affect "
                        "row a second time"), "21000")
                seen_keys.add(enc)
                d = existing.to_dict(schema)
                values = {}
                for c, v in assigns:
                    if isinstance(v, tuple) and len(v) == 2 \
                            and v[0] == "__excluded__":
                        v = bound.get(v[1])
                    elif isinstance(v, tuple) and len(v) == 2 \
                            and v[0] == "__expr__":
                        v = expr_fns[c](d)
                    elif isinstance(v, tuple) and len(v) == 2 \
                            and v[0] == "__nextval__":
                        v = self._client.sequence_next(self.database,
                                                       v[1])
                    values[c] = pg_coerce(schema.column(c).type, v)
                IM.txn_write_with_indexes(
                    txn, table,
                    QLWriteOp(WriteOpKind.UPDATE, op.doc_key, values),
                    self._table, old_row_dict=d)
                n += 1
                touched.append({**d, **values})
            return n, touched

        n, touched = self._run_statement_txn(body)
        if stmt.returning:
            # PG: RETURNING yields only rows actually inserted/updated
            return self._returning_result(f"INSERT 0 {n}", table,
                                          stmt.returning, touched)
        return PgResult(f"INSERT 0 {n}")

    @staticmethod
    def _has_column(schema, name: str) -> bool:
        try:
            schema.column(name)
            return True
        except KeyError:
            return False

    # ------------------------------------------------- system virtual tables
    def _virtual_table_rows(self, name: str):
        """pg_catalog / information_schema vtables computed from the master
        catalog (ref: src/yb/master/yql_*_vtable.* building system tables
        from catalog state). Returns (columns [(name, oid)], row dicts) or
        None for regular tables. Names accept an optional schema prefix
        (the parser collapses pg_catalog.pg_tables to its last component).
        """
        key = name.lower()
        if key.startswith("pg_catalog."):
            key = key[len("pg_catalog."):]
        if key == "information_schema.tables":
            key = "tables"
        elif key == "information_schema.columns":
            key = "columns"
        elif key in ("tables", "columns"):
            # unqualified: PG search_path does NOT include
            # information_schema — resolve as a user table
            return None
        if key == "pg_views":
            cols = [("schemaname", 25), ("viewname", 25),
                    ("definition", 25)]
            return cols, [{"schemaname": "public",
                           "viewname": m["name"],
                           "definition": m["sql"]}
                          for m in self._client.list_views(self.database)]
        if key not in ("pg_tables", "tables", "pg_class", "pg_namespace",
                       "pg_attribute", "columns", "pg_type", "pg_indexes"):
            return self._view_rows(name)
        tables = self._client.list_tables(self.database)
        if key == "pg_tables":
            cols = [("schemaname", 25), ("tablename", 25),
                    ("tableowner", 25)]
            rows = [{"schemaname": "public", "tablename": t["name"],
                     "tableowner": "yugabyte"} for t in tables]
        elif key == "tables":
            cols = [("table_catalog", 25), ("table_schema", 25),
                    ("table_name", 25), ("table_type", 25)]
            rows = [{"table_catalog": self.database,
                     "table_schema": "public", "table_name": t["name"],
                     "table_type": "BASE TABLE"} for t in tables]
        elif key == "pg_class":
            cols = [("oid", 20), ("relname", 25), ("relkind", 25),
                    ("relnamespace", 20)]
            rows = [{"oid": i + 16384, "relname": t["name"],
                     "relkind": "r", "relnamespace": 2200}
                    for i, t in enumerate(tables)]
        elif key == "pg_namespace":
            cols = [("oid", 20), ("nspname", 25)]
            rows = [{"oid": 11, "nspname": "pg_catalog"},
                    {"oid": 2200, "nspname": "public"}]
        elif key == "pg_type":
            cols = [("oid", 20), ("typname", 25)]
            rows = [{"oid": o, "typname": n}
                    for o, n in ((16, "bool"), (20, "int8"), (23, "int4"),
                                 (25, "text"), (701, "float8"),
                                 (17, "bytea"), (1114, "timestamp"))]
        elif key == "pg_indexes":
            cols = [("schemaname", 25), ("tablename", 25),
                    ("indexname", 25), ("indexdef", 25)]
            rows = []
            for t in tables:
                for w in t.get("indexes", []):
                    rows.append({
                        "schemaname": "public", "tablename": t["name"],
                        "indexname": w["index_name"],
                        "indexdef": "CREATE INDEX %s ON %s (%s)" % (
                            w["index_name"], t["name"],
                            ", ".join(w.get("columns")
                                      or [w["column"]]))})
        else:  # pg_attribute / information_schema columns
            from yugabyte_tpu.common.wire import schema_from_wire
            if key == "pg_attribute":
                cols = [("attrelid", 20), ("attname", 25),
                        ("atttypid", 20), ("attnum", 20)]
            else:
                cols = [("table_name", 25), ("column_name", 25),
                        ("data_type", 25), ("ordinal_position", 20)]
            rows = []
            for i, t in enumerate(tables):
                schema = schema_from_wire(t["schema"])
                for j, c in enumerate(schema.columns):
                    if key == "pg_attribute":
                        rows.append({"attrelid": i + 16384,
                                     "attname": c.name,
                                     "atttypid": PG_OIDS[c.type],
                                     "attnum": j + 1})
                    else:
                        rows.append({"table_name": t["name"],
                                     "column_name": c.name,
                                     "data_type": c.type.value,
                                     "ordinal_position": j + 1})
        return cols, rows

    def _select_virtual(self, stmt: P.Select, cols, rows) -> PgResult:
        by_name = dict(cols)
        known = set(by_name)
        out_cols = stmt.columns or [c for c, _o in cols]
        for c in out_cols + [f[0] for f in stmt.where]:
            if c not in known:
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")
        dicts = [d for d in rows
                 if row_matches(d, [list(f) for f in stmt.where])]
        if stmt.count_star:
            out = _page_rows([[len(dicts)]], stmt)
            return PgResult(f"SELECT {len(out)}", [("count", 20)], out)
        if stmt.aggregates or stmt.group_by:
            col_desc, rows_out = self._aggregate(
                stmt, lambda c: by_name.get(c, 25), dicts)
            rows_out = self._order_agg_rows(col_desc, rows_out,
                                            stmt.order_by)
            col_desc, rows_out = _project_group_output(stmt, col_desc,
                                                       rows_out)
            rows_out = _page_rows(rows_out, stmt)
            return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)
        dicts = self._order_rows(dicts, stmt.order_by)
        rows_out = [[d.get(c) for c in out_cols] for d in dicts]
        if stmt.distinct:
            rows_out = _dedup_rows(rows_out)
        rows_out = _page_rows(rows_out, stmt)
        return PgResult(f"SELECT {len(rows_out)}",
                        [(c, by_name[c]) for c in out_cols], rows_out)

    # ------------------------------------------------------------- SELECT
    def _split_where(self, table: YBTable,
                     where: List[Tuple[str, str, object]]):
        """-> (doc_key or None, pushdown filters). A full primary key
        (all components bound by equality) becomes a point read; anything
        else is pushed down to the tserver scan (ref ybgate pushdown).

        Exactly ONE equality predicate per key column is consumed into the
        doc key; duplicates (e.g. `id = 1 AND id = 2`) stay in the residual
        and are re-checked against the fetched row, so contradictory
        conjunctions correctly return nothing."""
        schema = table.schema
        where = self._coerce_where(schema, where)
        key_names = [c.name for c in schema.hash_columns] + \
            [c.name for c in schema.range_columns]
        eq: Dict[str, object] = {}
        consumed: set = set()
        for i, (c, op, v) in enumerate(where):
            if op == "=" and c in key_names and c not in eq:
                eq[c] = v
                consumed.add(i)
        if all(k in eq for k in key_names):
            dk = DocKey(
                hash_components=tuple(eq[c.name]
                                      for c in schema.hash_columns),
                range_components=tuple(eq[c.name]
                                       for c in schema.range_columns))
            residual = [f for i, f in enumerate(where) if i not in consumed]
            return dk, residual
        return None, list(where)

    @staticmethod
    def _coerce_where(schema, where):
        """Coerce WHERE literals to each referenced column's storage type
        (timestamp text -> micros, ...); unknown columns pass through."""
        out = []
        for c, op, v in where:
            if isinstance(c, tuple) and c and c[0] == "jsonb":
                # -> yields json text: canonicalize the comparison value
                # so semantically equal spellings match the stored form;
                # ->> yields plain text — compare raw
                t = None if c[3] else DataType.JSONB
            else:
                try:
                    t = schema.column(c).type
                except KeyError:
                    t = None
            out.append((c, op, pg_coerce(t, v)))
        return out

    def _select_row_dicts(self, stmt: P.Select, table) -> List[dict]:
        """Materialize the matching rows as dicts (all columns)."""
        return list(self._iter_row_dicts(stmt, table))

    def _iter_row_dicts(self, stmt: P.Select, table):
        """Lazily yield the matching rows as dicts (all columns): the
        shared retrieval half of SELECT — point read / index lookup /
        pushed-down scan — before projection/aggregation/ordering.  The
        scan path streams from client.scan's paged generator, so a
        suspended portal holds no materialized result (bounded memory)."""
        schema = table.schema
        dk, filters = self._split_where(table, stmt.where)
        # ORDER BY / GROUP BY / aggregates need the full match set; only a
        # bare SELECT can stop at LIMIT rows early
        early_limit = (stmt.limit if not stmt.order_by and not stmt.group_by
                       and not stmt.aggregates and not stmt.count_star
                       and not stmt.distinct else None)
        if early_limit is not None and getattr(stmt, "offset", 0):
            # the post-fetch OFFSET slice still needs those leading rows
            early_limit += stmt.offset
        if dk is not None:
            if self._txn is not None:
                row = self._txn.read_row(table, dk)
            else:
                row = self._client.read_row(table, dk)
            if row is not None:
                d = row.to_dict(schema)
                if row_matches(d, filters):
                    yield d
            return
        # Index-accelerated path: a readable secondary index on an
        # equality predicate replaces the full scan. Skipped inside a
        # transaction block: index_lookup's reads would escape the txn
        # snapshot/overlay (the scan path pins both).
        residual: List = []
        picked = (IM.choose_index(table, [tuple(f) for f in filters])
                  if self._txn is None else None)
        if picked is not None:
            idx, value, residual = picked
            idx_table = self._table(idx.index_name)
            rows = IM.index_lookup(self._client, table, idx_table,
                                   idx, value)
        else:
            rows = self._scan(table, filters)
        n = 0
        for row in rows:
            d = row.to_dict(schema)
            if residual and not row_matches(d, residual):
                continue
            yield d
            n += 1
            if early_limit is not None and n >= early_limit:
                return

    _AGG_OUT_NAMES = {"COUNT": "count", "SUM": "sum", "AVG": "avg",
                      "MIN": "min", "MAX": "max"}

    @staticmethod
    def _order_agg_rows(col_desc, rows_out, order_by):
        """ORDER BY over aggregate OUTPUT columns (group key or an output
        label like `count`; PG orders the Agg node's result the same
        way). Unknown names raise 42703 instead of silently no-op'ing."""
        if not order_by:
            return rows_out
        names = [n for n, _oid in col_desc]
        out = list(rows_out)
        for col, desc in reversed(order_by):
            bare = col.split(".")[-1]
            if bare not in names:
                raise PgError(Status.InvalidArgument(
                    f'column "{col}" does not exist'), "42703")
            i = names.index(bare)
            out.sort(key=lambda r: (r[i] is None,
                                    0 if r[i] is None else r[i]),
                     reverse=desc)
        return out

    def _aggregate(self, stmt: P.Select, col_oid, dicts: List[dict]
                   ) -> Tuple[List[Tuple[str, int]], List[List[object]]]:
        """GROUP BY + aggregate evaluation (in-memory over the pushed-down
        match set; the reference pushes these into DocDB for YCQL and
        evaluates in PG for YSQL — ref pgsql aggregate paths).
        col_oid: column name -> PG type oid (table schema or vtable)."""
        def agg_oid(func: str, col: Optional[str]) -> int:
            func = func.split()[0]
            if func == "COUNT":
                return 20
            if func == "AVG":
                return 701
            base = col_oid(col)
            return 701 if (func == "SUM" and base == 701) else \
                (20 if func == "SUM" else base)

        group_cols = _group_cols(stmt.group_by)
        groups: Dict[object, List[dict]] = {}
        for d in dicts:
            key = tuple(d.get(c) for c in group_cols) if group_cols \
                else None
            groups.setdefault(key, []).append(d)
        if not dicts and not group_cols:
            groups[None] = []
        col_desc: List[Tuple[str, int]] = []
        for c in group_cols:
            col_desc.append((c, col_oid(c)))
        for func, col in stmt.aggregates:
            col_desc.append((self._AGG_OUT_NAMES[func.split()[0]],
                             agg_oid(func, col)))
        def agg_value(func, col, members):
            vals = ([1 for _ in members] if col is None
                    else [m[col] for m in members
                          if m.get(col) is not None])
            if func.endswith(" DISTINCT"):
                func = func.split()[0]
                vals = list(dict.fromkeys(vals))  # O(n) ordered dedup
            if func == "COUNT":
                return len(vals)
            if not vals:
                return None
            if func == "SUM":
                return sum(vals)
            if func == "AVG":
                return sum(vals) / len(vals)
            if func == "MIN":
                return min(vals)
            return max(vals)  # MAX

        from yugabyte_tpu.common.wire import FILTER_OPS
        rows_out = []
        def _gk(k):
            if k is None:
                return (1,)
            if isinstance(k, tuple):
                return (0,) + tuple((v is None, 0 if v is None else v)
                                    for v in k)
            return (0, k)
        # HAVING literals coerce against the referenced column's storage
        # type (MIN/MAX keep the column type; COUNT/SUM/AVG are numeric)
        having = []
        for item, op, want in stmt.having:
            ref_col = None
            if item[0] == "agg" and str(item[1]).upper() in ("MAX", "MIN"):
                ref_col = item[2]
            elif item[0] == "col":
                ref_col = item[1]
            t = None
            if ref_col and ref_col != "*":
                try:
                    if col_oid(ref_col) in (1114, 1184):
                        t = DataType.TIMESTAMP
                except (KeyError, PgError):
                    pass
            having.append((item, op, pg_coerce(t, want)))
        for key in sorted(groups, key=_gk):
            members = groups[key]
            # HAVING gates the group BEFORE projection (ref: PG executor
            # nodeAgg qual evaluation); having-only aggregates are
            # computed here and never emitted
            ok = True
            for item, op, want in having:
                if item[0] == "agg":
                    got = agg_value(item[1], item[2], members)
                else:
                    if item[1] not in group_cols:
                        raise PgError(Status.InvalidArgument(
                            f'column "{item[1]}" must appear in GROUP BY '
                            f'or be used in an aggregate function'),
                            "42803")
                    got = key[group_cols.index(item[1])]
                if got is None or not FILTER_OPS[op](got, want):
                    ok = False
                    break
            if not ok:
                continue
            row: List[object] = list(key) if group_cols else []
            for func, col in stmt.aggregates:
                row.append(agg_value(func, col, members))
            rows_out.append(row)
        return col_desc, rows_out

    @staticmethod
    def _order_rows(dicts: List[dict],
                    order_by: List[Tuple[str, bool]]) -> List[dict]:
        """Stable multi-key sort (last key first). PG default null
        placement falls out of one key shape: is_none sorts nulls last
        ASC and — under reverse — first DESC."""
        out = list(dicts)
        for col, desc in reversed(order_by):
            out.sort(key=lambda d: (d.get(col) is None,
                                    0 if d.get(col) is None else d.get(col)),
                     reverse=desc)
        return out

    def _select_stream(self, stmt: P.Select) -> Optional[PgResult]:
        """Streaming plan for portal execution, or None when the statement
        needs the full match set (aggregates/ORDER BY/joins/virtual
        tables) — those fall back to the materialized _select."""
        if (stmt.count_star or stmt.aggregates or stmt.group_by
                or stmt.order_by or stmt.scalar_items or stmt.joins
                or stmt.having or stmt.distinct or stmt.or_where
                or stmt.offset
                or any(op in ("exists", "not exists")
                       or isinstance(v, P.Select)
                       for _c, op, v in stmt.where)
                or self._virtual_table_rows(stmt.table) is not None):
            return None
        stmt = self._strip_base_qualifiers(stmt)
        table = self._table(stmt.table)
        schema = table.schema
        known = {c.name for c in schema.columns}
        for c in list(stmt.columns or []) + [f[0] for f in stmt.where]:
            if isinstance(c, tuple) and c and c[0] == "jsonb":
                self._check_jsonb_base(c, schema)
                c = c[1]
            if c not in known:
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")
        out_cols = stmt.columns or [c.name for c in schema.columns
                                    if not c.dropped]
        col_desc = [(c, PG_OIDS[schema.column(c).type]) for c in out_cols]

        def gen():
            for d in self._iter_row_dicts(stmt, table):
                yield [d.get(c) for c in out_cols]

        return PgResult("SELECT 0", col_desc, row_iter=gen())

    # -------------------------------------------------------------- JOIN
    def _select_join(self, stmt: P.Select) -> PgResult:
        """Left-deep join pipeline over doc scans (ref: the PG executor's
        join nodes as used through pggate scans, pg_doc_op.h):

          - HASH JOIN by default: the joined table's filtered scan builds
            an equality map probed by the rows joined so far.
          - INDEX NESTED-LOOP when the joined table's join column is its
            single-column primary key: batched point reads replace the
            build-side scan (the doc store IS the index).

        Single-table WHERE predicates push into each table's scan, except
        predicates on a LEFT-joined table, which must filter AFTER the
        join (pushing them into the build side would keep null-extended
        rows PG drops)."""
        base_alias = stmt.alias or stmt.table
        tables: List[Tuple[str, YBTable]] = [(base_alias,
                                              self._table(stmt.table))]
        for j in stmt.joins:
            tables.append((j.alias or j.table, self._table(j.table)))
        by_alias = dict(tables)
        if len(by_alias) != len(tables):
            raise PgError(Status.InvalidArgument(
                "duplicate table alias in FROM"), "42712")

        def has_col(t: YBTable, col: str) -> bool:
            try:
                t.schema.column(col)
                return True
            except KeyError:
                return False

        def resolve(ref: str) -> Tuple[str, str]:
            if "." in ref:
                a, c = ref.split(".", 1)
                if a not in by_alias:
                    raise PgError(Status.InvalidArgument(
                        f'missing FROM-clause entry for table "{a}"'),
                        "42P01")
                if not has_col(by_alias[a], c):
                    raise PgError(Status.InvalidArgument(
                        f'column "{ref}" does not exist'), "42703")
                return a, c
            owners = [a for a, t in tables if has_col(t, ref)]
            if not owners:
                raise PgError(Status.InvalidArgument(
                    f'column "{ref}" does not exist'), "42703")
            if len(owners) > 1:
                raise PgError(Status.InvalidArgument(
                    f'column reference "{ref}" is ambiguous'), "42702")
            return owners[0], ref

        left_joined = {j.alias or j.table for j in stmt.joins
                       if j.kind == "left"}
        pushdown: Dict[str, List] = {a: [] for a, _t in tables}
        residual: List[Tuple[str, str, object]] = []
        for c, op, v in stmt.where:
            a, col = resolve(c)
            if a in left_joined:
                residual.append((f"{a}.{col}", op, v))
            else:
                pushdown[a].append((col, op, v))

        base_table = by_alias[base_alias]
        rows = [{f"{base_alias}.{k}": v for k, v in d.items()}
                for d in self._iter_row_dicts(
                    P.Select(stmt.table, None, pushdown[base_alias]),
                    base_table)]

        joined = {base_alias}
        for j in stmt.joins:
            alias = j.alias or j.table
            table = by_alias[alias]
            sch = table.schema
            la, lc = resolve(j.on[0])
            ra, rc = resolve(j.on[1])
            if ra == alias and la in joined:
                pa, pc, jc = la, lc, rc
            elif la == alias and ra in joined:
                pa, pc, jc = ra, rc, lc
            else:
                raise PgError(Status.InvalidArgument(
                    "JOIN ON must equate a joined-table column with a "
                    "column of an earlier FROM entry"), "42P01")
            probe_key = f"{pa}.{pc}"
            # left-joined tables' predicates were already diverted to the
            # post-join `residual` above, so pushdown[alias] is exactly
            # the safe build-side filter set either way
            filters = pushdown[alias]

            use_point = (j.kind == "inner" and not filters
                         and len(sch.hash_columns) == 1
                         and sch.num_range_key_columns == 0
                         and sch.hash_columns[0].name == jc)
            if use_point:
                # index nested-loop: the join column is the PK — point
                # reads on distinct probe values beat a full build scan
                cache: Dict[object, List[dict]] = {}

                def matches_for(v, _t=table, _s=sch, _c=cache):
                    if v not in _c:
                        row = (self._txn.read_row(_t, DocKey(
                            hash_components=(v,))) if self._txn is not None
                            else self._client.read_row(_t, DocKey(
                                hash_components=(v,))))
                        _c[v] = [] if row is None else [row.to_dict(_s)]
                    return _c[v]
            else:
                build: Dict[object, List[dict]] = {}
                for d in self._iter_row_dicts(
                        P.Select(j.table, None, filters), table):
                    build.setdefault(d.get(jc), []).append(d)

                def matches_for(v, _b=build):
                    return _b.get(v, [])

            null_row = {f"{alias}.{c.name}": None
                        for c in sch.columns if not c.dropped}
            out = []
            for left in rows:
                v = left.get(probe_key)
                ms = matches_for(v) if v is not None else []
                if ms:
                    for d in ms:
                        nr = dict(left)
                        nr.update({f"{alias}.{k}": val
                                   for k, val in d.items()})
                        out.append(nr)
                elif j.kind == "left":
                    out.append({**left, **null_row})
            rows = out
            joined.add(alias)

        if residual:
            rows = [r for r in rows if row_matches(r, residual)]

        if stmt.count_star:
            out = _page_rows([[len(rows)]], stmt)
            return PgResult(f"SELECT {len(out)}", [("count", 20)], out)
        if stmt.aggregates or stmt.group_by:
            # aggregate over the joined row set: resolve references to
            # their qualified "alias.col" form, then reuse the shared
            # GROUP BY/HAVING machinery (ref: PG plans Agg above the
            # join tree the same way)
            from dataclasses import replace as _replace

            def qual(c):
                return "%s.%s" % resolve(c) if c else c

            def qual_having(item):
                if item[0] == "col":
                    return ("col", qual(item[1]))
                return ("agg", item[1], qual(item[2]) if item[2] else None)

            agg_stmt = _replace(
                stmt,
                group_by=_map_group_by(stmt.group_by, qual),
                aggregates=[(f, qual(c) if c else None)
                            for f, c in stmt.aggregates],
                having=[(qual_having(i), op, v)
                        for i, op, v in stmt.having],
                columns=[qual(c) for c in stmt.columns]
                if stmt.columns else None)



            def col_oid(qc):
                a, c = qc.split(".", 1)
                return PG_OIDS[by_alias[a].schema.column(c).type]

            col_desc, rows_out = self._aggregate(agg_stmt, col_oid, rows)
            rows_out = self._order_agg_rows(
                [(n.split(".")[-1], o) for n, o in col_desc], rows_out,
                stmt.order_by)
            col_desc, rows_out = _project_group_output(agg_stmt, col_desc,
                                                       rows_out)
            # label group columns by their bare name, like PG
            col_desc = [(n.split(".")[-1], o) for n, o in col_desc]
            rows_out = _page_rows(rows_out, stmt)
            return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)
        if stmt.scalar_items:
            raise PgError(Status.NotSupported(
                "scalar functions over joins are not supported"), "0A000")
        if stmt.columns:
            proj = [resolve(c) for c in stmt.columns]
        else:
            proj = [(a, c.name) for a, t in tables
                    for c in t.schema.columns if not c.dropped]
        col_desc = [(c, PG_OIDS[by_alias[a].schema.column(c).type])
                    for a, c in proj]
        if stmt.order_by:
            qorder = [("%s.%s" % resolve(c), d) for c, d in stmt.order_by]
            rows = self._order_rows(rows, qorder)
        rows_out = [[r.get(f"{a}.{c}") for a, c in proj] for r in rows]
        if stmt.distinct:
            rows_out = _dedup_rows(rows_out)
        rows_out = _page_rows(rows_out, stmt)
        return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)

    @staticmethod
    def _strip_base_qualifiers(stmt: P.Select) -> P.Select:
        """`SELECT t.x FROM t [t_alias]` without joins: drop the table
        qualifier so the single-table machinery sees bare columns."""
        from dataclasses import replace
        pref = {stmt.table, stmt.alias or stmt.table}

        def fix(c):
            if isinstance(c, tuple) and c and c[0] == "jsonb":
                # qualified jsonb path: strip the table prefix off the
                # BASE column (t.body->>'a' == body->>'a' here)
                return ("jsonb", fix(c[1]), c[2], c[3])
            if isinstance(c, str) and "." in c:
                a, col = c.split(".", 1)
                if a in pref:
                    return col
            return c
        def fix_item(it):
            if it[0] == "col":
                return ("col", fix(it[1]))
            if it[0] == "jsonb":
                return fix(it)
            if it[0] == "func":
                return ("func", it[1], [fix_item(a) for a in it[2]])
            if it[0] == "op":
                return ("op", it[1], fix_item(it[2]), fix_item(it[3]))
            return it

        def fix_having(item):
            if item[0] == "col":
                return ("col", fix(item[1]))
            return ("agg", item[1], fix(item[2]) if item[2] else item[2])

        return replace(
            stmt,
            columns=[fix(c) for c in stmt.columns] if stmt.columns else None,
            where=[(fix(c), op, v) for c, op, v in stmt.where],
            or_where=[[(fix(c), op, v) for c, op, v in br]
                      for br in stmt.or_where],
            order_by=[(fix(c), d) for c, d in stmt.order_by],
            scalar_items=[fix_item(i) for i in stmt.scalar_items],
            group_by=_map_group_by(stmt.group_by, fix),
            aggregates=[(f, fix(c) if c else c)
                        for f, c in stmt.aggregates],
            having=[(fix_having(i), op, v) for i, op, v in stmt.having])

    # --------------------------------------------------------- subqueries
    def _resolve_subqueries(self, stmt: P.Select):
        """Evaluate uncorrelated subqueries in WHERE up front (ref: PG
        SubLink planning — hashed subplans for IN, one-shot InitPlans for
        scalar/EXISTS). Returns (new_stmt, always_false): IN-subqueries
        become literal tuples, scalar subqueries become literals,
        EXISTS resolves to dropping the predicate or emptying the result.
        A subquery referencing the outer row (correlation) fails inside
        its own execution with a clear column error."""
        from dataclasses import replace as _replace
        if not any(isinstance(v, P.Select) or op in ("exists", "not exists")
                   or (op == "not in" and isinstance(v, tuple)
                       and any(x is None for x in v))
                   for _c, op, v in stmt.where):
            return stmt, False

        def one_column_values(sub: P.Select) -> list:
            res = self._select(sub)
            rows = res.rows if res.rows is not None else \
                list(res.row_iter or [])
            if rows and len(rows[0]) != 1:
                raise PgError(Status.InvalidArgument(
                    "subquery must return only one column"), "42601")
            return [r[0] for r in rows]

        new_where = []
        for c, op, v in stmt.where:
            if op in ("exists", "not exists"):
                sub = v
                res = self._select(_replace(sub, limit=1))
                rows = res.rows if res.rows is not None else \
                    list(res.row_iter or [])
                hit = bool(rows)
                if (op == "exists") != hit:
                    return stmt, True  # predicate constant-false
                continue  # constant-true: drop
            if isinstance(v, P.Select):
                vals = one_column_values(v)
                if op == "in":
                    new_where.append((c, "in", tuple(vals)))
                elif op == "not in":
                    if any(x is None for x in vals):
                        return stmt, True  # NOT IN with NULL: matches none
                    new_where.append((c, "not in", tuple(vals)))
                else:  # scalar subquery under a comparison
                    if len(vals) > 1:
                        raise PgError(Status.InvalidArgument(
                            "more than one row returned by a subquery "
                            "used as an expression"), "21000")
                    if not vals or vals[0] is None:
                        return stmt, True  # NULL comparison: matches none
                    new_where.append((c, op, vals[0]))
            elif op == "not in" and isinstance(v, tuple) \
                    and any(x is None for x in v):
                return stmt, True
            else:
                new_where.append((c, op, v))
        return _replace(stmt, where=new_where), False

    def _empty_select_result(self, stmt: P.Select) -> PgResult:
        """Result over a constant-false WHERE. Plain selects get zero rows
        with the right column description; UNGROUPED aggregates still
        produce their single row over the empty set (PG: SELECT MAX(x)
        ... WHERE false -> one NULL row, COUNT -> 0)."""
        if stmt.count_star:
            out = _page_rows([[0]], stmt)
            return PgResult(f"SELECT {len(out)}", [("count", 20)], out)
        stmt = self._strip_base_qualifiers(stmt)
        table = self._table(stmt.table)
        schema = table.schema
        if stmt.aggregates or stmt.group_by:
            # resolve types over every FROM entry (qualified refs from a
            # join must not KeyError against the base schema alone)
            by_alias = {stmt.alias or stmt.table: table}
            for j in stmt.joins:
                by_alias[j.alias or j.table] = self._table(j.table)

            def col_oid(c):
                if "." in c:
                    a, cc = c.split(".", 1)
                    t = by_alias.get(a)
                    if t is not None:
                        try:
                            return PG_OIDS[t.schema.column(cc).type]
                        except KeyError:
                            pass
                else:
                    for t in by_alias.values():
                        try:
                            return PG_OIDS[t.schema.column(c).type]
                        except KeyError:
                            continue
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")

            col_desc, rows_out = self._aggregate(stmt, col_oid, [])
            col_desc = [(n.split(".")[-1], o) for n, o in col_desc]
            return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)
        out_cols = stmt.columns or [c.name for c in schema.columns
                                    if not c.dropped]
        try:
            col_desc = [(c.split(".")[-1],
                         PG_OIDS[schema.column(c.split(".")[-1]).type])
                        for c in out_cols]
        except KeyError:
            col_desc = [(c, 25) for c in out_cols]
        return PgResult("SELECT 0", col_desc, [])

    # ----------------------------------------------------------- TRUNCATE
    def _truncate(self, stmt: P.Truncate) -> PgResult:
        """Remove every row from each table; RESTART IDENTITY resets
        owned SERIAL sequences to 1 (ref: PG ExecuteTruncate +
        ResetSequence). Row removal rides the transactional delete path
        so secondary indexes stay consistent — the functional equivalent
        of the reference's per-tablet truncate (tablet.cc Truncate),
        traded for index/MVCC safety at this layer."""
        if stmt.restart_identity and self._txn is not None:
            # the sequence registry is not transactional: a reset inside
            # an explicit transaction could not roll back with the row
            # deletes, silently recycling ids after ROLLBACK
            raise PgError(Status.NotSupported(
                "TRUNCATE ... RESTART IDENTITY cannot run inside a "
                "transaction block"), "0A000")
        # resolve every name BEFORE deleting anything: a typo in the
        # second table must not leave the first one emptied
        # (PG's ExecuteTruncate opens all relations first)
        tables = [self._table(name) for name in stmt.tables]
        for table in tables:
            def body(txn, _t=table):
                keys = self._target_keys(_t, [], txn)
                for k in keys:
                    IM.txn_write_with_indexes(
                        txn, _t, QLWriteOp(WriteOpKind.DELETE_ROW, k),
                        self._table)
                return len(keys)

            self._run_statement_txn(body)
        if stmt.restart_identity:
            for table in tables:
                for c in table.schema.columns:
                    if c.default_seq is not None:
                        self._client.drop_sequence(self.database,
                                                   c.default_seq,
                                                   if_exists=True)
                        self._client.create_sequence(self.database,
                                                     c.default_seq,
                                                     start=1)
        return PgResult("TRUNCATE TABLE")

    # ------------------------------------------------------------ EXPLAIN
    def _explain(self, stmt: P.Explain) -> PgResult:
        """Report the plan the executor's classification would pick,
        PG-tree-style (ref: src/postgres/.../commands/explain.c). The
        node names mirror the actual execution paths: point reads and
        index lookups surface as Index Scan, pushed-down scans as
        Seq Scan (with the pushed Filter), joins as Hash Join / Nested
        Loop exactly per _select_join's choice."""
        lines = self._plan_lines(stmt.stmt, indent=0)
        if stmt.analyze:
            t0 = time.monotonic()
            res = self._execute_stmt(stmt.stmt)
            ms = (time.monotonic() - t0) * 1e3
            n = len(res.rows) if res.row_iter is None \
                else sum(1 for _ in res.row_iter)
            lines.append(f"(actual rows={n})")
            lines.append(f"Execution Time: {ms:.3f} ms")
        return PgResult("EXPLAIN", [("QUERY PLAN", 25)],
                        [[ln] for ln in lines])

    @staticmethod
    def _explain_cond_text(conds) -> str:
        def one(c, op, v):
            if isinstance(c, tuple) and c and c[0] == "jsonb":
                path = "".join(
                    ("->>" if (c[3] and i == len(c[2]) - 1) else "->")
                    + (repr(s) if isinstance(s, int) else f"'{s}'")
                    for i, s in enumerate(c[2]))
                c = f"{c[1]}{path}"
            if isinstance(v, P.Select):
                v = "(SubPlan)"
            elif isinstance(v, str):
                v = f"'{v}'"
            elif isinstance(v, (tuple, list)):
                v = "(" + ", ".join(map(repr, v)) + ")"
            return f"({c} {op} {v})"
        return " AND ".join(one(*f) for f in conds)

    # Plan nodes: (label, [detail lines], [child nodes]) rendered
    # PG-tree-style by _render_plan.
    def _plan_lines(self, stmt, indent: int = 0) -> List[str]:
        return self._render_plan(self._plan_node(stmt))

    @classmethod
    def _render_plan(cls, node, pad: str = "",
                     arrow: bool = False) -> List[str]:
        """PG explain tree layout: details indent 6 under an arrowed
        node (2 at the root), child arrows align with the details."""
        label, details, children = node
        out = [pad + ("->  " if arrow else "") + label]
        body_pad = pad + ("      " if arrow else "  ")
        out += [body_pad + d for d in details]
        for ch in children:
            out += cls._render_plan(ch, body_pad, True)
        return out

    def _plan_node(self, stmt):
        """-> (label, details, children) for one DML statement."""
        if isinstance(stmt, P.UnionSelect):
            return ("Append", [],
                    [self._plan_node(s) for s in stmt.selects])
        if isinstance(stmt, P.Insert):
            return (f"Insert on {stmt.table}", [], [("Result", [], [])])
        if isinstance(stmt, P.Update):
            return (f"Update on {stmt.table}", [],
                    [self._scan_node(stmt.table, stmt.where)])
        if isinstance(stmt, P.Delete):
            return (f"Delete on {stmt.table}", [],
                    [self._scan_node(stmt.table, stmt.where)])
        # Select: Limit / Sort / Aggregate wrappers around the scan or
        # join tree, in the executor's actual sequencing order
        if stmt.joins:
            node = self._join_plan_node(stmt)
        elif stmt.or_where:
            branches = " OR ".join(
                "(" + self._explain_cond_text(br) + ")"
                for br in stmt.or_where)
            node = (f"Seq Scan on {stmt.table}",
                    [f"Filter: {branches}"], [])
        else:
            node = self._scan_node(stmt.table, stmt.where)
        if stmt.aggregates or stmt.group_by or stmt.count_star:
            label = "HashAggregate" if stmt.group_by else "Aggregate"
            details = []
            gcols = _group_cols(stmt.group_by)
            if gcols:
                details.append("Group Key: " + ", ".join(gcols))
            node = (label, details, [node])
        elif stmt.order_by:
            node = ("Sort", ["Sort Key: " + ", ".join(
                f"{c} DESC" if d else c for c, d in stmt.order_by)],
                [node])
        if stmt.limit is not None:
            node = ("Limit", [], [node])
        return node

    def _scan_node(self, table_name: str, where):
        """Access-path node mirroring _iter_row_dicts' classification:
        full-PK equality -> pkey Index Scan; readable secondary index on
        an equality -> Index Scan; else pushed-down Seq Scan."""
        if self._virtual_table_rows(table_name) is not None:
            return (f"Seq Scan on {table_name}", [], [])
        table = self._table(table_name)
        try:
            dk, filters = self._split_where(table, where)
        except (PgError, StatusError):
            dk, filters = None, list(where)
        if dk is not None:
            key_names = [c.name for c in table.schema.hash_columns] \
                + [c.name for c in table.schema.range_columns]
            keyf = [f for f in where if f[0] in key_names and f[1] == "="]
            details = ["Index Cond: " + self._explain_cond_text(keyf)]
            rest = [f for f in filters if f not in keyf]
            if rest:
                details.append("Filter: " + self._explain_cond_text(rest))
            return (f"Index Scan using {table_name}_pkey on {table_name}",
                    details, [])
        picked = (IM.choose_index(table, [tuple(f) for f in filters
                                          if isinstance(f[0], str)])
                  if self._txn is None else None)
        if picked is not None:
            idx, value, residual = picked
            vals = value if isinstance(value, tuple) else (value,)
            details = ["Index Cond: "
                       + self._explain_cond_text(
                           list(zip(idx.columns, ["="] * len(vals),
                                    vals)))]
            if residual:
                details.append("Filter: "
                               + self._explain_cond_text(residual))
            return (f"Index Scan using {idx.index_name} on {table_name}",
                    details, [])
        details = []
        if filters:
            details.append("Filter: " + self._explain_cond_text(filters))
        return (f"Seq Scan on {table_name}", details, [])

    def _join_plan_node(self, stmt: P.Select):
        """Left-deep join tree mirroring _select_join's hash-vs-point
        choice per joined table; the base scan is the deepest left
        child."""
        node = self._scan_node(stmt.table, [])
        for j in stmt.joins:
            left_ref, right_ref = j.on
            ja = j.alias or j.table
            if left_ref.split(".")[0] == ja \
                    and right_ref.split(".")[0] != ja:
                left_ref, right_ref = right_ref, left_ref
            right_col = right_ref.split(".")[-1]
            try:
                sch = self._table(j.table).schema
                use_point = (j.kind == "inner"
                             and len(sch.hash_columns) == 1
                             and sch.num_range_key_columns == 0
                             and sch.hash_columns[0].name == right_col)
            except (PgError, StatusError, KeyError):
                use_point = False
            details = [f"Join Cond: ({left_ref} = {right_ref})"]
            if use_point:
                inner = (f"Index Scan using {j.table}_pkey on {j.table}",
                         [], [])
                node = ("Nested Loop", details, [node, inner])
            else:
                label = ("Hash Join" if j.kind == "inner"
                         else "Hash Left Join")
                hash_node = ("Hash", [],
                             [(f"Seq Scan on {j.table}", [], [])])
                node = (label, details, [node, hash_node])
        if stmt.where:
            label, details, children = node
            details = details + ["Filter: "
                                 + self._explain_cond_text(stmt.where)]
            node = (label, details, children)
        return node

    def _select_or(self, stmt: P.Select) -> PgResult:
        """OR disjunction (ref: PG BitmapOr over index/seq paths): fetch
        each conjunction branch through the normal pushdown machinery,
        deduplicate rows by primary key, then run the usual
        aggregate/order/project pipeline over the union."""
        from dataclasses import replace as _replace
        if stmt.joins:
            raise PgError(Status.NotSupported(
                "OR combined with JOIN is not supported"), "0A000")
        stripped = self._strip_base_qualifiers(stmt)
        base = _replace(stripped, or_where=[])
        if self._virtual_table_rows(base.table) is not None:
            raise PgError(Status.NotSupported(
                "OR over system tables is not supported"), "0A000")
        table = self._table(base.table)
        schema = table.schema
        key_names = [c.name for c in schema.hash_columns] + \
            [c.name for c in schema.range_columns]
        self._validate_select_cols(stripped, schema)
        merged: Dict[tuple, dict] = {}
        for branch in stripped.or_where:
            b_sel = _replace(base, where=list(branch), limit=None,
                             order_by=[], distinct=False)
            resolved, always_false = self._resolve_subqueries(b_sel)
            if always_false:
                continue
            # fetch ALL columns per branch: projection happens after merge
            fetch = _replace(resolved, columns=None, aggregates=[],
                             group_by=None, scalar_items=[], having=[],
                             count_star=False)
            for d in self._iter_row_dicts(fetch, table):
                merged.setdefault(tuple(d.get(k) for k in key_names), d)
        dicts = list(merged.values())
        # re-enter the normal pipeline with the merged row set
        return self._project_dicts(base, table, dicts)

    def _project_dicts(self, stmt: P.Select, table, dicts) -> PgResult:
        """The post-fetch half of _select: aggregates / HAVING / ORDER BY
        / DISTINCT / projection over an already-fetched row set."""
        schema = table.schema
        if stmt.count_star:
            out = _page_rows([[len(dicts)]], stmt)
            return PgResult(f"SELECT {len(out)}", [("count", 20)], out)
        if stmt.aggregates or stmt.group_by:
            col_desc, rows_out = self._aggregate(
                stmt, lambda c: PG_OIDS[schema.column(c).type], dicts)
            # order over the FULL group output (PG permits ORDER BY any
            # grouping column, even one the SELECT list projects out),
            # THEN project to the select list
            rows_out = self._order_agg_rows(col_desc, rows_out,
                                            stmt.order_by)
            col_desc, rows_out = _project_group_output(stmt, col_desc,
                                                       rows_out)
            rows_out = _page_rows(rows_out, stmt)
            return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)
        dicts = self._order_rows(dicts, stmt.order_by)
        if stmt.scalar_items:
            col_desc, rows_out = self._project_scalar(stmt.scalar_items,
                                                      schema, dicts)
            if stmt.distinct:
                rows_out = _dedup_rows(rows_out)
            rows_out = _page_rows(rows_out, stmt)
            return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)
        out_cols = stmt.columns or [c.name for c in schema.columns
                                    if not c.dropped]
        col_desc = [(c, PG_OIDS[schema.column(c).type]) for c in out_cols]
        rows_out = [[d.get(c) for c in out_cols] for d in dicts]
        if stmt.distinct:
            rows_out = _dedup_rows(rows_out)
        rows_out = _page_rows(rows_out, stmt)
        return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)

    def _select_union(self, stmt: P.UnionSelect) -> PgResult:
        """UNION [ALL] chain: left-associative combine; any non-ALL link
        dedups the accumulated set (PG set-operation semantics). Column
        names come from the first member."""
        first = self._select(stmt.selects[0])
        if first.rows is None:
            first = PgResult(first.tag, first.columns,
                             list(first.row_iter or []))
        col_desc = first.columns
        acc = [tuple(r) for r in first.rows]
        for sel, all_link in zip(stmt.selects[1:], stmt.alls):
            res = self._select(sel)
            rows = res.rows if res.rows is not None else \
                list(res.row_iter or [])
            if len(res.columns or []) != len(col_desc or []):
                raise PgError(Status.InvalidArgument(
                    "each UNION query must have the same number of "
                    "columns"), "42601")
            acc.extend(tuple(r) for r in rows)
            if not all_link:
                seen = set()
                deduped = []
                for r in acc:
                    if r not in seen:
                        seen.add(r)
                        deduped.append(r)
                acc = deduped
        rows_out = [list(r) for r in acc]
        if stmt.order_by:
            names = [c for c, _oid in (col_desc or [])]
            for col, desc in reversed(stmt.order_by):
                if col not in names:
                    raise PgError(Status.InvalidArgument(
                        f'column "{col}" does not exist'), "42703")
                i = names.index(col)
                rows_out.sort(
                    key=lambda r: (r[i] is None,
                                   0 if r[i] is None else r[i]),
                    reverse=desc)
        rows_out = _page_rows(rows_out, stmt)
        return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)

    def _select(self, stmt) -> PgResult:
        if isinstance(stmt, P.UnionSelect):
            return self._select_union(stmt)
        if getattr(stmt, "table", None) is None and stmt.scalar_items:
            # FROM-less scalar SELECT: one row over an empty binding
            col_desc, rows_out = self._project_scalar(
                stmt.scalar_items, Schema(columns=[]), [{}])
            return PgResult(f"SELECT {len(rows_out)}", col_desc, rows_out)
        if stmt.or_where:
            return self._select_or(stmt)
        resolved, always_false = self._resolve_subqueries(stmt)
        if always_false:
            return self._empty_select_result(stmt)
        stmt = resolved
        if stmt.joins:
            return self._select_join(stmt)
        stmt = self._strip_base_qualifiers(stmt)
        vt = self._virtual_table_rows(stmt.table)
        if vt is not None:
            return self._select_virtual(stmt, *vt)
        table = self._table(stmt.table)
        self._validate_select_cols(stmt, table.schema)
        dicts = self._select_row_dicts(stmt, table)
        return self._project_dicts(stmt, table, dicts)

    def _validate_select_cols(self, stmt: P.Select, schema) -> None:
        """Every column reference (select list, WHERE incl. OR branches,
        ORDER BY, GROUP BY, aggregates, HAVING) must exist — one shared
        check so the OR path cannot diverge from the plain path."""
        known = {c.name for c in schema.columns}
        if stmt.aggregates or stmt.group_by:
            # ORDER BY may reference the aggregate OUTPUT labels
            known = known | {self._AGG_OUT_NAMES[f.split()[0]]
                             for f, _c in stmt.aggregates}
        check_cols = list(stmt.columns or []) \
            + [f[0] for f in stmt.where if f[0]] \
            + [f[0] for br in stmt.or_where for f in br if f[0]] \
            + [c for c, _d in stmt.order_by] \
            + _group_cols(stmt.group_by) \
            + [c for _f, c in stmt.aggregates if c is not None] \
            + [i[1] for i, _o, _v in stmt.having if i[0] == "col"] \
            + [i[2] for i, _o, _v in stmt.having
               if i[0] == "agg" and i[2] is not None]
        for c in check_cols:
            if isinstance(c, tuple) and c and c[0] == "jsonb":
                self._check_jsonb_base(c, schema)
                c = c[1]
            if c not in known:
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")

    @staticmethod
    def _check_jsonb_base(c: tuple, schema) -> None:
        """-> / ->> applies only to jsonb columns — WHERE must reject a
        text column exactly like the select list does (PG: 42883)."""
        try:
            t = schema.column(c[1]).type
        except KeyError:
            raise PgError(Status.InvalidArgument(
                f'column "{c[1]}" does not exist'), "42703")
        if t is not DataType.JSONB:
            raise PgError(Status.InvalidArgument(
                f"operator -> does not apply to type {t.value}"), "42883")

    def _compile_row_expr(self, it, schema):
        """Compile one row expression — ("col", name) | ("lit", v) |
        ("func", name, args) | ("op", op, l, r) — ONCE per statement to a
        (result DataType or None, fn(row_dict) -> value) pair; shared by
        the scalar select list and read-modify-write UPDATE."""
        from yugabyte_tpu.yql import bfunc

        def compile_item(it):
            """-> (result DataType or None, fn(row_dict) -> value)"""
            if it[0] == "col":
                name = it[1]
                try:
                    t = schema.column(name).type
                except KeyError:
                    raise PgError(Status.InvalidArgument(
                        f'column "{name}" does not exist'), "42703")
                return t, (lambda d, _c=name: d.get(_c))
            if it[0] == "lit":
                v = it[1]
                return bfunc.infer_type(v), (lambda d, _v=v: _v)
            if it[0] == "jsonb":
                from yugabyte_tpu.common import jsonb as _jsonb
                try:
                    t = schema.column(it[1]).type
                except KeyError:
                    raise PgError(Status.InvalidArgument(
                        f'column "{it[1]}" does not exist'), "42703")
                if t is not DataType.JSONB:
                    raise PgError(Status.InvalidArgument(
                        f"operator -> does not apply to type {t.value}"),
                        "42883")
                out_t = DataType.STRING if it[3] else DataType.JSONB
                return out_t, (lambda d, _c=it[1], _p=it[2], _a=it[3]:
                               _jsonb.navigate(d.get(_c), _p, _a))
            if it[0] == "case":
                # CASE: first matching WHEN wins; no match and no ELSE ->
                # NULL (PG ExecEvalCase). Conditions use SQL three-valued
                # logic: a NULL comparison never matches.
                def compile_cond(c):
                    if c[0] == "cmp":
                        _t1, lf = compile_item(c[2])
                        _t2, rf = compile_item(c[3])
                        o = c[1]

                        def ev(d, _lf=lf, _rf=rf, _o=o):
                            a, b = _lf(d), _rf(d)
                            if a is None or b is None:
                                return False
                            try:
                                return {"=": a == b, "!=": a != b,
                                        "<": a < b, "<=": a <= b,
                                        ">": a > b, ">=": a >= b}[_o]
                            except TypeError:
                                raise PgError(Status.InvalidArgument(
                                    f"CASE comparison between "
                                    f"{type(a).__name__} and "
                                    f"{type(b).__name__}"), "42883")
                        return ev
                    if c[0] == "isnull":
                        _t, f = compile_item(c[1])
                        neg = c[2]
                        return lambda d, _f=f, _n=neg: \
                            (_f(d) is not None) if _n else (_f(d) is None)
                    subs = [compile_cond(x) for x in c[1]]
                    if c[0] == "and":
                        return lambda d, _s=subs: all(f(d) for f in _s)
                    return lambda d, _s=subs: any(f(d) for f in _s)

                branches = [(compile_cond(cond), compile_item(res))
                            for cond, res in it[1]]
                els = compile_item(it[2]) if it[2] is not None else None
                types = [t for _c, (t, _f) in branches if t is not None]
                if els is not None and els[0] is not None:
                    types.append(els[0])
                out_t = None
                if types:
                    out_t = (DataType.DOUBLE
                             if any(t in (DataType.DOUBLE, DataType.FLOAT)
                                    for t in types)
                             and all(t in (DataType.DOUBLE, DataType.FLOAT,
                                           DataType.INT64, DataType.INT32)
                                     for t in types)
                             else types[0])

                def ev_case(d, _b=branches, _e=els):
                    for cf, (_t, rf) in _b:
                        if cf(d):
                            return rf(d)
                    return _e[1](d) if _e is not None else None
                return out_t, ev_case
            if it[0] == "op":
                # arithmetic with SQL NULL propagation and PG numeric
                # typing (int op int -> int, '/' truncates; any float
                # operand -> float; division by zero -> 22012)
                lt, lf = compile_item(it[2])
                rt, rf = compile_item(it[3])
                numeric = (DataType.INT64, DataType.DOUBLE,
                           DataType.FLOAT, DataType.INT32, None)
                if lt not in numeric or rt not in numeric:
                    raise PgError(Status.InvalidArgument(
                        f"operator {it[1]} does not accept type "
                        f"{(lt if lt not in numeric else rt)}"), "42883")
                both_int = (lt == DataType.INT64 and rt == DataType.INT64)
                # PG numeric typing: int op int stays int ('/' truncates
                # toward zero); any float operand promotes to float
                out_t = DataType.INT64 if both_int else DataType.DOUBLE
                o = it[1]

                def ev_op(d, _o=o, _lf=lf, _rf=rf, _int=both_int):
                    a = _lf(d)
                    b = _rf(d)
                    if a is None or b is None:
                        return None
                    if not isinstance(a, (int, float)) \
                            or not isinstance(b, (int, float)) \
                            or isinstance(a, bool) or isinstance(b, bool):
                        # untyped (builtin-ANY) operand turned out
                        # non-numeric at runtime
                        raise PgError(Status.InvalidArgument(
                            f"operator {_o} requires numeric operands"),
                            "42883")
                    try:
                        if _o == "+":
                            return a + b
                        if _o == "-":
                            return a - b
                        if _o == "*":
                            return a * b
                        if _o == "%":
                            # PG %: the result sign follows the DIVIDEND
                            r = abs(a) % abs(b)
                            return r if a >= 0 else -r
                        if _int:
                            q = abs(a) // abs(b)
                            return q if (a >= 0) == (b >= 0) else -q
                        return a / b
                    except ZeroDivisionError:
                        raise PgError(Status.InvalidArgument(
                            "division by zero"), "22012")
                return out_t, ev_op
            if str(it[1]).lower() == "nextval":
                # sequence allocation is a CLIENT call, not a pure builtin
                # (ref: PG ExecEvalNextValueExpr -> nextval_internal)
                if len(it[2]) != 1 or it[2][0][0] != "lit":
                    raise PgError(Status.InvalidArgument(
                        "nextval takes one literal sequence name"),
                        "42883")
                seq = it[2][0][1]
                return DataType.INT64, (
                    lambda d, _s=seq: self._client.sequence_next(
                        self.database, _s))
            sub = [compile_item(a) for a in it[2]]
            try:
                decl = bfunc.resolve(it[1], [t for t, _f in sub])
            except bfunc.BFError as e:
                raise PgError(Status.InvalidArgument(str(e)), "42883")
            if decl.fn is None:
                raise PgError(Status.InvalidArgument(
                    f"{it[1]} is not valid here"), "42883")

            def ev(d, _decl=decl, _fns=[f for _t, f in sub], _n=it[1]):
                try:
                    return _decl.fn(*[f(d) for f in _fns])
                except bfunc.BFError as e:
                    raise PgError(Status.InvalidArgument(str(e)), "22000")
                except Exception as e:
                    raise PgError(Status.InvalidArgument(f"{_n}: {e}"),
                                  "22000")
            return (None if decl.ret_type is bfunc.ANY else decl.ret_type), ev

        return compile_item(it)

    def _project_scalar(self, items, schema, dicts):
        """Scalar-builtin select list (yql/bfunc.py, the bfpg registry
        equivalent). Each item compiles ONCE per statement — signature
        resolution is type-driven and row-invariant — to a closure run
        per row. Labels follow PG (function outputs are labeled by the
        function name)."""
        col_desc = []
        fns = []
        for it in items:
            if it[0] == "func":
                label = it[1].lower()
            elif it[0] == "case":
                label = "case"       # PG's label for CASE expressions
            elif it[0] in ("op", "lit", "jsonb"):
                label = "?column?"   # PG's label for anonymous expressions
            else:
                label = it[1]
            t, fn = self._compile_row_expr(it, schema)
            col_desc.append((label, PG_OIDS.get(t, 25)))
            fns.append(fn)
        rows_out = [[fn(d) for fn in fns] for d in dicts]
        return col_desc, rows_out

    # ------------------------------------------------------ UPDATE/DELETE
    def _scan(self, table: YBTable, filters):
        """Paged multi-tablet scan; inside a transaction it pins the txn
        snapshot AND passes the txn id so the scan sees the transaction's
        own provisional writes (same overlay point reads use)."""
        read_ht = None
        txn_id = None
        if self._txn is not None:
            from yugabyte_tpu.common.hybrid_time import HybridTime
            read_ht = HybridTime(self._txn.read_ht)
            txn_id = self._txn.txn_id
        return self._client.scan(table, read_ht=read_ht,
                                 filters=filters or None, txn_id=txn_id)

    def _target_rows(self, table: YBTable,
                     where: List[Tuple[str, str, object]], txn=None,
                     split=None):
        """(doc_key, row_dict) pairs matching WHERE — the read half of a
        read-modify-write UPDATE (SET v = v + 1 must evaluate against the
        transaction's snapshot of each row). `split` short-circuits the
        WHERE decomposition when the caller already did it."""
        from yugabyte_tpu.common.hybrid_time import HybridTime
        schema = table.schema
        txn = txn or self._txn
        dk, filters = split if split is not None \
            else self._split_where(table, where)
        if dk is not None:
            row = (txn.read_row(table, dk) if txn
                   else self._client.read_row(table, dk))
            if row is None:
                return []
            d = row.to_dict(schema)
            return [(dk, d)] if row_matches(d, filters) else []
        if txn is not None:
            rows = self._client.scan(table, read_ht=HybridTime(txn.read_ht),
                                     filters=filters or None,
                                     txn_id=txn.txn_id)
        else:
            rows = self._scan(table, filters)
        return [(row.doc_key, row.to_dict(schema)) for row in rows]

    def _target_keys(self, table: YBTable,
                     where: List[Tuple[str, str, object]], txn=None):
        """Doc keys matching WHERE: point lookup for a full key, pushed-
        down scan otherwise (PG semantics: UPDATE/DELETE take any WHERE).
        With `txn`, reads pin that transaction's snapshot + overlay."""
        dk, filters = self._split_where(table, where)
        if dk is not None and not filters:
            return [dk]  # blind-write fast path: no row read needed
        return [k for k, _d in self._target_rows(table, where, txn,
                                                 split=(dk, filters))]

    def _resolve_dml_where(self, table_name: str, where):
        """Subquery support in UPDATE/DELETE predicates: resolve through
        the SELECT machinery. Returns (where, always_false)."""
        probe = P.Select(table_name, None, list(where))
        resolved, always_false = self._resolve_subqueries(probe)
        return resolved.where, always_false

    def _update(self, stmt: P.Update) -> PgResult:
        table = self._table(stmt.table)
        schema = table.schema
        if stmt.returning:
            self._returning_cols(schema, stmt.returning)  # fail pre-write
        where, none_match = self._resolve_dml_where(stmt.table, stmt.where)
        if none_match:
            return (self._returning_result("UPDATE 0", table,
                                           stmt.returning, [])
                    if stmt.returning else PgResult("UPDATE 0"))
        stmt = P.Update(stmt.table, stmt.assignments, where,
                        stmt.returning)
        key_names = {c.name for c in schema.hash_columns} | \
            {c.name for c in schema.range_columns}
        bad = [c for c, _v in stmt.assignments if c in key_names]
        if bad:
            # a PK update is a row move (delete+insert); not supported
            raise PgError(Status.NotSupported(
                f"cannot update primary key column(s) {bad}"), "0A000")
        names = [c for c, _v in stmt.assignments]
        if len(set(names)) != len(names):
            dup = next(c for c in names if names.count(c) > 1)
            raise PgError(Status.InvalidArgument(
                f'multiple assignments to same column "{dup}"'), "42601")
        exprs = {c: v[1] for c, v in stmt.assignments
                 if isinstance(v, tuple) and len(v) == 2
                 and v[0] == "__expr__"}
        plain = {c: v for c, v in stmt.assignments
                 if not (isinstance(v, tuple) and len(v) == 2
                         and v[0] == "__expr__")}
        for c in list(plain):
            try:
                plain[c] = pg_coerce(schema.column(c).type, plain[c])
            except KeyError:
                raise PgError(Status.InvalidArgument(
                    f'column "{c}" does not exist'), "42703")
        if exprs:
            # SET col = <expression over the row>: read-modify-write under
            # the statement transaction (PG evaluates the RHS against the
            # row's snapshot; a blind write would lose concurrent deltas)
            fns = {}
            for c, node in exprs.items():
                t, fn = self._compile_row_expr(node, schema)
                try:
                    want = schema.column(c).type
                except KeyError:
                    raise PgError(Status.InvalidArgument(
                        f'column "{c}" does not exist'), "42703")
                ok = (t is None or t == want
                      or (want == DataType.DOUBLE
                          and t in (DataType.INT64, DataType.INT32,
                                    DataType.FLOAT)))
                if not ok:
                    raise PgError(Status.InvalidArgument(
                        f'column "{c}" is of type {want.name} but '
                        f'expression is of type {t.name}'), "42804")
                fns[c] = fn

            def body(txn):
                pairs = self._target_rows(table, stmt.where, txn)
                new_dicts = []
                for k, d in pairs:
                    values = dict(plain)
                    for c, fn in fns.items():
                        values[c] = fn(d)
                    IM.txn_write_with_indexes(
                        txn, table, QLWriteOp(WriteOpKind.UPDATE, k,
                                              values), self._table)
                    new_dicts.append({**d, **values})
                return len(pairs), new_dicts

            n, new_dicts = self._run_statement_txn(body)
            if stmt.returning:
                return self._returning_result(
                    f"UPDATE {n}", table, stmt.returning, new_dicts)
            return PgResult(f"UPDATE {n}")

        dk, filters = self._split_where(table, stmt.where)
        if (dk is not None and not filters and not table.indexes
                and self._txn is None and not stmt.returning):
            # point update, no indexes: the single-shard fast path is
            # already atomic (RETURNING needs the full row — txn path)
            self._write(table, [QLWriteOp(WriteOpKind.UPDATE, dk,
                                          dict(plain))])
            return PgResult("UPDATE 1")

        def body(txn):
            if stmt.returning:
                # RETURNING needs each row's remaining columns
                pairs = self._target_rows(table, stmt.where, txn)
            else:
                pairs = [(k, None)
                         for k in self._target_keys(table, stmt.where,
                                                    txn)]
            for k, _d in pairs:
                IM.txn_write_with_indexes(
                    txn, table,
                    QLWriteOp(WriteOpKind.UPDATE, k,
                              dict(plain)), self._table)
            return (len(pairs),
                    [{**d, **plain} for _k, d in pairs if d is not None])

        n, new_dicts = self._run_statement_txn(body)
        if stmt.returning:
            return self._returning_result(
                f"UPDATE {n}", table, stmt.returning, new_dicts)
        return PgResult(f"UPDATE {n}")

    def _delete(self, stmt: P.Delete) -> PgResult:
        table = self._table(stmt.table)
        if stmt.returning:
            self._returning_cols(table.schema, stmt.returning)
        where, none_match = self._resolve_dml_where(stmt.table, stmt.where)
        if none_match:
            return (self._returning_result("DELETE 0", table,
                                           stmt.returning, [])
                    if stmt.returning else PgResult("DELETE 0"))
        stmt = P.Delete(stmt.table, where, stmt.returning)
        dk, filters = self._split_where(table, stmt.where)
        if (dk is not None and not filters and not table.indexes
                and self._txn is None and not stmt.returning):
            self._write(table, [QLWriteOp(WriteOpKind.DELETE_ROW, dk)])
            return PgResult("DELETE 1")

        def body(txn):
            if stmt.returning:
                # RETURNING projects the OLD rows (PG semantics)
                pairs = self._target_rows(table, stmt.where, txn)
            else:
                pairs = [(k, None)
                         for k in self._target_keys(table, stmt.where,
                                                    txn)]
            for k, _d in pairs:
                IM.txn_write_with_indexes(
                    txn, table, QLWriteOp(WriteOpKind.DELETE_ROW, k),
                    self._table)
            return (len(pairs),
                    [d for _k, d in pairs if d is not None])

        n, old_dicts = self._run_statement_txn(body)
        if stmt.returning:
            return self._returning_result(
                f"DELETE {n}", table, stmt.returning, old_dicts)
        return PgResult(f"DELETE {n}")

    # ------------------------------------------------------- transactions
    def _txn_control(self, stmt: P.TxnControl) -> PgResult:
        # any transaction boundary invalidates open portals (PG destroys
        # non-holdable portals at txn end; a suspended portal's iterator
        # is pinned to the old txn's snapshot/overlay) — and cursors, for
        # the same reason
        self.txn_epoch += 1
        if stmt.kind != "begin":
            # WITH HOLD cursors survive transaction end (PG DECLARE docs).
            # On COMMIT an unmaterialized hold cursor is persisted (PG's
            # PersistHoldablePortal) — drained through the still-open txn
            # so its snapshot is honored, never re-read later. On ROLLBACK
            # an unmaterialized hold cursor was created by the aborted
            # transaction: PG destroys it (its lazy scan could serve the
            # txn's rolled-back writes); already-persisted ones survive.
            aborting = stmt.kind != "commit" or self.txn_failed
            held = {}
            for n, cur in self._cursors.items():
                if not cur.hold:
                    continue
                if not cur.materialized:
                    if aborting:
                        continue  # destroyed with the aborted txn
                    cur.materialize()
                held[n] = cur
            self._cursors = held
        if stmt.kind == "begin":
            if self._txn is None:
                self._txn = self._txn_manager.begin()
            return PgResult("BEGIN")
        if stmt.kind == "commit":
            txn, self._txn = self._txn, None
            failed, self.txn_failed = self.txn_failed, False
            if txn is None:
                return PgResult("COMMIT")
            if failed:
                txn.abort()
                return PgResult("ROLLBACK")
            txn.commit()
            return PgResult("COMMIT")
        txn, self._txn = self._txn, None
        self.txn_failed = False
        if txn is not None:
            txn.abort()
        return PgResult("ROLLBACK")


