#!/usr/bin/env python
"""Lint: no silently swallowed errors in the storage-critical layers.

The failure-containment design routes every background I/O error to the
DB background-error slot (storage/db.py), the WAL seal (consensus/log.py),
or at minimum a TRACE line — an `except Exception: pass` in storage/,
consensus/ or tablet/ is exactly the hole that turns an injected disk
fault into silent corruption instead of a contained FAILED tablet.

Flags every broad exception handler (bare `except:`, `except Exception`,
`except BaseException`) whose body only discards the error (pass /
continue / bare return), unless:

  - it routes the error somewhere: a raise, a TRACE(...) call, or a call
    into the containment surface (background_error / mark_failed / _fail
    / set_background_error);
  - it is inside a `__del__` (interpreter-teardown swallows are
    idiomatic and unroutable);
  - the except line carries an explicit `# lint: swallow-ok` waiver.

Run as a script (exit 1 on offense) or via check_paths() from the tier-1
test that wires this into CI.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

DEFAULT_DIRS = ("yugabyte_tpu/storage", "yugabyte_tpu/consensus",
                "yugabyte_tpu/tablet")

_BROAD = {"Exception", "BaseException"}
_ROUTING_NAMES = ("TRACE", "trace")
_ROUTING_ATTRS = ("background_error", "set_background_error",
                  "mark_failed", "_fail")
_WAIVER = "lint: swallow-ok"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    for node in ([t.elts] if isinstance(t, ast.Tuple) else [[t]])[0]:
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD for n in names)


def _routes_error(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name in _ROUTING_NAMES or any(a in name
                                             for a in _ROUTING_ATTRS):
                return True
    return False


def _only_discards(handler: ast.ExceptHandler) -> bool:
    """Body is nothing but pass / continue / bare return — the error is
    dropped on the floor with no side channel."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Return) and stmt.value is None:
            continue
        return False
    return True


def _in_del(tree: ast.AST, handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "__del__":
            for sub in ast.walk(node):
                if sub is handler:
                    return True
    return False


def check_file(path: str) -> List[Tuple[str, int, str]]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (_is_broad(node) and _only_discards(node)):
            continue
        if _routes_error(node) or _in_del(tree, node):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if _WAIVER in line:
            continue
        out.append((path, node.lineno,
                    "broad except swallows the error (route it to the "
                    "background-error slot or TRACE)"))
    return out


def check_paths(root: str, dirs=DEFAULT_DIRS) -> List[Tuple[str, int, str]]:
    offenses = []
    for d in dirs:
        base = os.path.join(root, d)
        for dirpath, _dirs, files in os.walk(base):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    offenses.extend(check_file(os.path.join(dirpath, fn)))
    return offenses


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    offenses = check_paths(root)
    for path, lineno, msg in offenses:
        print(f"{os.path.relpath(path, root)}:{lineno}: {msg}")
    if offenses:
        print(f"{len(offenses)} swallowed-error offense(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
