"""Aux subsystems: webserver/metrics endpoints, raft-replicated snapshots,
export/import backup-restore, yugabyted launcher (ref: metrics endpoints
util/metrics.h:449; snapshot flow ent backup_service; bin/yugabyted)."""

import json
import shutil
import time
import urllib.request

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.tools.yb_admin import AdminClient
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING),
             ColumnSchema("n", DataType.INT64)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 3)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("auxcluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def table(cluster):
    client = cluster.new_client()
    client.create_namespace("db")
    t = client.create_table("db", "t", SCHEMA, num_tablets=2)
    cluster.wait_all_replicas_running(t.table_id)
    # deadline-poll READY raft leaders (master's replica view can lead
    # the tservers' election state): the first writes below must not
    # race the elections against the client retry budget
    cluster.wait_for_table_leaders("db", "t")
    for i in range(50):
        client.write(t, [QLWriteOp(WriteOpKind.INSERT, dk(f"k{i:03d}"),
                                   {"v": f"v{i}", "n": i})])
    return t


def _get(addr: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return r.read().decode()


def test_webserver_endpoints(cluster, table):
    mws = cluster.masters[0].webserver
    assert mws is not None
    assert _get(mws.address, "/healthz").strip() == "ok"
    status = json.loads(_get(mws.address, "/status"))
    assert status["is_leader"] is True
    assert status["num_tablets"] >= 2
    assert len(status["tservers"]) == 3
    tws = cluster.tservers[0].webserver
    prom = _get(tws.address, "/prometheus-metrics")
    assert "rows_inserted" in prom
    tablets = json.loads(_get(tws.address, "/tablets"))
    assert any(t["role"] == "leader" or t["role"] == "follower"
               for t in tablets)


def test_snapshot_on_all_replicas(cluster, table):
    master = cluster.leader_master()
    meta = master.catalog.create_table_snapshot("db", "t")
    sid = meta["snapshot_id"]
    # Raft-replicated: EVERY replica of every tablet holds the snapshot.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        counts = []
        for tablet_id in meta["tablet_ids"]:
            for ts in cluster.tservers:
                try:
                    peer = ts.tablet_manager.get_tablet(tablet_id)
                except Exception:  # noqa: BLE001
                    continue
                counts.append(sid in peer.tablet.list_snapshots())
        if counts and all(counts):
            break
        time.sleep(0.1)
    assert counts and all(counts), "snapshot missing on some replica"
    snaps = master.catalog.list_snapshots()
    assert any(s["snapshot_id"] == sid for s in snaps)


def test_export_import_restore(cluster, table, tmp_path):
    admin = AdminClient(cluster.master_addrs())
    try:
        meta = cluster.leader_master().catalog.create_table_snapshot(
            "db", "t")
        out = str(tmp_path / "backup")
        admin.export_snapshot(meta["snapshot_id"], out)
        admin.import_snapshot(out, "db", "t_restored")
        client = cluster.new_client()
        restored = client.open_table("db", "t_restored")
        for i in (0, 25, 49):
            row = client.read_row(restored, dk(f"k{i:03d}"))
            assert row is not None
            assert row.columns[SCHEMA.column_id("v")] == f"v{i}"
        rows = list(client.scan(restored))
        assert len(rows) == 50
    finally:
        admin.client.close()


def test_snapshot_is_point_in_time(cluster, table, tmp_path):
    client = cluster.new_client()
    master = cluster.leader_master()
    meta = master.catalog.create_table_snapshot("db", "t")
    # Mutations after the snapshot must not appear in a restore of it.
    client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk("post-snap"),
                                   {"v": "late", "n": 999})])
    admin = AdminClient(cluster.master_addrs())
    try:
        out = str(tmp_path / "pit")
        admin.export_snapshot(meta["snapshot_id"], out)
        admin.import_snapshot(out, "db", "t_pit")
        restored = client.open_table("db", "t_pit")
        assert client.read_row(restored, dk("post-snap")) is None
        assert client.read_row(restored, dk("k001")) is not None
    finally:
        admin.client.close()


def test_delete_snapshot(cluster, table):
    master = cluster.leader_master()
    meta = master.catalog.create_table_snapshot("db", "t")
    sid = meta["snapshot_id"]
    master.catalog.delete_snapshot(sid)
    assert not any(s["snapshot_id"] == sid
                   for s in master.catalog.list_snapshots())
    # tserver-side deletion propagates asynchronously: poll, don't race.
    # Generous deadline: under a full-suite run on a 1-core box the
    # heartbeat that carries the deletion can be starved well past 20s.
    deadline = time.monotonic() + 60

    def _gone():
        return all(sid not in ts.tablet_manager.get_tablet(tid)
                   .tablet.list_snapshots()
                   for ts in cluster.tservers
                   for tid in ts.tablet_manager.tablet_ids())
    while not _gone():
        assert time.monotonic() < deadline, (
            f"snapshot {sid} still present on a tserver after 20s")
        time.sleep(0.1)


def test_yugabyted_single_node(tmp_path):
    from yugabyte_tpu.tools.yugabyted import YugabytedNode
    from yugabyte_tpu.yql.cql.executor import QLProcessor
    from yugabyte_tpu.client.client import YBClient
    flags.set_flag("replication_factor", 1)
    node = YugabytedNode(str(tmp_path / "node"))
    try:
        eps = node.endpoints()
        assert "master_rpc" in eps and "tserver_rpc" in eps
        client = YBClient(node.master_addrs)
        ql = QLProcessor(client)
        ql.execute("CREATE KEYSPACE app")
        ql.execute("USE app")
        ql.execute("CREATE TABLE kv (k TEXT PRIMARY KEY, v TEXT) "
                   "WITH tablets = 1")
        ql.execute("INSERT INTO kv (k, v) VALUES ('one', '1')")
        rs = ql.execute("SELECT v FROM kv WHERE k = 'one'")
        assert rs.rows == [["1"]]
        client.close()
    finally:
        flags.reset_flag("replication_factor")
        node.shutdown()


def test_observability_endpoints(tmp_path):
    """/rpcz, /tracez, /threadz on a live tserver webserver (ref
    rpc/rpcz_store.cc and the debug-util pages)."""
    import json
    import urllib.request
    from yugabyte_tpu.integration.mini_cluster import (
        MiniCluster, MiniClusterOptions)
    from yugabyte_tpu.utils import flags
    from yugabyte_tpu.utils.trace import Trace, TRACE

    old_rf = flags.get_flag("replication_factor")
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1, fs_root=str(tmp_path / "fs"))).start()
    try:
        ts = c.tservers[0]
        # generate some RPC traffic + a completed trace
        client = c.new_client()
        client.list_tservers()
        with Trace("test-op"):
            TRACE("step one")
            TRACE("step two")
        base = f"http://{ts.webserver.address}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                return json.loads(r.read())

        rpcz = get("/rpcz")
        assert "inbound_recent" in rpcz and "inbound_in_flight" in rpcz
        # the tserver heartbeats/reports produced inbound traffic somewhere;
        # at minimum the structure is served and entries carry the fields
        for e in rpcz["inbound_recent"]:
            assert {"svc", "mth", "duration_ms", "peer"} <= set(e)
        tz = get("/tracez")
        # flat span ring + spans grouped by trace_id (per-hop view)
        assert any(t["name"] == "test-op" and "step one" in t["dump"]
                   for t in tz["spans"])
        assert all("trace_id" in t and "span_id" in t for t in tz["spans"])
        assert any(g["n_spans"] >= 1 and g["spans"]
                   for g in tz["traces"])
        th = get("/threadz")
        assert any("webserver" in t["name"] for t in th)
        assert all("stack" in t for t in th)
    finally:
        c.shutdown()
        flags.set_flag("replication_factor", old_rf)
