"""SysCatalog: the master's own catalog table.

Capability parity with the reference (ref: src/yb/master/sys_catalog.h:77-95
— "the sys catalog is a single-tablet DocDB table replicated across all
masters via Raft"). Entries are (entry_type, entry_id) -> JSON metadata,
written through the exact same TabletPeer/WriteQuery/Raft/LSM stack user
tablets use — master failover replays the sys catalog WAL like any tablet.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import HybridClock
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.tablet.tablet_peer import TabletPeer
from yugabyte_tpu.utils import jsonutil

SYS_CATALOG_TABLET_ID = "sys.catalog"

SYS_SCHEMA = Schema(
    columns=[
        ColumnSchema("entry_type", DataType.STRING),
        ColumnSchema("entry_id", DataType.STRING),
        ColumnSchema("metadata", DataType.STRING),
    ],
    num_hash_key_columns=2)


class SysCatalog:
    """Typed wrapper over the sys catalog TabletPeer."""

    def __init__(self, data_dir: str, master_id: str,
                 master_ids, transport, clock: Optional[HybridClock] = None):
        self.peer = TabletPeer(
            SYS_CATALOG_TABLET_ID, data_dir, SYS_SCHEMA,
            server_id=master_id, server_ids=list(master_ids),
            transport=transport, clock=clock)

    def start(self) -> "SysCatalog":
        self.peer.start(election_timer=True)
        return self

    @staticmethod
    def _key(entry_type: str, entry_id: str) -> DocKey:
        return DocKey(hash_components=(entry_type, entry_id))

    # ------------------------------------------------------------- mutations
    def upsert(self, entry_type: str, entry_id: str, metadata: dict) -> None:
        self.peer.write([QLWriteOp(
            WriteOpKind.INSERT, self._key(entry_type, entry_id),
            {"metadata": jsonutil.dumps(metadata, sort_keys=True)})])

    def delete(self, entry_type: str, entry_id: str) -> None:
        self.peer.write([QLWriteOp(
            WriteOpKind.DELETE_ROW, self._key(entry_type, entry_id))])

    # ----------------------------------------------------------------- reads
    def get(self, entry_type: str, entry_id: str) -> Optional[dict]:
        row = self.peer.tablet.read_row(self._key(entry_type, entry_id))
        if row is None:
            return None
        return jsonutil.loads(
            row.columns[SYS_SCHEMA.column_id("metadata")])

    def scan_all(self) -> Iterator[Tuple[str, str, dict]]:
        """(entry_type, entry_id, metadata) for every live entry — the
        catalog-loader path on master failover (ref catalog_loaders.cc)."""
        for row in self.peer.tablet.scan(use_device=False):
            etype, eid = row.doc_key.hash_components
            yield etype, eid, jsonutil.loads(
                row.columns[SYS_SCHEMA.column_id("metadata")])

    def shutdown(self) -> None:
        self.peer.shutdown()
