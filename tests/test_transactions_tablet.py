"""Tablet-level transaction mechanics: intents, conflict detection,
read-your-writes, commit apply, abort cleanup (ref: docdb/docdb-test.cc
transactional cases, conflict_resolution-test, randomized_docdb-test)."""

import pytest

from yugabyte_tpu.common.hybrid_time import HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.conflict_resolution import TransactionConflict
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.docdb.intents import TransactionMetadata, txn_intents
from yugabyte_tpu.tablet.tablet import Tablet

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


def ins(k: str, v: str) -> QLWriteOp:
    return QLWriteOp(WriteOpKind.INSERT, dk(k), {"v": v})


@pytest.fixture
def tablet(tmp_path):
    statuses = {}
    t = Tablet("t-txn", str(tmp_path / "t"), SCHEMA)
    t.status_resolver = lambda st_tablet, txn_id, read_ht=None: statuses.get(
        txn_id, {"status": "pending", "commit_ht": None})
    yield t, statuses
    t.close()


def commit(tablet: Tablet, statuses, meta) -> HybridTime:
    commit_ht = tablet.clock.now()
    statuses[meta.txn_id] = {"status": "committed",
                             "commit_ht": commit_ht.value}
    return commit_ht


def test_txn_write_invisible_until_commit(tablet):
    t, statuses = tablet
    meta = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("a", "txn-val")], meta)
    # Plain snapshot read: pending intent is invisible.
    assert t.read_row(dk("a")) is None
    # Read-your-writes: the owning txn sees it.
    row = t.read_row(dk("a"), txn_id=meta.txn_id)
    assert row is not None and row.columns[0] == "txn-val"
    # Commit (status only): data visible through the overlay BEFORE the
    # intents are physically applied.
    commit(t, statuses, meta)
    row = t.read_row(dk("a"))
    assert row is not None and row.columns[0] == "txn-val"


def test_apply_moves_intents_to_regular(tablet):
    t, statuses = tablet
    meta = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("a", "v1"), ins("b", "v2")], meta)
    commit_ht = commit(t, statuses, meta)
    t.apply_txn_update("apply", meta.txn_id, commit_ht.value,
                       t.clock.now().value, (1, 100))
    assert txn_intents(t.intents_db, meta.txn_id) == []
    for k, v in (("a", "v1"), ("b", "v2")):
        row = t.read_row(dk(k))
        assert row is not None and row.columns[0] == v
        assert row.write_ht.value == commit_ht.value
    # Scan sees both rows exactly once.
    rows = list(t.scan(use_device=False))
    assert sorted(r.doc_key.hash_components[0] for r in rows) == ["a", "b"]


def test_abort_cleanup(tablet):
    t, statuses = tablet
    meta = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("a", "doomed")], meta)
    statuses[meta.txn_id] = {"status": "aborted", "commit_ht": None}
    t.apply_txn_update("cleanup", meta.txn_id, 0,
                       t.clock.now().value, (1, 101))
    assert txn_intents(t.intents_db, meta.txn_id) == []
    assert t.read_row(dk("a")) is None
    assert t.read_row(dk("a"), txn_id=meta.txn_id) is None


def test_txn_conflict_with_pending_txn(tablet):
    t, statuses = tablet
    m1 = TransactionMetadata.new("status-tab")
    m2 = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("hot", "one")], m1)
    with pytest.raises(TransactionConflict):
        t.write_transactional([ins("hot", "two")], m2)
    # Plain writes also refuse to stomp on live intents.
    with pytest.raises(TransactionConflict):
        t.write([ins("hot", "plain")])
    # Disjoint keys never conflict.
    t.write_transactional([ins("cold", "fine")], m2)


def test_conflict_clears_after_abort(tablet):
    t, statuses = tablet
    m1 = TransactionMetadata.new("status-tab")
    m2 = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("hot", "one")], m1)
    statuses[m1.txn_id] = {"status": "aborted", "commit_ht": None}
    t.write_transactional([ins("hot", "two")], m2)  # no conflict now
    commit(t, statuses, m2)
    row = t.read_row(dk("hot"))
    assert row is not None and row.columns[0] == "two"


def test_snapshot_write_conflict(tablet):
    t, statuses = tablet
    read_ht = t.clock.now()
    t.write([ins("k", "newer-committed")])
    meta = TransactionMetadata.new("status-tab", read_ht=read_ht.value)
    with pytest.raises(TransactionConflict):
        t.write_transactional([ins("k", "stale")], meta)


def test_same_txn_multiple_batches(tablet):
    t, statuses = tablet
    meta = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("x", "1")], meta)
    t.write_transactional([ins("y", "2")], meta)   # no self-conflict
    t.write_transactional([ins("x", "3")], meta)   # overwrite own intent
    commit_ht = commit(t, statuses, meta)
    t.apply_txn_update("apply", meta.txn_id, commit_ht.value,
                       t.clock.now().value, (1, 102))
    row = t.read_row(dk("x"))
    assert row is not None and row.columns[0] == "3"
    assert t.read_row(dk("y")).columns[0] == "2"


def test_restart_preserves_unresolved_intents(tmp_path):
    statuses = {}
    t = Tablet("t-r", str(tmp_path / "t"), SCHEMA)
    meta = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("a", "pending")], meta)
    t.flush()
    t.close()
    t2 = Tablet("t-r", str(tmp_path / "t"), SCHEMA)
    t2.status_resolver = lambda st, txn, read_ht=None: statuses.get(
        txn, {"status": "pending", "commit_ht": None})
    # 2 strong intents (liveness + value column) + 1 weak doc-key intent.
    assert len(txn_intents(t2.intents_db, meta.txn_id)) == 3
    assert t2.read_row(dk("a")) is None
    commit_ht = commit(t2, statuses, meta)
    t2.apply_txn_update("apply", meta.txn_id, commit_ht.value,
                        t2.clock.now().value, (1, 103))
    assert t2.read_row(dk("a")).columns[0] == "pending"
    t2.close()


def test_late_cleanup_skips_foreign_intent(tablet):
    """ADVICE r1 #1: after txn A's intent at a key is resolved and txn B
    legally writes its own intent there, a LATE duplicate cleanup
    notification for A must not tombstone B's live intent."""
    t, statuses = tablet
    ma = TransactionMetadata.new("status-tab")
    mb = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("hot", "a-val")], ma)
    statuses[ma.txn_id] = {"status": "aborted", "commit_ht": None}
    t.apply_txn_update("cleanup", ma.txn_id, 0, t.clock.now().value, (1, 200))
    # B takes over the key (conflict resolution permits overwriting a
    # resolved intent).
    t.write_transactional([ins("hot", "b-val")], mb)
    assert len(txn_intents(t.intents_db, mb.txn_id)) == 3
    # Duplicate/late cleanup for A arrives again: must be a no-op for B.
    t.apply_txn_update("cleanup", ma.txn_id, 0, t.clock.now().value, (1, 201))
    assert len(txn_intents(t.intents_db, mb.txn_id)) == 3
    commit_ht = commit(t, statuses, mb)
    t.apply_txn_update("apply", mb.txn_id, commit_ht.value,
                       t.clock.now().value, (1, 202))
    row = t.read_row(dk("hot"))
    assert row is not None and row.columns[0] == "b-val"


def test_late_apply_does_not_publish_foreign_intent(tablet):
    """ADVICE r1 #1 (apply side): a late duplicate APPLY for txn A must not
    publish txn B's uncommitted value at A's commit time."""
    t, statuses = tablet
    ma = TransactionMetadata.new("status-tab")
    mb = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("hot", "a-val")], ma)
    commit_ht = commit(t, statuses, ma)
    t.apply_txn_update("apply", ma.txn_id, commit_ht.value,
                       t.clock.now().value, (1, 210))
    t.write_transactional([ins("hot", "b-uncommitted")], mb)
    # Late duplicate apply for A: B's pending intent must stay provisional.
    t.apply_txn_update("apply", ma.txn_id, commit_ht.value,
                       t.clock.now().value, (1, 211))
    row = t.read_row(dk("hot"))
    assert row is not None and row.columns[0] == "a-val"
    assert len(txn_intents(t.intents_db, mb.txn_id)) == 3


def test_intents_flush_persists_regular_first(tablet):
    """ADVICE r1 #3: the intents DB's flushed frontier must never advance
    past the regular DB's, or a crash between the two flushes replays
    OP_UPDATE_TXN against already-tombstoned intents and loses rows."""
    t, statuses = tablet
    meta = TransactionMetadata.new("status-tab")
    t.write_transactional([ins("a", "v1")], meta)
    commit_ht = commit(t, statuses, meta)
    t.apply_txn_update("apply", meta.txn_id, commit_ht.value,
                       t.clock.now().value, (5, 500))
    # Flush ONLY the intents DB: the pre-flush hook must persist the
    # regular DB first so its frontier covers the apply op.
    t.intents_db.flush()
    reg_f = t.regular_db.versions.flushed_frontier
    int_f = t.intents_db.versions.flushed_frontier
    assert int_f is not None and reg_f is not None
    assert reg_f.op_id_max >= int_f.op_id_max
