"""Exactly-once writes: the per-tablet retryable-request registry.

Capability parity with the reference (ref: src/yb/consensus/
retryable_requests.cc): every client write carries (client_id,
request_id); the pair rides the REPLICATED write-batch payload, so every
replica rebuilds the registry as entries apply — dedup state survives
leader changes and restarts (WAL replay repopulates it). A retry of a
write whose first attempt already replicated returns the original result
instead of applying twice; a retry racing its own in-flight first attempt
is pushed back to the client's retry loop until the fate settles.

Entries expire after retryable_request_timeout_s (ref
retryable_request_timeout_secs): a client that retries longer than that
has long since exhausted its RPC budget.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from yugabyte_tpu.utils import flags

flags.define_flag("retryable_request_timeout_s", 660,
                  "replicated write dedup records are kept this long "
                  "(ref retryable_request_timeout_secs)")
flags.define_flag("retryable_request_inflight_timeout_s", 120,
                  "an appended-but-never-applied request tag (its log entry "
                  "was overwritten without the abort watcher firing) stops "
                  "blocking retries after this long")

RequestId = Tuple[bytes, int]  # (client uuid bytes, per-client counter)


class RetryableRequests:
    def __init__(self):
        self._lock = threading.Lock()
        # replicated: request -> (result ht value, wall time recorded)
        self._replicated: Dict[RequestId, Tuple[int, float]] = {}
        self._in_flight: Dict[RequestId, float] = {}   # -> tracked-at time
        self._last_gc = 0.0

    def check_or_track(self, client_id: bytes, request_id: int
                       ) -> Tuple[str, Optional[int]]:
        """-> ("duplicate", ht) | ("in_flight", None) | ("new", None).
        "new" registers the request as in-flight."""
        req = (client_id, request_id)
        now = time.monotonic()
        with self._lock:
            self._maybe_gc(now)
            hit = self._replicated.get(req)
            if hit is not None:
                return "duplicate", hit[0]
            t = self._in_flight.get(req)
            if t is not None:
                if (now - t < flags.get_flag(
                        "retryable_request_inflight_timeout_s")):
                    return "in_flight", None
                # expired in-flight (orphaned tag): treat as new
            self._in_flight[req] = now
            return "new", None

    def track_appended(self, client_id: bytes, request_id: int) -> None:
        """Log-append hook on EVERY replica: a stored-but-unapplied entry's
        request is in-flight, so a retry arriving at a freshly elected
        leader before applies catch up is pushed back, not re-executed."""
        req = (client_id, request_id)
        with self._lock:
            if req not in self._replicated:
                self._in_flight.setdefault(req, time.monotonic())

    def replicated(self, client_id: bytes, request_id: int,
                   ht_value: int) -> None:
        """Called on EVERY replica as the write batch applies (and during
        WAL replay) — this is what makes dedup survive failover."""
        req = (client_id, request_id)
        with self._lock:
            self._replicated[req] = (ht_value, time.monotonic())
            self._in_flight.pop(req, None)

    def failed(self, client_id: bytes, request_id: int) -> None:
        """The attempt definitively did NOT replicate (rejected before
        append, or the fate watcher saw the entry overwritten)."""
        with self._lock:
            self._in_flight.pop((client_id, request_id), None)

    def inherit_from(self, parent: "RetryableRequests") -> None:
        """Tablet split: both children adopt the parent's records so dedup
        survives the split (the reference copies the retryable-requests
        structure into the children the same way)."""
        with parent._lock:
            replicated = dict(parent._replicated)
            in_flight = dict(parent._in_flight)
        with self._lock:
            self._replicated.update(replicated)
            for req, t in in_flight.items():
                self._in_flight.setdefault(req, t)

    def _maybe_gc(self, now: float) -> None:
        if now - self._last_gc < 10.0:
            return
        self._last_gc = now
        ttl = flags.get_flag("retryable_request_timeout_s")
        dead = [r for r, (_ht, t) in self._replicated.items()
                if now - t > ttl]
        for r in dead:
            del self._replicated[r]
        # orphaned in-flight tags (overwritten follower entries, clients
        # that never retried) must not accumulate forever
        in_ttl = flags.get_flag("retryable_request_inflight_timeout_s")
        stale = [r for r, t in self._in_flight.items() if now - t > in_ttl]
        for r in stale:
            del self._in_flight[r]

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicated)
