"""TPU merge + MVCC-GC kernel: the north-star compaction hot path.

Replaces the reference's three sequential hot loops (SURVEY.md section 3.4):
 1. k-way MergingIterator min-heap merge   (ref: rocksdb/table/merger.cc:51)
 2. CompactionIterator seqno/version dedup (ref: rocksdb/db/compaction_iterator.cc:97)
 3. DocDBCompactionFilter MVCC GC          (ref: docdb/docdb_compaction_filter.cc:74-320)

with ONE fused data-parallel program:
 - merge: multi-operand `lax.sort` over (key words, key_len, ~ht, ~write_id)
   — sorted-run union via a single large sort that XLA tiles efficiently,
   instead of a pointer-chasing heap. Keys sort in exact memcmp order
   (see ops/slabs.py).
 - version GC: segmented prefix ops. Within each full-key segment (versions
   sorted HT-descending), every version with ht > history_cutoff is retained
   history; among versions with ht <= cutoff only the FIRST (the version
   visible at the cutoff) survives — the overwrite rule of
   docdb_compaction_filter.cc:166.
 - subtree overwrite: a root-level (DocKey, no subkeys) write at ht_r <=
   cutoff overwrites every deeper entry with ht <= ht_r (the overwrite-stack
   truncation of docdb_compaction_filter.cc:104-123, restricted to depth-2
   documents: row + column entries, which covers the relational data model;
   deeper docs take the CPU semantic path).
 - TTL expiry: entries whose (write_time + ttl) <= cutoff become tombstones,
   dropped entirely at major compactions (docdb_compaction_filter.cc:260-279).
 - tombstone GC: visible-at-cutoff tombstones are dropped at major
   compactions (docdb_compaction_filter.cc:316-319).

All control flow is static; shapes are static per (N, W); no data-dependent
Python inside jit. int64 is avoided (TPU-unfriendly): hybrid times travel as
two uint32 limbs and TTL arithmetic is two-limb 20/32-bit.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_tpu.ops.slabs import (
    FLAG_HAS_TTL, FLAG_OBJECT_INIT, FLAG_TOMBSTONE, KVSlab)


@dataclass(frozen=True)
class GCParams:
    history_cutoff_ht: int      # HybridTime.value; versions above stay
    is_major_compaction: bool   # bottommost level: tombstones can vanish
    retain_deletes: bool = False  # e.g. during index backfill (ref :288)


def _le_u64(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def _seg_propagate_last(vals, is_set, new_seg):
    """Within segments (new_seg marks starts), propagate forward the most
    recent tuple of values where is_set, else zeros.

    Monoid of functions f(x) = v if has else (bottom if blocked else x);
    composition is associative, so lax.associative_scan applies.
    """
    def combine(a, b):
        *a_vals, a_set, a_bound = a
        *b_vals, b_set, b_bound = b
        out_vals = tuple(
            jnp.where(b_set, bv, jnp.where(b_bound, jnp.zeros_like(av), av))
            for av, bv in zip(a_vals, b_vals))
        out_set = b_set | (a_set & ~b_bound)
        out_bound = a_bound | b_bound
        return (*out_vals, out_set, out_bound)

    init = tuple(jnp.where(is_set, v, 0) for v in vals) + (is_set, new_seg)
    res = jax.lax.associative_scan(combine, init)
    return res[: len(vals)]


@functools.partial(jax.jit, static_argnames=("is_major", "retain_deletes"))
def _merge_gc_impl(key_words, key_len, doc_key_len, ht_hi, ht_lo, write_id,
                   flags, ttl_hi, ttl_lo, idx,
                   cutoff_hi, cutoff_lo, cutoff_phys_hi, cutoff_phys_lo,
                   is_major: bool, retain_deletes: bool):
    n, w = key_words.shape
    u32max = jnp.uint32(0xFFFFFFFF)

    # ---- 1. the merge: one big lexicographic sort -------------------------
    operands = [key_words[:, j] for j in range(w)]
    operands += [key_len.astype(jnp.int32), ht_hi ^ u32max, ht_lo ^ u32max,
                 write_id ^ u32max, idx.astype(jnp.int32)]
    sorted_ops = jax.lax.sort(operands, num_keys=len(operands))
    s_words = jnp.stack(sorted_ops[:w], axis=1)
    s_len = sorted_ops[w]
    perm = sorted_ops[w + 4]
    s_ht_hi = sorted_ops[w + 1] ^ u32max
    s_ht_lo = sorted_ops[w + 2] ^ u32max
    s_wid = sorted_ops[w + 3] ^ u32max
    s_dkl = doc_key_len[perm]
    s_flags = flags[perm]
    s_ttl_hi = ttl_hi[perm]
    s_ttl_lo = ttl_lo[perm]

    # ---- 2. segment structure --------------------------------------------
    prev_words = jnp.concatenate([jnp.zeros((1, w), s_words.dtype), s_words[:-1]], axis=0)
    prev_len = jnp.concatenate([jnp.full((1,), -1, s_len.dtype), s_len[:-1]])
    same_key = jnp.all(s_words == prev_words, axis=1) & (s_len == prev_len)
    same_key = same_key.at[0].set(False)
    new_seg = ~same_key

    # doc segments: equality of the DocKey prefix (masked word compare)
    word_idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    nbytes = jnp.clip(s_dkl[:, None] - word_idx * 4, 0, 4)
    mask = jnp.where(nbytes >= 4, u32max,
                     jnp.where(nbytes == 0, jnp.uint32(0),
                               (u32max << ((4 - nbytes).astype(jnp.uint32) * 8)) & u32max))
    doc_words = s_words & mask
    prev_doc_words = jnp.concatenate([jnp.zeros((1, w), s_words.dtype), doc_words[:-1]], axis=0)
    prev_dkl = jnp.concatenate([jnp.full((1,), -1, s_dkl.dtype), s_dkl[:-1]])
    same_doc = jnp.all(doc_words == prev_doc_words, axis=1) & (s_dkl == prev_dkl)
    same_doc = same_doc.at[0].set(False)
    new_doc = ~same_doc

    # ---- 3. version visibility within full-key segments -------------------
    c = _le_u64(s_ht_hi, s_ht_lo, cutoff_hi, cutoff_lo)  # at-or-below history cutoff
    c_i = c.astype(jnp.int32)
    total = jnp.cumsum(c_i)
    base = jax.lax.cummax(jnp.where(new_seg, total - c_i, 0))
    within_c = total - base                      # rank among <=cutoff versions in segment
    visible_slot = c & (within_c == 1)           # the version readable at cutoff
    keep_version = ~c | visible_slot

    # ---- 4. TTL expiry (two-limb add/compare; phys time = ht >> 12) -------
    has_ttl = (s_flags & FLAG_HAS_TTL) != 0
    phys_hi = s_ht_hi                            # bits 20..51 of phys micros
    phys_lo = (s_ht_lo >> 12)                    # low 20 bits
    sum_lo = phys_lo + s_ttl_lo
    carry = sum_lo >> 20
    sum_hi = phys_hi + s_ttl_hi + carry
    sum_lo = sum_lo & jnp.uint32(0xFFFFF)
    expired = has_ttl & ((sum_hi < cutoff_phys_hi) |
                         ((sum_hi == cutoff_phys_hi) & (sum_lo <= cutoff_phys_lo)))
    is_tomb = ((s_flags & FLAG_TOMBSTONE) != 0) | (expired & c)

    # ---- 5. root-subtree overwrite ---------------------------------------
    # Compare FULL DocHybridTime (ht, write_id): columns written in the same
    # batch as a row init marker share its HT but have larger write_ids, and
    # must NOT count as overwritten.
    is_root = s_len == s_dkl
    ov_flag = is_root & visible_slot
    ov_hi, ov_lo, ov_wid = _seg_propagate_last(
        (s_ht_hi, s_ht_lo, s_wid), ov_flag, new_doc)
    has_ov = (ov_hi != 0) | (ov_lo != 0)
    dht_le = (s_ht_hi < ov_hi) | ((s_ht_hi == ov_hi) & (
        (s_ht_lo < ov_lo) | ((s_ht_lo == ov_lo) & (s_wid <= ov_wid))))
    covered = (~is_root) & has_ov & dht_le

    # ---- 6. tombstone GC at major compactions ----------------------------
    drop_tomb = (visible_slot & is_tomb & jnp.bool_(is_major)
                 & jnp.bool_(not retain_deletes))

    keep = keep_version & ~covered & ~drop_tomb
    already_tomb = (s_flags & FLAG_TOMBSTONE) != 0
    make_tombstone = expired & keep & c & ~already_tomb & jnp.bool_(not is_major)
    return perm, keep, make_tombstone


def merge_and_gc_device(slab: KVSlab, params: GCParams, device=None
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run the fused merge+GC program on `device` (default: JAX default device).

    Returns (perm, keep, make_tombstone) as host numpy arrays:
      perm[i]  = input index of the i-th entry in merged order
      keep[i]  = survives compaction
      make_tombstone[i] = value must be rewritten as a tombstone (TTL expiry
                          at a non-major compaction)
    """
    if slab.n == 0:
        empty_i = np.zeros(0, dtype=np.int32)
        empty_b = np.zeros(0, dtype=bool)
        return empty_i, empty_b, empty_b
    cutoff = params.history_cutoff_ht
    cutoff_phys = cutoff >> 12
    ttl_us = slab.ttl_ms * 1000
    args = (
        jnp.asarray(slab.key_words), jnp.asarray(slab.key_len),
        jnp.asarray(slab.doc_key_len),
        jnp.asarray(slab.ht_hi), jnp.asarray(slab.ht_lo),
        jnp.asarray(slab.write_id),
        jnp.asarray(slab.flags),
        jnp.asarray((ttl_us >> 20).astype(np.uint32)),
        jnp.asarray((ttl_us & 0xFFFFF).astype(np.uint32)),
        jnp.arange(slab.n, dtype=jnp.int32),
        jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
        jnp.uint32(cutoff_phys >> 20), jnp.uint32(cutoff_phys & 0xFFFFF),
    )
    if device is not None:
        args = jax.device_put(args, device)
    perm, keep, mk = _merge_gc_impl(*args, is_major=params.is_major_compaction,
                                    retain_deletes=params.retain_deletes)
    return np.asarray(perm), np.asarray(keep), np.asarray(mk)
