"""Crash-fault tests: real processes, kill -9, torn WAL tails, crash
points (round-2 Missing #7 / Weak #7; ref src/yb/integration-tests/
external_mini_cluster.h, rocksdb/db/fault_injection_test.cc,
cluster_verifier.h).

These spawn real master/tserver subprocesses (integration/
external_mini_cluster.py) — the only way a test can kill -9 a server.
"""

import os
import time

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.integration.external_mini_cluster import (
    ExternalMiniCluster)
from yugabyte_tpu.utils.status import StatusError


def _schema():
    return Schema([ColumnSchema("k", DataType.STRING),
                   ColumnSchema("v", DataType.INT64)],
                  num_hash_key_columns=1, num_range_key_columns=0)


def _op(k, v):
    return QLWriteOp(WriteOpKind.INSERT, DocKey(hash_components=(k,)),
                     {"v": v})


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = ExternalMiniCluster(
        str(tmp_path_factory.mktemp("extcluster")), num_tservers=3,
        rf=3).start()
    yield c
    c.shutdown()


def _wait_writes_ok(client, table, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            client.write(table, [_op("warmup", 0)])
            return
        except StatusError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.3)


def test_leader_kill9_mid_load_loses_no_acked_write(cluster):
    """The headline crash test: kill -9 a tserver while a client hammers
    writes; every ACKED write must survive, and all replicas must
    converge to identical checksums."""
    cluster.wait_tservers_alive(3)
    client = cluster.new_client()
    client.create_namespace("crashns")
    table = client.create_table("crashns", "t", _schema(), num_tablets=2)
    _wait_writes_ok(client, table)
    acked = {}
    victim = 0
    killed = False
    for i in range(300):
        k = f"row{i:04d}"
        try:
            client.write(table, [_op(k, i)])
            acked[k] = i
        except StatusError:
            pass  # unacked: free to be lost or applied
        if i == 120:
            cluster.tservers[victim].kill9()   # mid-load, no warning
            killed = True
    assert killed and len(acked) > 250
    # cluster must still serve (RF=3 survives one loss)
    for k, v in list(acked.items())[:20]:
        row = client.read_row(table, DocKey(hash_components=(k,)))
        assert row is not None
    # restart the victim on its old data dir; it must catch up
    cluster.tservers[victim].start()
    _wait_writes_ok(client, table)
    # every acked write present at a consistent snapshot
    seen = {}
    for row in client.scan(table):
        d = row.to_dict(table.schema)
        if d["k"] in acked:
            seen[d["k"]] = d["v"]
    missing = {k for k in acked if k not in seen}
    assert not missing, f"lost {len(missing)} acked writes: {sorted(missing)[:5]}"
    # replicas byte-converge (incl. the restarted one)
    cluster.verify_replica_checksums(client, table)
    client.close()


def test_crash_point_mid_flush_recovers(cluster):
    """kill -9 exactly between SST write and manifest install
    (db.flush:before_manifest): the orphan SST must be ignored and every
    row recovered from the WAL."""
    cluster.wait_tservers_alive(3)
    client = cluster.new_client()
    client.create_namespace("flushns")
    table = client.create_table("flushns", "tf", _schema(), num_tablets=1)
    _wait_writes_ok(client, table)
    for i in range(40):
        client.write(table, [_op(f"pre{i:03d}", i)])
    # re-arm ts1 to die mid-flush, then force the flush path by restarting
    # it with the crash point armed (bootstrap replays then flushes on
    # write volume; drive writes until it dies)
    victim = 1
    # a tiny memstore makes the flush (and its crash point) fire quickly
    cluster.restart_tserver(victim,
                            crash_point="db.flush:before_manifest",
                            extra_flags={"memstore_size_bytes": 4096})
    deadline = time.monotonic() + 90
    i = 0
    while cluster.tservers[victim].alive():
        try:
            client.write(table, [_op(f"fl{i:05d}", i)])
            i += 1
        except StatusError:
            pass  # the victim may lead this tablet and die mid-write
        if time.monotonic() > deadline:
            pytest.fail("flush crash point did not fire in time")
    # normal restart: recovery must see every row despite the torn flush
    cluster.tservers[victim].start()
    _wait_writes_ok(client, table)
    for k, v in [("pre000", 0), (f"fl{i-1:05d}", i - 1)]:
        row = client.read_row(table, DocKey(hash_components=(k,)))
        assert row is not None, k
    cluster.verify_replica_checksums(client, table)
    client.close()


def test_torn_wal_tail_replay(cluster, tmp_path):
    """Truncate the WAL mid-record on a killed node; restart must stop at
    the torn record and rejoin, re-fetching the tail from the leader."""
    cluster.wait_tservers_alive(3)
    client = cluster.new_client()
    client.create_namespace("tornns")
    table = client.create_table("tornns", "tt", _schema(), num_tablets=1)
    _wait_writes_ok(client, table)
    for i in range(60):
        client.write(table, [_op(f"w{i:03d}", i)])
    victim = 2
    cluster.tservers[victim].kill9()
    # tear the last WAL segment of every tablet dir on the victim
    root = cluster.tservers[victim].fs_root
    torn = 0
    for dirpath, _dirs, files in os.walk(root):
        wals = sorted(f for f in files if f.startswith("wal-"))
        if wals and dirpath.endswith("wal"):
            p = os.path.join(dirpath, wals[-1])
            size = os.path.getsize(p)
            if size > 7:
                with open(p, "r+b") as f:
                    f.truncate(size - 7)  # mid-record
                torn += 1
    assert torn > 0, "no WAL segment found to tear"
    cluster.tservers[victim].start()
    _wait_writes_ok(client, table)
    # all rows still readable; replicas reconverge (the torn replica
    # re-replicates its missing tail from the leader)
    for i in range(0, 60, 7):
        row = client.read_row(table,
                              DocKey(hash_components=(f"w{i:03d}",)))
        assert row is not None
    cluster.verify_replica_checksums(client, table)
    client.close()


def test_master_kill9_and_restart(cluster):
    """The control plane dies and returns: data plane writes keep working
    (leaders keep leases without the master), and DDL works again after
    the master restarts on its sys catalog."""
    cluster.wait_tservers_alive(3)
    client = cluster.new_client()
    client.create_namespace("mns")
    table = client.create_table("mns", "tm", _schema(), num_tablets=1)
    _wait_writes_ok(client, table)
    cluster.master.kill9()
    # data path unaffected by a dead master (locations already cached)
    for i in range(10):
        client.write(table, [_op(f"m{i}", i)])
    cluster.master.start()
    client2 = cluster.new_client()
    deadline = time.monotonic() + 60
    while True:
        try:
            client2.create_namespace("mns2")
            break
        except StatusError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    t2 = client2.open_table("mns", "tm")
    row = client2.read_row(t2, DocKey(hash_components=("m3",)))
    assert row is not None and row.to_dict(t2.schema)["v"] == 3
    client.close()
    client2.close()
