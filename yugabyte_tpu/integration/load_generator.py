"""Sustained-load correctness generator: linked-list chains under churn.

The reference proves durability under chaos with a linked-list workload
(ref: src/yb/integration-tests/linked_list-test.cc + the rate-paced
writers of src/yb/util/load_generator.h): writers append rows that chain
to their predecessor; after arbitrary failover/compaction/split churn, a
full verification walk proves that

  - every ACKED row is present (no lost writes),
  - every present row was actually sent (no phantom rows; writes whose
    ack was lost in a crash window count as "maybe" — the reference's
    OperationOutcomeUnknown bucket),
  - every row's chain predecessor exists (prefix durability: an acked
    row can never outlive the earlier row it links to).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.session import YBSession
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.utils import ybsan
from yugabyte_tpu.utils import lock_rank

LINKED_LIST_SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("prev", DataType.STRING),
             ColumnSchema("i", DataType.INT64)],
    num_hash_key_columns=1)


def chain_key(chain: int, idx: int) -> str:
    return f"c{chain:03d}-{idx:09d}"


@dataclass
class ChainState:
    chain: int
    next_idx: int = 0
    acked: int = 0                       # rows [0, acked) are guaranteed
    maybe: Set[int] = field(default_factory=set)   # ack lost in a crash


@dataclass
class LoadReport:
    written_acked: int
    written_maybe: int
    errors: int


class LinkedListLoadGenerator:  # yblint: disable=ybsan-coverage (each writer thread owns its disjoint ChainState slot; `errors` is a best-effort harness counter; reports are built after join, so results are HB-ordered)
    """N writer threads, one chain each, paced to ops_per_sec total."""

    def __init__(self, client: YBClient, table, n_chains: int = 4,
                 ops_per_sec: float = 200.0):
        self._client = client
        self._table = table
        self._rate_per_chain = ops_per_sec / n_chains
        self.chains = [ChainState(c) for c in range(n_chains)]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.errors = 0

    # ------------------------------------------------------------- writers
    def _writer(self, st: ChainState) -> None:
        session = YBSession(self._client)
        period = 1.0 / self._rate_per_chain
        while not self._stop.is_set():
            t0 = time.monotonic()
            idx = st.next_idx
            prev = chain_key(st.chain, idx - 1) if idx else ""
            op = QLWriteOp(
                WriteOpKind.INSERT,
                DocKey(hash_components=(chain_key(st.chain, idx),)),
                {"prev": prev, "i": idx})
            try:
                session.apply(self._table, op)
                session.flush()
            except StatusError:
                # ack lost: the write may or may not have landed (a retry
                # may still commit it server-side) — the reference's
                # OperationOutcomeUnknown bucket
                st.maybe.add(idx)
                st.next_idx = idx + 1
                self.errors += 1
                time.sleep(0.2)
                continue
            st.acked = idx + 1
            st.next_idx = idx + 1
            elapsed = time.monotonic() - t0
            if elapsed < period:
                time.sleep(period - elapsed)

    def start(self) -> "LinkedListLoadGenerator":
        for st in self.chains:
            t = threading.Thread(target=self._writer, args=(st,),
                                 daemon=True, name=f"ll-writer-{st.chain}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> LoadReport:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        return LoadReport(
            written_acked=sum(s.acked - len([m for m in s.maybe
                                             if m < s.acked])
                              for s in self.chains),
            written_maybe=sum(len(s.maybe) for s in self.chains),
            errors=self.errors)

    # ------------------------------------------------------------ verifier
    def verify(self, client: Optional[YBClient] = None) -> Dict[str, int]:
        """Full-scan verification of the invariants; raises AssertionError
        with a precise message on any violation.  Returns counters."""
        client = client or self._client
        present: Dict[int, Set[int]] = {s.chain: set() for s in self.chains}
        for row in client.scan(self._table):
            d = row.to_dict(LINKED_LIST_SCHEMA)
            k = d["k"]
            chain = int(k[1:4])
            idx = int(k.split("-")[1])
            assert d["i"] == idx, f"row {k} carries wrong index {d['i']}"
            if idx:
                assert d["prev"] == chain_key(chain, idx - 1), \
                    f"row {k} links to {d['prev']!r}"
            present[chain].add(idx)
        lost: List[str] = []
        phantom: List[str] = []
        broken: List[str] = []
        for st in self.chains:
            have = present.get(st.chain, set())
            for idx in range(st.acked):
                if idx not in have and idx not in st.maybe:
                    lost.append(chain_key(st.chain, idx))
            sent_max = st.next_idx
            for idx in have:
                if idx >= sent_max:
                    phantom.append(chain_key(st.chain, idx))
            # prefix durability: a present row's predecessor must exist
            # unless that predecessor's ack was itself lost AND it truly
            # never landed — in which case the successor could only have
            # been written if the writer moved on (maybe bucket), fine;
            # but an ACKED predecessor must always exist (covered by
            # `lost` above). Here check presence-chain consistency:
            for idx in have:
                if idx and (idx - 1) not in have \
                        and (idx - 1) not in st.maybe:
                    broken.append(chain_key(st.chain, idx))
        assert not lost, f"LOST acked rows: {lost[:10]} (+{len(lost)-10 if len(lost)>10 else 0})"
        assert not phantom, f"PHANTOM rows never sent: {phantom[:10]}"
        assert not broken, f"BROKEN chains (missing predecessor): {broken[:10]}"
        return {"present": sum(len(v) for v in present.values()),
                "acked": sum(s.acked for s in self.chains),
                "maybe": sum(len(s.maybe) for s in self.chains)}


YCSB_SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


@dataclass
class YcsbReport:
    ops: int
    seconds: float
    ops_per_sec: float
    p50_ms: float
    p99_ms: float
    errors: int
    reads: int
    writes: int
    mix: str = "a"
    scans: int = 0
    scan_rows: int = 0


class YcsbALoadGenerator:  # yblint: disable=ybsan-coverage (workers write only their own _lat_ms/_counts slot — disjoint by worker id — and report() runs after join)
    """Max-rate YCSB-A (50/50 read-update over a Zipf-ish hot set) —
    the reference's perf harness workload (ref: yb-perf v1.0.7 YCSB-A on
    a 3-node RF=3 cluster; src/yb/util/load_generator.h's multi-threaded
    session writers). Unpaced: each thread issues its next op as soon as
    the previous completes, so the measured rate IS the cluster's
    sustainable throughput at this concurrency. Per-op latencies are
    kept whole (ops counts are bounded by the run length) for exact
    percentiles."""

    def __init__(self, client: YBClient, table, n_threads: int = 8,
                 key_space: int = 10_000, value_bytes: int = 64):
        self._client = client
        self._table = table
        self._n_threads = n_threads
        self._key_space = key_space
        self._value = "v" * value_bytes
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lat_ms: List[List[float]] = []
        self._counts: List[List[int]] = []  # [ops, errors, reads, writes]
        self._t0 = 0.0
        self._t1 = 0.0

    def _worker(self, wid: int) -> None:
        import random
        rng = random.Random(1000 + wid)
        session = YBSession(self._client)
        lat = self._lat_ms[wid]
        cnt = self._counts[wid]
        while not self._stop.is_set():
            # hot-set skew: 80% of ops hit 20% of the key space
            if rng.random() < 0.8:
                kid = rng.randrange(max(1, self._key_space // 5))
            else:
                kid = rng.randrange(self._key_space)
            key = f"u{kid:08d}"
            t0 = time.monotonic()
            try:
                if rng.random() < 0.5:
                    session.apply(self._table, QLWriteOp(
                        WriteOpKind.INSERT,
                        DocKey(hash_components=(key,)),
                        {"v": self._value}))
                    session.flush()
                    cnt[3] += 1
                else:
                    self._client.read_row(self._table,
                                          DocKey(hash_components=(key,)))
                    cnt[2] += 1
                lat.append((time.monotonic() - t0) * 1000.0)
                cnt[0] += 1
            except StatusError:
                cnt[1] += 1
                time.sleep(0.05)

    def start(self) -> "YcsbALoadGenerator":
        self._t0 = time.monotonic()
        for i in range(self._n_threads):
            self._lat_ms.append([])
            self._counts.append([0, 0, 0, 0])
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True, name=f"ycsb-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> YcsbReport:
        # measurement window ends at stop-request time: a worker stuck in
        # stop-unaware client retry backoff would otherwise inflate the
        # denominator with an idle join tail and understate ops/s
        self._t1 = time.monotonic()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        lats = sorted(x for ls in self._lat_ms for x in ls)
        ops = sum(c[0] for c in self._counts)
        secs = self._t1 - self._t0

        def pct(p: float) -> float:
            """Nearest-rank percentile: ceil(p*n)-1, so p50 of two samples
            is the lower one (the naive int(p*n) index reports the MAX of
            two samples as the median)."""
            if not lats:
                return 0.0
            import math
            return lats[max(0, min(len(lats) - 1,
                                   math.ceil(p * len(lats)) - 1))]

        return YcsbReport(
            ops=ops, seconds=round(secs, 1),
            ops_per_sec=round(ops / secs, 1) if secs else 0.0,
            p50_ms=round(pct(0.50), 2), p99_ms=round(pct(0.99), 2),
            errors=sum(c[1] for c in self._counts),
            reads=sum(c[2] for c in self._counts),
            writes=sum(c[3] for c in self._counts))


# YCSB core-workload mixes (ref: the YCSB core package definitions;
# yb-perf harness runs A/B/C on the 3-node RF=3 cluster). Probabilities
# per op category; absent categories are 0.
YCSB_MIXES = {
    "a": {"read": 0.50, "update": 0.50},   # update-heavy
    "b": {"read": 0.95, "update": 0.05},   # read-heavy
    "c": {"read": 1.00},                   # read-only
    "d": {"read": 0.95, "insert": 0.05},   # read-latest
    "e": {"scan": 0.95, "insert": 0.05},   # short-range scans
    "f": {"read": 0.50, "rmw": 0.50},      # read-modify-write
}


class YcsbLoadGenerator:
    """Batched YCSB driver riding the PR-11 serve path: reads go through
    the batched `multi_read` RPC (the PR-10 device point-read path under
    it), writes coalesce through the YBSession batcher into per-tablet
    group commits, scans ride the scan RPC page path (resident-slab scans
    under it when the device cache is live), and F does read-modify-write
    through the batcher. Unpaced like YcsbALoadGenerator: the measured
    rate IS the sustainable throughput at this concurrency.

    Latency accounting is per BATCH phase: every op in a batch completed
    when its batch RPC(s) settled, so each phase contributes one
    (latency, n_ops) sample and percentiles weight by op count — p99 is
    the latency an op (not a batch) experiences at the 99th percentile.
    """

    def __init__(self, client: YBClient, table, mix: str = "b",
                 n_threads: int = 4, key_space: int = 10_000,
                 value_bytes: int = 64, batch_size: int = 512,
                 scan_len: int = 50, follower_reads: bool = False):
        if mix not in YCSB_MIXES:
            raise ValueError(f"unknown YCSB mix {mix!r}")
        self._client = client
        self._table = table
        self.mix = mix
        self._probs = YCSB_MIXES[mix]
        self._n_threads = n_threads
        self._key_space = key_space
        self._value = "v" * value_bytes
        self._batch = batch_size
        self._scan_len = scan_len
        self._follower = follower_reads
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # weighted latency samples: (batch_ms, n_ops) per phase
        self._samples: List[List[tuple]] = []
        # [_, errors, reads, writes, scans, scan_rows, rmws] — phase
        # helpers touch DISJOINT slots so the write flush can overlap
        # the read batch on a side thread without racy counters
        self._counts: List[List[int]] = []
        self._insert_high = key_space  # guarded-by: _insert_lock; D-mix "latest" insert cursor
        self._insert_lock = lock_rank.tracked(
            threading.Lock(), "ycsb._insert_lock")
        self._t0 = 0.0
        self._t1 = 0.0

    @staticmethod
    def _key(kid: int) -> str:
        return f"u{kid:08d}"

    def _sample_kid(self, rng) -> int:
        if self.mix == "d":
            # read-latest: prefer the most recently inserted tail
            with self._insert_lock:
                high = self._insert_high
            if rng.random() < 0.8:
                return high - 1 - rng.randrange(
                    max(1, min(high, self._key_space // 5)))
            return rng.randrange(high)
        # hot-set skew: 80% of ops hit 20% of the key space
        if rng.random() < 0.8:
            return rng.randrange(max(1, self._key_space // 5))
        return rng.randrange(self._key_space)

    # -------------------------------------------------------------- preload
    def load(self, n_keys: Optional[int] = None,
             batch_size: int = 1024, retries: int = 5) -> int:
        """Preload the key space through the batcher (the YCSB load
        phase); returns rows written. Failed ops retry per the batcher's
        per-op demux — a fresh cluster's election tail fails only the
        groups that raced it, and only those ops are re-sent."""
        from yugabyte_tpu.client.session import SessionFlushError
        n = n_keys if n_keys is not None else self._key_space
        session = YBSession(self._client, max_batch_ops=batch_size)
        pending = [QLWriteOp(WriteOpKind.INSERT,
                             DocKey(hash_components=(self._key(kid),)),
                             {"v": self._value})
                   for kid in range(n)]
        for attempt in range(retries + 1):
            for op in pending:
                session.apply(self._table, op)
            try:
                session.flush()
                return n
            except SessionFlushError as e:
                if attempt >= retries:
                    raise
                pending = [op for _t, op, _e in e.per_op]
                time.sleep(0.5 * (attempt + 1))
        return n

    # -------------------------------------------------------------- workers
    def _worker(self, wid: int) -> None:
        import random
        rng = random.Random(2000 + wid)
        # the write phase runs on a side thread: give it its own rng and
        # session so the read phase never shares either mid-tick
        wrng = random.Random(3000 + wid)
        session = YBSession(self._client)
        samples = self._samples[wid]
        cnt = self._counts[wid]
        probs = self._probs
        while not self._stop.is_set():
            # draw this tick's batch composition from the mix
            n_read = n_write = n_rmw = n_scan = 0
            for _ in range(self._batch):
                r = rng.random()
                acc = 0.0
                for kind, p in probs.items():
                    acc += p
                    if r < acc:
                        break
                if kind == "read":
                    n_read += 1
                elif kind == "rmw":
                    n_rmw += 1
                elif kind == "scan":
                    # scans are RPC-bound per op: cap the per-tick count
                    # so one tick stays responsive to stop()
                    n_scan += 1
                else:
                    n_write += 1
            writer = None
            if n_write:
                # overlap the write flush (raft replicate wall) with the
                # read batch: the tick's wall time is max(write, read),
                # not the sum
                def _w(n=n_write):
                    try:
                        self._do_writes(wrng, session, n, samples, cnt)
                    except StatusError:
                        cnt[1] += 1
                writer = threading.Thread(target=_w, daemon=True)
                writer.start()
            try:
                if n_read:
                    self._do_reads(rng, n_read, samples, cnt)
                if n_rmw:
                    self._do_rmw(rng, n_rmw, samples, cnt)
                for _ in range(min(n_scan, 32)):
                    self._do_scan(rng, samples, cnt)
                    if self._stop.is_set():
                        break
            except StatusError:
                cnt[1] += 1
                time.sleep(0.05)
            if writer is not None:
                writer.join()

    def _do_writes(self, rng, session, n: int, samples, cnt) -> None:
        insert = "insert" in self._probs
        t0 = time.monotonic()
        for _ in range(n):
            if insert:
                with self._insert_lock:
                    kid = self._insert_high
                    self._insert_high += 1
            else:
                kid = self._sample_kid(rng)
            session.apply(self._table, QLWriteOp(
                WriteOpKind.INSERT,
                DocKey(hash_components=(self._key(kid),)),
                {"v": self._value}))
        session.flush()
        samples.append(((time.monotonic() - t0) * 1000.0, n))
        cnt[3] += n

    def _do_reads(self, rng, n: int, samples, cnt) -> None:
        keys = [DocKey(hash_components=(self._key(self._sample_kid(rng)),))
                for _ in range(n)]
        t0 = time.monotonic()
        self._client.multi_read(self._table, keys,
                                follower_read=self._follower)
        samples.append(((time.monotonic() - t0) * 1000.0, n))
        cnt[2] += n

    def _do_rmw(self, rng, n: int, samples, cnt) -> None:
        session = YBSession(self._client)
        keys = [DocKey(hash_components=(self._key(self._sample_kid(rng)),))
                for _ in range(n)]
        t0 = time.monotonic()
        rows = self._client.multi_read(self._table, keys)
        for dk_, row in zip(keys, rows):
            prior = ""
            if row is not None:
                prior = row.to_dict(self._table.schema).get("v") or ""
            session.apply(self._table, QLWriteOp(
                WriteOpKind.INSERT, dk_,
                {"v": (prior + "m")[-len(self._value):] or "m"}))
        session.flush()
        samples.append(((time.monotonic() - t0) * 1000.0, n))
        cnt[6] += n

    def _do_scan(self, rng, samples, cnt) -> None:
        import itertools
        t0 = time.monotonic()
        rows = list(itertools.islice(
            self._client.scan(self._table, page_size=self._scan_len),
            self._scan_len))
        samples.append(((time.monotonic() - t0) * 1000.0, 1))
        cnt[4] += 1
        cnt[5] += len(rows)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "YcsbLoadGenerator":
        self._t0 = time.monotonic()
        for i in range(self._n_threads):
            self._samples.append([])
            self._counts.append([0] * 7)
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True, name=f"ycsb-{self.mix}-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> YcsbReport:
        self._t1 = time.monotonic()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=60)
        samples = sorted(s for ws in self._samples for s in ws)
        reads = sum(c[2] for c in self._counts)
        writes = sum(c[3] for c in self._counts)
        scans = sum(c[4] for c in self._counts)
        rmws = sum(c[6] for c in self._counts)
        ops = reads + writes + scans + rmws  # an RMW is ONE logical op
        secs = self._t1 - self._t0
        total_w = sum(w for _ms, w in samples)

        def pct(p: float) -> float:
            """Op-weighted percentile over batch latencies: every op in
            a batch experienced that batch's latency."""
            if not samples:
                return 0.0
            target = p * total_w
            seen = 0
            for ms, w in samples:
                seen += w
                if seen >= target:
                    return ms
            return samples[-1][0]

        return YcsbReport(
            ops=ops, seconds=round(secs, 1),
            ops_per_sec=round(ops / secs, 1) if secs else 0.0,
            p50_ms=round(pct(0.50), 2), p99_ms=round(pct(0.99), 2),
            errors=sum(c[1] for c in self._counts),
            reads=reads + rmws,
            writes=writes + rmws,
            mix=self.mix,
            scans=scans,
            scan_rows=sum(c[5] for c in self._counts))
