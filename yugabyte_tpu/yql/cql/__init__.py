from yugabyte_tpu.yql.cql.executor import QLProcessor, ResultSet

__all__ = ["QLProcessor", "ResultSet"]
