"""TPU scan/filter kernel: batched MVCC snapshot resolution + range filter.

The scan-path half of the north star (SURVEY.md section 7 stage 4): where the
reference resolves MVCC visibility one iterator step at a time — min-heap
MergingIterator (ref: rocksdb/table/merger.cc:51) over block iterators
(ref: rocksdb/table/block_based_table_reader.cc:1168) with per-key seeks in
DocRowwiseIterator — this kernel resolves an ENTIRE key range in one fused
device program:

  1. radix merge of all input runs (memtable + SSTs), reusing the compaction
     sort (ops/merge_gc.sort_and_gc)
  2. snapshot GC with cutoff = read_ht: exactly one surviving version per
     key — the one visible at the read time — with tombstones, TTL-expired
     values and root-overwrite-covered entries dropped (snapshot=True mode)
  3. lexicographic range mask over the sorted key words (the block-index +
     seek equivalent, done as a vectorized compare)

The output is a bit-packed keep mask over the merged order; the host gathers
surviving (key, value) pairs — values never cross to the device (slabs.py).
"""

from __future__ import annotations

import functools
from typing import Iterator, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_tpu.ops import merge_gc
from yugabyte_tpu.ops.merge_gc import (
    _ROW_KEY_LEN, _ROW_WORDS, StagedCols, sort_and_gc)
from yugabyte_tpu.ops.slabs import KVSlab, _pad_keys_to_words


def _pack_bound(key: Optional[bytes], w: int) -> Tuple[np.ndarray, int]:
    if not key:
        return np.zeros(w, dtype=np.uint32), 0
    words, lens = _pad_keys_to_words([key], width_words=w)
    return words[0], int(lens[0])


@functools.partial(jax.jit, static_argnames=(
    "w", "has_lower", "has_upper", "upper_truncated"))
def _scan_fused(cols, sort_rows, n_sort, cutoff_hi, cutoff_lo, cph, cpl,
                lo_words, lo_len, hi_words, hi_len,
                w: int, has_lower: bool, has_upper: bool,
                upper_truncated: bool = False):
    n = cols.shape[1]
    perm, keep, _ = sort_and_gc(
        cols, cutoff_hi, cutoff_lo, cph, cpl,
        w=w, is_major=True, retain_deletes=False,
        sort_rows=sort_rows, n_sort=n_sort, snapshot=True)
    s_words = cols[_ROW_WORDS:, :][:, perm]
    s_len = cols[_ROW_KEY_LEN][perm].astype(jnp.int32)

    # lexicographic (words, byte-length) compare == memcmp on the raw keys:
    # zero-padded words tie exactly when one key is a prefix of the other,
    # and then the shorter key sorts first
    def cmp_bound(b_words, b_len):
        lt = jnp.zeros(n, bool)
        eq = jnp.ones(n, bool)
        for i in range(w):
            bw = b_words[i]
            lt = lt | (eq & (s_words[i] < bw))
            eq = eq & (s_words[i] == bw)
        lt = lt | (eq & (s_len < b_len))
        eq = eq & (s_len == b_len)
        return lt, eq  # key < bound, key == bound

    if has_lower:
        lt, _ = cmp_bound(lo_words, lo_len)
        keep = keep & ~lt
    if has_upper:
        lt, eq = cmp_bound(hi_words, hi_len)
        # A truncated bound (full upper longer than the key stride) must
        # keep keys EQUAL to the truncated prefix: their full bytes can
        # still be < the full bound; the host re-checks them exactly.
        keep = keep & ((lt | eq) if upper_truncated else lt)

    def pack_bits(b):
        b32 = b.reshape(n // 32, 32).astype(jnp.uint32)
        return (b32 << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(
            axis=1, dtype=jnp.uint32)

    return perm, pack_bits(keep)


def scan_visible(staged: StagedCols, read_ht_value: int,
                 lower_key: Optional[bytes] = None,
                 upper_key: Optional[bytes] = None,
                 upper_truncated: bool = False
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Run the scan kernel over a staged cols matrix.

    Returns (perm, keep) as host arrays over the merged order: entry
    perm[i] of the staged input survives iff keep[i]; surviving entries are
    exactly the versions visible at read_ht within [lower_key, upper_key).
    """
    import time as _time
    from yugabyte_tpu.utils.metrics import record_kernel_dispatch
    w_bytes_cap = staged.w  # key words available
    lo_w, lo_l = _pack_bound(lower_key, w_bytes_cap)
    hi_w, hi_l = _pack_bound(upper_key, w_bytes_cap)
    cutoff = read_ht_value
    cutoff_phys = cutoff >> 12
    t0 = _time.monotonic()
    perm, keep_p = _scan_fused(
        staged.cols_dev, jnp.asarray(staged.sort_rows), jnp.int32(staged.n_sort),
        jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
        jnp.uint32(cutoff_phys >> 20), jnp.uint32(cutoff_phys & 0xFFFFF),
        jnp.asarray(lo_w), jnp.int32(lo_l), jnp.asarray(hi_w), jnp.int32(hi_l),
        w=staged.w, has_lower=lower_key is not None,
        has_upper=upper_key is not None, upper_truncated=upper_truncated)
    perm = np.asarray(perm)
    keep = merge_gc._unpack_bits(np.asarray(keep_p), staged.n_pad)
    keep = keep & (perm < staged.n)
    # the np.asarray transfers block, so the wall time covers compute +
    # keep-mask download
    record_kernel_dispatch("kernel_scan", staged.n, staged.n_pad,
                           (_time.monotonic() - t0) * 1e3)
    return perm, keep


class SlabSource:
    """Scan input backed by a decoded host slab (memtables, cache-miss
    SSTs): keys/values come straight from the slab arrays."""

    def __init__(self, slab: KVSlab, staged: Optional[StagedCols] = None):
        self.slab = slab
        self.staged = staged
        self.n = slab.n

    def to_slab(self) -> KVSlab:
        return self.slab

    def entry(self, i: int) -> Tuple[bytes, bytes, int]:
        sl = self.slab
        ht = (int(sl.ht_hi[i]) << 32) | int(sl.ht_lo[i])
        return sl.key_bytes(i), sl.values[int(sl.value_idx[i])], ht


class ResidentSource:
    """Scan input served from the HBM slab cache: the device filter runs
    over the RESIDENT column matrix — no host block decode to stage the
    scan — and keys/values of SURVIVORS are fetched lazily from the SST
    reader's blocks, so decode happens only for blocks that actually
    hold visible entries (a narrow range scan touches one block of a
    fully resident file instead of all of them).

    Caller contract: the file must not hold deep documents (the resident
    kernel path is depth-2 only — check reader.props.has_deep)."""

    def __init__(self, reader, staged: StagedCols):
        self.slab = None
        self.reader = reader
        self.staged = staged
        self.n = staged.n
        # per-block first-row offsets: block handles record their entry
        # counts (storage/sst.py index format)
        self._row_offs = np.concatenate(
            ([0], np.cumsum([h[2] for h in reader.block_handles])))
        self._blk_idx = -1
        self._blk = None

    def to_slab(self) -> KVSlab:
        return self.reader.read_all()

    def entry(self, i: int) -> Tuple[bytes, bytes, int]:
        b = int(np.searchsorted(self._row_offs, i, side="right") - 1)
        if b != self._blk_idx:
            self._blk = self.reader.read_block(b)
            self._blk_idx = b
        sl = self._blk
        j = i - int(self._row_offs[b])
        ht = (int(sl.ht_hi[j]) << 32) | int(sl.ht_lo[j])
        return sl.key_bytes(j), sl.values[int(sl.value_idx[j])], ht


def visible_entries_sources(sources, read_ht_value: int,
                            lower_key: Optional[bytes] = None,
                            upper_key: Optional[bytes] = None,
                            device=None
                            ) -> Iterator[Tuple[bytes, bytes, int]]:
    """Yield (key_prefix, value_bytes, ht_value) for every entry visible
    at read_ht in [lower_key, upper_key), in key order, over a mixed list
    of SlabSource / ResidentSource inputs — the merged+resolved scan
    stream, with resident inputs never decoded to stage the filter."""
    from yugabyte_tpu.ops.merge_gc import stage_slab
    from yugabyte_tpu.ops.slabs import FLAG_DEEP
    from yugabyte_tpu.storage.device_cache import concat_staged

    live = [s for s in sources if s.n]
    if not live:
        return
    if any(s.slab is not None and bool((s.slab.flags & FLAG_DEEP).any())
           for s in live):
        # Deep documents: the kernel's snapshot mode is depth-2 only —
        # resolve visibility on the host with the full overwrite stack.
        # (Resident sources only reach here for depth-2 files, but the
        # host path needs every input as a slab.)
        yield from _visible_entries_host([s.to_slab() for s in live],
                                         read_ht_value, lower_key,
                                         upper_key)
        return
    staged_list = [s.staged if s.staged is not None
                   else stage_slab(s.slab, device) for s in live]
    staged = (staged_list[0] if len(staged_list) == 1
              else concat_staged(staged_list))
    # the device compare sees only the first w*4 key bytes; longer bounds are
    # truncated there and enforced exactly on the host below
    stride = staged.w * 4
    lo_exact = lower_key if lower_key and len(lower_key) > stride else None
    hi_exact = upper_key if upper_key and len(upper_key) > stride else None
    perm, keep = scan_visible(staged, read_ht_value,
                              lower_key[:stride] if lower_key else None,
                              upper_key[:stride] if upper_key else None,
                              upper_truncated=hi_exact is not None)
    # map merged indices back to (source, local index)
    offsets = np.cumsum([0] + [s.n for s in live])
    sel = perm[keep]
    src_idx = np.searchsorted(offsets, sel, side="right") - 1
    local_idx = sel - offsets[src_idx]
    for j, li in zip(src_idx, local_idx):
        key, value, ht = live[int(j)].entry(int(li))
        if lo_exact is not None and key < lo_exact:
            continue
        if hi_exact is not None and key >= hi_exact:
            continue
        yield key, value, ht


def visible_entries(slabs: Sequence[KVSlab], read_ht_value: int,
                    lower_key: Optional[bytes] = None,
                    upper_key: Optional[bytes] = None,
                    device=None,
                    staged_inputs: Optional[Sequence[StagedCols]] = None,
                    ) -> Iterator[Tuple[bytes, bytes, int]]:
    """Slab-list form of visible_entries_sources (every input decoded on
    the host; staged_inputs, when given, skip the per-slab upload)."""
    staged_inputs = (list(staged_inputs) if staged_inputs is not None
                     else [None] * len(slabs))
    sources = [SlabSource(sl, st) for sl, st in zip(slabs, staged_inputs)]
    yield from visible_entries_sources(sources, read_ht_value, lower_key,
                                       upper_key, device=device)


def _visible_entries_host(slabs: Sequence[KVSlab], read_ht_value: int,
                          lower_key: Optional[bytes],
                          upper_key: Optional[bytes]
                          ) -> Iterator[Tuple[bytes, bytes, int]]:
    """Host-side snapshot resolution with FULL overwrite-stack semantics
    (deep documents). Uses the native merge+GC in snapshot shape: a major
    compaction at cutoff=read_ht keeps exactly one surviving version per
    visible key (plus retained history above the read time, filtered
    here), with tombstones dropped and subtree overwrites applied."""
    from yugabyte_tpu.ops.slabs import concat_slabs
    from yugabyte_tpu.storage.cpu_baseline import compact_cpu_baseline

    merged = concat_slabs(slabs)
    offsets = np.cumsum([0] + [s.n for s in slabs]).tolist()
    order, keep, _ = compact_cpu_baseline(merged, offsets, read_ht_value,
                                          True)
    read_ht = np.uint64(read_ht_value)
    for i, k in zip(order, keep):
        if not k:
            continue
        i = int(i)
        ht = (int(merged.ht_hi[i]) << 32) | int(merged.ht_lo[i])
        if ht > int(read_ht):
            continue  # history above the read time is not visible
        key = merged.key_bytes(i)
        if lower_key is not None and key < lower_key:
            continue
        if upper_key is not None and key >= upper_key:
            break
        yield key, merged.values[int(merged.value_idx[i])], ht
