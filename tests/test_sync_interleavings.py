"""Sync-point interleaving tests for the raft/mvcc hard parts (VERDICT r3
#9; SURVEY hard part #4): forced schedules the reference drives with
yb::SyncPoint hooks (ref src/yb/util/sync_point.h, hook style at
rocksdb/db/compaction_job.cc:443).

- leader change while a write is between local append and replication
- propagated safe time under partition: follower reads stay at their
  consistent (stale) snapshot, never expose a torn prefix, and converge
- a flush forced BETWEEN the two DBs of a transaction apply must not
  violate the intents-after-regular persistence order across restart
"""

import os
import threading
import time

import pytest

from yugabyte_tpu.consensus.raft import (NotLeader, OperationOutcomeUnknown,
                                         ReplicationAborted)
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.utils import sync_point
from tests.test_consensus import (PeerHarness, make_schema, wait_for,
                                  write_op)


@pytest.fixture(autouse=True)
def fast_raft_and_clean_points():
    from yugabyte_tpu.utils import flags
    flags.set_flag("raft_heartbeat_interval_ms", 15)
    flags.set_flag("ht_lease_duration_ms", 1000)
    # These tests pin exact interleavings with sync points; the heartbeat
    # batch window serializes batched RPCs per destination and has made
    # elections miss their window under full-suite load — disable it.
    import yugabyte_tpu.consensus.multi_raft_batcher  # noqa: F401 (flag def)
    flags.set_flag("multi_raft_batch_window_ms", 0)
    yield
    sync_point.clear()
    flags.reset_flag("raft_heartbeat_interval_ms")
    flags.reset_flag("ht_lease_duration_ms")
    flags.reset_flag("multi_raft_batch_window_ms")


def test_leader_change_during_in_flight_write(tmp_path):
    """A write paused between its local append and replication while the
    leadership moves must either commit under the old term (replicated
    before the new leader's log overwrites it) or abort — and when it
    aborts, NO replica may serve the row (acked-write safety)."""
    h = PeerHarness(tmp_path)
    try:
        leader = h.elect("ts0")
        leader.write([write_op(h.schema, "base", 1)])
        # BOTH followers must hold the full log before the partition: the
        # write above commits on ANY majority (possibly ts0+ts2), and a
        # ts1 still missing it when the partition freezes its log loses
        # every later election to ts2's longer log — votes denied
        # deterministically for the whole retry budget (the real CI flake,
        # diagnosed from the elect() dump: no starved threads, CANDIDATE
        # with completed-but-denied solicitations).
        wait_for(lambda: all(
            h.peers[s].raft.last_op_id[1] == leader.raft.last_op_id[1]
            for s in ("ts1", "ts2")), timeout=60.0,
            msg="followers hold the full pre-partition log")

        paused = threading.Event()
        release = threading.Event()

        def pause_once():
            sync_point.disarm("raft.replicate:after_local_append")
            paused.set()
            # generous: under full-suite CPU load the elections below can
            # take tens of seconds; an early return here would release the
            # paused write MID-election and change the interleaving
            release.wait(timeout=120)

        sync_point.arm("raft.replicate:after_local_append", pause_once)
        result = {}
        # Partition ts0 BEFORE issuing the racing write: ts0's per-peer
        # heartbeat loops wake on their own 15ms timer, and in the window
        # between the sync-point pause and a post-write partition they
        # could replicate the in-flight entry to ts2 but not ts1 — after
        # which ts2's longer log denies ts1's votes FOREVER (the
        # historical flake). With the partition first, the entry is
        # deterministically appended-but-unreplicated.
        h.transport.partition("ts0", "ts1")
        h.transport.partition("ts0", "ts2")

        def racing_write():
            try:
                h.peers["ts0"].write(
                    [write_op(h.schema, "inflight", 42)], timeout_s=45.0)
                result["ok"] = True
            except (NotLeader, ReplicationAborted) as e:
                result["err"] = e
            except OperationOutcomeUnknown as e:
                # the write's deadline expired while the new leader's
                # history was still converging: a REAL distributed answer
                # (commit-or-abort ambiguous) — the safety assertions below
                # weaken to replica agreement
                result["unknown"] = e

        t = threading.Thread(target=racing_write)
        t.start()
        assert paused.wait(30), "write never reached the sync point"
        # while ts0's write sits appended-but-unreplicated, move the
        # leadership; the new leader's no-op enters at the same index
        # the paused leader may hold a just-granted vote from a quorum
        # peer; retry the election rather than flaking on that window
        for attempt in range(8):
            try:
                h.elect("ts1")
                break
            except TimeoutError:
                if attempt == 7:
                    raise
        h.peers["ts1"].write([write_op(h.schema, "after", 7)])
        h.transport.heal()
        release.set()
        t.join(timeout=60)
        assert not t.is_alive(), "in-flight write never resolved"

        # old leader rejoins as follower; logs converge on ts1's history
        wait_for(lambda: not h.peers["ts0"].raft.is_leader(),
                 timeout=60.0, msg="old leader stepped down")
        if "err" in result:
            # aborted: the row must exist NOWHERE once logs converge
            def gone():
                try:
                    return h.peers["ts1"].read_row(
                        DocKey(range_components=("inflight",))) is None
                except NotLeader:
                    return False
            wait_for(gone, msg="aborted write absent on new leader")
        elif "ok" in result:
            # committed: it must be durable on the NEW leader's history
            row = h.peers["ts1"].read_row(
                DocKey(range_components=("inflight",)))
            assert row is not None
        else:
            assert "unknown" in result
            # ambiguous outcome: present-or-absent are both legal, but the
            # surviving history must be SINGLE — once converged, every
            # replica answers identically for the in-flight row
            def replicas_agree():
                answers = []
                for s in ("ts0", "ts1", "ts2"):
                    try:
                        peer = h.peers[s]
                        if s != "ts1":
                            # PR-11 follower-read gate (no digest
                            # exchange in this harness)
                            peer.grant_vouch(0)
                        row = peer.read_row(
                            DocKey(range_components=("inflight",)),
                            allow_follower=(s != "ts1"))
                    except NotLeader:
                        return False
                    answers.append(None if row is None
                                   else row.to_dict(h.schema)["v"])
                return len(set(answers)) == 1
            wait_for(replicas_agree, timeout=60.0,
                     msg="replicas agree on the ambiguous write")
        # the surviving history is identical on all peers
        wait_for(lambda: h.peers["ts1"].read_row(
            DocKey(range_components=("after",))) is not None,
            msg="post-failover write")
    finally:
        h.shutdown()


def test_partitioned_follower_reads_stay_consistent_then_converge(tmp_path):
    """Propagated safe time under partition: the cut-off follower keeps
    serving its OLD consistent snapshot (never a torn prefix of the new
    writes), and converges after healing (lease expiry vs follower read
    — SURVEY hard part #4)."""
    h = PeerHarness(tmp_path)
    try:
        leader = h.elect("ts0")
        leader.write([write_op(h.schema, f"pre{i}", i) for i in range(5)])
        follower = h.peers["ts2"]
        # PR-11 follower-read gate: vouch the replica (no digest
        # exchange runs in this harness)
        follower.grant_vouch(0)
        wait_for(lambda: follower.read_row(
            DocKey(range_components=("pre4",)), allow_follower=True)
            is not None, msg="follower caught up")

        h.transport.partition("ts0", "ts2")
        h.transport.partition("ts1", "ts2")
        # majority (ts0+ts1) commits new rows the follower can't see
        leader.write([write_op(h.schema, f"new{i}", i) for i in range(5)])

        # the stale follower still serves the OLD snapshot...
        row = follower.read_row(DocKey(range_components=("pre2",)),
                                allow_follower=True)
        assert row is not None
        # ...and none of the post-partition rows leak in
        for i in range(5):
            assert follower.read_row(DocKey(range_components=(f"new{i}",)),
                                     allow_follower=True) is None
        # leader-consistency reads on the follower stay rejected
        with pytest.raises(NotLeader):
            follower.read_row(DocKey(range_components=("pre2",)))

        h.transport.heal()
        wait_for(lambda: follower.read_row(
            DocKey(range_components=("new4",)), allow_follower=True)
            is not None, msg="follower converged after heal")
    finally:
        h.shutdown()


def test_flush_between_txn_apply_dbs_survives_restart(tmp_path):
    """Force a regular-DB flush at the sync point BETWEEN a transaction
    apply's two DB writes (regular rows landed, intent tombstones not
    yet): the flush-ordering invariant (intents frontier <= regular's)
    must make bootstrap replay re-derive the intent cleanup instead of
    losing or double-applying the rows."""
    from yugabyte_tpu.docdb.intents import TransactionMetadata
    from yugabyte_tpu.common.hybrid_time import HybridTime

    h = PeerHarness(tmp_path, n=1)
    try:
        leader = h.elect("ts0")
        tablet = leader.tablet
        txn_id = b"T" * 16
        meta = TransactionMetadata(txn_id=txn_id,
                                   status_tablet="status-1",
                                   priority=1)
        leader.write_transactional(
            [write_op(h.schema, "txnrow", 99)], meta)

        def flush_between():
            sync_point.disarm("tablet.apply_txn:between_dbs")
            tablet.regular_db.flush()

        sync_point.arm("tablet.apply_txn:between_dbs", flush_between)
        leader.submit_txn_update("apply", txn_id,
                                 leader.clock.now().value)

        row = leader.read_row(DocKey(range_components=("txnrow",)))
        assert row is not None and row.to_dict(h.schema)["v"] == 99
        h.shutdown()

        # restart: bootstrap replays from the min frontier; the row must
        # exist EXACTLY once and the intents must finish cleaning up
        h2 = PeerHarness(tmp_path, n=1)
        try:
            l2 = h2.elect("ts0")
            row = l2.read_row(DocKey(range_components=("txnrow",)))
            assert row is not None and row.to_dict(h2.schema)["v"] == 99
            # no resurrected intents: a fresh write on the same key wins
            l2.write([write_op(h2.schema, "txnrow", 100)])
            row = l2.read_row(DocKey(range_components=("txnrow",)))
            assert row.to_dict(h2.schema)["v"] == 100
        finally:
            h2.shutdown()
    except Exception:
        try:
            h.shutdown()
        except Exception:  # noqa: BLE001 — already shut down
            pass
        raise
