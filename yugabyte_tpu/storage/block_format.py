"""SST block format: columnar KV slabs, directly TPU-shippable.

The TPU-first replacement for the reference's delta-encoded blocks with
restart points (ref: src/yb/rocksdb/table/block_builder.cc — prefix
compression + restart array). Rationale: restart-point blocks must be decoded
*sequentially* per entry; slab blocks decode with O(1) numpy reshapes and ship
to device HBM as-is, and binary search over fixed-stride keys vectorizes.

Block layout (little-endian header, big-endian key bytes for memcmp order):

    u32 magic          0x53425459 ("YTBS")
    u32 n_entries
    u32 key_stride     bytes per key row (multiple of 4)
    u32 flags          bit0: zlib-compressed body
    u32 body_len       compressed body bytes
    u32 raw_len        uncompressed body bytes
    body:
        key slab       n * key_stride bytes (zero-padded, memcmp order)
        key_len        u16[n]
        doc_key_len    u16[n]
        ht_hi, ht_lo   u32[n] each
        write_id       u32[n]
        entry_flags    u8[n]   (slabs.FLAG_*)
        ttl_ms         i64[n]
        val_offsets    u32[n+1]
        val bytes
    u32 crc32(header[4:24] + body-as-stored)
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from yugabyte_tpu.ops.slabs import KVSlab
from yugabyte_tpu.utils.status import Status, StatusError

BLOCK_MAGIC = 0x53425459
_HEADER = struct.Struct("<IIIIII")

# Fixed-width column bytes per row in the body, AFTER the key slab:
# key_len(2) + doc_key_len(2) + ht_hi(4) + ht_lo(4) + write_id(4) +
# entry_flags(1) + ttl_ms(8).  The device block codec (ops/block_codec.py)
# computes its gather/scatter offsets from this constant and the region
# order above — any layout change here MUST move the codec kernels too
# (both are fingerprinted together in the kernel manifest).
META_BYTES_PER_ROW = 25
HEADER_BYTES = _HEADER.size          # 24
TRAILER_BYTES = 4                    # u32 crc32


def fixed_region_bytes(n: int, stride: int) -> int:
    """Bytes of the body's fixed-width columns (key slab + metadata
    arrays) — everything before val_offsets."""
    return n * stride + META_BYTES_PER_ROW * n


def split_raw_block(data) -> Tuple[int, int, bytes]:
    """Parse + integrity-check one raw block WITHOUT decoding columns:
    returns (n_entries, key_stride, uncompressed body bytes).

    The device-codec ingest path: magic/CRC/size checks are identical to
    decode_block (typed Status.Corruption, never wrong bytes), but the
    column transforms stay undone — the body ships to the device as raw
    words and value rows are zero-copy slices of it.  `data` may be a
    memoryview over the whole data file (zero-copy slicing; the CRC runs
    incrementally over the buffer)."""
    if len(data) < _HEADER.size + TRAILER_BYTES:
        raise StatusError(Status.Corruption("block too small"))
    magic, n, stride, flags, body_len, raw_len = _HEADER.unpack_from(data, 0)
    if magic != BLOCK_MAGIC:
        raise StatusError(Status.Corruption("bad block magic"))
    off = _HEADER.size
    if len(data) < off + body_len + TRAILER_BYTES:
        raise StatusError(Status.Corruption("block truncated"))
    stored = data[off: off + body_len]
    (crc,) = struct.unpack_from("<I", data, off + body_len)
    if crc != zlib.crc32(stored, zlib.crc32(data[4: off])):
        raise StatusError(Status.Corruption("block checksum mismatch"))
    body = zlib.decompress(stored) if (flags & 1) else stored
    if len(body) != raw_len:
        raise StatusError(Status.Corruption("block size mismatch"))
    if stride % 4 or fixed_region_bytes(n, stride) + 4 * (n + 1) > raw_len:
        raise StatusError(Status.Corruption("block geometry mismatch"))
    return n, stride, body


def raw_block_values(n: int, stride: int, body: bytes):
    """Zero-copy value rows of one uncompressed block body (the on-disk
    layout IS blob + offsets; no column decode happens)."""
    from yugabyte_tpu.ops.slabs import ValueArray
    p = fixed_region_bytes(n, stride)
    val_offsets = np.frombuffer(body, dtype="<u4", count=n + 1, offset=p)
    return ValueArray.from_blob(body[p + 4 * (n + 1):], val_offsets)


def encode_block(slab: KVSlab, start: int, end: int, compress: bool = False) -> bytes:
    """Serialize slab rows [start, end) into one block."""
    n = end - start
    kw = slab.key_words[start:end]
    stride = kw.shape[1] * 4
    key_bytes = kw.astype(">u4").tobytes()
    # values: one vectorized gather into (blob, offsets) — the disk layout.
    # Contiguous value_idx (the normal case after _gather_slab/pack_kvs
    # normalization) is a zero-copy slice.
    from yugabyte_tpu.ops.slabs import ValueArray
    va = ValueArray.from_list(slab.values)
    vi = slab.value_idx[start:end]
    if n and int(vi[-1]) - int(vi[0]) == n - 1 \
            and np.array_equal(vi, np.arange(vi[0], vi[0] + n, dtype=vi.dtype)):
        vals = va.slice_rows(int(vi[0]), int(vi[0]) + n)
    else:
        vals = va.gather(vi)
    body = b"".join([
        key_bytes,
        slab.key_len[start:end].astype(np.uint16).tobytes(),
        slab.doc_key_len[start:end].astype(np.uint16).tobytes(),
        slab.ht_hi[start:end].astype(np.uint32).tobytes(),
        slab.ht_lo[start:end].astype(np.uint32).tobytes(),
        slab.write_id[start:end].astype(np.uint32).tobytes(),
        slab.flags[start:end].astype(np.uint8).tobytes(),
        slab.ttl_ms[start:end].astype(np.int64).tobytes(),
        vals.offsets.astype(np.uint32).tobytes(),
        vals.blob(),
    ])
    raw_len = len(body)
    flags = 0
    stored = body
    if compress:
        c = zlib.compress(body, 1)
        if len(c) < raw_len:
            stored = c
            flags |= 1
    header = _HEADER.pack(BLOCK_MAGIC, n, stride, flags, len(stored), raw_len)
    crc = zlib.crc32(header[4:] + stored)
    return header + stored + struct.pack("<I", crc)


def decode_block(data: bytes) -> KVSlab:
    magic, n, stride, flags, body_len, raw_len = _HEADER.unpack_from(data, 0)
    if magic != BLOCK_MAGIC:
        raise StatusError(Status.Corruption("bad block magic"))
    off = _HEADER.size
    stored = data[off: off + body_len]
    (crc,) = struct.unpack_from("<I", data, off + body_len)
    if crc != zlib.crc32(data[4: off] + stored):
        raise StatusError(Status.Corruption("block checksum mismatch"))
    body = zlib.decompress(stored) if (flags & 1) else stored
    if len(body) != raw_len:
        raise StatusError(Status.Corruption("block size mismatch"))
    p = 0
    w = stride // 4
    key_words = np.frombuffer(body, dtype=">u4", count=n * w, offset=p
                              ).reshape(n, w).astype(np.uint32)
    p += n * stride
    key_len = np.frombuffer(body, dtype=np.uint16, count=n, offset=p).astype(np.int32)
    p += 2 * n
    doc_key_len = np.frombuffer(body, dtype=np.uint16, count=n, offset=p).astype(np.int32)
    p += 2 * n
    ht_hi = np.frombuffer(body, dtype=np.uint32, count=n, offset=p).copy()
    p += 4 * n
    ht_lo = np.frombuffer(body, dtype=np.uint32, count=n, offset=p).copy()
    p += 4 * n
    write_id = np.frombuffer(body, dtype=np.uint32, count=n, offset=p).copy()
    p += 4 * n
    entry_flags = np.frombuffer(body, dtype=np.uint8, count=n, offset=p).astype(np.uint32)
    p += n
    ttl_ms = np.frombuffer(body, dtype=np.int64, count=n, offset=p).copy()
    p += 8 * n
    val_offsets = np.frombuffer(body, dtype=np.uint32, count=n + 1, offset=p)
    p += 4 * (n + 1)
    from yugabyte_tpu.ops.slabs import ValueArray
    values = ValueArray.from_blob(body[p:], val_offsets)  # zero-copy
    return KVSlab(key_words, key_len, doc_key_len, ht_hi, ht_lo, write_id,
                  entry_flags, ttl_ms, np.arange(n, dtype=np.int32), values)


def block_overhead() -> int:
    return _HEADER.size + 4
