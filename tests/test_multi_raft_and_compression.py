"""MultiRaftBatcher + RPC compression (VERDICT r3 #8 / missing #4-5).

- Cross-tablet consensus heartbeats to one destination server share one
  multi_update_consensus RPC: message count per interval is O(peer
  servers), not O(tablets x peers).
- RPC frames above the size threshold travel zlib-compressed,
  transparently to every caller (remote bootstrap, CDC, scans).
"""

import time

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.consensus.multi_raft_batcher import MultiRaftBatcher
from yugabyte_tpu.consensus.transport import PeerUnreachable
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.rpc.messenger import Messenger
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


class TestBatcherUnit:
    def test_batches_within_window(self):
        sent = []

        def send(addr, items):
            sent.append((addr, list(items)))
            return [{"ok": i} for i in range(len(items))]

        b = MultiRaftBatcher(send)
        import threading
        out = {}

        def go(i):
            out[i] = b.submit("a:1", f"s/{i}", {"n": i})
        ts = [threading.Thread(target=go, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(out) == 8
        # all 8 heartbeats rode far fewer RPCs than 8 (same window)
        assert 1 <= len(sent) <= 3, [len(s[1]) for s in sent]
        assert sum(len(s[1]) for s in sent) == 8
        b.stop()

    def test_per_item_failure_isolated(self):
        def send(addr, items):
            return [{"err": "gone"} if d == "s/bad" else {"ok": 1}
                    for d, _r in items]

        b = MultiRaftBatcher(send)
        import threading
        errs, oks = [], []

        def good():
            oks.append(b.submit("a:1", "s/good", {}))

        def bad():
            try:
                b.submit("a:1", "s/bad", {})
            except PeerUnreachable as e:
                errs.append(e)
        t1, t2 = threading.Thread(target=good), threading.Thread(target=bad)
        t1.start(); t2.start(); t1.join(); t2.join()
        assert oks == [{"ok": 1}] and len(errs) == 1
        b.stop()

    def test_batch_send_failure_fans_out(self):
        def send(addr, items):
            raise PeerUnreachable("down")
        b = MultiRaftBatcher(send)
        with pytest.raises(PeerUnreachable):
            b.submit("a:1", "s/x", {})
        b.stop()


@pytest.mark.slow
def test_heartbeat_messages_scale_with_peers_not_tablets(tmp_path):
    """A server leading T tablets with followers on one other server must
    send O(1) heartbeat RPCs per interval, not O(T)."""
    flags.set_flag("replication_factor", 2)
    # Per-tablet heartbeat timers drift out of phase, so the collapse
    # ratio at the default 3ms window depends on machine speed; a 20ms
    # window (still well under the 50ms interval) makes coalescing
    # deterministic enough to assert on.
    flags.set_flag("multi_raft_batch_window_ms", 20)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=2,
        fs_root=str(tmp_path / "mrb"))).start()
    try:
        client = c.new_client()
        client.create_namespace("db")
        # 12 tablets across 2 servers
        t = client.create_table("db", "many", SCHEMA, num_tablets=12)
        c.wait_all_replicas_running(t.table_id)
        time.sleep(0.5)   # settle into heartbeat-only steady state
        b0 = c.tservers[0].transport.batcher
        b1 = c.tservers[1].transport.batcher
        hb0, ba0 = b0.counters()
        hb1, ba1 = b1.counters()
        time.sleep(2.0)
        hb0b, ba0b = b0.counters()
        hb1b, ba1b = b1.counters()
        hbs = (hb0b - hb0) + (hb1b - hb1)
        rpcs = (ba0b - ba0) + (ba1b - ba1)
        assert hbs > 50, "expected a steady heartbeat stream"
        # O(tablets) heartbeats collapsed into far fewer wire messages;
        # with a 3ms window and 50ms interval the floor is ~2 RPCs per
        # interval per direction. Assert 2x collapse: the timing-jittered
        # observed ratio on a loaded 1-core machine hovers around 3x, and
        # a missed-window heartbeat halves the batch without breaking the
        # O(peers) property this test guards.
        assert rpcs * 2 <= hbs, (hbs, rpcs)
    finally:
        flags.reset_flag("multi_raft_batch_window_ms")
        c.shutdown()
        flags.set_flag("replication_factor", 3)


class TestCompression:
    def test_large_frames_roundtrip_compressed(self):
        m1 = Messenger("srv")

        class Echo:
            def echo(self, blob: bytes) -> dict:
                return {"blob": blob, "n": len(blob)}
        m1.register_service("echo", Echo())
        m2 = Messenger("cli")
        try:
            blob = b"the quick brown fox " * 8192   # ~160KB, compressible
            resp = m2.call(m1.address, "echo", "echo", blob=blob)
            assert resp["blob"] == blob
            # below threshold passes untouched
            small = b"x" * 100
            assert m2.call(m1.address, "echo", "echo",
                           blob=small)["blob"] == small
            # incompressible data must still round-trip (stored raw when
            # compression does not shrink it)
            import os as _os
            rnd = _os.urandom(200_000)
            assert m2.call(m1.address, "echo", "echo",
                           blob=rnd)["blob"] == rnd
        finally:
            m2.shutdown()
            m1.shutdown()

    def test_disabled_by_flag(self):
        flags.set_flag("rpc_compression_min_bytes", 0)
        try:
            m1 = Messenger("srv2")

            class Echo:
                def echo(self, blob: bytes) -> dict:
                    return {"blob": blob}
            m1.register_service("echo", Echo())
            m2 = Messenger("cli2")
            try:
                blob = b"z" * 100_000
                assert m2.call(m1.address, "echo", "echo",
                               blob=blob)["blob"] == blob
            finally:
                m2.shutdown()
                m1.shutdown()
        finally:
            flags.set_flag("rpc_compression_min_bytes", 32 << 10)
