"""Mesh-sharded compaction pool: many tablets share one device mesh.

ROADMAP item 3's throughput service (what LUDA did for GPU compaction
offload): the headline is AGGREGATE multi-job rows/s across concurrent
tablets, not single-job latency. Queued jobs from concurrent tablets are
packed into shape-bucketed batch slots — one tablet job per mesh device,
ONE shard_map dispatch per wave (parallel/dist_compact.pooled_merge_gc,
the mesh-level extension of ops/run_merge.pack_runs_greedy's slot
packing) — while a job at or above `distributed_compaction_min_rows`
takes the whole mesh exclusively through the key-range-sharded
dist-native path.

Scheduling is RESYSTANCE-style measured, fair and contained:

  - measured per-bucket rates: every wave updates an EWMA device rows/s
    per shape bucket, every native completion the native twin; a bucket
    whose device rate falls below its native rate is DEMOTED (jobs run
    natively) until the measurements say otherwise — routing by
    observation, not calibration faith;
  - fairness: tablets are served in deficit order (least rows served
    first), and wave slots fill round-robin across tablet queue heads —
    a tablet saturating the queue cannot starve the others;
  - cancellation: every job carries a CancellationToken checked at each
    stage boundary; a cancelled job's partial outputs are swept and its
    input pins released, co-scheduled jobs unaffected;
  - fault containment: a device fault in a wave quarantines that shape
    bucket (storage/offload_policy.BucketQuarantine — same vocabulary as
    the single-device containment) and completes every affected job
    NATIVELY, byte-identically; a host-side failure in one job's write
    stage fails only that job's handle.

Per-slot merge products stay device-resident: each job's output spans
gather on ITS slot's device and install into the tablet's cache
partition (storage/device_cache.ShardPartition), so the resident
L0->L1->L2 chain survives sharding.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.cancellation import (CancellationToken,
                                             OperationCancelled)
from yugabyte_tpu.utils.trace import TRACE

@dataclass
class PoolRequest:
    """One tablet compaction job as the pool schedules it."""
    inputs: List                      # SSTReaders, newest-first pick order
    out_dir: str
    new_file_id: object               # callable -> next file id
    history_cutoff_ht: int
    is_major: bool
    retain_deletes: bool = False
    block_entries: Optional[int] = None
    input_ids: Optional[List[int]] = None
    device_cache: object = None       # NamespacedSlabCache / ShardPartition
    est_rows: int = 0
    # merge-only jobs (decisions service, no SST I/O): the bench's
    # device-stage rung and the unit tests use this form
    slabs: Optional[List] = None


class PoolJobHandle:
    """Caller's side of a submitted job: wait for the result, or cancel."""

    def __init__(self, tablet_id: str, cancel: CancellationToken):
        self.tablet_id = tablet_id
        self.cancel_token = cancel
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None
        self.submitted_at = time.monotonic()
        self.finished_at: Optional[float] = None

    def cancel(self, reason: str = "cancelled") -> None:
        self.cancel_token.cancel(reason)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("pool job still running")
        if self._exc is not None:
            raise self._exc
        return self._result

    def _resolve(self, result=None, exc: Optional[BaseException] = None
                 ) -> None:
        self._result = result
        self._exc = exc
        self.finished_at = time.monotonic()
        self._done.set()


@dataclass
class _Job:
    tablet_id: str
    request: PoolRequest
    handle: PoolJobHandle
    # set during wave staging
    filtered_inputs: List = field(default_factory=list)
    slabs: List = field(default_factory=list)
    staged: object = None
    dropped_rows: int = 0
    pins: List[int] = field(default_factory=list)


def _bucket_name(bucket: Tuple[int, int]) -> str:
    return f"k{bucket[0]}_m{bucket[1]}"


class CompactionPool:
    """One per tablet server (next to the thread pool it rides behind):
    the scheduler that turns a device mesh into a multi-tablet compaction
    throughput service."""

    def __init__(self, mesh, device=None, name: str = "compaction-pool"):
        from yugabyte_tpu.utils import lock_rank
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        self.mesh = mesh
        self.n_slots = int(mesh.devices.size)
        self.device = (device if device is not None
                       else list(mesh.devices.flat)[0])
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "compaction_pool.lock")
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, deque] = {}       # guarded-by: _lock
        self._credits: Dict[str, float] = {}      # rows served; _lock
        self._running: Dict[str, int] = {}        # guarded-by: _lock
        self._shutdown = False                    # guarded-by: _lock
        self._last_fill = 0.0                     # guarded-by: _lock
        e = ROOT_REGISTRY.entity("server", "compaction_pool")
        self._c_jobs = e.counter(
            "compaction_pool_jobs_total", "jobs submitted to the pool")
        self._c_waves = e.counter(
            "compaction_pool_waves_total",
            "pooled wave dispatches (one shard_map launch each)")
        self._c_wave_jobs = e.counter(
            "compaction_pool_wave_jobs_total",
            "jobs whose device stage rode a pooled wave slot")
        self._c_native = e.counter(
            "compaction_pool_native_completions_total",
            "pool jobs completed on the native path (bucket demoted, "
            "quarantined, or wave fault containment)")
        self._c_faults = e.counter(
            "compaction_pool_wave_faults_total",
            "wave dispatches that hit a device fault (bucket "
            "quarantined; jobs completed natively)")
        self._c_cancelled = e.counter(
            "compaction_pool_cancelled_total",
            "pool jobs cancelled before or during execution")
        self._g_queue = e.gauge(
            "compaction_pool_queue_depth", "jobs queued across tablets")
        self._g_running = e.gauge(
            "compaction_pool_running_count", "jobs currently executing")
        self._g_fill = e.gauge(
            "compaction_pool_slot_occupancy_ratio",
            "filled slots / mesh slots of the most recent wave")
        self._h_wall = e.histogram(
            "compaction_pool_job_wall_ms",
            "submit-to-done wall time per pool job")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._thread.start()

    # ------------------------------------------------------------- client API
    def partition_for(self, shared_cache, namespace: str, tablet_id: str):
        """The tablet's sticky cache partition: home shard =
        hash(tablet_id) mod mesh size, staged onto that shard's device."""
        from yugabyte_tpu.storage.device_cache import ShardPartition
        shard = hash(tablet_id) % self.n_slots
        return ShardPartition(shared_cache, namespace, shard,
                              list(self.mesh.devices.flat)[shard])

    def submit(self, tablet_id: str, request: PoolRequest,
               cancel: Optional[CancellationToken] = None) -> PoolJobHandle:
        token = cancel or CancellationToken(f"pool job {tablet_id}")
        handle = PoolJobHandle(tablet_id, token)
        job = _Job(tablet_id, request, handle)
        with self._cond:
            if self._shutdown:
                handle._resolve(exc=OperationCancelled(
                    "compaction pool shut down"))
                return handle
            q = self._queues.setdefault(tablet_id, deque())
            if tablet_id not in self._credits:
                # newcomers start at the current minimum so they are
                # served promptly without eternal priority
                self._credits[tablet_id] = min(self._credits.values(),
                                               default=0.0)
            q.append(job)
            self._c_jobs.increment()
            self._g_queue.set(self._queue_depth_unlocked())
            self._cond.notify_all()
        return handle

    def submit_compaction(self, tablet_id: str, *, inputs, out_dir,
                          new_file_id, history_cutoff_ht, is_major,
                          retain_deletes: bool = False,
                          block_entries: Optional[int] = None,
                          input_ids: Optional[List[int]] = None,
                          device_cache=None, est_rows: int = 0,
                          cancel: Optional[CancellationToken] = None
                          ) -> PoolJobHandle:
        """Keyword-argument convenience front for storage/db.py (which
        must not import this module's dataclasses — the pool object is
        dependency-injected through TabletOptions)."""
        return self.submit(tablet_id, PoolRequest(
            inputs=list(inputs), out_dir=out_dir, new_file_id=new_file_id,
            history_cutoff_ht=history_cutoff_ht, is_major=is_major,
            retain_deletes=retain_deletes, block_entries=block_entries,
            input_ids=list(input_ids) if input_ids is not None else None,
            device_cache=device_cache, est_rows=est_rows), cancel=cancel)

    def cancel_tablet(self, tablet_id: str,
                      reason: str = "tablet cancelled") -> int:
        """Cancel every queued and running job of one tablet. Queued jobs
        resolve immediately; running ones abort at their next stage
        boundary. Returns how many jobs were signalled."""
        n = 0
        with self._cond:
            for job in list(self._queues.get(tablet_id, ())):
                job.handle.cancel(reason)
                n += 1
        # running jobs: their token is shared with the handle
        return n

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            queued = [j for q in self._queues.values() for j in q]
            for q in self._queues.values():
                q.clear()
            self._g_queue.set(0)
            self._cond.notify_all()
        for job in queued:
            job.handle._resolve(exc=OperationCancelled(
                "compaction pool shut down"))
        self._thread.join(timeout=10)

    def snapshot(self) -> dict:
        """The /compactionz "pool" block: queue depth, per-tablet
        queued/running, packed-slot occupancy and the health board's
        measured per-bucket rates the scheduler routes by."""
        from yugabyte_tpu.storage.bucket_health import health_board
        rates = {}
        for rec in health_board().snapshot()["keys"]:
            if rec["family"] != "run_merge_fused":
                continue
            rates[_bucket_name(tuple(rec["bucket"]))] = {
                "device_rows_per_sec": rec["device_rows_per_sec"],
                "native_rows_per_sec": rec["native_rows_per_sec"],
                "state": rec["state"],
                "demoted": rec["state"] in ("degraded", "quarantined"),
            }
        with self._lock:
            tablets = {}
            for tid, q in self._queues.items():
                r = self._running.get(tid, 0)
                if q or r:
                    tablets[tid] = {"queued": len(q), "running": r}
            for tid, r in self._running.items():
                if r and tid not in tablets:
                    tablets[tid] = {"queued": 0, "running": r}
            return {
                "mesh_slots": self.n_slots,
                "queue_depth": self._queue_depth_unlocked(),
                "tablets": tablets,
                "slot_occupancy_ratio": round(self._last_fill, 3),
                "bucket_rates": rates,
                "waves": self._c_waves.value(),
                "wave_jobs": self._c_wave_jobs.value(),
                "native_completions": self._c_native.value(),
                "wave_faults": self._c_faults.value(),
                "cancelled": self._c_cancelled.value(),
            }

    # ------------------------------------------------------------- scheduling
    def _queue_depth_unlocked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _take_round(self) -> List[_Job]:
        """Pop up to n_slots queue heads in deficit-fair order: tablets
        sorted by rows served ascending, then round-robin across their
        queues until the slots fill or the queues drain."""
        with self._cond:
            while not self._shutdown \
                    and self._queue_depth_unlocked() == 0:
                self._cond.wait(timeout=0.5)
            if self._shutdown:
                return []
            order = sorted(
                (tid for tid, q in self._queues.items() if q),
                key=lambda tid: self._credits.get(tid, 0.0))
            picked: List[_Job] = []
            while len(picked) < self.n_slots:
                progressed = False
                for tid in order:
                    q = self._queues.get(tid)
                    if q and len(picked) < self.n_slots:
                        picked.append(q.popleft())
                        progressed = True
                if not progressed:
                    break
            for job in picked:
                self._running[job.tablet_id] = \
                    self._running.get(job.tablet_id, 0) + 1
            self._g_queue.set(self._queue_depth_unlocked())
            self._g_running.set(sum(self._running.values()))
            return picked

    def _loop(self) -> None:
        while True:
            jobs = self._take_round()
            if not jobs:
                with self._lock:
                    if self._shutdown:
                        return
                continue
            try:
                self._run_round(jobs)
            except Exception as e:  # noqa: BLE001 — scheduler must survive
                TRACE("compaction pool: round failed: %s", e)
                for job in jobs:
                    if not job.handle.done:
                        job.handle._resolve(exc=e)
            finally:
                with self._lock:
                    for job in jobs:
                        self._running[job.tablet_id] = max(
                            0, self._running.get(job.tablet_id, 0) - 1)
                    self._g_running.set(sum(self._running.values()))

    # -------------------------------------------------------------- execution
    def _finish(self, job: _Job, result=None,
                exc: Optional[BaseException] = None) -> None:
        if job.handle.done:
            return
        if isinstance(exc, OperationCancelled):
            self._c_cancelled.increment()
        rows = 0
        if result is not None:
            rows = getattr(result, "rows_in", 0) or \
                (sum(s.n for s in job.slabs) if job.slabs else 0)
        with self._lock:
            self._credits[job.tablet_id] = \
                self._credits.get(job.tablet_id, 0.0) + float(rows or 1)
        self._h_wall.increment(
            (time.monotonic() - job.handle.submitted_at) * 1e3)
        job.handle._resolve(result=result, exc=exc)

    def _run_round(self, jobs: List[_Job]) -> None:
        from yugabyte_tpu.ops.merge_gc import GCParams
        from yugabyte_tpu.storage import compaction as compaction_mod

        # stage every job (filter, read, pack / cache restage, pin);
        # failures and cancellations here affect only their own job
        staged_jobs: List[_Job] = []
        big_jobs: List[_Job] = []
        dist_min = flags.get_flag("distributed_compaction_min_rows")
        for job in jobs:
            try:
                job.handle.cancel_token.check()
                if self.n_slots > 1 and job.request.slabs is None \
                        and job.request.est_rows >= dist_min:
                    big_jobs.append(job)
                    continue
                self._stage_job(job)
                if job.staged is None:      # nothing to merge
                    continue
                staged_jobs.append(job)
            except BaseException as e:  # noqa: BLE001 — per-job containment
                self._unpin(job)
                self._finish(job, exc=e)

        # shape-bucketed wave groups: (k_pad, m, w, is_major,
        # retain_deletes) — each group is one shard_map dispatch
        groups: Dict[tuple, List[_Job]] = {}
        for job in staged_jobs:
            st = job.staged
            key = (st.k_pad, st.m, st.w, job.request.is_major,
                   job.request.retain_deletes)
            groups.setdefault(key, []).append(job)
        from yugabyte_tpu.storage.bucket_health import health_board
        board = health_board()
        for key, group in groups.items():
            bucket = key[:3]
            if not board.allow_device("run_merge_fused",
                                      (bucket[0], bucket[1])):
                # the health board parked the bucket (measured demotion,
                # open fault-quarantine window, or sticky mismatch): run
                # these natively until a probe / the decay re-opens it
                for job in group:
                    self._complete_natively(job, record_rate=True)
                continue
            self._run_wave(bucket, key[3], key[4], group)

        # whole-mesh jobs run after the waves (exclusive use of the mesh)
        for job in big_jobs:
            self._run_exclusive(job)

    def _stage_job(self, job: _Job) -> None:
        """Filter + read + pack one job's device-stage input. Resident
        hit: every input present in the job's cache partition restages
        ON DEVICE (ops/run_merge.stage_runs_from_staged — zero upload);
        miss: host pack (parallel/dist_compact.stage_pool_slot)."""
        from yugabyte_tpu.parallel.dist_compact import (pool_slot_bucket,
                                                        stage_pool_slot)
        from yugabyte_tpu.storage.compaction import filter_expired_inputs
        req = job.request
        if req.slabs is not None:
            # merge-only job (decisions service): slabs arrive pre-read
            job.filtered_inputs = []
            job.slabs = [s for s in req.slabs if s.n]
            if not job.slabs:
                job.staged = None
                self._finish(job, result=None)
                return
            b = pool_slot_bucket(job.slabs)
            job.staged = stage_pool_slot(job.slabs, *b)
            return
        inputs, dropped = filter_expired_inputs(
            req.inputs, req.history_cutoff_ht, req.is_major,
            req.retain_deletes)
        job.dropped_rows = sum(r.props.n_entries for r in dropped)
        inputs = [r for r in inputs if r.props.n_entries]
        job.filtered_inputs = inputs
        if not inputs:
            from yugabyte_tpu.storage.compaction import CompactionResult
            job.staged = None
            self._finish(job, result=CompactionResult(
                [], job.dropped_rows, 0))
            return
        cache = req.device_cache
        ids = req.input_ids
        if cache is not None and ids is not None:
            # keep the id pairing aligned with the FILTERED list
            id_of = {id(r): fid for r, fid in zip(req.inputs, ids)}
            ids = [id_of[id(r)] for r in inputs]
            for fid in ids:
                if cache.pin(fid):
                    job.pins.append(fid)
        job.slabs = [r.read_all() for r in inputs]
        job.slabs = [s for s in job.slabs if s.n]
        resident = (cache is not None and ids is not None
                    and all(cache.contains(fid) for fid in ids))
        if resident:
            from yugabyte_tpu.ops.run_merge import stage_runs_from_staged
            staged_list = [cache.get(fid) for fid in ids]
            if all(st is not None for st in staged_list):
                job.staged = stage_runs_from_staged(staged_list)
                return
        b = pool_slot_bucket(job.slabs)
        job.staged = stage_pool_slot(job.slabs, *b)

    def _unpin(self, job: _Job) -> None:
        cache = job.request.device_cache
        if cache is not None:
            for fid in job.pins:
                cache.unpin(fid)
        job.pins = []

    def _run_wave(self, bucket: Tuple[int, int, int], is_major: bool,
                  retain_deletes: bool, group: List[_Job]) -> None:
        from yugabyte_tpu.ops import device_faults
        from yugabyte_tpu.ops.merge_gc import GCParams
        from yugabyte_tpu.parallel.dist_compact import pooled_merge_gc
        from yugabyte_tpu.storage.bucket_health import health_board
        board = health_board()

        # waves are mesh-slot sized; a larger group runs in several
        waves = [group[i:i + self.n_slots]
                 for i in range(0, len(group), self.n_slots)]
        for wave in waves:
            with self._lock:
                self._last_fill = len(wave) / self.n_slots
            self._g_fill.set(len(wave) / self.n_slots)
            t0 = time.monotonic()
            try:
                handle = pooled_merge_gc(
                    self.mesh,
                    [(job.staged,
                      GCParams(job.request.history_cutoff_ht, is_major,
                               retain_deletes))
                     for job in wave])
            except Exception as e:  # noqa: BLE001 — wave fault containment
                if not device_faults.is_device_fault(e):
                    for job in wave:
                        self._unpin(job)
                        self._finish(job, exc=e)
                    continue
                # one shard's fault quarantines the BUCKET and completes
                # every wave job natively — co-scheduled tablets' jobs
                # finish byte-identically instead of aborting
                self._c_faults.increment()
                board.record_fault(
                    "run_merge_fused", (bucket[0], bucket[1]),
                    reason=f"pool wave fault: {type(e).__name__}: {e}")
                TRACE("compaction pool: wave device fault (%r) — bucket "
                      "k_pad=%d m=%d quarantined; completing %d job(s) "
                      "natively", e, bucket[0], bucket[1], len(wave))
                for job in wave:
                    self._complete_natively(job, record_rate=False)
                continue
            self._c_waves.increment()
            wall = max(time.monotonic() - t0, 1e-9)
            rows = sum(job.staged.n for job in wave)
            board.record_device("run_merge_fused",
                                (bucket[0], bucket[1]), rows, wall)
            for slot, job in enumerate(wave):
                self._c_wave_jobs.increment()
                try:
                    self._finish_wave_job(job, handle, slot)
                except BaseException as e:  # noqa: BLE001 — per-job
                    self._finish(job, exc=e)
                finally:
                    self._unpin(job)

    def _finish_wave_job(self, job: _Job, handle, slot: int) -> None:
        """Stage C of one wave job: write outputs from the slot's
        decisions through the sequential writer rules (byte-identical),
        installing survivor spans from the slot's device into the
        tablet's cache partition as each SST hits disk."""
        from yugabyte_tpu.storage.compaction import (
            CompactionResult, run_compaction_job_with_decisions)
        job.handle.cancel_token.check()
        perm, keep, mk = handle.decisions[slot]
        surv = perm[keep]
        mk_surv = mk[keep]
        req = job.request
        if req.slabs is not None:
            # merge-only job: the decisions ARE the result
            self._finish(job, result=(surv, mk_surv))
            return
        rows_in = sum(s.n for s in job.slabs) + job.dropped_rows
        on_span = None
        cache = req.device_cache
        if cache is not None:
            in_levels = [cache.level_of(fid)
                         for fid in (req.input_ids or [])
                         if fid is not None]
            out_level = 1 + max([lv for lv in in_levels
                                 if lv is not None], default=0)
            installed: List[int] = []

            def on_span(fid, base_path, start, end,
                        _lvl=out_level, _installed=installed):
                from yugabyte_tpu.storage import integrity
                st = handle.gather_span(slot, start, end)
                target = getattr(cache, "device", None)
                if target is not None and target != "native":
                    import jax as _jax
                    # commit the span to the partition's device so later
                    # merges never mix committed devices
                    st.cols_dev = _jax.device_put(st.cols_dev, target)
                if integrity.maybe_verify_resident_entry(st, base_path):
                    cache.put(fid, st, level=_lvl)
                    _installed.append(fid)
        result = run_compaction_job_with_decisions(
            job.filtered_inputs, job.slabs, req.out_dir, req.new_file_id,
            req.history_cutoff_ht, req.is_major, req.retain_deletes,
            req.block_entries, surv, mk_surv, rows_in,
            frontier_inputs=req.inputs, cancel=job.handle.cancel_token,
            on_span=on_span)
        self._finish(job, result=result)

    def _complete_natively(self, job: _Job, record_rate: bool) -> None:
        """Byte-identical native completion of one pool job (demoted
        bucket or wave-fault containment)."""
        from yugabyte_tpu.storage import compaction as compaction_mod
        try:
            job.handle.cancel_token.check()
            req = job.request
            t0 = time.monotonic()
            if req.slabs is not None:
                # merge-only job: the CPU baseline computes the identical
                # decisions (differential-tested against the kernel)
                from yugabyte_tpu.ops.slabs import concat_slabs
                from yugabyte_tpu.storage.cpu_baseline import (
                    compact_cpu_baseline)
                live = [s for s in job.slabs if s.n]
                merged = concat_slabs(live)
                offsets = np.concatenate(
                    ([0], np.cumsum([s.n for s in live]))).tolist()
                perm, keep, mk = compact_cpu_baseline(
                    merged, offsets, req.history_cutoff_ht, req.is_major,
                    req.retain_deletes)
                result = (perm[keep], mk[keep])
                rows = merged.n
            else:
                result = compaction_mod.run_compaction_job(
                    req.inputs, req.out_dir, req.new_file_id,
                    req.history_cutoff_ht, req.is_major,
                    req.retain_deletes, device="native",
                    block_entries=req.block_entries,
                    cancel=job.handle.cancel_token, _no_combined=True)
                rows = result.rows_in
            self._c_native.increment()
            if record_rate and job.staged is not None:
                from yugabyte_tpu.storage.bucket_health import health_board
                health_board().record_native(
                    "run_merge_fused", (job.staged.k_pad, job.staged.m),
                    rows, max(time.monotonic() - t0, 1e-9))
            self._finish(job, result=result)
        except BaseException as e:  # noqa: BLE001 — per-job containment
            self._finish(job, exc=e)
        finally:
            self._unpin(job)

    def _run_exclusive(self, job: _Job) -> None:
        """A mesh-sized job: the whole mesh, key-range-sharded
        (storage/compaction.run_compaction_job routes it through the
        dist-native path)."""
        from yugabyte_tpu.storage import compaction as compaction_mod
        req = job.request
        try:
            job.handle.cancel_token.check()
            result = compaction_mod.run_compaction_job(
                req.inputs, req.out_dir, req.new_file_id,
                req.history_cutoff_ht, req.is_major, req.retain_deletes,
                device=self.device, block_entries=req.block_entries,
                device_cache=req.device_cache, input_ids=req.input_ids,
                mesh=self.mesh, cancel=job.handle.cancel_token)
            self._finish(job, result=result)
        except BaseException as e:  # noqa: BLE001 — per-job containment
            self._finish(job, exc=e)
