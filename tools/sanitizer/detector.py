"""ybsan detector: vector-clock happens-before race detection.

The model is the classic VC detector (FastTrack's epoch optimisation on
the write side), sized for a CPython test process:

- Every thread owns a vector clock (dict ybsan-tid -> logical clock).
- Every synchronization object carries a clock the instrumentation
  joins through: TrackedLock release publishes the holder's clock into
  the lock, acquire joins it back (utils/lock_rank.py calls the shim);
  Thread.start stamps the child, Thread.join joins the child's final
  clock; queue.Queue put/get flow clocks through the channel;
  threadpool submit/execute flows through `bind_task`. Condition
  wait/notify orders through the condition's (tracked) inner lock: the
  waiter re-acquires only after the notifier released, which is exactly
  the edge the lock instrumentation records.
- Every watched attribute owns a shadow cell: last-write epoch
  (tid, clock, stack) plus a per-thread read map. An access that is
  not HB-ordered after the conflicting epoch is a race; the report
  carries BOTH stacks, the attribute, and the missing HB edge.

Watched attributes come from two sources (tools/sanitizer/instrument.py
wires both):
- auto-discovery: every class attribute carrying a `# guarded-by`
  annotation (the lock-discipline pass's own collection logic builds
  the index) — these additionally check lock POSSESSION once the object
  is observed shared;
- `@ybsan.shadow` opt-in for deliberately lock-free structures — these
  check the STATED discipline (single-writer[-per-key],
  publisher/consumer) and never possession.

False-positive posture: unknown is silent. A guard that is not a
TrackedLock (so neither possession nor HB through it can be observed)
suppresses checking of its attribute entirely; objects only ever
touched by one thread never report; pre-sharing (__init__/publication)
writes never report. Reports are latched and deduplicated by baseline
fingerprint — tools/sanitizer/report.py turns them into yblint
Findings against tools/analysis/baseline.txt.
"""

from __future__ import annotations

import itertools
import os
import threading
import weakref
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from yugabyte_tpu.utils import ybsan as _shim  # noqa: E402

CODE_WRITE_WRITE = "write-write"
CODE_READ_WRITE = "read-write"
CODE_GUARD_NOT_HELD = "guarded-by-without-lock"
CODE_SINGLE_WRITER = "shadow-single-writer"
CODE_ORDER = "shadow-order"
CODE_INTERNAL = "ybsan-internal-error"

_MAX_OBJECTS = 8192      # shadow-cell registry cap (FIFO eviction)
_MAX_REPORTS = 400

# the shared stack vocabulary lives in the shim so utils/lock_rank.py
# renders its cycle reports identically without importing tools/
_capture_stack = _shim.capture_stack
format_stack = _shim.format_stack


class RaceReport:
    """One latched finding. `site` is the innermost in-repo frame of the
    CURRENT access — the stable anchor report.py fingerprints on."""

    __slots__ = ("code", "cls_name", "attr", "key", "detail",
                 "cur_tid", "cur_thread", "cur_stack",
                 "prev_tid", "prev_thread", "prev_stack")

    def __init__(self, code: str, cls_name: str, attr: str,
                 key: Optional[str], detail: str,
                 cur_tid: int, cur_thread: str, cur_stack,
                 prev_tid: Optional[int], prev_thread: Optional[str],
                 prev_stack) -> None:
        self.code = code
        self.cls_name = cls_name
        self.attr = attr
        self.key = key
        self.detail = detail
        self.cur_tid = cur_tid
        self.cur_thread = cur_thread
        self.cur_stack = cur_stack or ()
        self.prev_tid = prev_tid
        self.prev_thread = prev_thread
        self.prev_stack = prev_stack or ()

    @property
    def attr_label(self) -> str:
        a = f"{self.cls_name}.{self.attr}"
        return f"{a}[{self.key!r}]" if self.key is not None else a

    def site(self) -> Tuple[str, int, str]:
        """(relpath, line, func) of the innermost repo frame of the
        current access (preferring non-test frames so the fingerprint
        anchors on the racing production code, not the test driver)."""
        best = None
        for fn, lineno, func in self.cur_stack:
            if not fn.startswith(REPO_ROOT):
                continue
            rel = os.path.relpath(fn, REPO_ROOT).replace(os.sep, "/")
            if best is None:
                best = (rel, lineno, func)
            if not rel.startswith("tests/"):
                return (rel, lineno, func)
        return best or ("<unknown>", 0, "<unknown>")

    def render(self) -> str:
        head = (f"[ybsan/{self.code}] {self.attr_label}: {self.detail}\n"
                f"  current access: thread {self.cur_thread!r} "
                f"(ybsan tid {self.cur_tid})\n"
                + format_stack(self.cur_stack))
        if self.prev_stack or self.prev_tid is not None:
            head += (f"\n  conflicting access: thread "
                     f"{self.prev_thread!r} (ybsan tid {self.prev_tid})\n"
                     + format_stack(self.prev_stack))
        return head


class _Cell:
    """Shadow cell of one watched attribute (one dict key for per-key
    disciplines): FastTrack-ish last-write epoch + read map."""

    __slots__ = ("w_tid", "w_clock", "w_stack", "w_thread",
                 "reads", "threads", "shared")

    def __init__(self) -> None:
        self.w_tid = -1
        self.w_clock = 0
        self.w_stack = ()
        self.w_thread = ""
        # reader tid -> (clock, stack, thread name)
        self.reads: Dict[int, Tuple[int, tuple, str]] = {}
        self.threads: set = set()
        self.shared = False


class _ThreadState:
    __slots__ = ("tid", "vc", "held", "busy", "name")

    def __init__(self, tid: int, name: str) -> None:
        self.tid = tid
        self.vc: Dict[int, int] = {tid: 1}
        self.held: Dict[int, int] = {}   # id(TrackedLock) -> depth
        self.busy = False
        self.name = name

    def tick(self) -> None:
        self.vc[self.tid] = self.vc.get(self.tid, 0) + 1


def _join(dst: Dict[int, int], src: Dict[int, int]) -> None:
    for t, c in src.items():
        if dst.get(t, 0) < c:
            dst[t] = c


class Detector:
    """The process race detector. One instance is installed into the
    yugabyte_tpu.utils.ybsan shim by tools.sanitizer.arm()."""

    def __init__(self) -> None:
        self._lock = threading.Lock()   # leaf lock: no callouts under it
        self._tids = itertools.count(1)
        self._tls = threading.local()
        self._reports: List[RaceReport] = []
        self._seen: set = set()         # dedupe key per latched report
        # id(obj) -> (type, {(attr, key): _Cell}) — FIFO-capped
        self._cells: Dict[int, Tuple[type, Dict[Tuple[str, Optional[str]],
                                                _Cell]]] = {}
        self._dead_keys: List[int] = []   # finalize-queue; GIL-atomic ops
        self._internal_errors = 0

    # ------------------------------------------------------ thread state
    def state(self) -> _ThreadState:
        st = getattr(self._tls, "st", None)
        if st is None:
            with self._lock:
                tid = next(self._tids)
            st = _ThreadState(tid, threading.current_thread().name)
            self._tls.st = st
        return st

    # ------------------------------------------------- sync-object edges
    def lock_acquired(self, lock) -> None:
        st = self.state()
        vc = getattr(lock, "ybsan_vc", None)
        if vc:
            _join(st.vc, vc)
        st.held[id(lock)] = st.held.get(id(lock), 0) + 1

    def lock_releasing(self, lock) -> None:
        st = self.state()
        vc = getattr(lock, "ybsan_vc", None)
        if vc is None:
            vc = {}
            try:
                lock.ybsan_vc = vc
            except AttributeError:
                return  # untracked duck type without the slot
        _join(vc, st.vc)
        st.tick()
        n = st.held.get(id(lock), 0)
        if n <= 1:
            st.held.pop(id(lock), None)
        else:
            st.held[id(lock)] = n - 1

    def thread_started(self, thread) -> None:
        """Caller (the starter) stamps the child and advances."""
        st = self.state()
        thread._ybsan_parent_vc = dict(st.vc)
        st.tick()

    def thread_run_begin(self, thread) -> None:
        st = self.state()
        pvc = getattr(thread, "_ybsan_parent_vc", None)
        if pvc:
            _join(st.vc, pvc)

    def thread_run_end(self, thread) -> None:
        st = self.state()
        thread._ybsan_end_vc = dict(st.vc)
        st.tick()

    def thread_joined(self, thread) -> None:
        evc = getattr(thread, "_ybsan_end_vc", None)
        if evc:
            _join(self.state().vc, evc)

    def channel_send(self, chan) -> None:
        st = self.state()
        with self._lock:
            vc = getattr(chan, "_ybsan_vc", None)
            if vc is None:
                vc = {}
                try:
                    chan._ybsan_vc = vc
                except AttributeError:
                    return
            _join(vc, st.vc)
        st.tick()

    def channel_recv(self, chan) -> None:
        vc = getattr(chan, "_ybsan_vc", None)
        if vc:
            st = self.state()
            with self._lock:
                _join(st.vc, vc)

    def bind_task(self, fn):
        """Threadpool submit -> execute HB edge: the returned wrapper
        joins the submitter's clock snapshot before running `fn`."""
        st = self.state()
        snap = dict(st.vc)
        st.tick()

        def _ybsan_task():
            rst = self.state()
            _join(rst.vc, snap)
            return fn()

        return _ybsan_task

    # ------------------------------------------------------ shadow cells
    def _cells_for(self, obj) -> Dict[Tuple[str, Optional[str]], _Cell]:
        while self._dead_keys:
            try:
                dead = self._dead_keys.pop()
            except IndexError:
                break
            self._cells.pop(dead, None)
        key = id(obj)
        ent = self._cells.get(key)
        if ent is not None and ent[0] is type(obj):
            return ent[1]
        # new object (or id reuse by a different type): fresh cell map
        cells: Dict[Tuple[str, Optional[str]], _Cell] = {}
        self._cells[key] = (type(obj), cells)
        # id() is an address: a dead object's id gets recycled, and a new
        # SAME-type object at that address would inherit the corpse's
        # cells and report false conflicts (observed on rpc client-conn
        # churn). Queue eviction at collection time — the callback must
        # NOT take the detector lock (gc can fire it mid-_access on the
        # thread already holding it), so it only appends to a list and
        # the next _cells_for drains it under the lock.
        try:
            weakref.finalize(obj, self._dead_keys.append, key)
        except TypeError:
            pass  # not weakref-able: FIFO cap + type check still apply
        if len(self._cells) > _MAX_OBJECTS:
            # FIFO eviction: dict preserves insertion order; losing old
            # cells only loses history (false negatives, never noise)
            self._cells.pop(next(iter(self._cells)))
        return cells

    def _holds_guard(self, st: _ThreadState, obj,
                     guard: str) -> Optional[bool]:
        """True/False = the current thread does/does not hold the
        declared guard; None = possession is unobservable (skip)."""
        try:
            g = object.__getattribute__(obj, guard)
        except AttributeError:
            return None
        if isinstance(g, threading.Condition):
            g = getattr(g, "_lock", None)
        # TrackedLock duck-typing (utils/lock_rank.py): the only lock
        # kind whose possession the instrumentation can see
        if g is not None and hasattr(g, "ybsan_vc") \
                and hasattr(g, "name"):
            return id(g) in st.held
        return None

    def _latch(self, rep: RaceReport) -> None:
        site = rep.site()
        dedupe = (rep.code, rep.cls_name, rep.attr, rep.key,
                  site[0], site[2])
        with self._lock:
            if dedupe in self._seen or len(self._reports) >= _MAX_REPORTS:
                return
            self._seen.add(dedupe)
            self._reports.append(rep)
        # satellite: the merged lock_rank violation report + counters
        from yugabyte_tpu.utils import lock_rank
        lock_rank.record_race(rep.render())

    def _hb_after(self, st: _ThreadState, tid: int, clock: int) -> bool:
        return st.vc.get(tid, 0) >= clock

    def access(self, obj, attr: str, is_write: bool,
               guard: Optional[str] = None,
               discipline: Optional[str] = None,
               key: Optional[str] = None) -> None:
        """One watched attribute access. Exactly one of guard/discipline
        describes the declared protocol."""
        st = self.state()
        if st.busy:
            return
        st.busy = True
        try:
            self._access(st, obj, attr, is_write, guard, discipline, key)
        except Exception as e:   # a sanitizer bug must not take the
            # app down mid-test, but it must FAIL the run: latch it as
            # its own loud report (never silently swallowed)
            with self._lock:
                self._internal_errors += 1
                if CODE_INTERNAL not in self._seen:
                    self._seen.add(CODE_INTERNAL)
                    self._reports.append(RaceReport(
                        CODE_INTERNAL, type(obj).__name__, attr, key,
                        f"detector raised {type(e).__name__}: {e}",
                        st.tid, st.name, _capture_stack(),
                        None, None, ()))
        finally:
            st.busy = False

    def _access(self, st: _ThreadState, obj, attr: str, is_write: bool,
                guard: Optional[str], discipline: Optional[str],
                key: Optional[str]) -> None:
        if guard is not None:
            held = self._holds_guard(st, obj, guard)
            if held is None:
                return   # unobservable guard: unknown is silent
        else:
            held = None
        cls_name = type(obj).__name__
        with self._lock:
            cells = self._cells_for(obj)
            cell = cells.get((attr, key))
            if cell is None:
                cell = cells[(attr, key)] = _Cell()
            cell.threads.add(st.tid)
            if len(cell.threads) > 1:
                cell.shared = True
            shared = cell.shared
            w_tid, w_clock = cell.w_tid, cell.w_clock
            w_stack, w_thread = cell.w_stack, cell.w_thread
            readers = list(cell.reads.items()) if is_write else ()
            clock_now = st.vc.get(st.tid, 0)
            # Stack capture dominates armed overhead; the clock only
            # advances at sync operations, so a same-epoch repeat access
            # by the same thread reuses the first capture (the report
            # shows the epoch's first site — epochs, not stacks, decide
            # whether a conflict exists).
            if is_write:
                if cell.w_tid == st.tid and cell.w_clock == clock_now \
                        and cell.w_stack:
                    stack = cell.w_stack
                else:
                    stack = _capture_stack()
                cell.w_tid, cell.w_clock = st.tid, clock_now
                cell.w_stack, cell.w_thread = stack, st.name
                cell.reads.clear()
            else:
                # read epochs only matter for write conflicts later;
                # capture the stack so THAT report can show this side
                prev = cell.reads.get(st.tid)
                if prev is not None and prev[0] == clock_now:
                    stack = prev[1]
                else:
                    stack = _capture_stack()
                    cell.reads[st.tid] = (clock_now, stack, st.name)

        # conflict checks outside the detector lock (latching re-takes it)
        check_reads = discipline != _shim.SINGLE_WRITER and \
            discipline != _shim.SINGLE_WRITER_PER_KEY
        if w_tid >= 0 and w_tid != st.tid \
                and not self._hb_after(st, w_tid, w_clock):
            kind = CODE_WRITE_WRITE if is_write else CODE_READ_WRITE
            if discipline in (_shim.SINGLE_WRITER,
                              _shim.SINGLE_WRITER_PER_KEY):
                if not is_write:
                    kind = None   # racy reads tolerated by declaration
                else:
                    kind = CODE_SINGLE_WRITER
            elif discipline == _shim.PUBLISHER_CONSUMER:
                kind = CODE_SINGLE_WRITER if is_write else CODE_ORDER
            if kind is not None:
                self._latch(RaceReport(
                    kind, cls_name, attr, key,
                    self._edge_detail(st, w_tid, w_clock, guard,
                                      discipline, "write"),
                    st.tid, st.name, stack, w_tid, w_thread, w_stack))
        if is_write and check_reads:
            for r_tid, (r_clock, r_stack, r_thread) in readers:
                if r_tid != st.tid \
                        and not self._hb_after(st, r_tid, r_clock):
                    self._latch(RaceReport(
                        CODE_READ_WRITE, cls_name, attr, key,
                        self._edge_detail(st, r_tid, r_clock, guard,
                                          discipline, "read"),
                        st.tid, st.name, stack, r_tid, r_thread,
                        r_stack))
                    break
        # possession: only for guarded-by attrs, only once shared
        if guard is not None and shared and held is False:
            self._latch(RaceReport(
                CODE_GUARD_NOT_HELD, cls_name, attr, key,
                f"{'write' if is_write else 'read'} without holding the "
                f"declared guard `{guard}` on a shared object "
                f"(annotated `# guarded-by: {guard}`)",
                st.tid, st.name, stack or _capture_stack(),
                None, None, ()))

    def _edge_detail(self, st: _ThreadState, o_tid: int, o_clock: int,
                     guard: Optional[str], discipline: Optional[str],
                     o_kind: str) -> str:
        have = st.vc.get(o_tid, 0)
        fix = (f"both sides must hold the declared guard `{guard}`"
               if guard is not None else
               f"declared discipline `{discipline}` requires an ordering "
               f"edge (lock release/acquire, queue put/get, thread "
               f"start/join)")
        return (f"no happens-before edge from the conflicting {o_kind} "
                f"(tid {o_tid} @ clock {o_clock}; this thread has only "
                f"observed tid {o_tid} up to clock {have}) — {fix}")

    # -------------------------------------------------------- inspection
    def reports(self) -> List[RaceReport]:
        with self._lock:
            return list(self._reports)

    def race_count(self) -> int:
        with self._lock:
            return len(self._reports)

    def internal_errors(self) -> int:
        with self._lock:
            return self._internal_errors

    def reset(self) -> None:
        with self._lock:
            self._reports.clear()
            self._seen.clear()
            self._cells.clear()
            self._internal_errors = 0
