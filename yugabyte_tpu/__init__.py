"""yugabyte_tpu: a TPU-native distributed document store.

A brand-new framework with the capabilities of YugabyteDB (reference:
/root/reference, see SURVEY.md): a sharded, Raft-replicated, MVCC document
store over an LSM storage engine, with distributed ACID transactions and
CQL/SQL/Redis query layers.

TPU-first design: the LSM hot path (compaction k-way merge, MVCC garbage
collection, scan/filter) runs as batched JAX sort/segment-reduce kernels on
TPU (`yugabyte_tpu.ops`), sharded across device meshes
(`yugabyte_tpu.parallel`), with a CPU fallback that produces byte-identical
SSTs.

Layer map (mirrors SURVEY.md section 1):
  utils/     - foundation: Status, flags, metrics, trace  (ref: src/yb/util)
  common/    - HybridTime, schema, partitioning           (ref: src/yb/common)
  docdb/     - doc key/value encoding, MVCC semantics     (ref: src/yb/docdb)
  storage/   - LSM engine: memtable, SST, compaction      (ref: src/yb/rocksdb)
  ops/       - TPU kernels: merge, GC, scan, bloom        (the new hot path)
  parallel/  - mesh sharding, distributed compaction      (ref: NCCL-less rpc)
  consensus/ - Raft, WAL                                  (ref: src/yb/consensus)
  tablet/    - tablet, MVCC manager, write pipeline       (ref: src/yb/tablet)
  server/    - tserver, master, heartbeats                (ref: src/yb/tserver, master)
  client/    - client, meta-cache, batcher                (ref: src/yb/client)
  yql/       - CQL-subset / Redis-subset / SQL frontends  (ref: src/yb/yql)
  models/    - workload models (YCSB) and the flagship
               compaction-pipeline "model" used for benchmarking
"""

__version__ = "0.1.0"
