"""YCQL-subset parser: hand-written tokenizer + recursive descent.

Capability parity with the reference's CQL frontend (ref: src/yb/yql/cql/ql/
parser/ — a bison grammar over the full CQL dialect; ptree/ analyzer). This
covers the core DML/DDL surface (the YCSB / kv-workload subset plus
multi-statement transactions): CREATE KEYSPACE / CREATE TABLE with
hash+range primary keys / DROP TABLE / INSERT (USING TTL) / SELECT with
WHERE + LIMIT / UPDATE / DELETE / BEGIN TRANSACTION ... END TRANSACTION.
Bind markers (?) fill from an ordered params list, like the reference's
prepared statements.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from yugabyte_tpu.utils.status import Status, StatusError


class ParseError(StatusError):
    def __init__(self, msg: str):
        super().__init__(Status.InvalidArgument(f"syntax error: {msg}"))


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<string>'(?:[^']|'')*')
    | (?P<blob>0[xX][0-9a-fA-F]+)
    | (?P<number>-?\d+\.\d+|-?\d+)
    | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<param>\$\d+)
    | (?P<op>->>|->|<=|>=|!=|[=<>(),;*?.+%/\[\]{}:-])
    )""", re.VERBOSE)


def tokenize(text: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ParseError(f"unexpected character {text[pos]!r}")
        pos = m.end()
        kind = m.lastgroup
        tok = m.group(kind)
        # `price-2` / `3-1`: a '-' directly after a value-like token is
        # the subtraction operator, not a negative-literal sign (PG lexes
        # '-' as an operator always; we keep the sign only where a value
        # cannot precede it, e.g. VALUES (-5))
        if kind == "number" and tok.startswith("-") and out and (
                out[-1][0] in ("name", "number", "blob", "param")
                or out[-1] == ("op", ")")):
            out.append(("op", "-"))
            out.append(("number", tok[1:]))
        else:
            out.append((kind, tok))
    return out


# --------------------------------------------------------------- statements
@dataclass
class CreateKeyspace:
    name: str
    if_not_exists: bool = False


@dataclass
class CreateTable:
    keyspace: Optional[str]
    name: str
    columns: List[Tuple[str, str]]            # (name, cql type)
    hash_keys: List[str]
    range_keys: List[str]
    num_tablets: int = 4
    if_not_exists: bool = False


@dataclass
class DropTable:
    keyspace: Optional[str]
    name: str
    if_exists: bool = False


@dataclass
class AlterTable:
    keyspace: Optional[str]
    name: str
    add_columns: List[Tuple[str, str]]   # (name, cql type)
    drop_columns: List[str]


@dataclass
class CreateIndex:
    index_name: Optional[str]
    keyspace: Optional[str]
    table: str
    columns: List[str]
    if_not_exists: bool = False


@dataclass
class FuncCall:
    """Builtin invocation in a select list or value expression (ref: the
    grammar's function_call; resolved against yql/bfunc.py's registry,
    the bfql/directory.cc equivalent)."""
    name: str
    args: List[object]                        # ColumnRef | FuncCall | literal


@dataclass(frozen=True)
class ColumnRef:
    name: str


@dataclass(frozen=True)
class TokenRef:
    """token(pk_cols): the row's 16-bit partition hash — the CQL token
    function used for partition-range scans by bulk readers (ref: the
    grammar's token function; our partition hash is
    common/partition.hash_column_compound_value)."""
    columns: Tuple[str, ...]


@dataclass(frozen=True)
class JsonOp:
    """JSONB path navigation: col->'key'->2->>'leaf' (ref: the reference's
    jsonb operators in ql — common/jsonb.cc ApplyJsonbOperators; PG's
    jsonb -> / ->> semantics). path holds object keys (str) and array
    indexes (int); as_text marks a trailing ->> (text extraction)."""
    column: str
    path: Tuple[object, ...]
    as_text: bool = False


@dataclass
class Insert:
    keyspace: Optional[str]
    table: str
    columns: List[str]
    values: List[object]                      # literal | FuncCall
    ttl_seconds: Optional[int] = None
    # lightweight transaction: INSERT ... IF NOT EXISTS (ref: the CQL
    # conditional DML surface; executed as a read-check-write txn like
    # the reference's conditional QLWriteRequest with if_expr)
    if_not_exists: bool = False


@dataclass
class Select:
    keyspace: Optional[str]
    table: str
    columns: Optional[List[str]]              # None = *
    where: List[Tuple[str, str, object]] = field(default_factory=list)
    limit: Optional[int] = None
    # SELECT DISTINCT <partition key cols> (CQL restricts DISTINCT to
    # the partition key; ref the grammar's distinct handling)
    distinct: bool = False
    # ORDER BY clustering_col [ASC|DESC] — valid only with the partition
    # key restricted (CQL semantics; ref: sem/analyzer order-by checks)
    order_by: List[Tuple[str, bool]] = field(default_factory=list)


@dataclass
class Update:
    keyspace: Optional[str]
    table: str
    assignments: List[Tuple[str, object]]
    where: List[Tuple[str, str, object]]
    ttl_seconds: Optional[int] = None
    # IF EXISTS / IF col op val [AND ...] conditions (LWT)
    if_exists: bool = False
    conditions: List[Tuple[str, str, object]] = field(default_factory=list)


@dataclass
class Delete:
    keyspace: Optional[str]
    table: str
    where: List[Tuple[str, str, object]]
    columns: Optional[List[str]] = None       # DELETE col FROM ...
    if_exists: bool = False
    conditions: List[Tuple[str, str, object]] = field(default_factory=list)


@dataclass
class Truncate:
    """TRUNCATE [TABLE] ks.t (ref: the CQL truncate statement, executed
    by the reference as a whole-tablet truncation)."""
    keyspace: Optional[str]
    table: str


@dataclass
class Transaction:
    statements: List[Union[Insert, Update, Delete]]


@dataclass
class UseKeyspace:
    name: str


Statement = Union[CreateKeyspace, CreateTable, DropTable, Insert, Select,
                  Update, Delete, Transaction, UseKeyspace]


class _Marker:
    """A `?` bind marker awaiting a parameter."""


MARKER = _Marker()


class Parser:
    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.pos = 0

    # ------------------------------------------------------------- helpers
    def peek(self) -> Optional[Tuple[str, str]]:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise ParseError("unexpected end of statement")
        self.pos += 1
        return tok

    def accept_kw(self, *words: str) -> bool:
        tok = self.peek()
        if tok and tok[0] == "name" and tok[1].upper() == words[0]:
            save = self.pos
            for i, w in enumerate(words):
                tok = self.peek()
                if not (tok and tok[0] == "name" and tok[1].upper() == w):
                    self.pos = save
                    return False
                self.pos += 1
            return True
        return False

    def expect_kw(self, *words: str) -> None:
        if not self.accept_kw(*words):
            raise ParseError(f"expected {' '.join(words)}, got {self.peek()}")

    def accept_op(self, op: str) -> bool:
        tok = self.peek()
        if tok and tok[0] == "op" and tok[1] == op:
            self.pos += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r}, got {self.peek()}")

    def name(self) -> str:
        tok = self.next()
        if tok[0] != "name":
            raise ParseError(f"expected identifier, got {tok[1]!r}")
        return tok[1]

    def qualified_name(self) -> Tuple[Optional[str], str]:
        first = self.name()
        if self.accept_op("."):
            return first, self.name()
        return None, first

    def _column_type(self) -> str:
        """Type name, including collections: LIST<T>, SET<T>, MAP<K,V>,
        FROZEN<...> (ref: common/ql_type.h). Returned as the canonical
        text form, e.g. 'MAP<TEXT,INT>'."""
        t = self.name().upper()
        if t == "FROZEN" and self.accept_op("<"):
            inner = self._column_type()
            self.expect_op(">")
            return f"FROZEN<{inner}>"
        if t in ("LIST", "SET", "MAP") and self.accept_op("<"):
            inner = [self._column_type()]
            while self.accept_op(","):
                inner.append(self._column_type())
            self.expect_op(">")
            return f"{t}<{','.join(inner)}>"
        return t

    def literal(self):
        # collection literals: [e, ...] list, {e, ...} set, {k: v, ...} map
        nxt = self.peek()
        if nxt == ("op", "["):
            self.next()
            out = []
            if not self.accept_op("]"):
                out.append(self.literal())
                while self.accept_op(","):
                    out.append(self.literal())
                self.expect_op("]")
            return out
        if nxt == ("op", "{"):
            self.next()
            if self.accept_op("}"):
                return {}
            try:
                first = self.literal()
                if self.accept_op(":"):        # map
                    m = {first: self.literal()}
                    while self.accept_op(","):
                        k = self.literal()
                        self.expect_op(":")
                        m[k] = self.literal()
                    self.expect_op("}")
                    return m
                s = {first}                    # set
                while self.accept_op(","):
                    s.add(self.literal())
                self.expect_op("}")
                return s
            except TypeError:
                raise ParseError(
                    "set/map literal elements must be hashable scalars")
        tok = self.next()
        kind, text = tok
        if kind == "string":
            return text[1:-1].replace("''", "'")
        if kind == "number":
            return float(text) if "." in text else int(text)
        if kind == "blob":
            return bytes.fromhex(text[2:])
        if kind == "op" and text == "?":
            return MARKER
        if kind == "name":
            u = text.upper()
            if u == "TRUE":
                return True
            if u == "FALSE":
                return False
            if u == "NULL":
                return None
        raise ParseError(f"expected literal, got {text!r}")

    # ----------------------------------------------------------- statements
    def parse(self) -> Statement:
        if self.accept_kw("CREATE", "KEYSPACE"):
            ine = self.accept_kw("IF", "NOT", "EXISTS")
            return CreateKeyspace(self.name(), ine)
        if self.accept_kw("CREATE", "TABLE"):
            return self._create_table()
        if self.accept_kw("CREATE", "INDEX"):
            return self._create_index()
        if self.accept_kw("DROP", "TABLE"):
            ife = self.accept_kw("IF", "EXISTS")
            ks, name = self.qualified_name()
            return DropTable(ks, name, ife)
        if self.accept_kw("ALTER", "TABLE"):
            ks, name = self.qualified_name()
            add, drop = [], []
            while True:
                if self.accept_kw("ADD"):
                    col = self.name()
                    add.append((col, self.name()))
                elif self.accept_kw("DROP"):
                    drop.append(self.name())
                else:
                    raise ParseError(
                        f"expected ADD or DROP, got {self.peek()}")
                if not self.accept_op(","):
                    break
            return AlterTable(ks, name, add, drop)
        if self.accept_kw("USE"):
            return UseKeyspace(self.name())
        if self.accept_kw("INSERT", "INTO"):
            return self._insert()
        if self.accept_kw("SELECT"):
            return self._select()
        if self.accept_kw("UPDATE"):
            return self._update()
        if self.accept_kw("DELETE"):
            return self._delete()
        if self.accept_kw("BEGIN", "TRANSACTION"):
            return self._transaction()
        if self.accept_kw("TRUNCATE"):
            self.accept_kw("TABLE")
            ks, name = self.qualified_name()
            return Truncate(ks, name)
        raise ParseError(f"unrecognized statement start: {self.peek()}")

    def _create_index(self) -> CreateIndex:
        """CREATE INDEX [IF NOT EXISTS] [name] ON [ks.]table (column)
        (ref: the YCQL grammar's index_stmt, ql/ptree/pt_create_index.h)."""
        ine = self.accept_kw("IF", "NOT", "EXISTS")
        index_name = None
        if not self.accept_kw("ON"):
            index_name = self.name()
            self.expect_kw("ON")
        ks, table = self.qualified_name()
        self.expect_op("(")
        columns = [self.name()]
        while self.accept_op(","):
            columns.append(self.name())
        self.expect_op(")")
        return CreateIndex(index_name, ks, table, columns, ine)

    def _create_table(self) -> CreateTable:
        ine = self.accept_kw("IF", "NOT", "EXISTS")
        ks, name = self.qualified_name()
        self.expect_op("(")
        columns: List[Tuple[str, str]] = []
        hash_keys: List[str] = []
        range_keys: List[str] = []
        while True:
            if self.accept_kw("PRIMARY", "KEY"):
                self.expect_op("(")
                if self.accept_op("("):   # ((h1, h2), r1, ...)
                    hash_keys.append(self.name())
                    while self.accept_op(","):
                        hash_keys.append(self.name())
                    self.expect_op(")")
                else:
                    hash_keys.append(self.name())
                while self.accept_op(","):
                    range_keys.append(self.name())
                self.expect_op(")")
            else:
                cname = self.name()
                ctype = self._column_type()
                columns.append((cname, ctype))
                if self.accept_kw("PRIMARY", "KEY"):
                    hash_keys.append(cname)
            if not self.accept_op(","):
                break
        self.expect_op(")")
        num_tablets = 4
        if self.accept_kw("WITH"):
            while True:
                prop = self.name().lower()
                self.expect_op("=")
                val = self.literal()
                if prop == "tablets":
                    num_tablets = int(val)
                if not self.accept_kw("AND"):
                    break
        if not hash_keys:
            raise ParseError("no PRIMARY KEY defined")
        return CreateTable(ks, name, columns, hash_keys, range_keys,
                           num_tablets, ine)

    def _peek2(self):
        return self.toks[self.pos + 1] if self.pos + 1 < len(self.toks) \
            else None

    def _func_call(self) -> FuncCall:
        fname = self.name()
        self.expect_op("(")
        if fname.upper() == "COUNT" and self.accept_op("*"):
            # COUNT(*) — the star is an aggregate-only argument form
            self.expect_op(")")
            return FuncCall(fname, ["*"])
        args: List[object] = []
        if not self.accept_op(")"):
            args.append(self._func_arg())
            while self.accept_op(","):
                args.append(self._func_arg())
            self.expect_op(")")
        return FuncCall(fname, args)

    def _func_arg(self):
        tok = self.peek()
        if tok and tok[0] == "name" and \
                tok[1].upper() not in ("TRUE", "FALSE", "NULL"):
            if self._peek2() == ("op", "("):
                return self._func_call()
            return ColumnRef(self.name())
        return self.literal()

    def _value_expr(self):
        """literal, or a builtin call over literals — INSERT ... VALUES
        (now(), uuid(), intasblob(7), ...)."""
        tok = self.peek()
        if tok and tok[0] == "name" and self._peek2() == ("op", "(") \
                and tok[1].upper() not in ("TRUE", "FALSE", "NULL"):
            return self._func_call()
        return self.literal()

    def _insert(self) -> Insert:
        ks, table = self.qualified_name()
        self.expect_op("(")
        cols = [self.name()]
        while self.accept_op(","):
            cols.append(self.name())
        self.expect_op(")")
        self.expect_kw("VALUES")
        self.expect_op("(")
        vals = [self._value_expr()]
        while self.accept_op(","):
            vals.append(self._value_expr())
        self.expect_op(")")
        ine = self.accept_kw("IF", "NOT", "EXISTS")
        ttl = None
        if self.accept_kw("USING", "TTL"):
            ttl = int(self.literal())
        if not ine:
            ine = self.accept_kw("IF", "NOT", "EXISTS")
        if len(cols) != len(vals):
            raise ParseError(f"{len(cols)} columns but {len(vals)} values")
        return Insert(ks, table, cols, vals, ttl, bool(ine))

    def _if_conditions(self):
        """Trailing IF EXISTS / IF col op literal [AND ...] of UPDATE and
        DELETE -> (if_exists, conditions)."""
        if not self.accept_kw("IF"):
            return False, []
        if self.accept_kw("EXISTS"):
            return True, []
        conds = []
        while True:
            col = self.name()
            tok = self.next()
            if tok[0] != "op" or tok[1] not in ("=", "<", ">", "<=",
                                                ">=", "!="):
                raise ParseError(
                    f"expected comparison in IF, got {tok[1]!r}")
            conds.append((col, tok[1], self.literal()))
            if not self.accept_kw("AND"):
                return False, conds

    def _json_path(self, col: str) -> JsonOp:
        """col ->'k' ->0 ... [->>'leaf'] — ->> is terminal (it yields
        text, which has no further json structure to navigate)."""
        path: List[object] = []
        as_text = False
        while True:
            if self.accept_op("->"):
                terminal = False
            elif self.accept_op("->>"):
                terminal = True
            else:
                break
            tok = self.next()
            if tok[0] == "string":
                path.append(tok[1][1:-1].replace("''", "'"))
            elif tok[0] == "number" and "." not in tok[1]:
                path.append(int(tok[1]))
            else:
                raise ParseError(
                    f"json path operand must be a text key or an array "
                    f"index, got {tok[1]!r}")
            if terminal:
                as_text = True
                if self.peek() in (("op", "->"), ("op", "->>")):
                    raise ParseError("->> returns text: no further json "
                                     "navigation is possible")
                break
        return JsonOp(col, tuple(path), as_text)

    def _token_args(self) -> TokenRef:
        """name [, name]* ')' of a token(...) call (opening paren already
        consumed) — shared by the select-list and WHERE grammars."""
        cols = [self.name()]
        while self.accept_op(","):
            cols.append(self.name())
        self.expect_op(")")
        return TokenRef(tuple(cols))

    def _select_item(self):
        tok = self.peek()
        if tok and tok[0] == "name" and tok[1].upper() == "TOKEN" \
                and self._peek2() == ("op", "("):
            self.name()
            self.expect_op("(")
            return self._token_args()
        if tok and tok[0] == "name" and self._peek2() == ("op", "("):
            return self._func_call()
        col = self.name()
        if self.peek() in (("op", "->"), ("op", "->>")):
            return self._json_path(col)
        return col

    def _select(self) -> Select:
        distinct = bool(self.accept_kw("DISTINCT"))
        if self.accept_op("*"):
            cols = None
        else:
            cols = [self._select_item()]
            while self.accept_op(","):
                cols.append(self._select_item())
        self.expect_kw("FROM")
        ks, table = self.qualified_name()
        where = self._where() if self.accept_kw("WHERE") else []
        order_by: List[Tuple[str, bool]] = []
        if self.accept_kw("ORDER", "BY"):
            while True:
                col = self.name()
                desc = bool(self.accept_kw("DESC"))
                if not desc:
                    self.accept_kw("ASC")
                order_by.append((col, desc))
                if not self.accept_op(","):
                    break
        limit = None
        if self.accept_kw("LIMIT"):
            limit = int(self.literal())
        self.accept_kw("ALLOW", "FILTERING")
        return Select(ks, table, cols, where, limit, order_by=order_by,
                      distinct=distinct)

    def _where(self) -> List[Tuple[str, str, object]]:
        conds = []
        while True:
            col = self.name()
            if col.upper() == "TOKEN" and self.accept_op("("):
                col = self._token_args()
            elif self.peek() in (("op", "->"), ("op", "->>")):
                col = self._json_path(col)
            if self.accept_kw("IN"):
                # col IN (v1, v2, ...) — drives the discrete ScanChoices
                # strategy (ref docdb/scan_choices.cc option iteration)
                self.expect_op("(")
                vals = [self.literal()]
                while self.accept_op(","):
                    vals.append(self.literal())
                self.expect_op(")")
                conds.append((col, "in", vals))
            else:
                tok = self.next()
                if tok[0] != "op" or tok[1] not in ("=", "<", ">", "<=",
                                                    ">=", "!="):
                    raise ParseError(f"expected comparison, got {tok[1]!r}")
                conds.append((col, tok[1], self.literal()))
            if not self.accept_kw("AND"):
                return conds

    def _update(self) -> Update:
        ks, table = self.qualified_name()
        ttl = None
        if self.accept_kw("USING", "TTL"):
            ttl = int(self.literal())
        self.expect_kw("SET")
        assignments = []
        while True:
            col = self.name()
            if self.accept_op("["):
                # element assignment: m['k'] = v / l[i] = v
                sub = self.literal()
                self.expect_op("]")
                self.expect_op("=")
                assignments.append(((col, sub), self.literal()))
            else:
                self.expect_op("=")
                nxt = self.peek()
                if nxt == ("name", col):
                    # col = col + X (append/merge) | col = col - X (remove)
                    self.next()
                    tok = self.next()
                    if tok[0] != "op" or tok[1] not in ("+", "-"):
                        raise ParseError(
                            f"expected + or - after '{col} = {col}'")
                    tag = "__append__" if tok[1] == "+" else "__remove__"
                    assignments.append((col, (tag, self.literal())))
                else:
                    assignments.append((col, self.literal()))
            if not self.accept_op(","):
                break
        self.expect_kw("WHERE")
        where = self._where()
        ife, conds = self._if_conditions()
        return Update(ks, table, assignments, where, ttl,
                      if_exists=ife, conditions=conds)

    def _delete_target(self):
        col = self.name()
        if self.accept_op("["):
            sub = self.literal()
            self.expect_op("]")
            return (col, sub)
        return col

    def _delete(self) -> Delete:
        cols = None
        if not (self.peek() and self.peek()[0] == "name"
                and self.peek()[1].upper() == "FROM"):
            cols = [self._delete_target()]
            while self.accept_op(","):
                cols.append(self._delete_target())
        self.expect_kw("FROM")
        ks, table = self.qualified_name()
        self.expect_kw("WHERE")
        where = self._where()
        ife, conds = self._if_conditions()
        return Delete(ks, table, where, cols,
                      if_exists=ife, conditions=conds)

    def _transaction(self) -> Transaction:
        stmts: List[Union[Insert, Update, Delete]] = []
        while True:
            if self.accept_kw("END", "TRANSACTION"):
                break
            if self.accept_op(";"):
                continue
            if self.accept_kw("INSERT", "INTO"):
                stmts.append(self._insert())
            elif self.accept_kw("UPDATE"):
                stmts.append(self._update())
            elif self.accept_kw("DELETE"):
                stmts.append(self._delete())
            else:
                raise ParseError(
                    f"only DML allowed in transactions, got {self.peek()}")
        return Transaction(stmts)


def parse(text: str) -> Statement:
    p = Parser(text)
    stmt = p.parse()
    p.accept_op(";")
    if p.peek() is not None:
        raise ParseError(f"trailing tokens: {p.peek()}")
    return stmt
