"""yblint: the project's unified AST analysis framework.

One parse + one walk per file, shared by every registered pass; a
whole-program ProjectIndex (symbol table, import aliases, class-attr
types, call graph) built exactly once per run for the cross-file passes;
per-file parallel execution; a committed per-pass-sectioned baseline for
justified suppressions; JSON and human output. Run as
`python -m tools.analysis` (see __main__.py), via `tools/check.sh`, or
from CI via `run_analysis()` / the tier-1 test in tests/test_yblint.py.

Adding a pass: subclass tools.analysis.core.AnalysisPass, implement
`run(ctx)` returning Findings (set `needs_index = True` for
`run(ctx, index)` whole-program passes), and append an instance to
tools.analysis.passes.ALL_PASSES. See tools/analysis/passes/ for the
nine shipped passes: jit trace-safety, lock discipline, blocking-call-
in-reactor, swallowed errors, metric naming, donation safety, error
propagation, resource lifetime and wire drift.
"""

from tools.analysis.core import (AnalysisPass, Baseline, FileContext,
                                 Finding, analyze_paths, run_analysis)

__all__ = ["AnalysisPass", "Baseline", "FileContext", "Finding",
           "analyze_paths", "run_analysis"]
