"""Differential tests: TPU merge+GC kernel vs the Python semantic model.

Mirrors the reference's randomized model-check strategy
(docdb/randomized_docdb-test.cc): generate random write histories, run the
device kernel and the loop-based oracle, require identical surviving entries.
"""

import random

import numpy as np
import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.compaction_model import ModelEntry, compact_model, sort_key
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.ops.merge_gc import GCParams, merge_and_gc_device
from yugabyte_tpu.ops.slabs import KVSlab, pack_doc_ht, pack_kvs


def slab_from_model(entries):
    """Build a KVSlab from ModelEntries (values encode tombstone/ttl flags)."""
    triples = []
    dkls = []
    for i, e in enumerate(entries):
        v = Value(primitive=i, is_tombstone=e.is_tombstone,
                  is_object=e.is_object_init, ttl_ms=e.ttl_ms)
        triples.append((e.key, pack_doc_ht(e.dht), v.encode()))
        dkls.append(e.doc_key_len)
    return pack_kvs(triples, doc_key_lens=dkls)


def run_kernel(entries, cutoff, is_major, retain_deletes=False):
    slab = slab_from_model(entries)
    perm, keep, mk = merge_and_gc_device(
        slab, GCParams(cutoff, is_major, retain_deletes))
    surviving = []
    for pos in range(len(entries)):
        if keep[pos]:
            surviving.append((entries[int(perm[pos])], bool(mk[pos])))
    return surviving


def check_match(entries, cutoff, is_major, retain_deletes=False):
    got = run_kernel(entries, cutoff, is_major, retain_deletes)
    want = compact_model(entries, cutoff, is_major, retain_deletes)
    got_c = [(sort_key(e), mk) for e, mk in got]
    want_c = [(sort_key(r.entry), r.as_tombstone) for r in want]
    assert got_c == want_c, (
        f"kernel kept {len(got)} vs model {len(want)}\n"
        f"kernel: {[ (e.key, e.dht, mk) for e, mk in got ]}\n"
        f"model:  {[ (r.entry.key, r.entry.dht, r.as_tombstone) for r in want ]}")


def ht(us, w=0):
    return DocHybridTime(HybridTime.from_micros(us), w)


def mk_key(row, col=None):
    dk = DocKey(range_components=(f"row{row:04d}",))
    dkl = len(dk.encode())
    if col is None:
        return dk.encode(), dkl
    return SubDocKey(dk, (("col", col),)).encode(include_ht=False), dkl


CUTOFF = HybridTime.from_micros(1000).value


class TestBasicGC:
    def test_old_versions_collapse(self):
        k, dkl = mk_key(1)
        entries = [ModelEntry(k, dkl, ht(t)) for t in (100, 200, 300)]
        kept = run_kernel(entries, CUTOFF, is_major=False)
        # Only the newest <=cutoff version survives.
        assert [e.dht.ht.physical_micros for e, _ in kept] == [300]

    def test_versions_above_cutoff_retained(self):
        k, dkl = mk_key(1)
        entries = [ModelEntry(k, dkl, ht(t)) for t in (100, 2000, 3000)]
        kept = run_kernel(entries, CUTOFF, is_major=False)
        assert sorted(e.dht.ht.physical_micros for e, _ in kept) == [100, 2000, 3000]

    def test_tombstone_dropped_only_at_major(self):
        k, dkl = mk_key(2)
        entries = [ModelEntry(k, dkl, ht(100)),
                   ModelEntry(k, dkl, ht(200), is_tombstone=True)]
        minor = run_kernel(entries, CUTOFF, is_major=False)
        assert [(e.dht.ht.physical_micros, e.is_tombstone) for e, _ in minor] == [(200, True)]
        major = run_kernel(entries, CUTOFF, is_major=True)
        assert major == []

    def test_retain_deletes_keeps_tombstone_at_major(self):
        k, dkl = mk_key(2)
        entries = [ModelEntry(k, dkl, ht(200), is_tombstone=True)]
        kept = run_kernel(entries, CUTOFF, is_major=True, retain_deletes=True)
        assert len(kept) == 1


class TestRowSemantics:
    def test_row_tombstone_covers_columns(self):
        rk, rdkl = mk_key(3)
        c0, _ = mk_key(3, col=0)
        c1, _ = mk_key(3, col=1)
        entries = [
            ModelEntry(c0, rdkl, ht(100, 1)),
            ModelEntry(c1, rdkl, ht(100, 2)),
            ModelEntry(rk, rdkl, ht(500), is_tombstone=True),
        ]
        major = run_kernel(entries, CUTOFF, is_major=True)
        assert major == []  # tombstone + everything under it vanish

    def test_insert_at_same_ht_not_covered(self):
        """Init marker + columns written in one batch (same HT, rising write_id)."""
        rk, rdkl = mk_key(4)
        c0, _ = mk_key(4, col=0)
        entries = [
            ModelEntry(rk, rdkl, ht(100, 0), is_object_init=True),
            ModelEntry(c0, rdkl, ht(100, 1)),
        ]
        kept = run_kernel(entries, CUTOFF, is_major=False)
        assert len(kept) == 2

    def test_newer_column_survives_row_tombstone(self):
        rk, rdkl = mk_key(5)
        c0, _ = mk_key(5, col=0)
        entries = [
            ModelEntry(rk, rdkl, ht(300), is_tombstone=True),
            ModelEntry(c0, rdkl, ht(400)),  # re-inserted after delete
        ]
        kept = run_kernel(entries, CUTOFF, is_major=True)
        assert [(e.key, e.dht.ht.physical_micros) for e, _ in kept] == [(c0, 400)]


class TestTTL:
    def test_expired_becomes_tombstone_minor_dropped_major(self):
        k, dkl = mk_key(6)
        entries = [ModelEntry(k, dkl, ht(100), ttl_ms=0)]  # expires immediately
        minor = run_kernel(entries, CUTOFF, is_major=False)
        assert [(e.dht.ht.physical_micros, mk) for e, mk in minor] == [(100, True)]
        major = run_kernel(entries, CUTOFF, is_major=True)
        assert major == []

    def test_unexpired_ttl_survives(self):
        k, dkl = mk_key(6)
        entries = [ModelEntry(k, dkl, ht(100), ttl_ms=10_000_000)]
        minor = run_kernel(entries, CUTOFF, is_major=False)
        assert [(e.dht.ht.physical_micros, mk) for e, mk in minor] == [(100, False)]


class TestRandomizedDifferential:
    @pytest.mark.parametrize("seed", range(8))
    @pytest.mark.parametrize("is_major", [False, True])
    def test_random_histories(self, seed, is_major):
        rng = random.Random(seed)
        entries = []
        wid = 0
        for _ in range(rng.randint(50, 250)):
            row = rng.randint(0, 10)
            col = rng.choice([None, 0, 1, 2])
            key, dkl = mk_key(row, col)
            t = rng.randint(1, 2000)
            kind = rng.random()
            entries.append(ModelEntry(
                key, dkl, ht(t, wid % 5),
                is_tombstone=kind < 0.15,
                is_object_init=(col is None and 0.15 <= kind < 0.25),
                ttl_ms=rng.choice([None, None, None, 0, 100, 10**9])))
            wid += 1
        # de-dup exact (key, dht) collisions — invalid in a real DB
        seen = set()
        uniq = []
        for e in entries:
            k = (e.key, e.dht)
            if k not in seen:
                seen.add(k)
                uniq.append(e)
        check_match(uniq, CUTOFF, is_major)

    def test_multi_run_merge_matches(self):
        """Entries split across several 'SSTs' merge to the same result."""
        rng = random.Random(99)
        entries = []
        for i in range(100):
            key, dkl = mk_key(rng.randint(0, 5), rng.choice([None, 0, 1]))
            entries.append(ModelEntry(key, dkl, ht(rng.randint(1, 1500), i % 7),
                                      is_tombstone=rng.random() < 0.2))
        seen = set()
        uniq = [e for e in entries
                if (e.key, e.dht) not in seen and not seen.add((e.key, e.dht))]
        check_match(uniq, CUTOFF, is_major=False)
