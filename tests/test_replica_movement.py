"""Remote bootstrap + membership change + load-balancer repair
(ref: integration-tests/remote_bootstrap-itest, ts_tablet_manager-itest;
cluster_balance.cc behavior)."""

import time

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


def wait_for(cond, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timeout: {msg}"
        time.sleep(0.05)


@pytest.fixture
def cluster(tmp_path):
    flags.set_flag("replication_factor", 3)
    flags.set_flag("load_balancer_dead_grace_ms", 1200)
    flags.set_flag("tserver_unresponsive_timeout_ms", 1500)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path / "cluster"))).start()
    yield c
    flags.reset_flag("load_balancer_dead_grace_ms")
    flags.reset_flag("tserver_unresponsive_timeout_ms")
    c.shutdown()


def test_manual_remote_bootstrap_and_config_change(cluster):
    client = cluster.new_client()
    client.create_namespace("db")
    table = client.create_table("db", "t", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    cluster.wait_for_table_leaders("db", "t")  # don't race the election
    for i in range(30):
        client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk(f"k{i}"),
                                       {"v": f"v{i}"})])
    # flush so the snapshot carries SSTs, not just WAL
    tablet = client.meta_cache.tablets(table.table_id)[0]
    leader_ts = next(ts for ts in cluster.tservers
                     if ts.server_id == tablet.leader)
    leader_ts.tablet_manager.get_tablet(tablet.tablet_id).tablet.flush()

    ts3 = cluster.add_tablet_server()
    wait_for(lambda: any(t["server_id"] == "ts3"
                         for t in client.list_tservers()), msg="ts3 joins")
    m = cluster.masters[0].messenger
    m.call(ts3.address, "tserver", "start_remote_bootstrap",
           tablet_id=tablet.tablet_id, source_addr=leader_ts.address)
    assert tablet.tablet_id in ts3.tablet_manager.tablet_ids()
    # Snapshot data landed in the new replica's LSM (reads via MVCC need
    # leader contact, which only starts once it joins the config below).
    peer3 = ts3.tablet_manager.get_tablet(tablet.tablet_id)
    assert sum(1 for _ in peer3.tablet.regular_db.iter_from(b"")) > 0
    # Promote to voter, then drop one old replica => still RF3.
    m.call(leader_ts.address, "tserver", "change_config",
           tablet_id=tablet.tablet_id, add=["ts3"])
    victim = next(r.server_id for r in tablet.replicas
                  if r.server_id != tablet.leader)
    m.call(leader_ts.address, "tserver", "change_config",
           tablet_id=tablet.tablet_id, remove=[victim])
    cfg = leader_ts.tablet_manager.get_tablet(
        tablet.tablet_id).raft.config.peer_ids
    servers = sorted(p.split("/", 1)[0] for p in cfg)
    assert "ts3" in servers and victim not in servers and len(servers) == 3
    # New voter participates: writes still commit and reach ts3.
    client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk("after-move"),
                                   {"v": "yes"})])
    wait_for(lambda: peer3.tablet.read_row(dk("after-move")) is not None,
             msg="replicated to ts3")


def test_load_balancer_repairs_dead_tserver(cluster):
    client = cluster.new_client()
    client.create_namespace("db2")
    table = client.create_table("db2", "t", SCHEMA, num_tablets=2)
    cluster.wait_all_replicas_running(table.table_id)
    cluster.wait_for_table_leaders("db2", "t")  # don't race the election
    for i in range(20):
        client.write(table, [QLWriteOp(WriteOpKind.INSERT, dk(f"k{i}"),
                                       {"v": f"v{i}"})])
    # Spare server for the balancer to move onto.
    cluster.add_tablet_server()
    wait_for(lambda: any(t["server_id"] == "ts3"
                         for t in client.list_tservers()), msg="ts3 joins")
    victim = cluster.tservers[0]
    victim_id = victim.server_id
    victim.shutdown()

    def repaired():
        locs = cluster.leader_master().catalog.get_table_locations(
            table.table_id)
        return all(victim_id not in [r["server_id"] for r in l["replicas"]]
                   and len(l["replicas"]) == 3
                   for l in locs)

    wait_for(repaired, timeout=60, msg="balancer replaces dead replicas")
    # Data still fully readable after the move.
    for i in range(20):
        row = client.read_row(table, dk(f"k{i}"))
        assert row is not None and \
            row.columns[SCHEMA.column_id("v")] == f"v{i}"
