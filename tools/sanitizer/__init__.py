"""ybsan — happens-before race sanitizer for the yugabyte_tpu tree.

Arming (`YBSAN=1 pytest ...`, or `arm()` from a test) installs a
vector-clock happens-before detector behind the instrumentation shim
(yugabyte_tpu/utils/ybsan.py) and patches:

- threading.Thread start/join and queue.Queue put/get (HB edges);
- every class the `# guarded-by` annotation index names (shadow cells
  + lock-possession checks, auto-discovered with the lock-discipline
  pass's own collection logic);
- every `@ybsan.shadow` opt-in class (stated-discipline checks).

TrackedLock acquire/release and threadpool submit/execute report
through the shim from inside the package — no patching needed.

The armed gate: tests/conftest.py calls `session_gate()` at pytest
session finish; any race report whose fingerprint is not justified in
tools/analysis/baseline.txt fails the run. See README "Concurrency
sanitizer".
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

from tools.sanitizer.detector import Detector, RaceReport
from tools.sanitizer.instrument import Instrumenter
from yugabyte_tpu.utils import ybsan as _shim

# re-exported discipline vocabulary
SINGLE_WRITER = _shim.SINGLE_WRITER
SINGLE_WRITER_PER_KEY = _shim.SINGLE_WRITER_PER_KEY
PUBLISHER_CONSUMER = _shim.PUBLISHER_CONSUMER
shadow = _shim.shadow

_detector: Optional[Detector] = None
_instrumenter: Optional[Instrumenter] = None


class _Hooks:
    """The table installed into the shim: detector edges + shadow
    patching for classes decorated after arming."""

    def __init__(self, det: Detector, ins: Instrumenter) -> None:
        self.lock_acquired = det.lock_acquired
        self.lock_releasing = det.lock_releasing
        self.bind_task = det.bind_task
        self.patch_shadow = ins.patch_shadow


def armed() -> bool:
    return _detector is not None


def enabled() -> bool:
    return _shim.enabled()


def arm() -> Detector:
    """Idempotent: install the detector and apply every patch family."""
    global _detector, _instrumenter
    if _detector is not None:
        return _detector
    det = Detector()
    ins = Instrumenter(det)
    pre_registered = _shim.install(_Hooks(det, ins))
    ins.patch_globals()
    missed = ins.patch_annotated()
    for cls, spec in pre_registered:
        ins.patch_shadow(cls, spec)
    if missed:
        print("ybsan: arm() could not instrument: "
              + ", ".join(missed), file=sys.stderr)
    _detector, _instrumenter = det, ins
    return det


def disarm() -> None:
    global _detector, _instrumenter
    if _instrumenter is not None:
        _instrumenter.unpatch_all()
    _shim.install(None)
    _detector = _instrumenter = None


def detector() -> Optional[Detector]:
    return _detector


def reports() -> List[RaceReport]:
    return _detector.reports() if _detector is not None else []


def reset() -> None:
    if _detector is not None:
        _detector.reset()


def patch_class(cls: type, guards: Optional[Dict[str, str]] = None,
                shadow_spec: Optional[Dict[str, str]] = None) -> None:
    """Manual instrumentation for test fixtures (classes outside the
    yugabyte_tpu annotation index)."""
    if _instrumenter is None:
        raise RuntimeError("ybsan is not armed")
    _instrumenter.patch_class(cls, guards=guards, shadow=shadow_spec)


def session_gate(baseline_path: Optional[str] = None) -> List[str]:
    """The armed-run gate: returns human-readable failures — race
    reports not justified in the committed baseline (plus any detector
    internal errors). Empty list = race-clean."""
    from tools.analysis.core import DEFAULT_BASELINE
    from tools.sanitizer import report as _report
    if _detector is None:
        return []
    new, known = _report.split_reports(
        reports(), baseline_path or DEFAULT_BASELINE)
    if not new:
        return []
    return [_report.render_summary(new, known)]
