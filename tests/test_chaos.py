"""Network nemesis + chaos harness mechanics (PR: robustness).

Fast (tier-1) coverage of the fault fabric itself:

  - NemesisRules semantics: symmetric/one-way partitions, server-prefix
    matching, probabilistic drops, latency, duplicate + drop-response
    verdicts;
  - the messenger's nemesis hook end-to-end over real sockets (blocked
    link -> ServiceUnavailable, dropped request -> RpcTimeout, response
    drop executes the handler exactly once, duplicate executes twice);
  - the messenger's dropped-response metric (satellite: the silent
    `pass` at the caller-gone send is now counted and TRACE-routed);
  - LocalTransport parity over the shared rule engine;
  - NemesisController window over a live MiniCluster: leader partition,
    heal, convergence, term monotonicity, /compactionz device_faults
    block.

The multi-cycle crash/partition/device-fault soak is the slow-marked
tests/test_chaos_soak.py.
"""

import threading
import time

import pytest

from yugabyte_tpu.consensus.transport import LocalTransport, PeerUnreachable
from yugabyte_tpu.rpc import nemesis
from yugabyte_tpu.rpc.messenger import (Messenger, RpcTimeout,
                                        ServiceUnavailable)


@pytest.fixture(autouse=True)
def _nemesis_clean():
    nemesis.uninstall()
    yield
    nemesis.uninstall()


# ------------------------------------------------------------------ rules


def test_rules_symmetric_and_one_way_partition():
    r = nemesis.NemesisRules()
    r.partition("a", "b")
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("a", "b")
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("b", "a")
    r.heal()
    r.partition("a", "b", one_way=True)
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("a", "b")
    r.check_link("b", "a")  # reverse direction flows


def test_rules_server_prefix_matches_tablet_channels():
    r = nemesis.NemesisRules()
    r.partition("ts0", "ts1")
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("ts0/t1", "ts1/t1")
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("ts1/t9", "ts0/t9")
    r.check_link("ts0/t1", "ts2/t1")  # uninvolved server unaffected


def test_rules_isolate_and_endpoint_names():
    r = nemesis.NemesisRules()
    r.register_endpoint("127.0.0.1:1234", "ts0")
    r.isolate("ts0")
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("client", "127.0.0.1:1234")
    with pytest.raises(nemesis.LinkBlocked):
        r.check_link("127.0.0.1:1234", "ts1")


def test_rules_drop_probability_and_counts():
    r = nemesis.NemesisRules(seed=1)
    r.drop("a", "b", 1.0)
    with pytest.raises(nemesis.LinkDropped):
        r.check_link("a", "b")
    r.check_link("b", "a")  # direction-scoped
    assert r.injected_counts().get("dropped", 0) == 1


def test_rules_verdicts_and_latency():
    r = nemesis.NemesisRules()
    r.duplicate("a", "b", 1.0)
    r.drop("a", "b", 1.0, response=True)
    v = r.check_link("a", "b")
    assert v.duplicate and v.drop_response
    r.heal()
    r.latency("a", "b", 0.05)
    t0 = time.monotonic()
    v = r.check_link("a", "b")
    assert time.monotonic() - t0 >= 0.045
    assert not v.duplicate and not v.drop_response


# -------------------------------------------------------------- messenger


class _EchoService:
    def __init__(self):
        self.calls = 0
        self.release = threading.Event()
        self.release.set()

    def echo(self, x):
        self.calls += 1
        self.release.wait(timeout=5)
        return x


@pytest.fixture
def pair():
    server = Messenger("chaos-server")
    client = Messenger("chaos-client")
    svc = _EchoService()
    server.register_service("echo", svc)
    yield server, client, svc
    client.shutdown()
    server.shutdown()


def test_messenger_partition_and_heal(pair):
    server, client, svc = pair
    rules = nemesis.install()
    rules.register_endpoint(server.address, "srv")
    rules.register_endpoint("chaos-client", "cli")
    assert client.call(server.address, "echo", "echo", x=1) == 1
    rules.partition("cli", "srv")
    with pytest.raises(ServiceUnavailable):
        client.call(server.address, "echo", "echo", x=2)
    rules.heal()
    assert client.call(server.address, "echo", "echo", x=3) == 3


def test_messenger_drop_is_timeout_without_execution(pair):
    server, client, svc = pair
    rules = nemesis.install()
    rules.register_endpoint(server.address, "srv")
    rules.drop("chaos-client", "srv", 1.0)
    before = svc.calls
    with pytest.raises(RpcTimeout):
        client.call(server.address, "echo", "echo", x=1)
    assert svc.calls == before, "a dropped request must never execute"


def test_messenger_response_drop_executes_once(pair):
    server, client, svc = pair
    rules = nemesis.install()
    rules.register_endpoint(server.address, "srv")
    rules.drop("chaos-client", "srv", 1.0, response=True)
    before = svc.calls
    with pytest.raises(RpcTimeout):
        client.call(server.address, "echo", "echo", x=1)
    assert svc.calls == before + 1, \
        "response loss delivers + executes exactly once"


def test_messenger_duplicate_executes_twice(pair):
    server, client, svc = pair
    rules = nemesis.install()
    rules.register_endpoint(server.address, "srv")
    rules.duplicate("chaos-client", "srv", 1.0)
    before = svc.calls
    assert client.call(server.address, "echo", "echo", x=7) == 7
    assert svc.calls == before + 2, "duplicate delivery executes twice"


def test_messenger_counts_dropped_responses(pair):
    """Satellite: the caller-gone response drop is counted + traced, not
    silently passed. Driven against a closed socket directly — relying
    on real TCP teardown here races FIN-vs-RST timing (the first send
    into a dead peer can still land in the kernel buffer)."""
    import socket

    server, client, svc = pair
    a, b = socket.socketpair()
    b.close()
    a.close()  # the caller is gone before the handler responds
    before = server._responses_dropped.value()
    server._dispatch(a, threading.Lock(),
                     {"id": 1, "svc": "echo", "mth": "echo",
                      "args": {"x": 1}}, peer=None)
    assert svc.calls >= 1, "handler still executes"
    assert server._responses_dropped.value() == before + 1


# -------------------------------------------------------- local transport


class _FakePeer:
    def __init__(self):
        self.updates = 0
        self.votes = 0

    def handle_update(self, req):
        self.updates += 1
        return "ok"

    def handle_vote_request(self, req):
        self.votes += 1
        return "granted"


def test_local_transport_one_way_partition_and_duplicate():
    t = LocalTransport()
    a, b = _FakePeer(), _FakePeer()
    t.register("p0", a)
    t.register("p1", b)
    t.partition("p0", "p1", one_way=True)
    with pytest.raises(PeerUnreachable):
        t.update_consensus("p0", "p1", object())
    assert t.update_consensus("p1", "p0", object()) == "ok"
    t.heal()
    t.set_duplicate_probability("p0", "p1", 1.0)
    assert t.update_consensus("p0", "p1", object()) == "ok"
    assert b.updates == 2
    t.heal()
    t.set_drop_probability(1.0)
    with pytest.raises(PeerUnreachable):
        t.request_vote("p0", "p1", object())
    t.set_drop_probability(0.0)
    assert t.request_vote("p0", "p1", object()) == "granted"


def test_local_transport_unknown_fault_target_fails_loudly():
    t = LocalTransport()
    t.register("p0", _FakePeer())
    with pytest.raises(ValueError):
        t.partition("p0", "nope")
    with pytest.raises(ValueError):
        t.isolate("nope")
    with pytest.raises(ValueError):
        t.set_latency("nope", "p0", 0.1)


# ----------------------------------------------------------- mini cluster


def test_nemesis_controller_leader_partition_window(tmp_path):
    """A leader partition window over a live MiniCluster: a new leader
    emerges among the connected majority, writes keep working, terms
    stay monotonic, and after heal the cluster converges healthy."""
    from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
    from yugabyte_tpu.docdb.doc_key import DocKey
    from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
    from yugabyte_tpu.integration.chaos import NemesisController
    from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                       MiniClusterOptions)
    from yugabyte_tpu.utils import flags

    schema = Schema(columns=[ColumnSchema("k", DataType.STRING),
                             ColumnSchema("v", DataType.STRING)],
                    num_hash_key_columns=1)
    flags.set_flag("replication_factor", 3)
    cluster = MiniCluster(MiniClusterOptions(
        num_tservers=3, fs_root=str(tmp_path / "cluster"))).start()
    nem = NemesisController(cluster, seed=42)
    try:
        client = cluster.new_client()
        client.create_namespace("db")
        table = client.create_table("db", "t", schema, num_tablets=1)
        cluster.wait_all_replicas_running(table.table_id)
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        client.write(table, [QLWriteOp(WriteOpKind.INSERT,
                                       DocKey(hash_components=("k0",)),
                                       {"v": "before"})])
        terms_before = nem.capture_terms()

        old_leader = nem.partition_leader(tablet_id)
        # a new leader must emerge among the connected majority
        new_leader = cluster.wait_for_tablet_leader(
            tablet_id, timeout_s=30, exclude={old_leader})
        assert new_leader != old_leader
        client.write(table, [QLWriteOp(WriteOpKind.INSERT,
                                       DocKey(hash_components=("k1",)),
                                       {"v": "during"})])

        nem.heal()
        nem.wait_all_healthy(table.table_id, timeout_s=60)
        nem.check_terms_monotonic(terms_before, nem.capture_terms())
        for k, want in (("k0", "before"), ("k1", "during")):
            row = client.read_row(table, DocKey(hash_components=(k,)))
            assert row is not None and \
                row.columns[schema.column_id("v")] == want
        # /compactionz carries the device-fault containment block, and a
        # quarantined shape bucket is visible on it
        from yugabyte_tpu.storage.offload_policy import bucket_quarantine
        bucket_quarantine().quarantine((4, 65536), reason="chaos-test")
        try:
            page = cluster.tservers[0].compactionz()
            assert "device_faults" in page
            quarantined = page["device_faults"]["quarantined_buckets"]
            assert [e for e in quarantined
                    if e["bucket"] == [4, 65536]
                    and e["reason"] == "chaos-test"], quarantined
        finally:
            bucket_quarantine().clear()
    finally:
        nem.close()
        cluster.shutdown()
