"""Sustained-load correctness generator: linked-list chains under churn.

The reference proves durability under chaos with a linked-list workload
(ref: src/yb/integration-tests/linked_list-test.cc + the rate-paced
writers of src/yb/util/load_generator.h): writers append rows that chain
to their predecessor; after arbitrary failover/compaction/split churn, a
full verification walk proves that

  - every ACKED row is present (no lost writes),
  - every present row was actually sent (no phantom rows; writes whose
    ack was lost in a crash window count as "maybe" — the reference's
    OperationOutcomeUnknown bucket),
  - every row's chain predecessor exists (prefix durability: an acked
    row can never outlive the earlier row it links to).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.session import YBSession
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils.status import StatusError

LINKED_LIST_SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("prev", DataType.STRING),
             ColumnSchema("i", DataType.INT64)],
    num_hash_key_columns=1)


def chain_key(chain: int, idx: int) -> str:
    return f"c{chain:03d}-{idx:09d}"


@dataclass
class ChainState:
    chain: int
    next_idx: int = 0
    acked: int = 0                       # rows [0, acked) are guaranteed
    maybe: Set[int] = field(default_factory=set)   # ack lost in a crash


@dataclass
class LoadReport:
    written_acked: int
    written_maybe: int
    errors: int


class LinkedListLoadGenerator:
    """N writer threads, one chain each, paced to ops_per_sec total."""

    def __init__(self, client: YBClient, table, n_chains: int = 4,
                 ops_per_sec: float = 200.0):
        self._client = client
        self._table = table
        self._rate_per_chain = ops_per_sec / n_chains
        self.chains = [ChainState(c) for c in range(n_chains)]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.errors = 0

    # ------------------------------------------------------------- writers
    def _writer(self, st: ChainState) -> None:
        session = YBSession(self._client)
        period = 1.0 / self._rate_per_chain
        while not self._stop.is_set():
            t0 = time.monotonic()
            idx = st.next_idx
            prev = chain_key(st.chain, idx - 1) if idx else ""
            op = QLWriteOp(
                WriteOpKind.INSERT,
                DocKey(hash_components=(chain_key(st.chain, idx),)),
                {"prev": prev, "i": idx})
            try:
                session.apply(self._table, op)
                session.flush()
            except StatusError:
                # ack lost: the write may or may not have landed (a retry
                # may still commit it server-side) — the reference's
                # OperationOutcomeUnknown bucket
                st.maybe.add(idx)
                st.next_idx = idx + 1
                self.errors += 1
                time.sleep(0.2)
                continue
            st.acked = idx + 1
            st.next_idx = idx + 1
            elapsed = time.monotonic() - t0
            if elapsed < period:
                time.sleep(period - elapsed)

    def start(self) -> "LinkedListLoadGenerator":
        for st in self.chains:
            t = threading.Thread(target=self._writer, args=(st,),
                                 daemon=True, name=f"ll-writer-{st.chain}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> LoadReport:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        return LoadReport(
            written_acked=sum(s.acked - len([m for m in s.maybe
                                             if m < s.acked])
                              for s in self.chains),
            written_maybe=sum(len(s.maybe) for s in self.chains),
            errors=self.errors)

    # ------------------------------------------------------------ verifier
    def verify(self, client: Optional[YBClient] = None) -> Dict[str, int]:
        """Full-scan verification of the invariants; raises AssertionError
        with a precise message on any violation.  Returns counters."""
        client = client or self._client
        present: Dict[int, Set[int]] = {s.chain: set() for s in self.chains}
        for row in client.scan(self._table):
            d = row.to_dict(LINKED_LIST_SCHEMA)
            k = d["k"]
            chain = int(k[1:4])
            idx = int(k.split("-")[1])
            assert d["i"] == idx, f"row {k} carries wrong index {d['i']}"
            if idx:
                assert d["prev"] == chain_key(chain, idx - 1), \
                    f"row {k} links to {d['prev']!r}"
            present[chain].add(idx)
        lost: List[str] = []
        phantom: List[str] = []
        broken: List[str] = []
        for st in self.chains:
            have = present.get(st.chain, set())
            for idx in range(st.acked):
                if idx not in have and idx not in st.maybe:
                    lost.append(chain_key(st.chain, idx))
            sent_max = st.next_idx
            for idx in have:
                if idx >= sent_max:
                    phantom.append(chain_key(st.chain, idx))
            # prefix durability: a present row's predecessor must exist
            # unless that predecessor's ack was itself lost AND it truly
            # never landed — in which case the successor could only have
            # been written if the writer moved on (maybe bucket), fine;
            # but an ACKED predecessor must always exist (covered by
            # `lost` above). Here check presence-chain consistency:
            for idx in have:
                if idx and (idx - 1) not in have \
                        and (idx - 1) not in st.maybe:
                    broken.append(chain_key(st.chain, idx))
        assert not lost, f"LOST acked rows: {lost[:10]} (+{len(lost)-10 if len(lost)>10 else 0})"
        assert not phantom, f"PHANTOM rows never sent: {phantom[:10]}"
        assert not broken, f"BROKEN chains (missing predecessor): {broken[:10]}"
        return {"present": sum(len(v) for v in present.values()),
                "acked": sum(s.acked for s in self.chains),
                "maybe": sum(len(s.maybe) for s in self.chains)}


YCSB_SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


@dataclass
class YcsbReport:
    ops: int
    seconds: float
    ops_per_sec: float
    p50_ms: float
    p99_ms: float
    errors: int
    reads: int
    writes: int


class YcsbALoadGenerator:
    """Max-rate YCSB-A (50/50 read-update over a Zipf-ish hot set) —
    the reference's perf harness workload (ref: yb-perf v1.0.7 YCSB-A on
    a 3-node RF=3 cluster; src/yb/util/load_generator.h's multi-threaded
    session writers). Unpaced: each thread issues its next op as soon as
    the previous completes, so the measured rate IS the cluster's
    sustainable throughput at this concurrency. Per-op latencies are
    kept whole (ops counts are bounded by the run length) for exact
    percentiles."""

    def __init__(self, client: YBClient, table, n_threads: int = 8,
                 key_space: int = 10_000, value_bytes: int = 64):
        self._client = client
        self._table = table
        self._n_threads = n_threads
        self._key_space = key_space
        self._value = "v" * value_bytes
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lat_ms: List[List[float]] = []
        self._counts: List[List[int]] = []  # [ops, errors, reads, writes]
        self._t0 = 0.0
        self._t1 = 0.0

    def _worker(self, wid: int) -> None:
        import random
        rng = random.Random(1000 + wid)
        session = YBSession(self._client)
        lat = self._lat_ms[wid]
        cnt = self._counts[wid]
        while not self._stop.is_set():
            # hot-set skew: 80% of ops hit 20% of the key space
            if rng.random() < 0.8:
                kid = rng.randrange(max(1, self._key_space // 5))
            else:
                kid = rng.randrange(self._key_space)
            key = f"u{kid:08d}"
            t0 = time.monotonic()
            try:
                if rng.random() < 0.5:
                    session.apply(self._table, QLWriteOp(
                        WriteOpKind.INSERT,
                        DocKey(hash_components=(key,)),
                        {"v": self._value}))
                    session.flush()
                    cnt[3] += 1
                else:
                    self._client.read_row(self._table,
                                          DocKey(hash_components=(key,)))
                    cnt[2] += 1
                lat.append((time.monotonic() - t0) * 1000.0)
                cnt[0] += 1
            except StatusError:
                cnt[1] += 1
                time.sleep(0.05)

    def start(self) -> "YcsbALoadGenerator":
        self._t0 = time.monotonic()
        for i in range(self._n_threads):
            self._lat_ms.append([])
            self._counts.append([0, 0, 0, 0])
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True, name=f"ycsb-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> YcsbReport:
        # measurement window ends at stop-request time: a worker stuck in
        # stop-unaware client retry backoff would otherwise inflate the
        # denominator with an idle join tail and understate ops/s
        self._t1 = time.monotonic()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        lats = sorted(x for ls in self._lat_ms for x in ls)
        ops = sum(c[0] for c in self._counts)
        secs = self._t1 - self._t0

        def pct(p: float) -> float:
            """Nearest-rank percentile: ceil(p*n)-1, so p50 of two samples
            is the lower one (the naive int(p*n) index reports the MAX of
            two samples as the median)."""
            if not lats:
                return 0.0
            import math
            return lats[max(0, min(len(lats) - 1,
                                   math.ceil(p * len(lats)) - 1))]

        return YcsbReport(
            ops=ops, seconds=round(secs, 1),
            ops_per_sec=round(ops / secs, 1) if secs else 0.0,
            p50_ms=round(pct(0.50), 2), p99_ms=round(pct(0.99), 2),
            errors=sum(c[1] for c in self._counts),
            reads=sum(c[2] for c in self._counts),
            writes=sum(c[3] for c in self._counts))
