"""CatalogManager + TSManager: DDL, tablet placement, tserver liveness.

Capability parity with the reference (ref: src/yb/master/catalog_manager.h:141
— namespace/table/tablet lifecycle; ts_manager.h — TSDescriptor registry from
heartbeats; catalog_loaders.cc — in-memory state rebuilt from the sys catalog
on master failover; catalog_manager_bg_tasks.cc — background reconciliation
re-sending unacknowledged tablet-creation work).

All durable state lives in the SysCatalog; everything here is a cache keyed
off it, rebuilt by `ensure_loaded()` whenever this master (re)gains
sys-catalog leadership.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Set, Tuple

from yugabyte_tpu.common.partition import PartitionSchema
from yugabyte_tpu.common.wire import (
    partition_from_wire, partition_schema_from_wire, partition_to_wire,
    schema_from_wire, schema_to_wire)
from yugabyte_tpu.master.sys_catalog import SysCatalog
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.utils import lock_rank

flags.define_flag("tserver_unresponsive_timeout_ms", 3000,
                  "a tserver missing heartbeats this long is treated as dead "
                  "(ref tserver_unresponsive_timeout_ms)")
flags.define_flag("replication_factor", 3,
                  "default table replication factor (ref replication_factor)")
# extra MVCC history beyond a PITR schedule's interval, covering snapshot
# timing jitter + heartbeat propagation of the retention override
_SCHEDULE_RETENTION_SLACK_S = 60.0

# Same definition as tablet.py (define_flag is idempotent for identical
# defaults and raises loudly on drift): a master-only process needs the
# value for snapshot history floors without importing the tablet stack.
flags.define_flag(
    "timestamp_history_retention_interval_sec", 900,
    "how far back in time reads are repeatable; compaction keeps overwritten "
    "values younger than this (ref tablet_retention_policy.h:29)")


def _base_history_retention_s() -> float:
    return float(flags.get_flag("timestamp_history_retention_interval_sec"))


flags.define_flag("index_backfill_grace_ms", 500,
                  "wait between index creation and the backfill snapshot so "
                  "every writer observes the index in write mode first (the "
                  "reference waits for schema-version acks from all "
                  "tservers, ref backfill_index.cc WaitForSchemaVersion)")


class TSDescriptor:
    def __init__(self, server_id: str, addr: str):
        self.server_id = server_id
        self.addr = addr
        self.last_heartbeat = time.monotonic()
        self.num_tablets = 0
        self.reported_tablets: Set[str] = set()
        # replicas this server reports in FAILED state (background storage
        # error): the load balancer re-replicates them without waiting for
        # the whole server to go silent
        self.failed_tablets: Set[str] = set()
        # the corruption subset of failed_tablets (scrub / read-path CRC /
        # digest divergence): rebuilt IN PLACE from a healthy peer — the
        # server is fine, the replica's data is not
        self.corrupt_tablets: Set[str] = set()

    def alive(self) -> bool:
        timeout = flags.get_flag("tserver_unresponsive_timeout_ms") / 1000.0
        return time.monotonic() - self.last_heartbeat < timeout


class TSManager:
    """ref src/yb/master/ts_manager.h"""

    def __init__(self):
        self._descs: Dict[str, TSDescriptor] = {}
        self._lock = threading.Lock()

    def heartbeat(self, server_id: str, addr: str,
                  report: List[dict]) -> TSDescriptor:  # yblint: wire-pair(tablet_report, reads)
        with self._lock:
            desc = self._descs.get(server_id)
            if desc is None or desc.addr != addr:
                desc = TSDescriptor(server_id, addr)
                self._descs[server_id] = desc
            desc.last_heartbeat = time.monotonic()
            desc.num_tablets = len(report)
            desc.reported_tablets = {t["tablet_id"] for t in report}
            desc.failed_tablets = {t["tablet_id"] for t in report
                                   if t.get("state") == "FAILED"}
            desc.corrupt_tablets = {t["tablet_id"] for t in report
                                    if t.get("state") == "FAILED"
                                    and t.get("failed_corrupt")}
            return desc

    def live_descriptors(self) -> List[TSDescriptor]:
        with self._lock:
            return [d for d in self._descs.values() if d.alive()]

    def all_descriptors(self) -> List[TSDescriptor]:
        with self._lock:
            return list(self._descs.values())

    def addr_map(self) -> Dict[str, str]:
        with self._lock:
            return {sid: d.addr for sid, d in self._descs.items()}

    def get(self, server_id: str) -> Optional[TSDescriptor]:
        with self._lock:
            return self._descs.get(server_id)


class CatalogManager:
    def __init__(self, sys_catalog: SysCatalog, messenger):
        self.sys = sys_catalog
        self.messenger = messenger
        self.ts_manager = TSManager()
        self._lock = lock_rank.tracked(threading.RLock(),
                                       "catalog._lock")
        self._loaded_term = -1  # guarded-by: _lock
        self.namespaces: Dict[str, dict] = {}  # guarded-by: _lock
        self.tables: Dict[str, dict] = {}  # guarded-by: _lock
        self.tablets: Dict[str, dict] = {}  # guarded-by: _lock
        self.sequences: Dict[str, dict] = {}  # "ns.name" -> {next, ...}
        self.views: Dict[str, dict] = {}      # "ns.name" -> {sql, ...}
        # volatile: tablet_id -> (leader server_id, term); replica acks
        self.tablet_leaders: Dict[str, Tuple[str, int]] = {}  # guarded-by: _lock
        self._confirmed: Set[Tuple[str, str]] = set()  # (tablet_id, server)
        # volatile: authoritative Raft config index per tablet (from leader
        # reports); used to recognize evicted stale replicas.
        self._config_indexes: Dict[str, int] = {}
        # memoized table_id -> required history retention (PITR schedules);
        # None = rebuild on next heartbeat (see _history_retention_for)
        self._retention_by_table: Optional[Dict[str, float]] = None

    # ------------------------------------------------------------ leadership
    def is_leader(self) -> bool:
        return (self.sys.peer.raft.is_leader()
                and self.sys.peer.raft.leader_ready())

    def ensure_loaded(self) -> None:
        """Rebuild caches from the sys catalog after (re)gaining leadership
        (ref catalog_loaders.cc)."""
        term = self.sys.peer.raft.current_term
        with self._lock:
            if self._loaded_term == term:
                return
            namespaces: Dict[str, dict] = {}
            tables: Dict[str, dict] = {}
            tablets: Dict[str, dict] = {}
            sequences: Dict[str, dict] = {}
            views: Dict[str, dict] = {}
            for etype, eid, meta in self.sys.scan_all():
                if etype == "namespace":
                    namespaces[eid] = meta
                elif etype == "table":
                    tables[eid] = meta
                elif etype == "tablet":
                    tablets[eid] = meta
                elif etype == "sequence":
                    sequences[eid] = meta
                elif etype == "view":
                    views[eid] = meta
            self.namespaces = namespaces
            self.tables = tables
            self.tablets = tablets
            self.sequences = sequences
            self.views = views
            self._confirmed.clear()
            self._replication_cache = None
            self._loaded_term = term
            TRACE("catalog loaded at term %d: %d namespaces, %d tables, "
                  "%d tablets", term, len(namespaces), len(tables),
                  len(tablets))

    # ------------------------------------------------------------------- DDL
    def create_namespace(self, name: str) -> None:
        with self._lock:
            if name in self.namespaces:
                raise StatusError(Status.AlreadyPresent(
                    f"namespace {name!r} exists"))
            meta = {"name": name}
            self.sys.upsert("namespace", name, meta)
            self.namespaces[name] = meta

    def list_namespaces(self) -> List[str]:
        with self._lock:
            return sorted(self.namespaces)

    # ------------------------------------------------------------ sequences
    # PG sequences (ref: src/postgres/src/backend/commands/sequence.c;
    # YSQL routes them through the master-side sequences table,
    # src/yb/yql/pggate pg_sequence_cache). Allocation persists through
    # the sys catalog BEFORE returning, so a master restart never hands
    # out a duplicate block.
    def create_sequence(self, namespace: str, name: str, start: int = 1,
                        if_not_exists: bool = False) -> None:
        key = f"{namespace}.{name}"
        with self._lock:
            if key in self.sequences:
                if if_not_exists:
                    return
                raise StatusError(Status.AlreadyPresent(
                    f"sequence {name!r} exists"))
            meta = {"namespace": namespace, "name": name,
                    "next": int(start)}
            self.sys.upsert("sequence", key, meta)
            self.sequences[key] = meta

    def drop_sequence(self, namespace: str, name: str,
                      if_exists: bool = False) -> None:
        key = f"{namespace}.{name}"
        with self._lock:
            if key not in self.sequences:
                if if_exists:
                    return
                raise StatusError(Status.NotFound(
                    f"sequence {name!r} does not exist"))
            self.sys.delete("sequence", key)
            del self.sequences[key]

    def sequence_next(self, namespace: str, name: str,
                      cache: int = 1) -> int:
        """Allocate [returned, returned+cache) and persist the advance."""
        key = f"{namespace}.{name}"
        cache = max(1, int(cache))
        with self._lock:
            meta = self.sequences.get(key)
            if meta is None:
                raise StatusError(Status.NotFound(
                    f"sequence {name!r} does not exist"))
            val = int(meta["next"])
            meta = dict(meta, next=val + cache)
            self.sys.upsert("sequence", key, meta)
            self.sequences[key] = meta
            return val

    # --------------------------------------------------------------- views
    # PG views stored as the defining SELECT text in the sys catalog
    # (ref: PG pg_rewrite / DefineView; YSQL keeps view defs in the
    # postgres catalog replicated through the master's sys catalog).
    def create_view(self, namespace: str, name: str, sql: str,
                    or_replace: bool = False) -> None:
        key = f"{namespace}.{name}"
        with self._lock:
            if key in self.views and not or_replace:
                raise StatusError(Status.AlreadyPresent(
                    f"view {name!r} exists"))
            if self._find_table(namespace, name) is not None:
                raise StatusError(Status.AlreadyPresent(
                    f"{name!r} is a table"))
            meta = {"namespace": namespace, "name": name, "sql": sql}
            self.sys.upsert("view", key, meta)
            self.views[key] = meta

    def drop_view(self, namespace: str, name: str,
                  if_exists: bool = False) -> None:
        key = f"{namespace}.{name}"
        with self._lock:
            if key not in self.views:
                if if_exists:
                    return
                raise StatusError(Status.NotFound(
                    f"view {name!r} does not exist"))
            self.sys.delete("view", key)
            del self.views[key]

    def get_view(self, namespace: str, name: str) -> Optional[str]:
        with self._lock:
            meta = self.views.get(f"{namespace}.{name}")
            return None if meta is None else meta["sql"]

    def list_views(self, namespace: str) -> List[dict]:
        """[{name, sql}] in name order — one call serves catalog queries
        (pg_views) without per-view lookups."""
        with self._lock:
            return sorted(({"name": m["name"], "sql": m["sql"]}
                           for m in self.views.values()
                           if m["namespace"] == namespace),
                          key=lambda m: m["name"])

    def _find_table(self, namespace: str, name: str) -> Optional[str]:
        with self._lock:
            for tid, t in self.tables.items():
                if t["namespace"] == namespace and t["name"] == name:
                    return tid
        return None

    def create_table(self, namespace: str, name: str, schema_wire: dict,
                     partition_schema_wire: dict, num_tablets: int,
                     replication_factor: Optional[int] = None) -> dict:
        rf = replication_factor or flags.get_flag("replication_factor")
        with self._lock:
            if namespace not in self.namespaces:
                raise StatusError(Status.NotFound(
                    f"namespace {namespace!r} not found"))
            if f"{namespace}.{name}" in self.views:
                raise StatusError(Status.AlreadyPresent(
                    f"{name!r} is a view"))
            if self._find_table(namespace, name) is not None:
                raise StatusError(Status.AlreadyPresent(
                    f"table {namespace}.{name} exists"))
            live = self.ts_manager.live_descriptors()
            if len(live) < rf:
                raise StatusError(Status.ServiceUnavailable(
                    f"need {rf} live tservers for RF={rf}, have {len(live)}"))
            table_id = uuid.uuid4().hex[:16]
            ps = partition_schema_from_wire(partition_schema_wire)
            partitions = ps.create_partitions(num_tablets)
            tablet_metas: List[dict] = []
            for i, part in enumerate(partitions):
                tablet_id = f"{table_id}.t{i:04d}"
                # Reuse the snapshot validated above — re-listing here could
                # see fewer than rf live tservers (TOCTOU).
                replicas = self._pick_replicas(live, rf, seed_index=i)
                tablet_metas.append({
                    "tablet_id": tablet_id, "table_id": table_id,
                    "partition": partition_to_wire(part),
                    "hash_partitioning": ps.hash_partitioning,
                    "replicas": replicas})
            table_meta = {
                "table_id": table_id, "name": name, "namespace": namespace,
                "schema": schema_wire,
                "partition_schema": partition_schema_wire,
                "tablet_ids": [t["tablet_id"] for t in tablet_metas]}
            # Persist FIRST so a crash never leaves orphan replicas the
            # heartbeat cleanup would misread as live state (see
            # tablets_to_delete below); replica creation is re-driven by the
            # reconciler until every ack lands.
            self.sys.upsert("table", table_id, table_meta)
            for tm in tablet_metas:
                self.sys.upsert("tablet", tm["tablet_id"], tm)
            self.tables[table_id] = table_meta
            for tm in tablet_metas:
                self.tablets[tm["tablet_id"]] = tm
        self.reconcile_tablets()
        return table_meta

    def _pick_replicas(self, live: List[TSDescriptor], rf: int,
                       seed_index: int) -> List[str]:
        """Least-loaded placement over live tservers (ref
        CatalogManager::SelectReplicasForTablet round-robin by load)."""
        live = sorted(live, key=lambda d: (d.num_tablets, d.server_id))
        picked = [live[(seed_index + j) % len(live)] for j in range(rf)]
        # rotation can alias on small clusters; dedup preserving order
        seen, out = set(), []
        for d in picked:
            if d.server_id not in seen:
                seen.add(d.server_id)
                out.append(d)
        for d in live:
            if len(out) >= rf:
                break
            if d.server_id not in seen:
                seen.add(d.server_id)
                out.append(d)
        for d in out:
            d.num_tablets += 1  # keeps subsequent picks spreading
        return [d.server_id for d in out]

    # ---------------------------------------------------------------- alter
    def alter_table(self, namespace: str, name: str,
                    add_columns: Sequence[Tuple[str, str]] = (),
                    drop_columns: Sequence[str] = ()) -> dict:
        """Online ALTER TABLE ADD/DROP COLUMN (ref CatalogManager::
        AlterTable + async AlterTable tasks, catalog_manager.cc): the new
        schema persists with a bumped version, then propagates to every
        hosted replica — directly here for latency, and via heartbeat
        reconciliation for replicas that miss the push (see
        process_heartbeat schema piggyback). ADD appends a slot (ids
        stable, no data rewrite); DROP tombstones the slot in place."""
        from yugabyte_tpu.common.schema import DataType
        with self._lock:
            # read-modify-write under the catalog lock: concurrent ALTERs
            # must serialize or one silently loses its column AND collides
            # on schema_version (tservers already at the winning version
            # would never be repaired by heartbeat reconciliation)
            table = next((t for t in self.tables.values()
                          if t["namespace"] == namespace
                          and t["name"] == name), None)
            if table is None:
                raise StatusError(Status.NotFound(
                    f"table {namespace}.{name}"))
            schema = schema_from_wire(table["schema"])
            try:
                for col, type_name in add_columns:
                    schema = schema.with_added_column(col,
                                                      DataType(type_name))
                for col in drop_columns:
                    schema = schema.with_dropped_column(col)
            except (ValueError, KeyError) as e:
                raise StatusError(Status.InvalidArgument(str(e))) from e
            version = table.get("schema_version", 0) + 1
            table = dict(table, schema=schema_to_wire(schema),
                         schema_version=version)
            self.sys.upsert("table", table["table_id"], table)
            self.tables[table["table_id"]] = table
            tablet_ids = [t for t in table["tablet_ids"]
                          if t in self.tablets]
            targets = [(t, s) for t in tablet_ids
                       for s in self.tablets[t]["replicas"]]
        addr_map = self.ts_manager.addr_map()

        def push():
            # fire-and-forget latency optimization (the reference's async
            # AlterTable tasks); heartbeat reconciliation is the guarantee
            for tablet_id, server_id in targets:
                addr = addr_map.get(server_id)
                if addr is None:
                    continue
                try:
                    self.messenger.call(addr, "tserver",
                                        "alter_tablet_schema",
                                        timeout_s=2.0, tablet_id=tablet_id,
                                        schema=table["schema"],
                                        version=version)
                except StatusError:
                    pass
        threading.Thread(target=push, daemon=True,
                         name="alter-push").start()
        return table

    def _schema_updates_for(self, report: List[dict]) -> List[dict]:  # yblint: wire-pair(tablet_report, reads)
        """Heartbeat piggyback: alter orders for reported tablets whose
        schema version lags the catalog's (the reconciliation half of
        alter_table — a replica that missed the direct push, or was
        bootstrapped from an old snapshot, converges here)."""
        out = []
        with self._lock:
            for t in report:
                tm = self.tablets.get(t.get("tablet_id"))
                if tm is None:
                    continue
                table = self.tables.get(tm["table_id"])
                if table is None:
                    continue
                want = table.get("schema_version", 0)
                if t.get("schema_version", 0) < want:
                    out.append({"tablet_id": t["tablet_id"],
                                "schema": table["schema"],
                                "version": want})
        return out

    # --------------------------------------------------------------- indexes
    def create_index(self, namespace: str, table_name: str, index_name: str,
                     column, num_tablets: int = 2) -> dict:
        """CREATE INDEX: create the index table, attach IndexInfo to the
        indexed table (write-and-delete mode), wait out the schema
        propagation grace, run the tablet-side backfill, then flip the
        index readable (ref: src/yb/master/backfill_index.cc
        MultiStageAlterTable + BackfillTable state machine, compressed to
        WRITE_AND_DELETE -> backfill -> READABLE)."""
        from yugabyte_tpu.common.index import (
            STATE_BACKFILLING, STATE_READABLE, IndexInfo,
            index_table_schema)
        from yugabyte_tpu.common.schema import Schema
        from yugabyte_tpu.common.wire import schema_from_wire, schema_to_wire

        with self._lock:
            table_id = self._find_table(namespace, table_name)
            if table_id is None:
                raise StatusError(Status.NotFound(
                    f"table {namespace}.{table_name} not found"))
            table_meta = self.tables[table_id]
            for w in table_meta.get("indexes", []):
                if w["index_name"] == index_name:
                    raise StatusError(Status.AlreadyPresent(
                        f"index {index_name!r} exists"))
            main_schema = schema_from_wire(table_meta["schema"])
        columns = [column] if isinstance(column, str) else list(column)
        try:
            idx_schema = index_table_schema(main_schema, columns)
        except (ValueError, KeyError) as e:
            raise StatusError(Status.InvalidArgument(str(e)))
        idx_meta = self.create_table(
            namespace, index_name, schema_to_wire(idx_schema),
            {"hash_partitioning": True}, num_tablets)
        info = IndexInfo(index_name, idx_meta["table_id"],
                         tuple(columns), STATE_BACKFILLING)
        self._set_index_state(table_id, info)
        # Schema propagation grace: every writer must observe the index in
        # write mode before the backfill snapshot is taken, or a write
        # racing the backfill scan would leave the index missing its entry
        # (the reference waits for all tservers to ack the schema version;
        # our clients refresh table metadata on a TTL instead). The grace
        # must comfortably exceed that TTL — a handle cached just before
        # the index persisted stays stale for a full TTL.
        grace_ms = max(flags.get_flag("index_backfill_grace_ms"),
                       3 * flags.get_flag("table_cache_ttl_ms"))
        time.sleep(grace_ms / 1000.0)
        try:
            self._backfill_index(namespace, table_id, info)
        except BaseException:
            # failure-atomic DDL: detach the half-built index and drop its
            # table so CREATE INDEX can be retried (a permanently
            # 'backfilling' index would tax every DML and serve no reads)
            with self._lock:
                table = dict(self.tables[table_id])
                table["indexes"] = [w for w in table.get("indexes", [])
                                    if w["index_name"] != index_name]
                self.sys.upsert("table", table_id, table)
                self.tables[table_id] = table
            try:
                self.delete_table(namespace, index_name)
            except StatusError:
                pass
            raise
        info.state = STATE_READABLE
        self._set_index_state(table_id, info)
        return info.to_wire()

    def _set_index_state(self, table_id: str, info) -> None:
        with self._lock:
            table = dict(self.tables[table_id])
            idxs = [w for w in table.get("indexes", [])
                    if w["index_name"] != info.index_name]
            idxs.append(info.to_wire())
            table["indexes"] = idxs
            self.sys.upsert("table", table_id, table)
            self.tables[table_id] = table

    def _backfill_index(self, namespace: str, table_id: str, info) -> None:
        """Drive one backfill_index_tablet RPC per main-table tablet (ref
        backfill_index.cc BackfillChunk; the tserver scans its local tablet
        at a snapshot and writes index entries at that read time)."""
        with self._lock:
            tablet_ids = [t for t in self.tables[table_id]["tablet_ids"]
                          if t in self.tablets
                          and len(self._split_children_in_catalog(t)) != 2]
        deadline = time.monotonic() + 60.0
        for tablet_id in tablet_ids:
            while True:
                # leaders arrive via heartbeats; a freshly created table's
                # tablets may still be electing — wait, don't abort
                addr_map = self.ts_manager.addr_map()
                with self._lock:
                    leader = self.tablet_leaders.get(tablet_id)
                addr = addr_map.get(leader[0]) if leader else None
                if addr is not None:
                    try:
                        self.messenger.call(
                            addr, "tserver", "backfill_index_tablet",
                            timeout_s=300.0, tablet_id=tablet_id,
                            namespace=namespace,
                            index_table=info.index_name,
                            column=list(info.columns))
                        break
                    except StatusError as e:
                        if time.monotonic() > deadline:
                            raise
                        TRACE("index backfill of %s retrying: %s",
                              tablet_id, e)
                elif time.monotonic() > deadline:
                    raise StatusError(Status.ServiceUnavailable(
                        f"no leader for {tablet_id}; index backfill "
                        f"aborted"))
                time.sleep(0.1)

    def delete_table(self, namespace: str, name: str) -> None:
        with self._lock:
            table_id = self._find_table(namespace, name)
            if table_id is None:
                raise StatusError(Status.NotFound(
                    f"table {namespace}.{name} not found"))
            meta = self.tables[table_id]
            for tablet_id in meta["tablet_ids"]:
                self.sys.delete("tablet", tablet_id)
                self.tablets.pop(tablet_id, None)
                self.tablet_leaders.pop(tablet_id, None)
            self.sys.delete("table", table_id)
            self.tables.pop(table_id, None)
        # Actual replica teardown rides the next heartbeat response
        # (tablets_to_delete), mirroring the reference's deferred deletes.

    # --------------------------------------------------------------- lookups
    def get_table(self, namespace: str, name: str) -> dict:
        with self._lock:
            table_id = self._find_table(namespace, name)
            if table_id is None:
                raise StatusError(Status.NotFound(
                    f"table {namespace}.{name} not found"))
            return dict(self.tables[table_id])

    def list_tables(self, namespace: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [dict(t) for t in self.tables.values()
                    if namespace is None or t["namespace"] == namespace]

    def balancer_snapshot(self) -> Tuple[Dict[str, dict],
                                         Dict[str, tuple]]:
        """Locked (tablets, tablet_leaders) shallow snapshot for the load
        balancer's read-only scan — it runs off the heartbeat threads and
        must not iterate the live guarded dicts bare."""
        with self._lock:
            return ({tid: dict(tm) for tid, tm in self.tablets.items()},
                    dict(self.tablet_leaders))

    def tablet_replicas(self, tablet_id: str) -> List[str]:
        with self._lock:
            return list(self.tablets[tablet_id]["replicas"])

    def has_tablet(self, tablet_id: str) -> bool:
        with self._lock:
            return tablet_id in self.tablets

    def get_table_locations(self, table_id: str) -> List[dict]:
        addr_map = self.ts_manager.addr_map()
        with self._lock:
            table = self.tables.get(table_id)
            if table is None:
                raise StatusError(Status.NotFound(f"table {table_id}"))
            out = []
            for tablet_id in table["tablet_ids"]:
                if len(self._split_children_in_catalog(tablet_id)) == 2:
                    continue  # split parent: clients route to the children
                tm = self.tablets[tablet_id]
                leader = self.tablet_leaders.get(tablet_id)
                out.append({
                    "tablet_id": tablet_id,
                    "partition": tm["partition"],
                    "replicas": [{"server_id": s,
                                  "addr": addr_map.get(s)}
                                 for s in tm["replicas"]],
                    "leader": leader[0] if leader else None})
            out.sort(key=lambda t: t["partition"]["start"])
            return out

    # ------------------------------------------------------------ heartbeats
    def process_heartbeat(self, server_id: str, addr: str,
                          report: List[dict]) -> dict:  # yblint: wire-pair(tablet_report, reads)
        desc = self.ts_manager.heartbeat(server_id, addr, report)
        to_delete = []
        reported_ids = {t["tablet_id"] for t in report}
        with self._lock:
            # Confirmation tracks what the tserver REPORTS, not what was
            # ever acked: a wiped/re-provisioned tserver stops reporting a
            # tablet and the reconciler must re-drive its creation.
            self._confirmed = {(tid, sid) for (tid, sid) in self._confirmed
                               if sid != server_id or tid in reported_ids}
            for t in report:
                tablet_id = t["tablet_id"]
                if tablet_id not in self.tablets:
                    if t.get("split_parent") in self.tablets:
                        # ADOPT a freshly split child the tservers created
                        # (ref CatalogManager::RegisterNewTabletForSplit).
                        self._adopt_split_child_locked(t)
                    else:
                        # Not in the catalog => table dropped (or orphan of
                        # a failed create persisted-first): tear it down.
                        to_delete.append(tablet_id)
                        continue
                # Evicted stale replica (ref master-driven tombstoning of
                # not-in-config replicas): this server is not in the
                # tablet's replica set AND its config predates the
                # authoritative one — its data was moved elsewhere.
                auth_index = self._config_indexes.get(tablet_id)
                if (server_id not in self.tablets[tablet_id]["replicas"]
                        and auth_index is not None
                        and t.get("config_index", 0) < auth_index):
                    to_delete.append(tablet_id)
                    continue
                self._confirmed.add((tablet_id, server_id))
                if t["role"] == "leader" and t.get("leader_ready"):
                    cur = self.tablet_leaders.get(tablet_id)
                    if cur is None or t["term"] >= cur[1]:
                        self.tablet_leaders[tablet_id] = (server_id,
                                                          t["term"])
                        self._config_indexes[tablet_id] = max(
                            self._config_indexes.get(tablet_id, 0),
                            t.get("config_index", 0))
                        # The leader's ACTIVE consensus config is the truth
                        # for replica membership; the catalog follows it
                        # (a crash between ChangeConfig and catalog persist
                        # heals here).
                        reported = t.get("replica_servers")
                        if (reported and sorted(reported)
                                != sorted(self.tablets[tablet_id]
                                          ["replicas"])):
                            self._persist_tablet_replicas_locked(
                                tablet_id, list(reported))
        resp = {
            "addr_map": self.ts_manager.addr_map(),
            "tablets_to_delete": to_delete,
        }
        try:
            with self._lock:
                repl = self._replication_work_for(reported_ids)
            if repl:
                resp["replication"] = repl
        except Exception:  # noqa: BLE001 — must never fail heartbeats
            pass
        try:
            keys = self.universe_keys_provider()
            if keys:
                resp["universe_keys"] = keys
        except Exception:  # noqa: BLE001 — must never fail heartbeats
            pass
        try:
            # always present (possibly {}): the tserver resets tablets NOT
            # in the map to zero, so deleting a schedule releases the deep
            # retention instead of pinning it until restart
            resp["history_retention"] = self._history_retention_for(
                reported_ids)
        except Exception:  # noqa: BLE001 — must never fail heartbeats
            pass
        try:
            updates = self._schema_updates_for(report)
            if updates:
                resp["schema_updates"] = updates
        except Exception:  # noqa: BLE001 — must never fail heartbeats
            pass
        return resp

    def _history_retention_for(self, tablet_ids) -> dict:
        """Per-tablet minimum MVCC history retention implied by active PITR
        snapshot schedules: a restore target can be up to interval_s older
        than its covering snapshot, so tablets under a schedule must retain
        at least interval_s (+slack) of history or compaction collapses the
        versions the restore needs (ref tablet_retention_policy.cc
        AllowedHistoryCutoff fed by the snapshot coordinator).

        The per-table map is cached — heartbeats arrive ~1/s per tserver
        and must not pay a full sys-catalog scan each; schedule create/
        delete invalidates."""
        per_table = self._retention_by_table
        if per_table is None:
            per_table = {}
            for sched in self.list_snapshot_schedules():
                try:
                    table = self.get_table(sched["namespace"],
                                           sched["table"])
                except StatusError:
                    continue
                need = sched["interval_s"] + _SCHEDULE_RETENTION_SLACK_S
                tid = table["table_id"]
                per_table[tid] = max(per_table.get(tid, 0.0), need)
            self._retention_by_table = per_table
        if not per_table:
            return {}
        out = {}
        with self._lock:
            for tablet_id in tablet_ids:
                tm = self.tablets.get(tablet_id)
                if tm and tm["table_id"] in per_table:
                    out[tablet_id] = per_table[tm["table_id"]]
        return out

    def _adopt_split_child_locked(self, t: dict) -> None:  # yblint: wire-pair(tablet_report, reads)
        parent_id = t["split_parent"]
        parent_tm = self.tablets[parent_id]
        child_id = t["tablet_id"]
        tm = {"tablet_id": child_id, "table_id": t["table_id"],
              "partition": t["partition"],
              "hash_partitioning": parent_tm.get("hash_partitioning", True),
              "replicas": list(parent_tm["replicas"]),
              "split_parent": parent_id}
        self.sys.upsert("tablet", child_id, tm)
        self.tablets[child_id] = tm
        table = self.tables.get(t["table_id"])
        if table is not None and child_id not in table["tablet_ids"]:
            table = dict(table)
            table["tablet_ids"] = table["tablet_ids"] + [child_id]
            self.sys.upsert("table", table["table_id"], table)
            self.tables[table["table_id"]] = table
        TRACE("catalog: adopted split child %s of %s", child_id, parent_id)

    def _split_children_in_catalog(self, tablet_id: str) -> List[str]:
        with self._lock:
            return [c for c in (f"{tablet_id}.s0", f"{tablet_id}.s1")
                    if c in self.tablets]

    def retire_split_parents(self) -> int:
        """Drop split parents whose children are adopted and fully
        replicated; their hosts then tear the parent replicas down via the
        heartbeat to_delete path (ref deferred parent deletion in
        tablet_split_manager.cc)."""
        retired = 0
        with self._lock:
            for tablet_id, tm in list(self.tablets.items()):
                children = self._split_children_in_catalog(tablet_id)
                if len(children) != 2:
                    continue
                if not all((c, s) in self._confirmed
                           for c in children
                           for s in self.tablets[c]["replicas"]):
                    continue
                if not all(c in self.tablet_leaders for c in children):
                    continue
                table = self.tables.get(tm["table_id"])
                self.sys.delete("tablet", tablet_id)
                self.tablets.pop(tablet_id, None)
                self.tablet_leaders.pop(tablet_id, None)
                if table is not None and tablet_id in table["tablet_ids"]:
                    table = dict(table)
                    table["tablet_ids"] = [
                        x for x in table["tablet_ids"] if x != tablet_id]
                    self.sys.upsert("table", table["table_id"], table)
                    self.tables[table["table_id"]] = table
                retired += 1
                TRACE("catalog: retired split parent %s", tablet_id)
        return retired

    # ------------------------------------------------- encryption at rest
    # The key material itself lives OUTSIDE the data it encrypts (a
    # plaintext sidecar on the master, the stand-in for an external KMS —
    # ref ent/src/yb/master/universe_key_registry_service.cc sourcing keys
    # out-of-band): storing keys in the sys catalog would be circular on
    # restart. The Master owns the registry; this provider hook feeds the
    # heartbeat responses.
    universe_keys_provider = staticmethod(lambda: [])

    # ---------------------------------------------------- xCluster streams
    def setup_universe_replication(self, replication_id: str,
                                   source_master_addrs: List[str],
                                   tables: List[List[str]]) -> dict:
        """Register async replication from a source universe (ref:
        ent/src/yb/master/catalog_manager_ent.cc SetupUniverseReplication).
        tables: [src_namespace, src_table, dst_namespace, dst_table] rows;
        each target table's tablet leaders then run CDC pollers delivered
        via heartbeats. Partition counts must match — the pollers map
        source tablets by partition start."""
        entries = []
        for src_ns, src_table, dst_ns, dst_table in tables:
            with self._lock:
                dst_id = self._find_table(dst_ns, dst_table)
                if dst_id is None:
                    raise StatusError(Status.NotFound(
                        f"target table {dst_ns}.{dst_table} not found"))
                n_dst = len(self.tables[dst_id]["tablet_ids"])
            # validate against the SOURCE universe now: a tablet-count
            # mismatch would otherwise "succeed" and replicate nothing
            # (pollers match exact partition ranges)
            src_meta = None
            for addr in source_master_addrs:
                try:
                    src_meta = self.messenger.call(
                        addr, "master", "get_table", timeout_s=10.0,
                        namespace=src_ns, name=src_table)
                    break
                except StatusError as e:
                    if getattr(e, "extra", {}).get("not_leader"):
                        continue
                    raise StatusError(Status.InvalidArgument(
                        f"source table {src_ns}.{src_table}: "
                        f"{e.status.message}"))
            if src_meta is None:
                raise StatusError(Status.ServiceUnavailable(
                    "no reachable source master"))
            n_src = len(src_meta["tablet_ids"])
            if n_src != n_dst:
                raise StatusError(Status.InvalidArgument(
                    f"tablet count mismatch for {src_ns}.{src_table}: "
                    f"source {n_src} vs target {n_dst}"))
            entries.append({"src_namespace": src_ns,
                            "src_table": src_table,
                            "dst_table_id": dst_id,
                            "n_tablets": n_dst})
        meta = {"replication_id": replication_id,
                "source_master_addrs": list(source_master_addrs),
                "tables": entries, "checkpoints": {}}
        with self._lock:
            if self.sys.get("replication", replication_id) is not None:
                raise StatusError(Status.AlreadyPresent(
                    f"replication {replication_id!r} exists"))
            self.sys.upsert("replication", replication_id, meta)
            self._replication_cache = None
        return meta

    def delete_universe_replication(self, replication_id: str) -> None:
        with self._lock:
            self.sys.delete("replication", replication_id)
            self._replication_cache = None

    def _replications(self) -> List[dict]:
        """In-memory cache, invalidated by setup/delete/checkpoint writes
        — heartbeats (the hottest master path) must not scan the whole
        sys catalog when no replication is configured."""
        cache = getattr(self, "_replication_cache", None)
        if cache is None:
            cache = [m for t, _i, m in self.sys.scan_all()
                     if t == "replication"]
            self._replication_cache = cache
        return cache

    def update_replication_checkpoint(self, replication_id: str,
                                      tablet_id: str, index: int) -> None:
        with self._lock:
            meta = self.sys.get("replication", replication_id)
            if meta is None:
                return
            cp = meta.get("checkpoints", {})
            if cp.get(tablet_id, -1) >= index:
                return
            cp[tablet_id] = index
            meta["checkpoints"] = cp
            self.sys.upsert("replication", replication_id, meta)
            # update the heartbeat cache IN PLACE: invalidating here would
            # force a full sys-catalog rescan per checkpoint report
            cache = getattr(self, "_replication_cache", None)
            if cache is not None:
                for i, m in enumerate(cache):
                    if m.get("replication_id") == replication_id:
                        cache[i] = meta
                        break

    def _replication_work_for(self, reported_ids) -> List[dict]:
        """Heartbeat piggyback: poller specs for replicated target tablets
        this tserver reports (its leadership is checked tserver-side)."""
        out = []
        for meta in self._replications():
            for t in meta["tables"]:
                with self._lock:
                    table = self.tables.get(t["dst_table_id"])
                if table is None:
                    continue
                for tablet_id in table["tablet_ids"]:
                    if tablet_id not in reported_ids:
                        continue
                    out.append({
                        "replication_id": meta["replication_id"],
                        "tablet_id": tablet_id,
                        "source_master_addrs": meta["source_master_addrs"],
                        "src_namespace": t["src_namespace"],
                        "src_table": t["src_table"],
                        "checkpoint": meta.get("checkpoints", {}).get(
                            tablet_id, 0)})
        return out

    # ------------------------------------------------------------ snapshots
    def create_table_snapshot(self, namespace: str, name: str,
                              schedule_id: Optional[str] = None) -> dict:
        """Coordinate a consistent table snapshot: a raft-replicated
        snapshot barrier on every tablet (ref master SnapshotCoordinator,
        ent/src/yb/master/async_snapshot_tasks.cc); metadata persists in
        the sys catalog so restores survive master failover.

        snapshot_ht (master clock AFTER every barrier replicated) bounds
        the snapshot's coverage: all writes with HT <= any T <=
        snapshot_ht are contained — per tablet, a write with a smaller HT
        precedes the barrier in raft order — which is what PITR's
        restore-to-time selection relies on."""
        import time as _time
        table = self.get_table(namespace, name)
        snapshot_id = uuid.uuid4().hex[:16]
        # coverage bound sampled BEFORE the first barrier: a write with
        # HT <= this time precedes every barrier in per-tablet order, so
        # the snapshot provably contains all state up to snapshot_micros.
        # (Stamping after the barriers would claim coverage for writes
        # that landed between a tablet's barrier and the stamp — a PITR
        # restore would silently miss them.)
        snapshot_micros = int(_time.time() * 1e6)
        addr_map = self.ts_manager.addr_map()
        with self._lock:
            tablet_ids = [t for t in table["tablet_ids"]
                          if t in self.tablets]
            leaders = {t: self.tablet_leaders.get(t) for t in tablet_ids}
        for tablet_id in tablet_ids:
            leader = leaders.get(tablet_id)
            if leader is None or addr_map.get(leader[0]) is None:
                raise StatusError(Status.ServiceUnavailable(
                    f"no leader for {tablet_id}; snapshot aborted"))
            self.messenger.call(addr_map[leader[0]], "tserver",
                                "snapshot_tablet", timeout_s=60.0,
                                tablet_id=tablet_id,
                                snapshot_id=snapshot_id)
        # Guaranteed MVCC history floor inside this snapshot's files: the
        # base retention flag always applies; a schedule's deeper override
        # only counts for as long as the schedule has existed (the override
        # rides heartbeats, so versions older than the schedule may already
        # be compacted away).  Restores below the floor are rejected rather
        # than silently returning post-compaction state.
        effective_s = _base_history_retention_s()
        if schedule_id is not None:
            sched = self.sys.get("snapshot_schedule", schedule_id)
            if sched is not None:
                need = sched["interval_s"] + _SCHEDULE_RETENTION_SLACK_S
                age = max(0.0, _time.time()
                          - sched.get("created_unix", _time.time()))
                effective_s = max(effective_s,
                                  min(need, effective_s + age))
        meta = {"snapshot_id": snapshot_id, "namespace": namespace,
                "table": name, "table_id": table["table_id"],
                "schema": table["schema"],
                "partition_schema": table["partition_schema"],
                "tablet_ids": tablet_ids,
                "snapshot_micros": snapshot_micros,
                "history_floor_micros": int(snapshot_micros
                                            - effective_s * 1e6),
                "schedule_id": schedule_id}
        with self._lock:
            self.sys.upsert("snapshot", snapshot_id, meta)
        return meta

    # ----------------------------------------------- PITR snapshot schedules
    def create_snapshot_schedule(self, namespace: str, name: str,
                                 interval_s: float,
                                 retention_s: float) -> dict:
        """Periodic snapshots with retention — the PITR substrate (ref
        ent master SnapshotCoordinator schedules,
        master_snapshot_coordinator.cc). The master bg loop takes a
        snapshot every interval and prunes ones past retention; any time
        within retention is restorable (restore reads the earliest
        snapshot taken at-or-after the target time AT that time — MVCC
        history inside the snapshot files carries the exact state)."""
        self.get_table(namespace, name)   # validates existence
        sched = {"schedule_id": uuid.uuid4().hex[:16],
                 "namespace": namespace, "table": name,
                 "interval_s": float(interval_s),
                 "retention_s": float(retention_s),
                 "created_unix": time.time(),
                 "last_snapshot_unix": 0.0}
        with self._lock:
            self.sys.upsert("snapshot_schedule", sched["schedule_id"], sched)
        self._retention_by_table = None
        return sched

    def list_snapshot_schedules(self) -> List[dict]:
        return [m for t, _id, m in self.sys.scan_all()
                if t == "snapshot_schedule"]

    def delete_snapshot_schedule(self, schedule_id: str) -> None:
        # the schedule's snapshots go with it — with no schedule there is
        # no retention horizon left to ever prune them
        for snap in self.list_snapshots():
            if snap.get("schedule_id") == schedule_id:
                try:
                    self.delete_snapshot(snap["snapshot_id"])
                except StatusError:
                    pass
        with self._lock:
            self.sys.delete("snapshot_schedule", schedule_id)
        self._retention_by_table = None

    def run_snapshot_schedules(self) -> int:
        """One bg-loop tick: take due snapshots, prune expired ones.
        Returns snapshots taken."""
        import time as _time
        now = _time.time()
        taken = 0
        snapshots = self.list_snapshots()   # one catalog scan per tick
        for sched in self.list_snapshot_schedules():
            if now - sched["last_snapshot_unix"] >= sched["interval_s"]:
                try:
                    snapshots.append(self.create_table_snapshot(
                        sched["namespace"], sched["table"],
                        schedule_id=sched["schedule_id"]))
                    taken += 1
                    sched = dict(sched, last_snapshot_unix=now)
                    with self._lock:
                        # re-check under the lock: a concurrent
                        # delete_snapshot_schedule must not be undone by
                        # upserting our stale copy back
                        if self.sys.get("snapshot_schedule",
                                        sched["schedule_id"]) is not None:
                            self.sys.upsert("snapshot_schedule",
                                            sched["schedule_id"], sched)
                except StatusError:
                    pass  # table gone / no leader: retried next tick;
                    # retention pruning below must still run (a dropped
                    # table's expired snapshots would otherwise leak
                    # forever)
            horizon = (now - sched["retention_s"]) * 1e6
            for snap in snapshots:
                if snap.get("schedule_id") == sched["schedule_id"] and \
                        snap.get("snapshot_micros", 0) < horizon:
                    try:
                        self.delete_snapshot(snap["snapshot_id"])
                    except StatusError:
                        pass
        return taken

    def pick_restore_snapshot(self, namespace: str, name: str,
                              restore_micros: int) -> dict:
        """The PITR selection rule: the EARLIEST snapshot whose
        snapshot_micros >= the restore time contains the target state in
        its MVCC history (a snapshot taken before the target time lacks
        the writes between its barrier and the target)."""
        cands = [s for s in self.list_snapshots()
                 if s["namespace"] == namespace and s["table"] == name
                 and s.get("snapshot_micros", 0) >= restore_micros]
        if not cands:
            raise StatusError(Status.NotFound(
                f"no snapshot of {namespace}.{name} covers time "
                f"{restore_micros} — outside the retention window?"))
        best = min(cands, key=lambda s: s["snapshot_micros"])
        floor = best.get("history_floor_micros")
        if floor is not None and restore_micros < floor:
            raise StatusError(Status.InvalidArgument(
                f"restore time {restore_micros} predates snapshot "
                f"{best['snapshot_id']}'s guaranteed MVCC history floor "
                f"{floor}: compaction may have collapsed the needed "
                f"versions (raise timestamp_history_retention_interval_sec "
                f"or shorten the schedule interval)"))
        return best

    def list_snapshots(self) -> List[dict]:
        return [m for _t, _id, m in self.sys.scan_all()
                if _t == "snapshot"]

    def get_snapshot(self, snapshot_id: str) -> dict:
        meta = self.sys.get("snapshot", snapshot_id)
        if meta is None:
            raise StatusError(Status.NotFound(f"snapshot {snapshot_id}"))
        return meta

    def delete_snapshot(self, snapshot_id: str) -> None:
        meta = self.get_snapshot(snapshot_id)
        addr_map = self.ts_manager.addr_map()
        for tablet_id in meta["tablet_ids"]:
            for desc in self.ts_manager.all_descriptors():
                addr = addr_map.get(desc.server_id)
                if addr is None:
                    continue
                try:
                    self.messenger.call(addr, "tserver",
                                        "delete_tablet_snapshot",
                                        timeout_s=10.0,
                                        tablet_id=tablet_id,
                                        snapshot_id=snapshot_id)
                except StatusError:
                    pass  # replica gone / not hosting: fine
        with self._lock:
            self.sys.delete("snapshot", snapshot_id)

    def split_tablet(self, tablet_id: str) -> List[str]:
        """Drive a split through the tablet's leader (ref master
        TabletSplitManager)."""
        addr_map = self.ts_manager.addr_map()
        with self._lock:
            if tablet_id not in self.tablets:
                raise StatusError(Status.NotFound(f"tablet {tablet_id}"))
            leader = self.tablet_leaders.get(tablet_id)
        if leader is None or addr_map.get(leader[0]) is None:
            raise StatusError(Status.ServiceUnavailable(
                f"no known leader for {tablet_id}"))
        return self.messenger.call(addr_map[leader[0]], "tserver",
                                   "split_tablet", tablet_id=tablet_id)

    def _persist_tablet_replicas_locked(self, tablet_id: str,
                                        replicas: List[str]) -> None:
        tm = dict(self.tablets[tablet_id])
        tm["replicas"] = replicas
        self.sys.upsert("tablet", tablet_id, tm)
        self.tablets[tablet_id] = tm

    def update_tablet_replicas(self, tablet_id: str,
                               replicas: List[str]) -> None:
        with self._lock:
            if tablet_id in self.tablets:
                self._persist_tablet_replicas_locked(tablet_id, replicas)

    # -------------------------------------------------------- reconciliation
    def reconcile_tablets(self) -> int:
        """Issue (idempotent) create_tablet RPCs for replicas that have not
        yet reported the tablet (ref catalog_manager_bg_tasks.cc resending
        unacked CreateTablet work). Returns RPCs issued."""
        addr_map = self.ts_manager.addr_map()
        with self._lock:
            work = []
            for tablet_id, tm in self.tablets.items():
                table = self.tables.get(tm["table_id"])
                if table is None:
                    continue
                if tm.get("split_parent") in self.tablets:
                    # Split still propagating: every replica creates this
                    # child from its own parent snapshot when the SPLIT op
                    # applies — creating it empty here would diverge it.
                    continue
                # If live replicas already hold data, a missing one must be
                # REBUILT from them (remote bootstrap), not created empty —
                # an empty voter would need the whole log, which may be GC'd.
                leader = self.tablet_leaders.get(tablet_id)
                confirmed_any = any((tablet_id, s) in self._confirmed
                                    for s in tm["replicas"])
                source_addr = (addr_map.get(leader[0])
                               if confirmed_any and leader else None)
                for server_id in tm["replicas"]:
                    if (tablet_id, server_id) in self._confirmed:
                        continue
                    work.append((tablet_id, tm, table, server_id,
                                 source_addr))
        issued = [0]
        lock = threading.Lock()

        def send(tablet_id, tm, table, server_id, addr, source_addr):
            try:
                if source_addr is not None and source_addr != addr:
                    self.messenger.call(
                        addr, "tserver", "start_remote_bootstrap",
                        timeout_s=60.0, tablet_id=tablet_id,
                        source_addr=source_addr)
                else:
                    self.messenger.call(
                        addr, "tserver", "create_tablet", timeout_s=5.0,
                        tablet_id=tablet_id, table_id=tm["table_id"],
                        schema=table["schema"],
                        peer_server_ids=tm["replicas"],
                        partition=tm["partition"],
                        hash_partitioning=tm.get("hash_partitioning", True),
                        addr_map=addr_map)
                with lock:
                    issued[0] += 1
            except StatusError as e:
                TRACE("reconcile: create %s on %s failed: %s",
                      tablet_id, server_id, e)

        # Parallel fan-out: one blackholed tserver must not head-of-line
        # block creation on healthy ones (acks arrive via heartbeats, so a
        # straggler thread finishing late is harmless and idempotent).
        threads = []
        for tablet_id, tm, table, server_id, source_addr in work:
            addr = addr_map.get(server_id)
            if addr is None:
                continue
            t = threading.Thread(target=send, daemon=True,
                                 args=(tablet_id, tm, table, server_id,
                                       addr, source_addr))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=6.0)
        return issued[0]
