"""KV slabs: the TPU-native columnar representation of sorted-run entries.

This is the central TPU-first design decision of the storage engine
(SURVEY.md section 7 stage 4): instead of the reference's delta-encoded,
byte-granular SST entries (ref: src/yb/rocksdb/table/block_builder.cc), a
batch of KV entries is a structure-of-arrays "slab":

  key_words : uint32[N, W]  big-endian words of the key prefix (no HT suffix),
                            zero-padded to W*4 bytes. Because DocDB key
                            encoding is order-preserving bytewise
                            (docdb/doc_key.py), lexicographic order over
                            (key_words, key_len) == memcmp order over keys.
  key_len   : int32[N]      true byte length of the key prefix
  doc_key_len: int32[N]     byte length of the embedded DocKey (root prefix)
  ht_hi/ht_lo: uint32[N]    DocHybridTime.ht split into high/low words
  write_id  : uint32[N]
  flags     : uint32[N]     bit0 tombstone, bit1 object-init, bit2 has-TTL
  ttl_ms    : int64[N]      TTL in ms (0 = none)
  value_idx : int32[N]      index into the out-of-band value array

Values stay out-of-band (host memory / HBM byte buffer) because merge + GC
only permute and drop entries — value bytes move once, at output-write time.

Sorting a slab by (key_words..., key_len, ht_hi_desc, ht_lo_desc,
write_id_desc) reproduces exactly the reference's internal key order:
user key ascending, hybrid time descending (ref:
src/yb/rocksdb/db/dbformat.h internal key ordering + descending HT suffix,
common/doc_hybrid_time.cc:50).
"""

from __future__ import annotations

import struct

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.value import decode_control_fields
from yugabyte_tpu.docdb.value_type import ValueType

FLAG_TOMBSTONE = 1
FLAG_OBJECT_INIT = 2
FLAG_HAS_TTL = 4


@dataclass
class KVSlab:
    key_words: np.ndarray   # uint32 [N, W]
    key_len: np.ndarray     # int32  [N]
    doc_key_len: np.ndarray  # int32 [N]
    ht_hi: np.ndarray       # uint32 [N]
    ht_lo: np.ndarray       # uint32 [N]
    write_id: np.ndarray    # uint32 [N]
    flags: np.ndarray       # uint32 [N]
    ttl_ms: np.ndarray      # int64  [N]
    value_idx: np.ndarray   # int32  [N]
    values: List[bytes]     # out-of-band value payloads (indexed by value_idx)

    @property
    def n(self) -> int:
        return int(self.key_len.shape[0])

    @property
    def width_words(self) -> int:
        return int(self.key_words.shape[1])

    def key_bytes(self, i: int) -> bytes:
        return self.key_words[i].astype(">u4").tobytes()[: int(self.key_len[i])]

    def doc_ht(self, i: int) -> DocHybridTime:
        ht = (int(self.ht_hi[i]) << 32) | int(self.ht_lo[i])
        return DocHybridTime(HybridTime(ht), int(self.write_id[i]))


def _pad_keys_to_words(keys: Sequence[bytes], width_words: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized pack of variable-length key bytes into a zero-padded u32 word
    matrix. Avoids per-key Python in the inner loop (single-core host)."""
    n = len(keys)
    lens = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
    w = width_words if width_words is not None else max(1, int(-(-int(lens.max(initial=1)) // 4)))
    stride = w * 4
    if lens.max(initial=0) > stride:
        raise ValueError(f"key longer than slab stride {stride}")
    out = np.zeros((n, stride), dtype=np.uint8)
    flat = np.frombuffer(b"".join(keys), dtype=np.uint8)
    starts = np.concatenate(([0], np.cumsum(lens)))[:-1]  # works for n == 0 too
    # target flat positions: row*stride + offset-within-key
    within = np.arange(lens.sum(), dtype=np.int64) - np.repeat(starts, lens)
    rows = np.repeat(np.arange(n, dtype=np.int64), lens)
    out.reshape(-1)[rows * stride + within] = flat
    words = out.reshape(n, w, 4)
    words = (words[:, :, 0].astype(np.uint32) << 24) | (words[:, :, 1].astype(np.uint32) << 16) \
        | (words[:, :, 2].astype(np.uint32) << 8) | words[:, :, 3].astype(np.uint32)
    return words, lens.astype(np.int32)


def pack_kvs(entries: Sequence[Tuple[bytes, int, bytes]],
             doc_key_lens: Optional[Sequence[int]] = None,
             width_words: Optional[int] = None) -> KVSlab:
    """Build a slab from (key_prefix_bytes, packed_doc_ht, value_bytes) triples.

    packed_doc_ht = (ht.value << 32) | write_id as a 96-bit concept; we pass
    (ht_value, write_id) packed as a single int for convenience:
    int = ht_value * 2^32 + write_id.
    """
    n = len(entries)
    keys = [e[0] for e in entries]
    key_words, key_len = _pad_keys_to_words(keys, width_words)
    ht_hi = np.empty(n, dtype=np.uint32)
    ht_lo = np.empty(n, dtype=np.uint32)
    write_id = np.empty(n, dtype=np.uint32)
    flags = np.zeros(n, dtype=np.uint32)
    ttl_ms = np.zeros(n, dtype=np.int64)
    value_idx = np.arange(n, dtype=np.int32)
    values: List[bytes] = []
    for i, (_, packed, val) in enumerate(entries):
        wid = packed & 0xFFFFFFFF
        ht = packed >> 32
        ht_hi[i] = ht >> 32
        ht_lo[i] = ht & 0xFFFFFFFF
        write_id[i] = wid
        mf, ttl, off = decode_control_fields(val)
        tag = val[off]
        if tag == ValueType.kTombstone:
            flags[i] |= FLAG_TOMBSTONE
        elif tag == ValueType.kObject:
            flags[i] |= FLAG_OBJECT_INIT
        if ttl is not None:
            flags[i] |= FLAG_HAS_TTL
            ttl_ms[i] = ttl
        values.append(val)
    if doc_key_lens is None:
        dkl = np.array([_doc_key_len(k) for k in keys], dtype=np.int32)
    else:
        dkl = np.asarray(doc_key_lens, dtype=np.int32)
    return KVSlab(key_words, key_len, dkl, ht_hi, ht_lo, write_id, flags,
                  ttl_ms, value_idx, values)


def _doc_key_len(key_prefix: bytes) -> int:
    """Byte length of the DocKey portion (through the range-group kGroupEnd).

    Scans tag-structure: skips the hashed group's kGroupEnd if a hash prefix
    is present, then finds the range group's terminator. kGroupEnd bytes
    cannot appear inside components: every component encoding either escapes
    low bytes (strings escape only 0x00 — but '!' is 0x21; however string
    *content* can contain 0x21!). So we must parse, not scan.

    Keys that are NOT doc keys — intent reverse-index records and other
    system keys in the intents DB — count as one whole-key "document":
    they never share overwrite semantics with doc paths.
    """
    from yugabyte_tpu.docdb.doc_key import DocKey
    try:
        _, pos = DocKey.decode(key_prefix, 0)
    except (ValueError, IndexError, struct.error):
        return len(key_prefix)
    return pos


def pack_doc_ht(dht: DocHybridTime) -> int:
    return (dht.ht.value << 32) | dht.write_id


def unpack_keys(slab: KVSlab) -> List[bytes]:
    """Materialize key byte strings from a slab (host-side, for SST writing)."""
    raw = slab.key_words.astype(">u4").tobytes()
    stride = slab.width_words * 4
    return [raw[i * stride: i * stride + int(slab.key_len[i])] for i in range(slab.n)]


def concat_slabs(slabs: Sequence[KVSlab]) -> KVSlab:
    """Concatenate runs into one slab (inputs keep their own value arrays)."""
    w = max(s.width_words for s in slabs)
    parts_words = []
    value_offsets = []
    values: List[bytes] = []
    off = 0
    for s in slabs:
        kw = s.key_words
        if s.width_words < w:
            kw = np.pad(kw, ((0, 0), (0, w - s.width_words)))
        parts_words.append(kw)
        value_offsets.append(off)
        values.extend(s.values)
        off += len(s.values)
    return KVSlab(
        key_words=np.concatenate(parts_words, axis=0),
        key_len=np.concatenate([s.key_len for s in slabs]),
        doc_key_len=np.concatenate([s.doc_key_len for s in slabs]),
        ht_hi=np.concatenate([s.ht_hi for s in slabs]),
        ht_lo=np.concatenate([s.ht_lo for s in slabs]),
        write_id=np.concatenate([s.write_id for s in slabs]),
        flags=np.concatenate([s.flags for s in slabs]),
        ttl_ms=np.concatenate([s.ttl_ms for s in slabs]),
        value_idx=np.concatenate(
            [s.value_idx + o for s, o in zip(slabs, value_offsets)]).astype(np.int32),
        values=values,
    )
