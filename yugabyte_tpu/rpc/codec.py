"""Self-describing binary wire codec for RPC payloads.

The reference serializes RPC bodies as protobuf with a small binary header
(ref: src/yb/rpc/binary_call_parser.cc framing, gen_yrpc codegen for message
classes). Here the message set is small and Python-native, so instead of a
codegen step we use one compact tagged codec covering the closed type set
{None, bool, int, float, bytes, str, list, dict}; services exchange plain
dicts. Ints are arbitrary-precision (hybrid times are u64-sized), encoded
as length-prefixed big-endian two's complement.

Framing on the socket is [u32 little-endian length][payload] — the same
length-prefix scheme as the reference's binary call parser.
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

_F64 = struct.Struct("<d")


def _write_varint(out: List[bytes], n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(bytes((b | 0x80,)))
        else:
            out.append(bytes((b,)))
            return


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    shift = 0
    n = 0
    while True:
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _dump(obj: Any, out: List[bytes]) -> None:
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, int):
        raw = obj.to_bytes((obj.bit_length() + 8) // 8 or 1, "big", signed=True)
        out.append(b"i")
        _write_varint(out, len(raw))
        out.append(raw)
    elif isinstance(obj, float):
        out.append(b"f")
        out.append(_F64.pack(obj))
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(b"b")
        _write_varint(out, len(b))
        out.append(b)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(b"s")
        _write_varint(out, len(raw))
        out.append(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(b"l")
        _write_varint(out, len(obj))
        for item in obj:
            _dump(item, out)
    elif isinstance(obj, dict):
        out.append(b"d")
        _write_varint(out, len(obj))
        for k, v in obj.items():
            _dump(k, out)
            _dump(v, out)
    else:
        raise TypeError(f"not wire-encodable: {type(obj)!r}")


def dumps(obj: Any) -> bytes:
    out: List[bytes] = []
    _dump(obj, out)
    return b"".join(out)


def _load(buf: bytes, off: int) -> Tuple[Any, int]:
    tag = buf[off:off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"i":
        n, off = _read_varint(buf, off)
        return int.from_bytes(buf[off:off + n], "big", signed=True), off + n
    if tag == b"f":
        return _F64.unpack_from(buf, off)[0], off + _F64.size
    if tag == b"b":
        n, off = _read_varint(buf, off)
        return buf[off:off + n], off + n
    if tag == b"s":
        n, off = _read_varint(buf, off)
        return buf[off:off + n].decode("utf-8"), off + n
    if tag == b"l":
        n, off = _read_varint(buf, off)
        items = []
        for _ in range(n):
            item, off = _load(buf, off)
            items.append(item)
        return items, off
    if tag == b"d":
        n, off = _read_varint(buf, off)
        d = {}
        for _ in range(n):
            k, off = _load(buf, off)
            v, off = _load(buf, off)
            d[k] = v
        return d, off
    raise ValueError(f"bad wire tag {tag!r} at offset {off - 1}")


def loads(buf: bytes) -> Any:
    obj, off = _load(buf, 0)
    if off != len(buf):
        raise ValueError(f"trailing garbage: {len(buf) - off} bytes")
    return obj


# ------------------------------------------------------------ trace header
# Distributed trace propagation (the reference's RPC header carries an
# optional trace id the same way, ref: rpc/rpc_header.proto trace fields):
# request messages carry an optional TRACE_HEADER_KEY entry holding the
# caller's span context. Absent header = untraced caller (old peer) — the
# decode side tolerates it, so the wire stays backward compatible.

TRACE_HEADER_KEY = "trace"


def trace_to_wire(ctx: Any) -> Any:
    """Normalize a span context dict for the wire; None when untraced."""
    if not isinstance(ctx, dict) or not ctx.get("trace_id"):
        return None
    return {"trace_id": str(ctx["trace_id"]),
            "span_id": str(ctx.get("span_id") or ""),
            "sampled": bool(ctx.get("sampled", True))}


def trace_from_wire(wire: Any) -> Any:
    """Inverse of trace_to_wire; tolerates absent/malformed headers."""
    if not isinstance(wire, dict) or not wire.get("trace_id"):
        return None
    return {"trace_id": str(wire["trace_id"]),
            "span_id": str(wire.get("span_id") or ""),
            "sampled": bool(wire.get("sampled", True))}


# ------------------------------------------------- latency-budget header
# Serve-path latency attribution (utils/latency.py) rides next to the
# trace header: a request whose caller carries a LatencyBudget marks the
# op with LAT_HEADER_KEY={"op": <op>}; the server opens a matching
# budget for the handler and returns its stage map under the same key in
# the response, which the caller merges into its own budget. Absent
# header = unattributed caller; both directions tolerate it, so the wire
# stays backward compatible exactly like the trace header.

LAT_HEADER_KEY = "lat"


def lat_to_wire(budget: Any) -> Any:
    """Request-side marker for an attribution-carrying op; None when the
    caller holds no budget."""
    if budget is None or not getattr(budget, "op", None):
        return None
    return {"op": str(budget.op)}


def lat_op_from_wire(wire: Any) -> Any:
    """The op name of a request's latency header; None when absent or
    malformed (old client)."""
    if not isinstance(wire, dict) or not wire.get("op"):
        return None
    return str(wire["op"])


# ---------------------------------------------------------------- sidecars
# Bulk bytes values ride OUTSIDE the tagged payload as separate segments —
# the reference's RPC sidecars (ref: src/yb/rpc/rpc_context.h AddRpcSidecar,
# used by read_query.cc:598 for big scan pages, remote bootstrap chunks and
# CDC batches). The payload holds a tag 'B' + sidecar index; the segment
# bytes are never re-encoded, never scanned, and sent straight from the
# caller's buffer (memoryview) by the messenger's vectored send.

def _dump_sc(obj: Any, out: List[bytes], sidecars: List[memoryview],
             min_bytes: int) -> None:
    if isinstance(obj, (bytes, bytearray, memoryview)) \
            and len(obj) >= min_bytes:
        out.append(b"B")
        _write_varint(out, len(sidecars))
        sidecars.append(memoryview(obj))
    elif isinstance(obj, (list, tuple)):
        out.append(b"l")
        _write_varint(out, len(obj))
        for item in obj:
            _dump_sc(item, out, sidecars, min_bytes)
    elif isinstance(obj, dict):
        out.append(b"d")
        _write_varint(out, len(obj))
        for k, v in obj.items():
            _dump(k, out)  # keys are small scalars: never sidecar'd
            _dump_sc(v, out, sidecars, min_bytes)
    else:
        _dump(obj, out)


def dumps_with_sidecars(obj: Any, min_bytes: int
                        ) -> Tuple[bytes, List[memoryview]]:
    """(payload, sidecars): bytes values >= min_bytes are externalized."""
    out: List[bytes] = []
    sidecars: List[memoryview] = []
    _dump_sc(obj, out, sidecars, min_bytes)
    return b"".join(out), sidecars


def _load_sc(buf: bytes, off: int, sidecars) -> Tuple[Any, int]:
    tag = buf[off:off + 1]
    if tag == b"B":
        idx, off = _read_varint(buf, off + 1)
        return sidecars[idx], off
    if tag == b"l":
        n, off = _read_varint(buf, off + 1)
        items = []
        for _ in range(n):
            item, off = _load_sc(buf, off, sidecars)
            items.append(item)
        return items, off
    if tag == b"d":
        n, off = _read_varint(buf, off + 1)
        d = {}
        for _ in range(n):
            k, off = _load(buf, off)
            v, off = _load_sc(buf, off, sidecars)
            d[k] = v
        return d, off
    return _load(buf, off)


def loads_with_sidecars(buf: bytes, sidecars) -> Any:
    """Inverse of dumps_with_sidecars; sidecar entries are spliced back in
    as the bytes-like objects given (receive path passes exact-sized
    buffers filled straight from the socket — no reassembly copy)."""
    obj, off = _load_sc(buf, 0, sidecars)
    if off != len(buf):
        raise ValueError(f"trailing garbage: {len(buf) - off} bytes")
    return obj
