"""Crash-recovery chaos soak (PR: robustness) — the distributed twin of
PR 1's disk-fault harness.

A write workload runs against an RF3 MiniCluster while the nemesis
drives five consecutive fault cycles:

  1. tserver crash-stop mid-load + restart (WAL replay / catch-up),
  2. raft leader partition (a new leader must emerge in the connected
     majority; the stale leader rejoins on heal),
  3. injected ENOSPC on SST writes + device faults in the stage-B
     kernel path while compactions run under device_offload_mode=device
     (background-error containment + mid-job native fallback +
     shape-bucket quarantine underneath),
  4. at-rest corruption nemesis: bit-flips in a follower's written SST
     bytes, detected by one scrub cycle -> replica FAILED (corrupt) ->
     master rebuilds it in place from a healthy peer,
  5. slow-bucket nemesis: the 'slow' fault kind throttles the device
     dispatch path (latency only, no exception) with measured routing
     live — the bucket-health board must demote the slowed merge
     buckets, park their jobs on the native path, and re-promote them
     via a winning sampled probe once the slowness clears.

Invariants asserted after the cycles heal:
  - every ACKNOWLEDGED write is readable with its last-acked value,
  - raft terms never regress across any cycle,
  - all tablets converge RUNNING with ready leaders,
  - zero UNDETECTED mismatches: cross-replica digests agree on every
    tablet after the corruption cycle heals,
  - the host staging pool has zero leaked leases.

Slow-marked (tier-2): run with
  pytest tests/test_chaos_soak.py -m slow
YBTPU_SOAK_SECONDS scales the per-cycle hold (default ~3s).
"""

import os
import threading
import time

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.chaos import NemesisController
from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                   MiniClusterOptions)
from yugabyte_tpu.ops import device_faults
from yugabyte_tpu.storage import native_engine, offload_policy
from yugabyte_tpu.storage.bucket_health import health_board
from yugabyte_tpu.storage.device_cache import host_staging_pool
from yugabyte_tpu.utils import env as env_mod
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


class _Workload:
    """Sequential acked-write tracker: only writes the cluster ACKED are
    recorded, so the post-heal verification is exactly the durability
    contract (an unacked write may or may not survive)."""

    def __init__(self, client, table):
        self.client = client
        self.table = table
        self.acked = {}          # key -> last acked value (writer-only)
        self.attempts = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-soak-writer")

    def _run(self):
        i = 0
        while not self._stop.is_set():
            key, val = f"k{i % 500:04d}", f"v{i}"
            self.attempts += 1
            try:
                self.client.write(self.table, [QLWriteOp(
                    WriteOpKind.INSERT, dk(key), {"v": val})])
                self.acked[key] = val
            except Exception:
                # fault window: not acked, not recorded — the client's
                # replica walk + backoff already retried under the hood
                self.errors += 1
                time.sleep(0.05)
            i += 1

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=15)
        return dict(self.acked)


@pytest.mark.slow
@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_chaos_soak_three_nemesis_cycles(tmp_path):
    hold = float(os.environ.get("YBTPU_SOAK_SECONDS", 3))
    old_flags = {f: flags.get_flag(f) for f in
                 ("replication_factor", "memstore_size_bytes",
                  "device_offload_mode", "bucket_health_probe_interval_s")}
    flags.set_flag("replication_factor", 3)
    flags.set_flag("memstore_size_bytes", 16384)  # force flush/compaction
    flags.set_flag("device_offload_mode", "device")  # kernel path live
    fi_env = env_mod.FaultInjectionEnv()
    env_mod.set_env(fi_env)
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()

    cluster = MiniCluster(MiniClusterOptions(
        num_tservers=3, fs_root=str(tmp_path / "cluster"))).start()
    nem = NemesisController(cluster, seed=7)
    workload = None
    try:
        client = cluster.new_client()
        client.create_namespace("db")
        table = client.create_table("db", "soak", SCHEMA, num_tablets=2)
        cluster.wait_all_replicas_running(table.table_id)
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id

        workload = _Workload(cluster.new_client(), table).start()
        time.sleep(hold)  # baseline load before the first fault

        # ---- cycle 1: tserver crash-stop + restart ------------------
        terms = nem.capture_terms()
        nem.kill_tserver(1)
        time.sleep(hold)
        nem.restart_tserver(1)
        nem.wait_all_healthy(table.table_id, timeout_s=90)
        after = nem.capture_terms()
        nem.check_terms_monotonic(terms, after)

        # ---- cycle 2: raft leader partition -------------------------
        terms = after
        old_leader = nem.partition_leader(tablet_id)
        new_leader = cluster.wait_for_tablet_leader(
            tablet_id, timeout_s=45, exclude={old_leader})
        assert new_leader != old_leader
        time.sleep(hold)
        nem.heal()
        nem.wait_all_healthy(table.table_id, timeout_s=90)
        after = nem.capture_terms()
        nem.check_terms_monotonic(terms, after)

        # ---- cycle 3: ENOSPC + device faults during compaction ------
        terms = after
        fi_env.set_fault("enospc", path_filter=".sst", count=2)
        device_faults.arm("runtime", site="result", count=2)
        device_faults.arm("compile", site="dispatch", count=1)
        time.sleep(hold * 2)  # flushes + compactions under fault
        fi_env.clear_faults()
        device_faults.disarm_all()
        nem.wait_all_healthy(table.table_id, timeout_s=120)
        nem.check_terms_monotonic(terms, nem.capture_terms())

        # ---- cycle 4: at-rest corruption nemesis --------------------
        # bit-flip a FOLLOWER replica's written SST bytes, then force a
        # scrub cycle: detection must fail the replica (sticky corrupt)
        # and the master must rebuild it from a healthy peer.
        terms = nem.capture_terms()
        follower_ts = follower_peer = None
        for ts in cluster.tservers:
            peer = ts.tablet_manager.get_tablet(tablet_id)
            if not peer.raft.is_leader():
                follower_ts, follower_peer = ts, peer
                break
        assert follower_ts is not None
        follower_peer.tablet.flush()   # ensure at-rest bytes exist
        import glob as _glob
        data_files = sorted(_glob.glob(os.path.join(
            follower_peer.tablet.regular_db.db_dir, "*.sblock.0")))
        assert data_files, "follower flush produced no SST to corrupt"
        for path in reversed(data_files):  # newest first: a concurrent
            try:                           # compaction may eat the old
                fi_env.corrupt_range(path, length=64, nbits=3)
                break
            except OSError:
                continue
        old_scrub = flags.get_flag("scrub_interval_s")
        flags.set_flag("scrub_interval_s", 0.01)
        try:
            time.sleep(0.02)
            deadline = time.monotonic() + 30
            while follower_peer.state != "FAILED" \
                    and time.monotonic() < deadline:
                follower_ts.scrub_op.perform()
                time.sleep(0.1)
        finally:
            flags.set_flag("scrub_interval_s", old_scrub)
        assert follower_peer.state == "FAILED" \
            and follower_peer.failed_corrupt, \
            "scrub cycle must detect the corrupted SST"
        # master rebuild loop: the replica comes back RUNNING on a NEW
        # peer object with the corruption gone
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                p = follower_ts.tablet_manager.get_tablet(tablet_id)
                if p is not follower_peer and p.state == "RUNNING":
                    break
            except Exception:
                pass  # mid-rebuild
            time.sleep(0.2)
        nem.wait_all_healthy(table.table_id, timeout_s=120)
        nem.check_terms_monotonic(terms, nem.capture_terms())

        # ---- cycle 5: slow-bucket nemesis ---------------------------
        # Flip to MEASURED routing (the forced-device mode above was
        # cycle 3's kernel-path coverage) and throttle the device
        # dispatch with latency only: the health board must demote the
        # slowed merge buckets on the rate crossover, complete their
        # parked jobs natively (observable: record_native fires on the
        # degraded keys), then re-promote via a winning probe once the
        # slowness clears. Byte correctness of the parked completions
        # rides the verification below — acked reads plus the
        # cross-replica digest agreement cover every SST written here.
        board = health_board()
        flags.set_flag("device_offload_mode", "auto")
        # cycles 1-4 may have parked merge buckets behind a 300s fault
        # quarantine — that memory is THEIR proof, not this cycle's
        # subject: wipe the board so measured routing restarts live
        offload_policy.bucket_quarantine().clear()

        def _merge_keys(snap):
            return [k for k in snap["keys"]
                    if k["family"] == "run_merge_fused"]

        def _degraded(snap):
            return [k for k in snap["keys"]
                    if k["family"] == "run_merge_fused"
                    and k["state"] == "degraded"]

        deadline = time.monotonic() + 90
        while not _merge_keys(board.snapshot()) \
                and time.monotonic() < deadline:
            time.sleep(0.2)
        snap = board.snapshot()
        assert _merge_keys(snap), \
            "soak produced no merge-bucket traffic to throttle"
        # Seed each observed bucket barely-HEALTHY: native EWMA at its
        # live value (or a high floor), device just above it. The next
        # throttled completion folds ~0.7x into the device EWMA and
        # crosses below native — so demotion fires on a REAL measured
        # device completion, not on synthetic numbers.
        warm = int(flags.get_flag("bucket_health_warmup_obs"))
        for k in _merge_keys(snap):
            b = tuple(k["bucket"])
            rate = float(k["native_rows_per_sec"])
            if rate <= 0:
                board.record_native("run_merge_fused", b, 10**6, 1.0)
                rate = 1e6
            for _ in range(warm):
                board.record_device("run_merge_fused", b,
                                    int(rate * 1.05) + 1, 1.0)
        snap = board.snapshot()
        base = {tuple(k["bucket"]): k["native_obs"]
                for k in _merge_keys(snap)}
        demo0 = snap["counters"]["demotions"]
        promo0 = snap["counters"]["promotions"]
        device_faults.arm("slow", "dispatch", count=10**6, delay_s=0.05)
        deadline = time.monotonic() + 120
        while board.snapshot()["counters"]["demotions"] == demo0 \
                and time.monotonic() < deadline:
            time.sleep(0.2)
        snap = board.snapshot()
        assert snap["counters"]["demotions"] > demo0, \
            "slow nemesis did not demote any merge bucket"
        assert _degraded(snap)
        # parked jobs complete NATIVELY and the board measures them:
        # native_obs on a degraded bucket growing past its seed proves
        # a real native completion (no faults armed, no other recorder)
        deadline = time.monotonic() + 120
        parked = False
        while not parked and time.monotonic() < deadline:
            snap = board.snapshot()
            parked = any(k["native_obs"] > base[tuple(k["bucket"])]
                         for k in _degraded(snap)
                         if tuple(k["bucket"]) in base)
            if not parked:
                time.sleep(0.2)
        assert parked, \
            "no parked native completion observed on a degraded bucket"

        # the device recovers: clear the slowness, drag the seeded
        # native EWMAs back down, and let a sampled probe win (the
        # promotion event only fires from a REAL job's device result)
        device_faults.disarm_all()
        flags.set_flag("bucket_health_probe_interval_s", 0.0)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            snap = board.snapshot()
            if snap["counters"]["promotions"] > promo0:
                break
            for k in _degraded(snap):
                board.record_native("run_merge_fused", tuple(k["bucket"]),
                                    1, 1000.0)
            time.sleep(0.05)
        snap = board.snapshot()
        assert snap["counters"]["promotions"] > promo0, \
            "cleared bucket did not re-promote via a winning probe: " \
            f"counters={snap['counters']} states={snap['states']} " \
            f"merge_keys={_merge_keys(snap)!r}"
        nem.wait_all_healthy(table.table_id, timeout_s=90)

        # ---- verification -------------------------------------------
        acked = workload.stop()
        workload = None
        assert len(acked) >= 10, \
            f"soak produced too few acked writes: {len(acked)}"
        missing = []
        for key, want in sorted(acked.items()):
            row = client.read_row(table, dk(key))
            got = None if row is None else \
                row.columns[SCHEMA.column_id("v")]
            # the writer may have acked a NEWER value for this key after
            # the snapshot, but never an older one — compare sequence no.
            if got is None or int(got[1:]) < int(want[1:]):
                missing.append((key, want, got))
        assert not missing, \
            f"acknowledged writes lost after heal: {missing[:10]}"
        # zero UNDETECTED mismatches: after the corruption cycle healed,
        # every tablet's replicas agree digest-for-digest at one pinned
        # read time (divergence the loop failed to repair would show
        # here)
        from yugabyte_tpu.utils.status import StatusError
        for tid in client.meta_cache.tablets(table.table_id):
            read_ht = None
            for ts in cluster.tservers:  # pin one read time (leader-only)
                try:
                    read_ht = client._messenger.call(
                        ts.address, "tserver", "scan",
                        tablet_id=tid.tablet_id, limit=1)["read_ht"]
                    break
                except StatusError:
                    continue
            assert read_ht is not None, f"no leader for {tid.tablet_id}"
            sums = set()
            for ts in cluster.tservers:
                sums.add(client._messenger.call(
                    ts.address, "tserver", "checksum_tablet",
                    timeout_s=60.0, tablet_id=tid.tablet_id,
                    read_ht=read_ht)["checksum"])
            assert len(sums) == 1, \
                f"undetected replica divergence on {tid.tablet_id}: {sums}"
        assert host_staging_pool().outstanding() == 0, \
            "staging-pool leases leaked during the chaos run"
    finally:
        if workload is not None:
            workload.stop()
        nem.close()
        cluster.shutdown()
        env_mod.set_env(env_mod.Env())
        device_faults.disarm_all()
        offload_policy.bucket_quarantine().clear()
        for f, v in old_flags.items():
            flags.set_flag(f, v)
