"""The native C++ baseline must agree with the Python oracle AND the kernel."""

import random

import numpy as np
import pytest

from yugabyte_tpu.docdb.compaction_model import ModelEntry, compact_model, sort_key
from yugabyte_tpu.ops.merge_gc import GCParams, merge_and_gc_device
from yugabyte_tpu.storage.cpu_baseline import compact_cpu_baseline
from tests.test_merge_gc_kernel import slab_from_model, mk_key, ht, CUTOFF


def _sorted_runs(entries, n_runs=4):
    """Split entries into n_runs, each sorted in internal-key order."""
    rng = random.Random(0)
    runs = [[] for _ in range(n_runs)]
    for e in entries:
        runs[rng.randrange(n_runs)].append(e)
    ordered = []
    offsets = [0]
    for r in runs:
        r.sort(key=sort_key)
        ordered.extend(r)
        offsets.append(len(ordered))
    return ordered, offsets


@pytest.mark.parametrize("is_major", [False, True])
@pytest.mark.parametrize("seed", [0, 3])
def test_baseline_matches_kernel_and_model(seed, is_major):
    rng = random.Random(seed)
    entries, seen = [], set()
    for _ in range(500):
        key, dkl = mk_key(rng.randint(0, 30), rng.choice([None, 0, 1, 2]))
        e = ModelEntry(key, dkl, ht(rng.randint(1, 2000), rng.randint(0, 3)),
                       is_tombstone=rng.random() < 0.15,
                       is_object_init=rng.random() < 0.05,
                       ttl_ms=rng.choice([None] * 4 + [0, 10**9]))
        if (e.key, e.dht) in seen or (e.is_object_init and len(e.key) != e.doc_key_len):
            continue
        seen.add((e.key, e.dht))
        entries.append(e)
    ordered, offsets = _sorted_runs(entries)
    slab = slab_from_model(ordered)
    order, keep, mk = compact_cpu_baseline(slab, offsets, CUTOFF, is_major)
    got = sorted((sort_key(ordered[int(order[i])]), bool(mk[i]))
                 for i in range(len(ordered)) if keep[i])
    want = sorted((sort_key(r.entry), r.as_tombstone)
                  for r in compact_model(entries, CUTOFF, is_major))
    assert got == want
    # and the device kernel agrees too
    perm, kkeep, kmk = merge_and_gc_device(slab, GCParams(CUTOFF, is_major))
    kernel = sorted((sort_key(ordered[int(perm[p])]), bool(kmk[p]))
                    for p in np.nonzero(kkeep)[0])
    assert kernel == want
