"""Kernel compile-surface manifest: the statically-checked contract for
every jitted kernel family.

ROADMAP item 5 demands that "every new kernel family must land inside the
bucket/prewarm/cache discipline" — this module turns that discipline from
tribal knowledge into a committed artifact plus two checks:

- `generate()` (device-free; run under JAX_PLATFORMS=cpu) enumerates the
  declared bucket lattice of every kernel family — the shapes
  `prewarm_buckets` warms, the chunk buckets `_chunk_target_rows` re-lands
  big jobs on, the radix/scan/gather side families — and
  `jax.eval_shape`/`.lower()`s each (kernel, bucket) pair.  NO device
  execution, no compilation: only abstract evaluation and StableHLO
  emission.  The result — input/output avals, static-arg signature,
  donation aliasing, a lowering fingerprint, prewarm coverage and the
  offload-policy quarantine key — is committed as
  `tools/analysis/kernel_manifest.json`.

- `check_manifest()` (pure stdlib, no jax import, sub-second) recomputes
  per-family SOURCE fingerprints over the AST of the symbols that define
  each family's compile surface and compares them (plus the budgets and
  the lattice invariants) against the committed JSON.  Any kernel change
  that could move the compile surface therefore fails tier-1 until the
  manifest is regenerated — making surface growth a reviewed decision
  (the diff of kernel_manifest.json) instead of an accident.

The compile-surface BUDGET is the distinct-executable count per family
(entries x their boolean/impl variant axes).  Exceeding it fails both
regeneration and the committed-JSON check; raising a budget is a one-line
reviewed edit here.

CLI:  python -m tools.analysis.kernel_manifest --check   (fast, no jax)
                                               --verify  (regen+compare)
                                               --write   (regenerate)
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
MANIFEST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "kernel_manifest.json")
MANIFEST_RELPATH = "tools/analysis/kernel_manifest.json"

_RUN_MERGE = "yugabyte_tpu/ops/run_merge.py"
_MERGE_GC = "yugabyte_tpu/ops/merge_gc.py"
_SCAN = "yugabyte_tpu/ops/scan.py"
_PALLAS = "yugabyte_tpu/ops/pallas_merge.py"
_DIST = "yugabyte_tpu/parallel/dist_compact.py"
_POLICY = "yugabyte_tpu/storage/offload_policy.py"
_DEVICE_CACHE = "yugabyte_tpu/storage/device_cache.py"
_POINT_READ = "yugabyte_tpu/ops/point_read.py"
_BLOOM = "yugabyte_tpu/storage/bloom.py"
_LEARNED = "yugabyte_tpu/storage/learned_index.py"
_BLOCK_CODEC = "yugabyte_tpu/ops/block_codec.py"
_BLOCK_FORMAT = "yugabyte_tpu/storage/block_format.py"

# Per-family compile-surface definition: which source symbols shape the
# lowered program (fingerprinted for the fast drift gate), the budget
# (max distinct executables the declared lattice may mint), and where a
# drift finding anchors.  gc_over_sorted is shared by every merge family:
# editing the GC half re-fingerprints all of them, which is exactly right.
FAMILIES: Dict[str, dict] = {
    "run_merge_fused": {
        "budget": 36,
        "anchor": _RUN_MERGE,
        "symbols": {
            _RUN_MERGE: [
                "_merge_gc_runs_impl", "merge_network", "_lex_gt",
                "_FUSED_STATICS", "_merge_gc_runs_fused",
                "_merge_gc_runs_fused_donated", "quantize_width",
                "_quantize_cmp", "_CMP_LATTICE", "_cmp_schedule",
                "_PREWARM_SHAPES", "prewarm_buckets", "run_bucket",
                "_chunk_target_rows",
            ],
            _MERGE_GC: ["gc_over_sorted", "pack_bits_u32", "pad_template"],
        },
    },
    "merge_gc_fused": {
        "budget": 8,
        "anchor": _MERGE_GC,
        "symbols": {
            _MERGE_GC: [
                "_merge_gc_fused", "sort_and_gc", "gc_over_sorted",
                "bucket_size", "build_sort_schedule", "full_sort_sequence",
            ],
        },
    },
    "scan_fused": {
        "budget": 16,
        "anchor": _SCAN,
        "symbols": {
            _SCAN: ["_scan_fused", "_pack_bound"],
            _MERGE_GC: ["sort_and_gc", "gc_over_sorted", "bucket_size"],
        },
    },
    "scan_filtered": {
        # query pushdown (ROADMAP item 5): snapshot scan + row-level
        # predicate filter in one program. Predicates/bounds ride as
        # OPERAND DATA padded to the PRED_SLOTS lattice, so the compile
        # key is (n_pad, w, p_pad) x the presorted axis (a single SST
        # source skips the merge sort + gather — the CPU fast path).
        "budget": 16,
        "anchor": _SCAN,
        "symbols": {
            _SCAN: ["_scan_filtered_fused", "_pushdown_base", "_row_pass",
                    "_segment_any", "_seg_or_combine", "_doc_segments",
                    "_key_byte_at", "_cmp_words", "_pack_bound",
                    "_concat_vals_fused", "pack_vals", "VAL_WORDS",
                    "_VAL_ROWS", "PRED_SLOTS", "pred_slot_bucket",
                    "_PREWARM_NPADS", "_PREWARM_W"],
            _MERGE_GC: ["sort_and_gc", "gc_over_sorted", "bucket_size",
                        "pack_bits_u32"],
        },
    },
    "scan_agg": {
        # fused aggregating scan: COUNT/SUM/MIN/MAX via segment-reduce
        # over the filtered row set — one dispatch per (tablet, query),
        # scalars only cross back. Aggregate column selectors are data
        # (AGG_SLOTS lattice); has_vals covers the COUNT(*)-only shape;
        # the presorted axis mirrors scan_filtered.
        "budget": 32,
        "anchor": _SCAN,
        "symbols": {
            _SCAN: ["_scan_agg_fused", "_pushdown_base", "_row_pass",
                    "_segment_any", "_seg_or_combine", "_doc_segments",
                    "_key_byte_at", "_cmp_words", "_pack_bound",
                    "VAL_WORDS", "_VAL_ROWS", "PRED_SLOTS", "AGG_SLOTS",
                    "pred_slot_bucket", "agg_slot_bucket",
                    "_PREWARM_NPADS", "_PREWARM_W"],
            _MERGE_GC: ["sort_and_gc", "gc_over_sorted", "bucket_size"],
        },
    },
    "gather_staged": {
        "budget": 12,
        "anchor": _RUN_MERGE,
        "symbols": {
            _RUN_MERGE: ["_survivor_positions_impl", "_survivor_positions",
                         "_survivor_positions_donated",
                         "survivor_positions", "_gather_staged_output",
                         "gather_staged_output_span",
                         "gather_staged_outputs"],
            _MERGE_GC: ["bucket_size", "pad_template"],
        },
    },
    "restage_concat": {
        # device-side re-staging of cache-resident per-SST cols into the
        # merge layouts (run-major for the bitonic/lexsort path, one
        # contiguous padded matrix for the radix path) — the chained
        # L0->L1->L2 hot path launches the run-major form before every
        # merge over resident inputs
        "budget": 8,
        "anchor": _RUN_MERGE,
        "symbols": {
            _RUN_MERGE: ["_restage_concat", "_concat_staged_fused",
                         "stage_runs_from_staged"],
            _DEVICE_CACHE: ["concat_staged", "merged_column_stats"],
            _MERGE_GC: ["bucket_size", "pad_template"],
        },
    },
    "pallas_merge": {
        "budget": 12,
        "anchor": _PALLAS,
        "symbols": {
            _PALLAS: [
                "_pallas_merge_gc_fused", "_merge_level",
                "_make_tile_kernel", "_compute_splits", "default_tile",
                "supported",
            ],
            _MERGE_GC: ["gc_over_sorted"],
        },
    },
    "chunk_carve": {
        "budget": 8,
        "anchor": _RUN_MERGE,
        "symbols": {
            _RUN_MERGE: ["_chunk_split_search", "_carve_chunk",
                         "_W_ROUTE_CHUNK", "_chunk_target_rows"],
            _MERGE_GC: ["route_word_mask", "pad_template"],
        },
    },
    "point_read_probe": {
        # batched serve-path bloom gate: the device FNV hash over the
        # doc-key prefixes (one dispatch per multi_get chunk) + the
        # per-SST bit probe. storage/bloom.py is the CPU twin — its
        # builder arithmetic DEFINES the bit positions, so it is part of
        # this family's compile surface.
        "budget": 8,
        "anchor": _POINT_READ,
        "symbols": {
            _POINT_READ: ["_fnv64_fused", "_mul64_by_prime",
                          "_bloom_probe_fused", "bloom_device_words",
                          "pack_query_batch", "batch_bucket",
                          "BATCH_BUCKETS", "_PREWARM_MWORDS",
                          "_PREWARM_WIDTHS", "_K_MAX",
                          "BLOOM_PROBE_MAX_BITS"],
            _BLOOM: ["fnv64_masked", "BloomFilterBuilder", "BloomFilter"],
        },
    },
    "point_read_locate": {
        # vectorized point locate + survivor gather over resident slab
        # matrices, optionally seeded by the learned per-SST index
        # (ROADMAP item 4's serve-path kernel)
        "budget": 16,
        "anchor": _POINT_READ,
        "symbols": {
            _POINT_READ: ["_locate_gather_fused", "_seek_pred",
                          "_predict_pos", "_x_words", "_sub64",
                          "_f64ish", "_ge64", "_LG_WINDOW",
                          "batch_bucket", "BATCH_BUCKETS",
                          "_PREWARM_NPADS", "_PREWARM_WIDTHS"],
            _MERGE_GC: ["bucket_size", "pad_template"],
            _LEARNED: ["LINDEX_SEGMENTS", "LINDEX_MAX_ERR",
                       "model_operands", "_anchor_positions"],
        },
    },
    "index_fit": {
        # learned-index fit over staged (sorted) cols — runs at
        # flush/compaction write-through while the keys are in HBM for
        # free; the numpy twin in storage/learned_index.py shares the
        # inference arithmetic and is fingerprinted with it
        "budget": 4,
        "anchor": _POINT_READ,
        "symbols": {
            _POINT_READ: ["_index_fit_fused", "_predict_pos", "_x_words",
                          "_sub64", "_f64ish", "_ge64",
                          "fit_learned_index_device"],
            _LEARNED: ["fit_from_sorted_words", "fit_from_packed_keys",
                       "fit_from_slab", "finish_model", "_predict_host",
                       "_anchor_positions", "LINDEX_SEGMENTS",
                       "LINDEX_MIN_ENTRIES"],
        },
    },
    "block_decode": {
        # device SST block decode (ROADMAP item 2): raw block bodies ->
        # staged cols without host decode_block. The on-disk layout
        # (block_format.py) IS this family's compile surface: editing
        # encode_block/decode_block re-fingerprints both codec families.
        "budget": 8,
        "anchor": _BLOCK_CODEC,
        "symbols": {
            _BLOCK_CODEC: ["_block_decode_impl", "_block_decode_fused",
                           "_block_decode_fused_donated", "_bswap32",
                           "_quantize_width", "_PREWARM_DECODE",
                           "decode_avals", "prewarm_block_codec"],
            _BLOCK_FORMAT: ["encode_block", "decode_block",
                            "split_raw_block", "fixed_region_bytes",
                            "META_BYTES_PER_ROW"],
            _MERGE_GC: ["bucket_size", "pad_template"],
        },
    },
    "block_encode": {
        # device SST block encode: gathered survivor-span cols -> the
        # exact on-disk column encodings (host splices values + CRC).
        # Jit-keyed on shapes only (no static args), so the lattice is
        # the (n_out_pad, w_pad) span-gather vocabulary.
        "budget": 4,
        "anchor": _BLOCK_CODEC,
        "symbols": {
            _BLOCK_CODEC: ["_block_encode_impl", "_block_encode_fused",
                           "_bswap32", "encode_span", "_PREWARM_DECODE",
                           "prewarm_block_codec"],
            _BLOCK_FORMAT: ["encode_block", "split_raw_block",
                            "fixed_region_bytes", "META_BYTES_PER_ROW"],
            # the in-kernel bloom hash shares the point-read FNV limb
            # arithmetic; the numpy twin in storage/bloom.py DEFINES the
            # bit positions, so both are part of this compile surface
            _POINT_READ: ["_mul64_by_prime", "_FNV_OFFSET_HI",
                          "_FNV_OFFSET_LO", "_FNV_PRIME_LOW"],
            _BLOOM: ["fnv64_masked"],
            _MERGE_GC: ["bucket_size", "pad_template"],
        },
    },
    "dist_compact": {
        # mesh families: the key-range-sharded dist step (capacity
        # quantized to powers of two, n_shards from the mesh, both
        # is_major variants, a donated no-retry twin) and the
        # multi-tablet pool wave program (one job per device; buckets
        # shared with run_merge's lattice). shard_map cannot be lowered
        # without a real mesh, so entries are declared against the
        # 8-device bench mesh with no lowering fingerprint (like
        # pallas_merge) — prewarm_dist_compact warms exactly this
        # lattice on whatever mesh the server resolves.
        "budget": 16,
        "anchor": _DIST,
        "symbols": {
            _DIST: ["dist_compact_fn", "distributed_compact",
                    "distributed_compact_with_outputs",
                    "_distributed_compact_impl", "stage_sharded_cols",
                    "_dist_gather_span", "_quantized_capacity",
                    "_CAPACITY_MIN", "_MAX_CAPACITY_FACTOR",
                    "pool_wave_fn", "pooled_merge_gc", "stage_pool_slot",
                    "pool_slot_bucket", "prewarm_dist_compact",
                    "_PREWARM_CAPACITIES", "_PREWARM_POOL_SHAPES",
                    "_W_ROUTE", "_SAMPLES_PER_SHARD"],
            _RUN_MERGE: ["_merge_gc_runs_impl", "_cmp_schedule",
                         "quantize_width", "run_bucket",
                         "packed_run_ns"],
            _MERGE_GC: ["sort_and_gc", "gc_over_sorted",
                        "route_word_mask"],
        },
    },
}

# the row layout constant (ops/merge_gc.py): 8 metadata rows + key words
_ROW_WORDS = 8
_CMP_LATTICE = (2, 4, 6, 8, 12, 16, 24, 32)


# ---------------------------------------------------------------------------
# Source fingerprints (pure stdlib — the fast tier-1 gate must not pay a
# jax import, let alone a trace)
# ---------------------------------------------------------------------------

def _strip_docstrings(node: ast.AST) -> ast.AST:
    """Remove docstring Exprs so comment-grade edits don't trip the gate
    (the fingerprint must move only when the lowered program could)."""
    for n in ast.walk(node):
        body = getattr(n, "body", None)
        if isinstance(body, list) and body \
                and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            del body[0]
            if not body:
                body.append(ast.Pass())
    return node


def _module_symbols(source: str) -> Dict[str, ast.AST]:
    """Top-level name -> def/assign node of one module."""
    out: Dict[str, ast.AST] = {}
    tree = ast.parse(source)
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out[stmt.name] = stmt
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            out[stmt.target.id] = stmt
    return out


def source_fingerprint(family: str, root: str = REPO_ROOT,
                       source_overrides: Optional[Dict[str, str]] = None
                       ) -> str:
    """sha256 over the (docstring-stripped, position-free) AST dumps of
    the family's surface-defining symbols.  `source_overrides` maps a
    relpath to replacement source text (synthetic-drift tests)."""
    h = hashlib.sha256()
    spec = FAMILIES[family]["symbols"]
    for relpath in sorted(spec):
        if source_overrides and relpath in source_overrides:
            src = source_overrides[relpath]
        else:
            with open(os.path.join(root, relpath), encoding="utf-8") as fh:
                src = fh.read()
        symbols = _module_symbols(src)
        for name in sorted(spec[relpath]):
            node = symbols.get(name)
            dump = ("<missing>" if node is None else
                    ast.dump(_strip_docstrings(node),
                             include_attributes=False))
            h.update(f"{relpath}:{name}={dump}\n".encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Lattice invariants (pure): a declared/warmed bucket must sit ON the
# quantization lattice — a shape off it warms (or budgets) nothing real.
# ---------------------------------------------------------------------------

def _is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def bucket_lattice_errors(bucket: Dict[str, int]) -> List[str]:
    """Violations of the (k_pad, m, w, n_cmp) lattice for a run-merge
    shaped bucket; empty means the bucket is a valid lattice point."""
    errs: List[str] = []
    k_pad = bucket.get("k_pad")
    m = bucket.get("m")
    w = bucket.get("w")
    n_cmp = bucket.get("n_cmp")
    if k_pad is not None and not _is_pow2(int(k_pad)):
        errs.append(f"k_pad={k_pad} is not a power of two")
    if m is not None and (not _is_pow2(int(m)) or int(m) < 256):
        errs.append(f"m={m} is not a power-of-two run bucket >= 256")
    if w is not None and (not _is_pow2(int(w)) or int(w) < 4):
        errs.append(f"w={w} is not a quantize_width point (pow2 >= 4)")
    if n_cmp is not None and int(n_cmp) not in _CMP_LATTICE:
        errs.append(f"n_cmp={n_cmp} is not on the _CMP_LATTICE "
                    f"{_CMP_LATTICE}")
    n_shards = bucket.get("n_shards")
    slots = bucket.get("slots")
    capacity = bucket.get("capacity")
    if n_shards is not None and not _is_pow2(int(n_shards)):
        errs.append(f"n_shards={n_shards} is not a power of two")
    if slots is not None and not _is_pow2(int(slots)):
        errs.append(f"slots={slots} is not a power of two")
    if capacity is not None and (not _is_pow2(int(capacity))
                                 or int(capacity) < 64):
        errs.append(f"capacity={capacity} is not a quantized exchange "
                    "capacity (pow2 >= 64)")
    return errs


# ---------------------------------------------------------------------------
# The fast committed-JSON check (tier-1; < 5s because it never imports jax)
# ---------------------------------------------------------------------------

def load_manifest(path: str = MANIFEST_PATH) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


_UNSET = object()


def check_manifest(manifest=_UNSET,
                   root: str = REPO_ROOT,
                   source_overrides: Optional[Dict[str, str]] = None
                   ) -> List[Tuple[str, str, str]]:
    """(family, code, message) problems with the committed manifest vs the
    current sources.  Codes: manifest-missing, manifest-drift,
    budget-exceeded, budget-drift, off-lattice-bucket, family-missing.
    Omit `manifest` to check the committed JSON; an explicit None means
    "the manifest file is missing"."""
    if manifest is _UNSET:
        manifest = load_manifest()
    problems: List[Tuple[str, str, str]] = []
    if manifest is None:
        return [("run_merge_fused", "manifest-missing",
                 f"{MANIFEST_RELPATH} is missing or unparseable — "
                 "regenerate with `python -m tools.analysis."
                 "kernel_manifest --write`")]
    fams = manifest.get("families", {})
    for name, spec in FAMILIES.items():
        rec = fams.get(name)
        if rec is None:
            problems.append((name, "family-missing",
                             f"kernel family {name!r} has no manifest "
                             "record — regenerate the manifest"))
            continue
        fp = source_fingerprint(name, root, source_overrides)
        if rec.get("source_fingerprint") != fp:
            problems.append((
                name, "manifest-drift",
                f"compile surface of {name!r} changed (source "
                "fingerprint mismatch) without regenerating "
                f"{MANIFEST_RELPATH} — run `python -m tools.analysis."
                "kernel_manifest --write`, review the surface diff, and "
                "commit it"))
        if rec.get("budget") != spec["budget"]:
            problems.append((
                name, "budget-drift",
                f"{name!r} budget in the manifest ({rec.get('budget')}) "
                f"disagrees with the declared budget ({spec['budget']}) "
                "— regenerate the manifest"))
        n_exec = rec.get("distinct_executables")
        if spec["budget"] is not None and n_exec is not None \
                and n_exec > spec["budget"]:
            problems.append((
                name, "budget-exceeded",
                f"{name!r} declares {n_exec} distinct executables, over "
                f"its compile-surface budget of {spec['budget']} — "
                "shrink the lattice or raise the budget (a reviewed "
                "decision) in tools/analysis/kernel_manifest.py"))
        for entry in rec.get("entries", ()):
            errs = bucket_lattice_errors(entry.get("bucket", {}))
            for e in errs:
                problems.append((name, "off-lattice-bucket",
                                 f"{name} bucket {entry.get('key')}: {e}"))
    return problems


def entry_key(bucket: Dict[str, int], impl: str = "") -> str:
    parts = [f"{k}={bucket[k]}" for k in sorted(bucket)]
    if impl:
        parts.append(f"impl={impl}")
    return " ".join(parts)


# ---------------------------------------------------------------------------
# Generation (device-free: eval_shape + lower only; run with
# JAX_PLATFORMS=cpu — the CLI below forces it before importing jax)
# ---------------------------------------------------------------------------

def _aval_str(x) -> str:
    shape = "x".join(str(d) for d in x.shape)
    return f"{x.dtype.name}[{shape}]" if shape else f"{x.dtype.name}[]"


def _lowering_sha256(lowered_text: str) -> str:
    return hashlib.sha256(lowered_text.encode()).hexdigest()


def _full_cmp_rows(w: int) -> List[int]:
    """The unpruned compare schedule for key width w, quantized onto the
    n_cmp lattice — the schedule prewarm and the manifest share."""
    import numpy as np
    from yugabyte_tpu.ops.run_merge import _cmp_schedule
    rows, _n_cmp = _cmp_schedule(w, np.zeros(_ROW_WORDS + w, dtype=bool))
    return [int(r) for r in rows]


def _gen_run_merge_fused() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    for (k_pad, m, w, n_cmp) in sorted(run_merge._PREWARM_SHAPES):
        r = _ROW_WORDS + w
        n = k_pad * m
        u32 = jax.ShapeDtypeStruct((), jnp.uint32)
        args = (jax.ShapeDtypeStruct((r, n), jnp.uint32),
                jax.ShapeDtypeStruct((n_cmp,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                u32, u32, u32, u32)
        for impl in ("lexsort", "network"):
            statics = dict(k_pad=k_pad, m=m, w=w, n_cmp=n_cmp,
                           is_major=True, retain_deletes=False,
                           snapshot=False, lexsort=(impl == "lexsort"))
            out = jax.eval_shape(
                lambda *a: run_merge._merge_gc_runs_fused(*a, **statics),
                *args)
            text = lowering_text(run_merge._merge_gc_runs_fused, args,
                                 statics)
            bucket = {"k_pad": k_pad, "m": m, "w": w, "n_cmp": n_cmp}
            entries.append({
                "key": entry_key(bucket, impl),
                "bucket": bucket,
                "impl": impl,
                "static_args": statics,
                "in_avals": [_aval_str(a) for a in args],
                "out_avals": [_aval_str(o) for o in
                              jax.tree_util.tree_leaves(out)],
                # the donated twin aliases arg 0 (carved chunk buffers);
                # both variants exist per bucket, as does is_major
                "donation": {"donate_argnums": [0], "variants": 2},
                "variant_axes": {"is_major": 2, "donate": 2},
                "executables": 4,
                "prewarmed": True,
                "quarantine_key": [k_pad, m],
                "lowering_sha256": _lowering_sha256(text),
            })
    return {"entries": entries}


def _gen_merge_gc_fused() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import merge_gc
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = 4
    r = _ROW_WORDS + w
    for n_pad in (1 << 16, 1 << 20):
        u32 = jax.ShapeDtypeStruct((), jnp.uint32)
        args = (jax.ShapeDtypeStruct((r, n_pad), jnp.uint32),
                jax.ShapeDtypeStruct((4 + w,), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.int32),
                u32, u32, u32, u32)
        statics = dict(w=w, is_major=True, retain_deletes=False)
        out = jax.eval_shape(
            lambda *a: merge_gc._merge_gc_fused(*a, **statics), *args)
        text = lowering_text(merge_gc._merge_gc_fused, args, statics)
        bucket = {"n_pad": n_pad, "w": w}
        entries.append({
            "key": entry_key(bucket),
            "bucket": bucket,
            "static_args": statics,
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            # the pruned radix schedule rides as OPERANDS (sort_rows,
            # n_sort), so one executable covers every pruning — the
            # compile key is the shape bucket alone
            "donation": None,
            "variant_axes": {"is_major": 2},
            "executables": 2,
            "prewarmed": False,
            "quarantine_key": [1, n_pad],
            "lowering_sha256": _lowering_sha256(text),
        })
    return {"entries": entries}


def _gen_scan_fused() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import scan as scan_mod
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = 4
    r = _ROW_WORDS + w
    for n_pad in (1 << 16, 1 << 20):
        u32 = jax.ShapeDtypeStruct((), jnp.uint32)
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        args = (jax.ShapeDtypeStruct((r, n_pad), jnp.uint32),
                jax.ShapeDtypeStruct((4 + w,), jnp.int32), i32,
                u32, u32, u32, u32,
                jax.ShapeDtypeStruct((w,), jnp.uint32), i32,
                jax.ShapeDtypeStruct((w,), jnp.uint32), i32)
        statics = dict(w=w, has_lower=True, has_upper=True,
                       upper_truncated=False)
        out = jax.eval_shape(
            lambda *a: scan_mod._scan_fused(*a, **statics), *args)
        text = lowering_text(scan_mod._scan_fused, args, statics)
        bucket = {"n_pad": n_pad, "w": w}
        entries.append({
            "key": entry_key(bucket),
            "bucket": bucket,
            "static_args": statics,
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            "donation": None,
            # reachable bound combos: none/lower/upper/both x the
            # truncated-upper refinement (truncation only with an upper)
            "variant_axes": {"bounds": 6},
            "executables": 6,
            "prewarmed": False,
            "quarantine_key": [1, n_pad],
            "lowering_sha256": _lowering_sha256(text),
        })
    return {"entries": entries}


def _scan_pushdown_args(jax, jnp, n_pad: int, w: int, p_pad: int,
                        has_vals: bool):
    sdt = jax.ShapeDtypeStruct
    i32 = sdt((), jnp.int32)
    u32 = sdt((), jnp.uint32)
    b1 = sdt((), jnp.bool_)
    from yugabyte_tpu.ops.scan import _VAL_ROWS, VAL_WORDS
    return (sdt((_ROW_WORDS + w, n_pad), jnp.uint32),
            sdt((_VAL_ROWS, n_pad if has_vals else 1), jnp.uint32),
            sdt((4 + w,), jnp.int32), i32, u32, u32, u32, u32,
            sdt((w,), jnp.uint32), i32, sdt((w,), jnp.uint32), i32,
            b1, b1,
            sdt((p_pad,), jnp.uint32), sdt((p_pad,), jnp.int32),
            sdt((p_pad,), jnp.int32),
            sdt((p_pad,), jnp.uint32), sdt((p_pad,), jnp.uint32),
            sdt((p_pad, VAL_WORDS), jnp.uint32), sdt((p_pad,), jnp.int32))


def _gen_scan_filtered() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import scan as scan_mod
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = scan_mod._PREWARM_W
    for n_pad in scan_mod._PREWARM_NPADS:
        for p_pad in scan_mod.PRED_SLOTS:
          for presorted in (False, True):
            args = _scan_pushdown_args(jax, jnp, n_pad, w, p_pad, True)
            statics = dict(w=w, p_pad=p_pad, presorted=presorted)
            out = jax.eval_shape(
                lambda *a: scan_mod._scan_filtered_fused(*a, **statics),
                *args)
            text = lowering_text(scan_mod._scan_filtered_fused, args,
                                 statics)
            bucket = {"n_pad": n_pad, "p_pad": p_pad, "w": w}
            entries.append({
                "key": "scan_filtered " + entry_key(
                    bucket, "presorted" if presorted else "merge"),
                "bucket": bucket,
                "impl": "presorted" if presorted else "merge",
                "static_args": statics,
                "in_avals": [_aval_str(a) for a in args],
                "out_avals": [_aval_str(o) for o in
                              jax.tree_util.tree_leaves(out)],
                # inputs are LIVE slab-cache entries (cols + vals):
                # donation is forbidden by design
                "donation": None,
                "variant_axes": {},
                "executables": 1,
                "prewarmed": True,
                "quarantine_key": [1, n_pad],
                "lowering_sha256": _lowering_sha256(text),
            })
    # the per-source vals concat (row-aligned twin of concat_staged):
    # one representative — real k varies with the source count, like
    # concat_staged_fused in the restage_concat family
    n_in, k, n_pad = 1 << 16, 4, 1 << 18
    from yugabyte_tpu.ops.scan import _VAL_ROWS
    parts = tuple(jax.ShapeDtypeStruct((_VAL_ROWS, n_in), jnp.uint32)
                  for _ in range(k))
    args = (parts, jax.ShapeDtypeStruct((k,), jnp.int32))
    statics = dict(n_pad=n_pad)
    out = jax.eval_shape(
        lambda *a: scan_mod._concat_vals_fused(*a, **statics), *args)
    text = lowering_text(scan_mod._concat_vals_fused, args, statics)
    bucket = {"n_pad": n_pad}
    entries.append({
        "key": "concat_vals " + entry_key(bucket),
        "bucket": bucket,
        "static_args": statics,
        "in_avals": [_aval_str(a) for a in
                     jax.tree_util.tree_leaves(args)],
        "out_avals": [_aval_str(o) for o in
                      jax.tree_util.tree_leaves(out)],
        "donation": None,
        "variant_axes": {},
        "executables": 1,
        "prewarmed": False,
        "quarantine_key": None,
        "lowering_sha256": _lowering_sha256(text),
    })
    return {"entries": entries}


def _gen_scan_agg() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import scan as scan_mod
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = scan_mod._PREWARM_W
    for n_pad in scan_mod._PREWARM_NPADS:
        combos = [(p, c, True) for p in scan_mod.PRED_SLOTS
                  for c in scan_mod.AGG_SLOTS] + [(1, 1, False)]
        for p_pad, c_pad, has_vals in combos:
          for presorted in (False, True):
            sdt = jax.ShapeDtypeStruct
            args = _scan_pushdown_args(jax, jnp, n_pad, w, p_pad,
                                       has_vals) + (
                sdt((c_pad,), jnp.uint32), sdt((c_pad,), jnp.uint32),
                sdt((c_pad,), jnp.uint32))
            statics = dict(w=w, p_pad=p_pad, c_pad=c_pad,
                           has_vals=has_vals, presorted=presorted)
            out = jax.eval_shape(
                lambda *a: scan_mod._scan_agg_fused(*a, **statics),
                *args)
            text = lowering_text(scan_mod._scan_agg_fused, args, statics)
            bucket = {"c_pad": c_pad, "n_pad": n_pad, "p_pad": p_pad,
                      "w": w}
            impl = ("vals" if has_vals else "novals") + (
                "-presorted" if presorted else "-merge")
            entries.append({
                "key": "scan_agg " + entry_key(bucket, impl),
                "bucket": bucket,
                "impl": impl,
                "static_args": statics,
                "in_avals": [_aval_str(a) for a in args],
                "out_avals": [_aval_str(o) for o in
                              jax.tree_util.tree_leaves(out)],
                "donation": None,
                "variant_axes": {},
                "executables": 1,
                "prewarmed": True,
                "quarantine_key": [1, n_pad],
                "lowering_sha256": _lowering_sha256(text),
            })
    return {"entries": entries}


def _gen_gather_staged() -> dict:
    """Write-through gather lattice, derived from _PREWARM_SHAPES: every
    prewarm bucket's merge is immediately followed by one survivor scan
    over its n_pad = k_pad*m keep mask and per-span output gathers whose
    top n_out_pad bucket is m (prewarm_buckets warms exactly these)."""
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = 4
    r = _ROW_WORDS + w
    pos_pads = sorted({k_pad * m for (k_pad, m, _w, _c)
                       in run_merge._PREWARM_SHAPES})
    for n_pad in pos_pads:
        args = (jax.ShapeDtypeStruct((n_pad,), jnp.bool_),)
        out = jax.eval_shape(run_merge._survivor_positions, *args)
        text = lowering_text(run_merge._survivor_positions, args, {})
        bucket = {"n_pad": n_pad}
        entries.append({
            "key": "survivor_positions " + entry_key(bucket),
            "bucket": bucket,
            "static_args": {},
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            # the keep mask is the CHAINED buffer: dead after this scan,
            # so the donated twin reuses its HBM in place (the handle's
            # copy is poisoned — ops/run_merge.survivor_positions)
            "donation": {"donate_argnums": [0], "variants": 2},
            "variant_axes": {"donate": 2},
            "executables": 2,
            "prewarmed": True,
            "quarantine_key": None,
            "lowering_sha256": _lowering_sha256(text),
        })
    span_buckets = sorted({(k_pad * m, m) for (k_pad, m, _w, _c)
                           in run_merge._PREWARM_SHAPES})
    for n_pad, n_out_pad in span_buckets:
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        args = (jax.ShapeDtypeStruct((r, n_pad), jnp.uint32),
                jax.ShapeDtypeStruct((n_pad,), jnp.int32),
                jax.ShapeDtypeStruct((n_pad,), jnp.int32),
                jax.ShapeDtypeStruct((n_pad,), jnp.bool_),
                i32, i32)
        statics = dict(n_out_pad=n_out_pad)
        out = jax.eval_shape(
            lambda *a: run_merge._gather_staged_output(*a, **statics),
            *args)
        text = lowering_text(run_merge._gather_staged_output, args,
                             statics)
        bucket = {"n_out_pad": n_out_pad, "n_pad": n_pad, "w": w}
        entries.append({
            "key": "gather_staged_output " + entry_key(bucket),
            "bucket": bucket,
            "static_args": statics,
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            "donation": None,
            "variant_axes": {},
            "executables": 1,
            "prewarmed": True,
            "quarantine_key": None,
            "lowering_sha256": _lowering_sha256(text),
        })
    return {"entries": entries}


def _gen_restage_concat() -> dict:
    """Device-side re-staging of cache-resident cols: the run-major form
    (_restage_concat) per prewarm bucket — warmed, it fronts every merge
    of the chained path — plus one representative of the radix-path
    concat (_concat_staged_fused), which only the skew fallback uses."""
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = 4
    r = _ROW_WORDS + w
    for (k_pad, m, _w, _c) in sorted(set(run_merge._PREWARM_SHAPES)):
        parts = tuple(jax.ShapeDtypeStruct((r, m), jnp.uint32)
                      for _ in range(k_pad))
        args = (parts, jax.ShapeDtypeStruct((k_pad,), jnp.int32))
        statics = dict(w=w, m=m, k_pad=k_pad)
        out = jax.eval_shape(
            lambda *a: run_merge._restage_concat(*a, **statics), *args)
        text = lowering_text(run_merge._restage_concat, args, statics)
        bucket = {"k_pad": k_pad, "m": m, "w": w}
        entries.append({
            "key": "restage_concat " + entry_key(bucket),
            "bucket": bucket,
            "static_args": statics,
            "in_avals": [_aval_str(a) for a in
                         jax.tree_util.tree_leaves(args)],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            # inputs are LIVE slab-cache entries — donation is forbidden
            # here by design (the cache must survive the merge)
            "donation": None,
            "variant_axes": {},
            "executables": 1,
            "prewarmed": True,
            "quarantine_key": [k_pad, m],
            "lowering_sha256": _lowering_sha256(text),
        })
    n_in, k, n_pad = 1 << 16, 4, 1 << 18
    parts = tuple(jax.ShapeDtypeStruct((r, n_in), jnp.uint32)
                  for _ in range(k))
    args = (parts, jax.ShapeDtypeStruct((k,), jnp.int32))
    statics = dict(w=w, n_pad=n_pad)
    out = jax.eval_shape(
        lambda *a: run_merge._concat_staged_fused(*a, **statics), *args)
    text = lowering_text(run_merge._concat_staged_fused, args, statics)
    bucket = {"n_pad": n_pad, "w": w}
    entries.append({
        "key": "concat_staged_fused " + entry_key(bucket),
        "bucket": bucket,
        "static_args": statics,
        "in_avals": [_aval_str(a) for a in
                     jax.tree_util.tree_leaves(args)],
        "out_avals": [_aval_str(o) for o in
                      jax.tree_util.tree_leaves(out)],
        "donation": None,
        "variant_axes": {},
        "executables": 1,
        "prewarmed": False,
        "quarantine_key": None,
        "lowering_sha256": _lowering_sha256(text),
    })
    return {"entries": entries}


def _gen_pallas_merge() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import pallas_merge, run_merge

    entries = []
    for (k_pad, m, w, n_cmp) in sorted(run_merge._PREWARM_SHAPES):
        r = _ROW_WORDS + w
        n = k_pad * m
        rp = ((r + 1 + 7) // 8) * 8
        tile = min(pallas_merge.default_tile(rp), m)
        cmp_rows = tuple(_full_cmp_rows(w))
        u32 = jax.ShapeDtypeStruct((), jnp.uint32)
        args = (jax.ShapeDtypeStruct((r, n), jnp.uint32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                u32, u32, u32, u32)
        statics = dict(k_pad=k_pad, m=m, w=w, cmp_rows_t=cmp_rows,
                       tile=tile, is_major=True, retain_deletes=False,
                       snapshot=False, interpret=True)
        out = jax.eval_shape(
            lambda *a: pallas_merge._pallas_merge_gc_fused(*a, **statics),
            *args)
        bucket = {"k_pad": k_pad, "m": m, "n_cmp": n_cmp, "w": w}
        entries.append({
            "key": entry_key(bucket, "pallas"),
            "bucket": bucket,
            "impl": "pallas",
            "static_args": {k: (list(v) if isinstance(v, tuple) else v)
                            for k, v in statics.items()},
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            "donation": None,
            # Mosaic lowering needs a real TPU target, so the manifest
            # records abstract eval only; the cmp_rows_t static means the
            # PRUNED schedule widens this family beyond the full-schedule
            # point warmed here (bounded in practice: schedules are
            # prefix-stable and the miss counters watch the tail)
            "variant_axes": {"is_major": 2},
            "executables": 2,
            "prewarmed": True,
            "quarantine_key": [k_pad, m],
            "lowering_sha256": None,
        })
    return {"entries": entries}


def _gen_chunk_carve() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    w = 4
    r = _ROW_WORDS + w
    m, m_c, w_route = 1 << 20, 1 << 18, 4
    for k_pad in (2, 4):
        n_iters = int(m).bit_length() + 1
        args = (jax.ShapeDtypeStruct((r, k_pad * m), jnp.uint32),
                jax.ShapeDtypeStruct((k_pad,), jnp.int32),
                jax.ShapeDtypeStruct((7, w_route), jnp.uint32))
        statics = dict(k_pad=k_pad, m=m, w_route=w_route, n_iters=n_iters)
        out = jax.eval_shape(
            lambda *a: run_merge._chunk_split_search(*a, **statics), *args)
        text = lowering_text(run_merge._chunk_split_search, args, statics)
        bucket = {"k_pad": k_pad, "m": m, "n_iters": n_iters,
                  "w_route": w_route}
        entries.append({
            "key": "chunk_split_search " + entry_key(bucket),
            "bucket": bucket,
            "static_args": statics,
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            "donation": None,
            "variant_axes": {},
            "executables": 1,
            "prewarmed": False,
            "quarantine_key": [k_pad, m],
            "lowering_sha256": _lowering_sha256(text),
        })
        cargs = (jax.ShapeDtypeStruct((r, k_pad * m), jnp.uint32),
                 jax.ShapeDtypeStruct((k_pad,), jnp.int32),
                 jax.ShapeDtypeStruct((k_pad,), jnp.int32))
        cstatics = dict(m=m, m_c=m_c, k_pad=k_pad)
        out = jax.eval_shape(
            lambda *a: run_merge._carve_chunk(*a, **cstatics), *cargs)
        text = lowering_text(run_merge._carve_chunk, cargs, cstatics)
        bucket = {"k_pad": k_pad, "m": m, "m_c": m_c}
        entries.append({
            "key": "carve_chunk " + entry_key(bucket),
            "bucket": bucket,
            "static_args": cstatics,
            "in_avals": [_aval_str(a) for a in cargs],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            "donation": None,
            "variant_axes": {},
            "executables": 1,
            "prewarmed": False,
            "quarantine_key": [k_pad, m],
            "lowering_sha256": _lowering_sha256(text),
        })
    return {"entries": entries}


def _gen_point_read_probe() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import point_read
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    sdt = jax.ShapeDtypeStruct
    i32 = sdt((), jnp.int32)
    u32 = sdt((), jnp.uint32)
    for b in point_read.BATCH_BUCKETS:
        for w in point_read._PREWARM_WIDTHS:
            args = (sdt((b, w), jnp.uint32), sdt((b,), jnp.int32))
            statics = dict(w=w)
            out = jax.eval_shape(
                lambda *a: point_read._fnv64_fused(*a, **statics), *args)
            text = lowering_text(point_read._fnv64_fused, args, statics)
            bucket = {"b": b, "w": w}
            entries.append({
                "key": "fnv64 " + entry_key(bucket),
                "bucket": bucket,
                "static_args": statics,
                "in_avals": [_aval_str(a) for a in args],
                "out_avals": [_aval_str(o) for o in
                              jax.tree_util.tree_leaves(out)],
                "donation": None,
                "variant_axes": {},
                "executables": 1,
                "prewarmed": True,
                "quarantine_key": None,
                "lowering_sha256": _lowering_sha256(text),
            })
        for mw in point_read._PREWARM_MWORDS:
            args = (sdt((b,), jnp.uint32), sdt((b,), jnp.uint32),
                    sdt((mw,), jnp.uint32), u32, i32)
            out = jax.eval_shape(point_read._bloom_probe_fused, *args)
            text = lowering_text(point_read._bloom_probe_fused, args, {})
            bucket = {"b": b, "m_words": mw}
            entries.append({
                "key": "bloom_probe " + entry_key(bucket),
                "bucket": bucket,
                "static_args": {},
                "in_avals": [_aval_str(a) for a in args],
                "out_avals": [_aval_str(o) for o in
                              jax.tree_util.tree_leaves(out)],
                "donation": None,
                "variant_axes": {},
                "executables": 1,
                "prewarmed": True,
                "quarantine_key": None,
                "lowering_sha256": _lowering_sha256(text),
            })
    return {"entries": entries}


def _gen_point_read_locate() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import point_read
    from yugabyte_tpu.storage.learned_index import LINDEX_SEGMENTS
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    sdt = jax.ShapeDtypeStruct
    i32 = sdt((), jnp.int32)
    u32 = sdt((), jnp.uint32)
    for b in point_read.BATCH_BUCKETS:
        for w in point_read._PREWARM_WIDTHS:
            for n_pad in point_read._PREWARM_NPADS:
                for use_model in (False, True):
                    args = (sdt((8 + w, n_pad), jnp.uint32), i32,
                            sdt((b, w), jnp.uint32), sdt((b,), jnp.int32),
                            u32, u32,
                            sdt((LINDEX_SEGMENTS + 1,), jnp.uint32),
                            sdt((LINDEX_SEGMENTS + 1,), jnp.uint32),
                            sdt((LINDEX_SEGMENTS + 1,), jnp.int32),
                            i32, i32)
                    statics = dict(w=w, use_model=use_model)
                    out = jax.eval_shape(
                        lambda *a: point_read._locate_gather_fused(
                            *a, **statics), *args)
                    text = lowering_text(point_read._locate_gather_fused,
                                         args, statics)
                    bucket = {"b": b, "n_pad": n_pad, "w": w}
                    impl = "model" if use_model else "exact"
                    entries.append({
                        "key": "locate_gather " + entry_key(bucket, impl),
                        "bucket": bucket,
                        "impl": impl,
                        "static_args": statics,
                        "in_avals": [_aval_str(a) for a in args],
                        "out_avals": [_aval_str(o) for o in
                                      jax.tree_util.tree_leaves(out)],
                        # inputs are LIVE slab-cache entries: donation is
                        # forbidden by design (the cache must survive)
                        "donation": None,
                        "variant_axes": {},
                        "executables": 1,
                        "prewarmed": True,
                        "quarantine_key": [1, n_pad],
                        "lowering_sha256": _lowering_sha256(text),
                    })
    return {"entries": entries}


def _gen_index_fit() -> dict:
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import point_read
    from yugabyte_tpu.storage.learned_index import LINDEX_SEGMENTS
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    sdt = jax.ShapeDtypeStruct
    i32 = sdt((), jnp.int32)
    for w in point_read._PREWARM_WIDTHS:
        for n_pad in point_read._PREWARM_NPADS:
            args = (sdt((8 + w, n_pad), jnp.uint32), i32)
            statics = dict(n_segments=LINDEX_SEGMENTS, w=w)
            out = jax.eval_shape(
                lambda *a: point_read._index_fit_fused(*a, **statics),
                *args)
            text = lowering_text(point_read._index_fit_fused, args,
                                 statics)
            bucket = {"n_pad": n_pad, "w": w}
            entries.append({
                "key": "index_fit " + entry_key(bucket),
                "bucket": bucket,
                "static_args": statics,
                "in_avals": [_aval_str(a) for a in args],
                "out_avals": [_aval_str(o) for o in
                              jax.tree_util.tree_leaves(out)],
                "donation": None,
                "variant_axes": {},
                "executables": 1,
                "prewarmed": True,
                "quarantine_key": None,
                "lowering_sha256": _lowering_sha256(text),
            })
    return {"entries": entries}


def _gen_block_decode() -> dict:
    """Device block-codec decode lattice: the _PREWARM_DECODE (n_pad,
    w_pad) points.  Shapes-only compile keys (no static args — the
    gather-free program is keyed by its padded column shapes alone)."""
    import jax
    from yugabyte_tpu.ops import block_codec
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    for n_pad, w_pad in sorted(block_codec._PREWARM_DECODE):
        args = block_codec.decode_avals(n_pad, w_pad)
        out = jax.eval_shape(block_codec._block_decode_fused, *args)
        text = lowering_text(block_codec._block_decode_fused, args, {})
        bucket = {"n_pad": n_pad, "w": w_pad}
        entries.append({
            "key": "block_decode " + entry_key(bucket),
            "bucket": bucket,
            "static_args": {},
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            # the raw-word upload is TRANSIENT (values were sliced host-
            # side before the upload), so the donated twin reuses its HBM
            # for the cols output on capable backends
            "donation": {"donate_argnums": [0], "variants": 2},
            "variant_axes": {"donate": 2},
            "executables": 2,
            "prewarmed": True,
            "quarantine_key": [1, n_pad],
            "lowering_sha256": _lowering_sha256(text),
        })
    return {"entries": entries}


def _gen_block_encode() -> dict:
    """Device block-codec encode lattice: one shapes-only program per
    span-gather bucket (_PREWARM_DECODE mirrors the span n_out_pad
    vocabulary); NEVER donated — the same span cols install into the
    slab cache after the SST hits disk."""
    import jax
    import jax.numpy as jnp
    from yugabyte_tpu.ops import block_codec
    from yugabyte_tpu.utils.jax_setup import lowering_text

    entries = []
    sdt = jax.ShapeDtypeStruct
    for n_pad, w_pad in sorted(block_codec._PREWARM_DECODE):
        args = (sdt((_ROW_WORDS + w_pad, n_pad), jnp.uint32),)
        out = jax.eval_shape(block_codec._block_encode_fused, *args)
        text = lowering_text(block_codec._block_encode_fused, args, {})
        bucket = {"n_pad": n_pad, "w": w_pad}
        entries.append({
            "key": "block_encode " + entry_key(bucket),
            "bucket": bucket,
            "static_args": {},
            "in_avals": [_aval_str(a) for a in args],
            "out_avals": [_aval_str(o) for o in
                          jax.tree_util.tree_leaves(out)],
            "donation": None,
            "variant_axes": {},
            "executables": 1,
            "prewarmed": True,
            "quarantine_key": [1, n_pad],
            "lowering_sha256": _lowering_sha256(text),
        })
    return {"entries": entries}


def _gen_dist_compact() -> dict:
    # shard_map needs a real mesh, so these entries are declared (no
    # lowering fingerprint, like pallas_merge) against the 8-device
    # bench mesh: capacity is quantized to a power of two in
    # distributed_compact before the lru_cache key, and
    # prewarm_dist_compact warms exactly this lattice on the server's
    # actual mesh. Drift is caught by the source fingerprint.
    from yugabyte_tpu.parallel import dist_compact as dist_mod

    n_shards = 8
    entries = []
    for capacity in sorted(dist_mod._PREWARM_CAPACITIES):
        bucket = {"capacity": capacity, "n_shards": n_shards}
        entries.append({
            "key": "dist_compact " + entry_key(bucket),
            "bucket": bucket,
            "static_args": {"capacity": capacity,
                            "retain_deletes": False},
            "in_avals": None,   # mesh-dependent; see compile_keys
            "out_avals": None,
            # the no-retry twin donates the sharded input cols so XLA
            # reuses their HBM for the exchange scratch
            "donation": {"donate_argnums": [0], "variants": 2},
            "variant_axes": {"is_major": 2, "donate": 2},
            "executables": 4,
            "prewarmed": True,
            "quarantine_key": [n_shards, capacity],
            "lowering_sha256": None,
        })
    for (k_pad, m, w, n_cmp) in sorted(dist_mod._PREWARM_POOL_SHAPES):
        bucket = {"k_pad": k_pad, "m": m, "n_cmp": n_cmp,
                  "slots": n_shards, "w": w}
        entries.append({
            "key": "pool_wave " + entry_key(bucket),
            "bucket": bucket,
            "static_args": {"k_pad": k_pad, "m": m, "w": w,
                            "n_cmp": n_cmp, "retain_deletes": False},
            "in_avals": None,
            "out_avals": None,
            # wave inputs may be live cache-partition entries: the wave
            # program never donates
            "donation": None,
            "variant_axes": {"is_major": 2},
            "executables": 2,
            "prewarmed": True,
            "quarantine_key": [k_pad, m],
            "lowering_sha256": None,
        })
    return {
        "entries": entries,
        "compile_keys": {
            "capacity": "power-of-two >= 64 (quantized in "
                        "distributed_compact before the lru_cache key)",
            "n_shards": "mesh-determined (8-device bench mesh declared)",
            "is_major": [True, False],
            "retain_deletes": [False],
        },
    }


_GENERATORS = {
    "run_merge_fused": _gen_run_merge_fused,
    "merge_gc_fused": _gen_merge_gc_fused,
    "scan_fused": _gen_scan_fused,
    "scan_filtered": _gen_scan_filtered,
    "scan_agg": _gen_scan_agg,
    "gather_staged": _gen_gather_staged,
    "restage_concat": _gen_restage_concat,
    "pallas_merge": _gen_pallas_merge,
    "chunk_carve": _gen_chunk_carve,
    "point_read_probe": _gen_point_read_probe,
    "point_read_locate": _gen_point_read_locate,
    "index_fit": _gen_index_fit,
    "block_decode": _gen_block_decode,
    "block_encode": _gen_block_encode,
    "dist_compact": _gen_dist_compact,
}


def generate(root: str = REPO_ROOT) -> dict:
    """Regenerate the full manifest (imports jax; run under
    JAX_PLATFORMS=cpu — eval_shape/lower only, nothing executes)."""
    import jax
    if jax.default_backend() != "cpu":
        raise RuntimeError(
            "kernel_manifest.generate must run device-free: set "
            "JAX_PLATFORMS=cpu (the committed fingerprints are the CPU "
            f"lowering), got backend {jax.default_backend()!r}")
    families = {}
    for name, spec in FAMILIES.items():
        rec = _GENERATORS[name]()
        entries = rec.get("entries", [])
        rec.update({
            "source_fingerprint": source_fingerprint(name, root),
            "budget": spec["budget"],
            "distinct_executables": (
                sum(e["executables"] for e in entries)
                if entries else None),
        })
        for e in entries:
            errs = bucket_lattice_errors(e.get("bucket", {}))
            if errs:
                raise RuntimeError(
                    f"declared bucket off the lattice in {name}: "
                    f"{e['key']}: {'; '.join(errs)}")
        n = rec["distinct_executables"]
        if spec["budget"] is not None and n is not None \
                and n > spec["budget"]:
            raise RuntimeError(
                f"compile-surface budget exceeded for {name}: {n} "
                f"declared executables > budget {spec['budget']} — "
                "shrink the lattice or raise the budget in "
                "tools/analysis/kernel_manifest.py (a reviewed decision)")
        families[name] = rec
    return {
        "version": 1,
        "platform": "cpu",
        "jax_version": jax.__version__,
        "families": families,
    }


def manifest_bytes(manifest: dict) -> bytes:
    return (json.dumps(manifest, indent=1, sort_keys=True) + "\n").encode()


def surface_counts(manifest: Optional[dict] = None) -> Dict[str, int]:
    """family -> distinct-executable count from the committed manifest
    (0 for fingerprint-only families); used by the bench report and the
    kernel_compile_surface gauges."""
    if manifest is None:
        manifest = load_manifest()
    out: Dict[str, int] = {}
    if not manifest:
        return out
    for name, rec in sorted(manifest.get("families", {}).items()):
        out[name] = int(rec.get("distinct_executables") or 0)
    return out


def quarantine_surface_keys(manifest: Optional[dict] = None
                            ) -> List[Tuple[int, int]]:
    """The (k_pad, m) offload-policy quarantine keys of every declared
    bucket — the shape vocabulary storage/offload_policy.py speaks."""
    if manifest is None:
        manifest = load_manifest()
    keys = set()
    if manifest:
        for rec in manifest.get("families", {}).values():
            for e in rec.get("entries", ()):
                qk = e.get("quarantine_key")
                if qk:
                    keys.add((int(qk[0]), int(qk[1])))
    return sorted(keys)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import sys
    import time

    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis.kernel_manifest",
        description="kernel compile-surface manifest: fast drift check / "
                    "device-free regeneration")
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--check", action="store_true",
                   help="fast source-fingerprint + budget check against "
                        "the committed JSON (no jax import; < 5s)")
    g.add_argument("--verify", action="store_true",
                   help="regenerate in memory (JAX_PLATFORMS=cpu, "
                        "eval_shape/lower only) and byte-compare with "
                        "the committed JSON")
    g.add_argument("--write", action="store_true",
                   help="regenerate and write the committed JSON")
    ap.add_argument("--path", default=MANIFEST_PATH)
    args = ap.parse_args(argv)

    if args.check:
        t0 = time.monotonic()
        problems = check_manifest(load_manifest(args.path))
        for fam, code, msg in problems:
            print(f"[{fam}/{code}] {msg}", file=sys.stderr)
        dt = time.monotonic() - t0
        print(f"kernel_manifest --check: {len(problems)} problem(s) "
              f"in {dt:.2f}s")
        return 1 if problems else 0

    # --verify / --write import jax: force the device-free CPU backend
    # BEFORE the first jax import so nothing touches an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = __import__("time").monotonic()
    manifest = generate()
    data = manifest_bytes(manifest)
    dt = __import__("time").monotonic() - t0
    if args.write:
        with open(args.path, "wb") as fh:
            fh.write(data)
        print(f"wrote {args.path} ({len(data)} bytes) in {dt:.1f}s")
        return 0
    try:
        with open(args.path, "rb") as fh:
            committed = fh.read()
    except OSError:
        committed = b""
    if committed != data:
        print("kernel_manifest --verify: regenerated manifest differs "
              f"from {args.path} — run --write, review the surface "
              "diff, and commit it", file=sys.stderr)
        return 1
    print(f"kernel_manifest --verify: byte-identical ({dt:.1f}s)")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
