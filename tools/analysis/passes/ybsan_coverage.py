"""ybsan-coverage: every concurrent class opts into the sanitizer.

The race detector (tools/sanitizer) can only check state it knows
about: attributes named by a `# guarded-by:` annotation (auto-patched
at arm time) or declared via `@ybsan.shadow(...)` (stated lock-free
discipline). A class that spawns threads or shares threadpool state
with NEITHER is invisible to the armed run — its races simply cannot
be caught, which is exactly the gap this pass closes.

A ClassDef is flagged (`unsanitized-shared-state`) when its body:

  - constructs a thread        (`threading.Thread(...)` / `Thread(...)`),
  - constructs a shared pool   (`PriorityThreadPool(...)`), or
  - submits work to a pool     (`<x>.submit(...)`),

and the class carries neither a `# guarded-by:` annotation anywhere in
its body nor an `@ybsan.shadow(...)` decorator.

Satisfying the pass is a real commitment, not a checkbox: a new
`# guarded-by:` annotation is immediately enforced lexically by the
lock-discipline pass AND dynamically by ybsan; a new `@ybsan.shadow`
discipline is enforced on every armed run. A class whose shared state
is genuinely out of scope (e.g. it only hands off immutable payloads)
suppresses with `# yblint: disable=ybsan-coverage` on the class line
plus a trailing justification, or a justified baseline entry.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analysis.core import AnalysisPass, FileContext, Finding
from tools.analysis.passes.lock_discipline import _GUARDED_RE

PASS_NAME = "ybsan-coverage"

DEFAULT_DIRS = ("yugabyte_tpu",)

_THREAD_CTORS = {"Thread", "Timer"}
_POOL_CTORS = {"PriorityThreadPool", "ThreadPoolExecutor"}


def _call_trigger(node: ast.Call) -> Optional[str]:
    """Why this call makes the enclosing class concurrent, or None."""
    f = node.func
    if isinstance(f, ast.Name):
        if f.id in _THREAD_CTORS:
            return f"spawns a thread ({f.id}(...))"
        if f.id in _POOL_CTORS:
            return f"owns a thread pool ({f.id}(...))"
    elif isinstance(f, ast.Attribute):
        if f.attr in _THREAD_CTORS and isinstance(f.value, ast.Name) \
                and f.value.id == "threading":
            return f"spawns a thread (threading.{f.attr}(...))"
        if f.attr in _POOL_CTORS:
            return f"owns a thread pool ({f.attr}(...))"
        if f.attr == "submit":
            return "shares threadpool state (.submit(...))"
    return None


class YbsanCoveragePass(AnalysisPass):
    name = PASS_NAME

    def __init__(self, dirs=DEFAULT_DIRS):
        self.dirs = tuple(d.rstrip("/") + "/" for d in dirs)

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.dirs)

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        # innermost enclosing class per concurrent call site
        triggers: dict = {}  # id(ClassDef) -> (ClassDef, trigger, line)
        for node in ctx.nodes_of(ast.Call):
            why = _call_trigger(node)
            if why is None:
                continue
            for anc in ctx.ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    triggers.setdefault(id(anc), (anc, why, node.lineno))
                    break
        for cls, why, line in triggers.values():
            if self._has_shadow_decorator(cls):
                continue
            if self._has_guard_annotation(ctx, cls):
                continue
            out.append(ctx.finding(
                self.name, "unsanitized-shared-state", cls,
                f"class {cls.name} {why} at line {line} but declares no "
                f"`# guarded-by:` attribute and no @ybsan.shadow "
                f"discipline — its shared state is invisible to the "
                f"armed race sanitizer"))
        return out

    @staticmethod
    def _has_shadow_decorator(cls: ast.ClassDef) -> bool:
        for dec in cls.decorator_list:
            f = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(f, ast.Attribute) and f.attr == "shadow":
                return True
            if isinstance(f, ast.Name) and f.id == "shadow":
                return True
        return False

    @staticmethod
    def _has_guard_annotation(ctx: FileContext, cls: ast.ClassDef) -> bool:
        end = getattr(cls, "end_lineno", None) or cls.lineno
        for lineno in range(cls.lineno, end + 1):
            if _GUARDED_RE.search(ctx.line_text(lineno)):
                return True
        return False
