"""Batched device point-read kernels: bloom probe + block locate + gather.

ROADMAP item 4: TPU sequential scan runs at 12.4M rows/s while point reads
do ~87k/s, because every `DB.get` walks the block index in host Python one
key at a time — even though the key columns it searches increasingly sit in
HBM already (the device-resident slab cache, storage/device_cache.py).
This module batches the SST half of a point read into three fused device
programs over a padded key batch:

  1. `_fnv64_fused` — FNV-1a over the doc-key prefix of every query, in
     two uint32 limbs (int64 is avoided on device, like the hybrid-time
     limbs in ops/merge_gc.py). The exact twin of
     `storage/bloom.fnv64_masked`, which that module documents as the CPU
     path of this kernel.
  2. `_bloom_probe_fused` — double-hashed probe of one SST's bloom bits
     for the whole batch (ref: the reference's bloom-before-seek,
     rocksdb/table/block_based_table_reader.cc:1144): an SST none of the
     batch's keys can hit never pays a locate dispatch.
  3. `_locate_gather_fused` — vectorized binary seek over the RESIDENT
     staged column matrix (ops/merge_gc.StagedCols): for each query, the
     first entry in internal-key order with key == q and ht <= read_ht
     (the newest visible version — `DB.get`'s seek semantics), gathered
     with its (ht, wid) so the host only decodes the winner's block for
     value bytes. Optionally seeded by a learned per-SST index.

Learned per-SST index ("A Pragmatic Approach to Learned Indexing in
RocksDB", PAPERS.md): a tiny piecewise-linear model over the first 8 key
bytes, fit at flush/compaction time — `_index_fit_fused` runs over the
staged columns when they are already in HBM for free; the numpy twin in
storage/learned_index.py covers host-written SSTs. The model only narrows
the search window (static `_LG_WINDOW` steps instead of log2(n_pad)); a
misprediction beyond the recorded error bound is DETECTED by the binary-
search invariant check and the key falls back to the exact per-key path —
correctness never depends on the model.

Shapes bucket like every other kernel family: batches pad to
`BATCH_BUCKETS`, widths are `quantize_width` points, matrices are
`bucket_size` lattices — all registered in the compile-surface manifest
(tools/analysis/kernel_manifest.json) under the PR 7 budget/prewarm
discipline.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_tpu.ops.merge_gc import (
    _ROW_HT_HI, _ROW_HT_LO, _ROW_KEY_LEN, _ROW_WID, _ROW_WORDS, StagedCols,
    bucket_size)
from yugabyte_tpu.utils import jax_setup  # noqa: F401  (compilation cache)

# Learned-index lattice: segment count is a single static (the anchors
# array shape), and the error bound must fit the fixed window search —
# 2*err+1 candidate positions resolved in _LG_WINDOW halvings. The
# canonical constants live in storage/learned_index.py (jax-free, every
# flush imports it); the assert pins the window/bound lock-step.
from yugabyte_tpu.storage.learned_index import (  # noqa: E402
    LINDEX_MAX_ERR, LINDEX_MIN_ENTRIES, LINDEX_SEGMENTS)

_LG_WINDOW = 15
assert LINDEX_MAX_ERR == (1 << (_LG_WINDOW - 1)) - 2

_K_MAX = 12                 # BloomFilterBuilder clamps k to [1, 12]
# the u32 probe arithmetic needs i*(h2 % m) < 2^32 for i < _K_MAX
BLOOM_PROBE_MAX_BITS = 1 << 28

BATCH_BUCKETS = (64, 1024)


def batch_bucket(n: int) -> int:
    """Padded batch size: the two-point lattice keeps the compile surface
    at two executables per (kernel, shape) instead of one per batch."""
    return BATCH_BUCKETS[0] if n <= BATCH_BUCKETS[0] else BATCH_BUCKETS[1]


def point_read_metrics():
    """Process-wide batched-read observability (satellite: batch size
    histogram, learned-index hit/fallback counters, device fallbacks)."""
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    e = ROOT_REGISTRY.entity("server", "point_read")
    return {
        "batches": e.counter(
            "point_read_batches_total",
            "multi_get batches resolved through the device kernels"),
        "keys": e.counter(
            "point_read_batched_keys_total",
            "keys resolved through the batched device path"),
        "batch_rows": e.histogram(
            "point_read_batch_rows",
            "multi_get batch sizes reaching the device path"),
        "bloom_skips": e.counter(
            "point_read_bloom_skipped_sst_total",
            "per-SST locate dispatches skipped because the bloom probe "
            "rejected every key in the batch"),
        "learned_hits": e.counter(
            "point_read_learned_hit_total",
            "locate dispatches that used a learned per-SST index"),
        "learned_fallbacks": e.counter(
            "point_read_learned_fallback_total",
            "keys re-resolved exactly after a learned-index "
            "misprediction beyond the recorded error bound"),
        "device_fallbacks": e.counter(
            "point_read_device_fallback_total",
            "multi_get batches completed via the native per-key path "
            "after a device fault"),
        "max_error": e.gauge(
            "learned_index_max_error_rows",
            "recorded max-error bound (entry positions) of the most "
            "recently fitted learned per-SST index"),
    }


# ---------------------------------------------------------------------------
# FNV-1a in two uint32 limbs (exact twin of storage/bloom.fnv64_masked)
# ---------------------------------------------------------------------------

_FNV_OFFSET_HI = 0xCBF29CE4
_FNV_OFFSET_LO = 0x84222325
# FNV prime 0x100000001B3 = 2^40 + 0x1B3; the multiply below decomposes
# h*P mod 2^64 into shift/add limbs so no intermediate needs 64 bits
_FNV_PRIME_LOW = 0x1B3


def _mul64_by_prime(hi, lo):
    """(hi, lo) * 0x100000001B3 mod 2^64, in uint32 limb arithmetic.

    h*P = h*2^40 + h*0x1B3 (mod 2^64):
      h*2^40 contributes (lo << 8) to the high limb (everything above
      2^64 drops); h*0x1B3 is computed via a 16-bit split of `lo` so no
      partial product exceeds 2^25.
    """
    p = jnp.uint32(_FNV_PRIME_LOW)
    a = lo >> jnp.uint32(16)
    b = lo & jnp.uint32(0xFFFF)
    t = a * p                      # < 2^25
    u = b * p                      # < 2^25
    s1 = t << jnp.uint32(16)       # == (t & 0xFFFF) << 16 (wrapping)
    new_lo = s1 + u                # wrapping u32
    carry = (new_lo < s1).astype(jnp.uint32)
    new_hi = ((lo << jnp.uint32(8)) + hi * p
              + (t >> jnp.uint32(16)) + carry)
    return new_hi, new_lo


@functools.partial(jax.jit, static_argnames=("w",))
def _fnv64_fused(qwords, qlens, w: int):
    """FNV-1a over the first qlens[i] bytes of each query key.

    qwords: uint32 [B, w] big-endian packed key words (ops/slabs.py
    layout); qlens: int32 [B]. Returns (h1, h2) uint32 [B]: the double-
    hash pair the bloom builder/prober derive from the 64-bit hash
    (h1 = low word, h2 = high word | 1)."""
    b = qwords.shape[0]
    hi = jnp.full((b,), jnp.uint32(_FNV_OFFSET_HI))
    lo = jnp.full((b,), jnp.uint32(_FNV_OFFSET_LO))
    for j in range(w * 4):
        word = qwords[:, j // 4]
        byte = (word >> jnp.uint32(8 * (3 - (j % 4)))) & jnp.uint32(0xFF)
        active = qlens > j
        nhi, nlo = _mul64_by_prime(hi, lo ^ byte)
        hi = jnp.where(active, nhi, hi)
        lo = jnp.where(active, nlo, lo)
    return lo, hi | jnp.uint32(1)


@jax.jit
def _bloom_probe_fused(h1, h2, bloom_words, m_bits, k):
    """Double-hashed bloom probe of one SST for a whole key batch.

    h1/h2: uint32 [B]; bloom_words: uint32 [m_words_pad] little-endian
    bit words (the builder's byte layout viewed as '<u4'); m_bits uint32
    scalar (true filter size — padding words are never addressed);
    k int32 scalar. Position arithmetic matches the uint64 CPU path via
    modular identities: (h1 + i*h2) % m == ((h1%m) + (i*(h2%m)) % m) % m,
    every intermediate < 2^32 while m < BLOOM_PROBE_MAX_BITS."""
    m = m_bits
    h1m = h1 % m
    h2m = h2 % m
    ok = jnp.ones(h1.shape, bool)
    for i in range(_K_MAX):
        pos = (h1m + (jnp.uint32(i) * h2m) % m) % m
        word = bloom_words[pos >> jnp.uint32(5)]
        bit = (word >> (pos & jnp.uint32(31))) & jnp.uint32(1)
        ok = ok & ((bit == jnp.uint32(1)) | (jnp.int32(i) >= k))
    return ok


# ---------------------------------------------------------------------------
# Learned-index prediction (shared by fit and inference so the recorded
# error bound is measured with the inference arithmetic)
#
# The key coordinate is the two uint32 words at the file's common-prefix
# word offset p (word-aligned prefix skip: tablets share long key
# prefixes, and a coordinate that starts inside the shared bytes would
# collapse every key onto a handful of values). Anchors persist as EXACT
# uint32 limb pairs — segment selection and the (x - a0) differences are
# integer-exact; float32 enters only for the final interpolation of a
# difference, whose relative error is absorbed by the measured bound.
# ---------------------------------------------------------------------------

def _sub64(x_hi, x_lo, y_hi, y_lo):
    """(x - y) as two uint32 limbs (callers guarantee x >= y or mask)."""
    lo = x_lo - y_lo
    borrow = (x_lo < y_lo).astype(jnp.uint32)
    return x_hi - y_hi - borrow, lo


def _f64ish(hi, lo):
    """float32 value of a two-limb difference (exact compares happened
    already; only the interpolation ratio rides this)."""
    return (hi.astype(jnp.float32) * jnp.float32(4294967296.0)
            + lo.astype(jnp.float32))


def _ge64(x_hi, x_lo, y_hi, y_lo):
    return (x_hi > y_hi) | ((x_hi == y_hi) & (x_lo >= y_lo))


def _predict_pos(x_hi, x_lo, a_hi, a_lo, anchor_pos):
    """Piecewise-linear position prediction from exact two-limb anchors.
    a_hi/a_lo: uint32 [S+1] anchor coordinates at anchor_pos (int32
    [S+1], positions 0..n-1). Returns float32 predictions."""
    s = a_hi.shape[0] - 1
    seg = jnp.zeros(x_hi.shape, jnp.int32)
    for i in range(1, s):
        seg = seg + _ge64(x_hi, x_lo, a_hi[i], a_lo[i]).astype(jnp.int32)
    a0h, a0l = a_hi[seg], a_lo[seg]
    a1h, a1l = a_hi[seg + 1], a_lo[seg + 1]
    p0 = anchor_pos[seg].astype(jnp.float32)
    p1 = anchor_pos[seg + 1].astype(jnp.float32)
    ge0 = _ge64(x_hi, x_lo, a0h, a0l)
    dx = _f64ish(*_sub64(x_hi, x_lo, a0h, a0l))
    da = _f64ish(*_sub64(a1h, a1l, a0h, a0l))
    t = jnp.where(ge0 & (da > 0), dx / jnp.where(da > 0, da,
                                                 jnp.float32(1.0)),
                  jnp.float32(0.0))
    t = jnp.clip(t, 0.0, 1.0)
    return p0 + t * (p1 - p0)


def _x_words(words_by_row, p, w: int):
    """The coordinate limbs: key words p and p+1, p clamped to [0, w-2].
    words_by_row: callable j -> the j-th key-word vector (rows of a cols
    matrix or columns of a query batch)."""
    pp = jnp.clip(p, 0, w - 2)
    stacked_hi = jnp.stack([words_by_row(j) for j in range(w)])
    x_hi = jnp.take(stacked_hi, pp, axis=0)
    x_lo = jnp.take(stacked_hi, pp + 1, axis=0)
    return x_hi, x_lo


@functools.partial(jax.jit, static_argnames=("n_segments", "w"))
def _index_fit_fused(cols, n, n_segments: int, w: int):
    """Fit the per-SST model over an already-staged (sorted) cols matrix
    — the flush/compaction write-through path, where the sorted key
    columns are in HBM for free. Computes the prefix-skip offset p from
    the first/last entry in-kernel (no D2H), gathers exact anchor limbs,
    and measures max_err by predicting every real entry with the
    inference arithmetic — the bound is self-consistent by construction.
    Returns (a_hi u32 [S+1], a_lo u32 [S+1], p i32, max_err i32)."""
    from yugabyte_tpu.storage.learned_index import LINDEX_MAX_P
    n_pad = cols.shape[1]
    last = jnp.clip(n - 1, 0, n_pad - 1)
    # leading key words shared by the first and last entry — by
    # sortedness, shared by every entry in between. Capped at
    # LINDEX_MAX_P so the model depends only on the first 16 key bytes
    # (byte-identical to the host twins regardless of staged width).
    run = jnp.int32(1)
    p = jnp.int32(0)
    for j in range(min(w - 2, LINDEX_MAX_P)):
        eqj = (cols[_ROW_WORDS + j, 0]
               == cols[_ROW_WORDS + j, last]).astype(jnp.int32)
        run = run * eqj
        p = p + run
    x_hi, x_lo = _x_words(lambda j: cols[_ROW_WORDS + j], p, w)
    anchor_pos = (jnp.arange(n_segments + 1, dtype=jnp.int32)
                  * (n - jnp.int32(1))) // jnp.int32(n_segments)
    a_hi = x_hi[anchor_pos]
    a_lo = x_lo[anchor_pos]
    pred = _predict_pos(x_hi, x_lo, a_hi, a_lo, anchor_pos)
    idx = jnp.arange(n_pad, dtype=jnp.int32)
    err = jnp.abs(jnp.round(pred).astype(jnp.int32) - idx)
    max_err = jnp.max(jnp.where(idx < n, err, 0))
    return a_hi, a_lo, p, max_err


# ---------------------------------------------------------------------------
# Locate + gather
# ---------------------------------------------------------------------------

def _seek_pred(cols, i, n, qwords, qlens_u, rhi, rlo, w: int):
    """P(i) [B]: entry i is at-or-after query's seek point — key_i > q,
    or key_i == q with ht_i <= read_ht (versions sort HT-descending, so
    the FIRST true position is the newest visible version). Padding
    columns (all-0xFF words, sentinel len) evaluate key > q. P(n) := True."""
    ii = jnp.clip(i, 0, cols.shape[1] - 1)
    gt = jnp.zeros(i.shape, bool)
    eq = jnp.ones(i.shape, bool)
    for j in range(w):
        c = cols[_ROW_WORDS + j][ii]
        gt = gt | (eq & (c > qwords[:, j]))
        eq = eq & (c == qwords[:, j])
    klen = cols[_ROW_KEY_LEN][ii]
    gt = gt | (eq & (klen > qlens_u))
    eq = eq & (klen == qlens_u)
    ht_hi = cols[_ROW_HT_HI][ii]
    ht_lo = cols[_ROW_HT_LO][ii]
    le = (ht_hi < rhi) | ((ht_hi == rhi) & (ht_lo <= rlo))
    return jnp.where(i >= n, True, gt | (eq & le))


@functools.partial(jax.jit, static_argnames=("w", "use_model"))
def _locate_gather_fused(cols, n, qwords, qlens, rhi, rlo,
                         a_hi, a_lo, anchor_pos, p, max_err,
                         w: int, use_model: bool):
    """Batched point locate over one staged SST + survivor field gather.

    cols: uint32 [8+w, n_pad] resident slab matrix (sorted); n: int32
    real-entry count; qwords/qlens: the padded query batch; rhi/rlo: the
    read_ht limbs; a_hi/a_lo/anchor_pos/p/max_err: learned-index
    operands (ignored when use_model=False — the exact full seek runs).

    Returns (idx, hit, ht_hi, ht_lo, wid, miss) over [B]: idx is the
    seek position; hit means an exact key match visible at read_ht (its
    ht/wid gathered); miss flags a learned-index misprediction the
    binary-search invariant check caught — the caller must re-resolve
    those keys exactly (correctness never rides the model)."""
    n_pad = cols.shape[1]
    b = qwords.shape[0]
    qlens_u = qlens.astype(jnp.uint32)

    def pred(i):
        return _seek_pred(cols, i, n, qwords, qlens_u, rhi, rlo, w)

    if use_model:
        x_hi, x_lo = _x_words(lambda j: qwords[:, j], p, w)
        pi = jnp.round(_predict_pos(x_hi, x_lo, a_hi, a_lo, anchor_pos)
                       ).astype(jnp.int32)
        lo = jnp.clip(pi - max_err, 0, n)
        hi = jnp.clip(pi + max_err + jnp.int32(1), 0, n)
        steps = _LG_WINDOW
    else:
        lo = jnp.zeros((b,), jnp.int32)
        hi = jnp.zeros((b,), jnp.int32) + n
        steps = int(n_pad).bit_length()
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) >> 1
        p = pred(mid)
        lo = jnp.where(active & ~p, mid + jnp.int32(1), lo)
        hi = jnp.where(active & p, mid, hi)
    r = lo
    # binary-search invariant: the true seek point satisfies
    # (r == 0 or not P(r-1)) and (r == n or P(r)); a learned window that
    # excluded the answer fails one side and flags the key for exact
    # re-resolution. In exact mode the invariant holds by construction.
    if use_model:
        ok_left = (r == 0) | ~pred(jnp.maximum(r - 1, 0))
        ok_right = (r >= n) | pred(r)
        miss = ~(ok_left & ok_right)
    else:
        miss = jnp.zeros((b,), bool)
    rr = jnp.clip(r, 0, n_pad - 1)
    eq = jnp.ones((b,), bool)
    for j in range(w):
        eq = eq & (cols[_ROW_WORDS + j][rr] == qwords[:, j])
    eq = eq & (cols[_ROW_KEY_LEN][rr] == qlens_u)
    ht_hi = cols[_ROW_HT_HI][rr]
    ht_lo = cols[_ROW_HT_LO][rr]
    le = (ht_hi < rhi) | ((ht_hi == rhi) & (ht_lo <= rlo))
    hit = (r < n) & eq & le & ~miss
    wid = cols[_ROW_WID][rr]
    return r, hit, ht_hi, ht_lo, wid, miss


# ---------------------------------------------------------------------------
# Host wrappers (padding, per-reader bloom residency, dispatch metrics,
# device-fault injection sites)
# ---------------------------------------------------------------------------

def pack_query_batch(keys: Sequence[bytes], w: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a key batch to (batch_bucket(B), w) uint32 words + int32 lens.
    Keys longer than w*4 bytes are truncated in the word matrix but keep
    their true length, so the exact-match compare can never accept them
    (no entry of a w-wide SST has key_len > w*4)."""
    from yugabyte_tpu.ops.slabs import _pad_keys_to_words
    b_pad = batch_bucket(len(keys))
    clipped = [k[: w * 4] for k in keys]
    words, _lens = _pad_keys_to_words(clipped, width_words=w)
    out_w = np.zeros((b_pad, w), dtype=np.uint32)
    out_w[: len(keys)] = words
    out_l = np.zeros(b_pad, dtype=np.int32)
    out_l[: len(keys)] = [len(k) for k in keys]
    return out_w, out_l


def bloom_device_words(reader, device=None):
    """The SST's bloom bit array as a padded device uint32 vector, cached
    on the reader for its lifetime (blooms are ~1.25 bytes/key — tiny
    next to the staged key columns). Returns (words_dev, m_bits, k), or
    None when the filter is too large for the u32 probe arithmetic."""
    cached = getattr(reader, "_bloom_dev", None)
    if cached is not None:
        return cached
    bloom = reader.bloom
    if bloom.m_bits >= BLOOM_PROBE_MAX_BITS or bloom.m_bits == 0:
        return None
    words = np.frombuffer(bloom.bits.tobytes(), dtype="<u4")
    n_pad = bucket_size(len(words))
    padded = np.zeros(n_pad, dtype=np.uint32)
    padded[: len(words)] = words
    dev = (jax.device_put(padded, device) if device is not None
           else jnp.asarray(padded))
    reader._bloom_dev = (dev, int(bloom.m_bits), int(bloom.k))
    return reader._bloom_dev


def hash_batch(qwords: np.ndarray, dkls: np.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device FNV over the doc-key prefix of each padded query."""
    import time as _time
    from yugabyte_tpu.ops.run_merge import quantize_width
    from yugabyte_tpu.utils.metrics import record_kernel_dispatch
    t0 = _time.monotonic()
    # the batch is packed at a quantize_width point already; re-routing
    # the static through the quantizer keeps the lattice explicit
    h1, h2 = _fnv64_fused(jnp.asarray(qwords),
                          jnp.asarray(dkls, dtype=np.int32),
                          w=quantize_width(int(qwords.shape[1])))
    record_kernel_dispatch("kernel_point_hash", int(qwords.shape[0]),
                           int(qwords.shape[0]),
                           (_time.monotonic() - t0) * 1e3)
    return h1, h2


def probe_bloom(reader, h1, h2, device=None) -> Optional[np.ndarray]:
    """Probe one SST's bloom for the batch; None = no usable filter
    (treat every key as a maybe — the bloom is advisory)."""
    bd = bloom_device_words(reader, device)
    if bd is None:
        return None
    words, m_bits, k = bd
    ok = _bloom_probe_fused(h1, h2, words, jnp.uint32(m_bits),
                            jnp.int32(k))
    return np.asarray(ok)


def locate_batch(staged: StagedCols, qwords: np.ndarray,
                 qlens: np.ndarray, read_ht_value: int,
                 model_ops=None):
    """Run the locate+gather kernel over one staged SST.

    model_ops: (a_hi u32 [S+1], a_lo u32 [S+1], anchor_pos i32 [S+1],
    p int, max_err int) from storage/learned_index.model_operands, or
    None for the exact full binary seek. Returns numpy
    (idx, hit, ht_hi, ht_lo, wid, miss).
    """
    import time as _time
    from yugabyte_tpu.ops import device_faults
    from yugabyte_tpu.utils.metrics import record_kernel_dispatch
    b = int(qwords.shape[0])
    use_model = model_ops is not None
    if use_model:
        a_hi, a_lo, anchor_pos, p, max_err = model_ops
    else:
        a_hi = np.zeros(LINDEX_SEGMENTS + 1, dtype=np.uint32)
        a_lo = np.zeros(LINDEX_SEGMENTS + 1, dtype=np.uint32)
        anchor_pos = np.zeros(LINDEX_SEGMENTS + 1, dtype=np.int32)
        p = 0
        max_err = 0
    t0 = _time.monotonic()
    device_faults.maybe_fault("dispatch")
    out = _locate_gather_fused(
        staged.cols_dev, jnp.int32(staged.n), jnp.asarray(qwords),
        jnp.asarray(qlens, dtype=np.int32),
        jnp.uint32(read_ht_value >> 32),
        jnp.uint32(read_ht_value & 0xFFFFFFFF),
        jnp.asarray(a_hi), jnp.asarray(a_lo), jnp.asarray(anchor_pos),
        jnp.int32(p), jnp.int32(max_err), w=staged.w,
        use_model=use_model)
    device_faults.maybe_fault("result")
    idx, hit, ht_hi, ht_lo, wid, miss = (np.asarray(x) for x in out)
    record_kernel_dispatch("kernel_point_locate", b, b,
                           (_time.monotonic() - t0) * 1e3)
    return idx, hit, ht_hi, ht_lo, wid, miss


def fit_learned_index_device(staged: StagedCols) -> Optional[dict]:
    """Fit the learned index over an already-staged cols matrix (the
    device write-through path: compaction outputs' sorted keys are in
    HBM for free). Returns the persistable model dict, or None when the
    span is too small or the bound too loose to help."""
    from yugabyte_tpu.storage import learned_index
    if staged.n < LINDEX_MIN_ENTRIES or staged.w < 2:
        return None
    a_hi, a_lo, p, max_err = _index_fit_fused(
        staged.cols_dev, jnp.int32(staged.n),
        n_segments=LINDEX_SEGMENTS, w=staged.w)
    return learned_index.finish_model(np.asarray(a_hi), np.asarray(a_lo),
                                      int(np.asarray(p)),
                                      int(np.asarray(max_err)),
                                      staged.n)


# ---------------------------------------------------------------------------
# Prewarm (PrewarmKernelsOp folds this into the startup compile pass)
# ---------------------------------------------------------------------------

# (n_pad, w) lattice points the manifest declares for locate/fit; the
# probe/hash programs warm over (B, m_words) / (B, w) from the same sets
_PREWARM_NPADS = (1 << 16, 1 << 20)
_PREWARM_WIDTHS = (4, 8)
_PREWARM_MWORDS = (1 << 14, 1 << 18)


def prewarm_point_read() -> int:
    """Ahead-of-traffic compile of the declared point-read buckets
    (mirrors ops/run_merge.prewarm_buckets; called by PrewarmKernelsOp).
    Returns the number of executables compiled."""
    compiled = 0

    def _warm(what, lower_fn):
        nonlocal compiled
        try:
            lower_fn().compile()
            compiled += 1
        except Exception as e:  # noqa: BLE001 — prewarm must never block
            import sys as _sys                       # server startup
            print(f"[point_read] prewarm of {what} failed: {e!r}",
                  file=_sys.stderr, flush=True)

    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    u32 = jax.ShapeDtypeStruct((), jnp.uint32)
    sdt = jax.ShapeDtypeStruct
    for b in BATCH_BUCKETS:
        for w in _PREWARM_WIDTHS:
            _warm(f"fnv64 (B={b} w={w})",
                  lambda: _fnv64_fused.lower(
                      sdt((b, w), jnp.uint32), sdt((b,), jnp.int32), w=w))
        for mw in _PREWARM_MWORDS:
            _warm(f"bloom_probe (B={b} m_words={mw})",
                  lambda: _bloom_probe_fused.lower(
                      sdt((b,), jnp.uint32), sdt((b,), jnp.uint32),
                      sdt((mw,), jnp.uint32), u32, i32))
        for w in _PREWARM_WIDTHS:
            for n_pad in _PREWARM_NPADS:
                for use_model in (False, True):
                    _warm(f"locate (B={b} w={w} n_pad={n_pad} "
                          f"model={use_model})",
                          lambda: _locate_gather_fused.lower(
                              sdt((8 + w, n_pad), jnp.uint32), i32,
                              sdt((b, w), jnp.uint32),
                              sdt((b,), jnp.int32), u32, u32,
                              sdt((LINDEX_SEGMENTS + 1,), jnp.uint32),
                              sdt((LINDEX_SEGMENTS + 1,), jnp.uint32),
                              sdt((LINDEX_SEGMENTS + 1,), jnp.int32),
                              i32, i32, w=w, use_model=use_model))
    for w in _PREWARM_WIDTHS:
        for n_pad in _PREWARM_NPADS:
            _warm(f"index_fit (n_pad={n_pad} w={w})",
                  lambda: _index_fit_fused.lower(
                      sdt((8 + w, n_pad), jnp.uint32), i32,
                      n_segments=LINDEX_SEGMENTS, w=w))
    return compiled


def point_read_snapshot() -> dict:
    """Batched point-read block for /compactionz."""
    m = point_read_metrics()
    return {
        "batches": m["batches"].value(),
        "batched_keys": m["keys"].value(),
        "bloom_skipped_ssts": m["bloom_skips"].value(),
        "learned_index_hits": m["learned_hits"].value(),
        "learned_index_fallbacks": m["learned_fallbacks"].value(),
        "device_fallbacks": m["device_fallbacks"].value(),
        "learned_index_max_error": m["max_error"].value(),
    }
