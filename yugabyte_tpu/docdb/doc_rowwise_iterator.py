"""DocRowwiseIterator: assemble rows from flattened MVCC KV pairs.

Capability parity with the reference's read path (ref:
src/yb/docdb/doc_rowwise_iterator.cc:1036 Init, src/yb/docdb/doc_reader.h:73
DocDBTableReader, src/yb/docdb/subdoc_reader.h:80). Walks the merged
(internal_key, value) stream of a DB in memcmp order — key ascending, then
DocHybridTime DESCENDING — so for each distinct doc path the FIRST version
with ht <= read_ht is the visible one.

Visibility rules implemented (matching docdb semantics):
  - a row-level tombstone at the bare DocKey shadows every column write with
    an older DocHybridTime (init-marker overwrite semantics);
  - a column whose visible version is a tombstone is absent;
  - TTL: a value written at `t` with ttl expires at t + ttl — reads at or
    after the expiry treat it as absent (ref: docdb_compaction_filter.cc
    expiry rules :260-279 applied here at read time);
  - a row exists iff its liveness system column or any value column is
    visible (ref: doc_reader.cc row existence via liveness).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.common.schema import Schema
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey, split_key_and_ht
from yugabyte_tpu.docdb.doc_operations import kLivenessColumnId
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.docdb.value_type import ValueType
from yugabyte_tpu.ops.slabs import _doc_key_len


def _is_expired(value: Value, write_dht: DocHybridTime,
                read_ht: HybridTime) -> bool:
    if value.ttl_ms is None:
        return False
    expiry_micros = write_dht.ht.physical_micros + value.ttl_ms * 1000
    return read_ht.physical_micros >= expiry_micros


@dataclass
class Row:
    doc_key: DocKey
    columns: Dict[int, object]      # column id -> decoded primitive
    write_ht: HybridTime            # max HT contributing to this row

    def to_dict(self, schema: Schema) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for c, v in zip(schema.hash_columns, self.doc_key.hash_components):
            out[c.name] = v
        for c, v in zip(schema.range_columns, self.doc_key.range_components):
            out[c.name] = v
        for c in schema.value_columns:
            cid = schema.column_id(c.name)
            out[c.name] = self.columns.get(cid)
        return out


class DocRowwiseIterator:
    """Iterate rows of one table between doc-key bounds at a read time."""

    def __init__(self, db, schema: Schema, read_ht: HybridTime,
                 lower_doc_key: bytes = b"",
                 upper_doc_key: Optional[bytes] = None,
                 projection: Optional[Sequence[int]] = None):
        self._db = db
        self._schema = schema
        self._read_ht = read_ht
        self._lower = lower_doc_key
        self._upper = upper_doc_key
        self._projection = set(projection) if projection is not None else None
        # resume point for paging: encoded doc key to seek past
        self.next_doc_key: Optional[bytes] = None

    # The read_ht as a DocHybridTime upper bound: everything with
    # (ht, write_id) <= (read_ht, max) is visible.
    def _visible(self, dht: DocHybridTime) -> bool:
        return dht.ht.value <= self._read_ht.value

    def __iter__(self) -> Iterator[Row]:
        return self.rows()

    def rows(self, limit: Optional[int] = None) -> Iterator[Row]:
        stream = self._db.iter_from(self._lower)
        cur_doc: Optional[bytes] = None
        # per doc state. doc_overwrite is the DocHybridTime of the latest
        # visible bare-DocKey entry: BOTH a tombstone and an object init
        # marker replace the whole older subdocument (ref: docdb/doc.md
        # init-marker overwrite semantics), so either shadows older columns.
        doc_overwrite: Optional[DocHybridTime] = None
        columns: Dict[int, object] = {}
        seen_paths: set = set()
        liveness = False  # row exists: liveness marker OR any visible column,
        #                   tracked independently of the projection
        max_ht = HybridTime.kMin
        emitted = 0

        def finish() -> Optional[Row]:
            if cur_doc is None or not liveness:
                return None
            dk, _ = DocKey.decode(cur_doc)
            return Row(dk, dict(columns), max_ht)

        for ikey, raw_value in stream:
            prefix, dht = split_key_and_ht(ikey)
            if dht is None:
                continue
            dk_len = _doc_key_len(prefix)
            doc = prefix[:dk_len]
            if self._upper is not None and doc >= self._upper:
                break
            if doc != cur_doc:
                row = finish()
                if row is not None:
                    yield row
                    emitted += 1
                    if limit is not None and emitted >= limit:
                        self.next_doc_key = doc
                        return
                cur_doc = doc
                doc_overwrite = None
                columns = {}
                seen_paths = set()
                liveness = False
                max_ht = HybridTime.kMin
            if not self._visible(dht):
                continue
            subpath = prefix[dk_len:]
            if subpath in seen_paths:
                continue  # older version of an already-resolved path
            seen_paths.add(subpath)
            value = Value.decode(raw_value)
            shadowed = doc_overwrite is not None and dht < doc_overwrite
            if not subpath:
                # bare DocKey: row tombstone or object init marker — the
                # latest visible one shadows all older subdocument content
                doc_overwrite = dht
                if not value.is_tombstone and \
                        not _is_expired(value, dht, self._read_ht):
                    liveness = True
                    max_ht = max(max_ht, dht.ht, key=lambda h: h.value)
                continue
            if shadowed or value.is_tombstone or \
                    _is_expired(value, dht, self._read_ht):
                continue
            # decode the subkey path: (("col", cid),) for relational rows
            sdk = SubDocKey.decode(ikey)
            if len(sdk.subkeys) != 1 or not (
                    isinstance(sdk.subkeys[0], tuple) and sdk.subkeys[0][0] == "col"):
                continue  # deeper subdocument paths: not part of a flat row
            cid = sdk.subkeys[0][1]
            max_ht = max(max_ht, dht.ht, key=lambda h: h.value)
            liveness = True  # any visible column proves the row exists
            if cid == kLivenessColumnId:
                continue
            if self._projection is not None and cid not in self._projection:
                continue
            columns[cid] = value.primitive
        row = finish()
        if row is not None:
            yield row
        self.next_doc_key = None


def read_row(db, schema: Schema, doc_key: DocKey, read_ht: HybridTime,
             projection: Optional[Sequence[int]] = None) -> Optional[Row]:
    """Point row lookup (the QL read-one path)."""
    encoded = doc_key.encode()
    it = DocRowwiseIterator(db, schema, read_ht, lower_doc_key=encoded,
                            upper_doc_key=encoded + bytes([ValueType.kMaxByte]),
                            projection=projection)
    for row in it:
        return row
    return None
