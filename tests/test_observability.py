"""Observability layer: cross-node trace propagation, Prometheus
exposition correctness, /compactionz, endpoint smoke tests, and the
metric-name lint wiring (this PR's tentpole + satellites).

The trace tests exercise the full distributed path: a client write's span
context rides the RPC wire header (rpc/codec.py), is adopted by the
inbound tserver handler (rpc/messenger.py), propagates through the raft
replicate fan-out (consensus/raft.py) to peer servers, and all hops group
under one trace_id in /tracez.
"""

import json
import os
import re
import sys
import time
import urllib.error
import urllib.request

import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.rpc import codec
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils import trace as trace_mod
from yugabyte_tpu.utils.metrics import (MetricRegistry,
                                        registries_to_prometheus)
from yugabyte_tpu.utils.trace import TRACE, Trace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = Schema([ColumnSchema("k", DataType.STRING),
                 ColumnSchema("v", DataType.INT64)], 1, 0)


# ---------------------------------------------------------------------------
# Prometheus text-format grammar validation (line-by-line)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _parse_labels(s: str):
    """Parse `k="v",k2="v2"` honoring backslash escapes; returns dict or
    raises ValueError."""
    out = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq]
        if not _LABEL_RE.match(name):
            raise ValueError(f"bad label name {name!r}")
        if s[eq + 1] != '"':
            raise ValueError("label value not quoted")
        j = eq + 2
        val = []
        while True:
            c = s[j]
            if c == "\\":
                if s[j + 1] not in ('"', "\\", "n"):
                    raise ValueError(f"bad escape \\{s[j + 1]}")
                val.append(s[j:j + 2])
                j += 2
            elif c == '"':
                break
            elif c == "\n":
                raise ValueError("raw newline in label value")
            else:
                val.append(c)
                j += 1
        out[name] = "".join(val)
        i = j + 1
        if i < len(s):
            if s[i] != ",":
                raise ValueError(f"junk after label value: {s[i:]!r}")
            i += 1
    return out


def validate_prometheus_text(text: str):
    """Line-by-line validation of the exposition grammar: HELP/TYPE
    comments, sample syntax, label escaping, one TYPE per family emitted
    before (and contiguous with) its samples. Returns a list of error
    strings (empty = valid)."""
    errors = []
    types = {}          # family -> type
    family_done = set() # families whose sample block has ended
    current_family = None

    def family_of(name):
        if name in types:
            return name
        for suffix in ("_sum", "_count", "_bucket"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if types.get(base) in ("summary", "histogram"):
                    return base
        return None

    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    for ln, line in enumerate(lines, 1):
        if not line:
            errors.append(f"line {ln}: empty line inside exposition")
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                errors.append(f"line {ln}: malformed comment {line!r}")
                continue
            kind, name = parts[1], parts[2]
            if not _NAME_RE.match(name):
                errors.append(f"line {ln}: bad metric name {name!r}")
                continue
            if kind == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                        "counter", "gauge", "summary", "histogram",
                        "untyped"):
                    errors.append(f"line {ln}: bad TYPE line {line!r}")
                    continue
                if name in types:
                    errors.append(f"line {ln}: duplicate TYPE for {name}")
                    continue
                types[name] = parts[3]
            continue
        # sample line: name[{labels}] value
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$", line)
        if m is None:
            errors.append(f"line {ln}: unparseable sample {line!r}")
            continue
        name, _braced, labels, value = m.groups()
        if labels is not None:
            try:
                _parse_labels(labels)
            except (ValueError, IndexError) as e:
                errors.append(f"line {ln}: {e}")
        try:
            float(value)
        except ValueError:
            if value not in ("NaN", "+Inf", "-Inf"):
                errors.append(f"line {ln}: bad sample value {value!r}")
        fam = family_of(name)
        if fam is None:
            errors.append(f"line {ln}: sample {name} has no TYPE")
            continue
        if fam in family_done and fam != current_family:
            errors.append(f"line {ln}: family {fam} not contiguous")
        if current_family is not None and fam != current_family:
            family_done.add(current_family)
        current_family = fam
    return errors


class TestPrometheusExposition:
    def test_type_help_and_escaping(self):
        reg = MetricRegistry()
        # attributes with every character the escaper must handle
        ent = reg.entity("tablet", "t9", {
            "table_name": 'we"ird\\na"me', "note": "line1\nline2"})
        ent.counter("evil_rows_total", "rows with \\ and\nnewlines").increment(3)
        ent.gauge("evil_depth_count", "a gauge").set(1.5)
        h = ent.histogram("evil_latency_ms", "histo")
        for v in (1, 5, 9):
            h.increment(v)
        # the same family from a SECOND entity must share one TYPE line
        reg.entity("tablet", "t10").counter("evil_rows_total").increment(1)
        text = reg.to_prometheus()
        errs = validate_prometheus_text(text)
        assert not errs, "\n".join(errs)
        assert "# TYPE evil_rows_total counter" in text
        assert text.count("# TYPE evil_rows_total counter") == 1
        assert "# HELP evil_rows_total" in text
        assert '\\"ird\\\\na\\"me' in text      # escaped label value
        assert "line1\\nline2" in text
        assert "# TYPE evil_latency_ms summary" in text
        assert "evil_latency_ms_min" in text and "evil_latency_ms_max" in text
        # min/max carry real observed bounds
        assert re.search(r"evil_latency_ms_min\{[^}]*\} 1(\.0)?\b", text)
        assert re.search(r"evil_latency_ms_max\{[^}]*\} 9(\.0)?\b", text)

    def test_to_json_min_max(self):
        reg = MetricRegistry()
        h = reg.entity("server", "x").histogram("j_latency_ms")
        h.increment(2.0)
        h.increment(8.0)
        data = json.loads(reg.to_json())
        m = data[0]["metrics"][0]
        assert m["min"] == 2.0 and m["max"] == 8.0

    def test_multi_registry_merge_dedupes(self):
        reg = MetricRegistry()
        reg.entity("server", "a").counter("merge_a_total").increment()
        text = registries_to_prometheus([reg, reg])
        assert text.count("merge_a_total{") == 1
        assert not validate_prometheus_text(text)


# ---------------------------------------------------------------------------
# Trace-header codec round-trip (incl. absent-header back-compat)
# ---------------------------------------------------------------------------

class TestTraceHeaderCodec:
    def test_roundtrip(self):
        ctx = {"trace_id": "ab" * 8, "span_id": "cd" * 4, "sampled": True}
        wire = codec.trace_to_wire(ctx)
        req = {"id": 1, "svc": "s", "mth": "m", "args": {},
               codec.TRACE_HEADER_KEY: wire}
        decoded = codec.loads(codec.dumps(req))
        got = codec.trace_from_wire(decoded[codec.TRACE_HEADER_KEY])
        assert got == {"trace_id": "ab" * 8, "span_id": "cd" * 4,
                       "sampled": True}

    def test_absent_header_backward_compat(self):
        # an old peer's request has no trace key: decode yields None ctx
        req = {"id": 1, "svc": "s", "mth": "m", "args": {"x": 1}}
        decoded = codec.loads(codec.dumps(req))
        assert codec.trace_from_wire(
            decoded.get(codec.TRACE_HEADER_KEY)) is None
        # malformed headers degrade to untraced, never raise
        assert codec.trace_from_wire("garbage") is None
        assert codec.trace_from_wire({"span_id": "x"}) is None
        assert codec.trace_to_wire(None) is None

    def test_messenger_adopts_wire_context(self):
        from yugabyte_tpu.rpc.messenger import Messenger

        class Svc:
            def probe(self):
                TRACE("inside handler")
                t = trace_mod.current_trace()
                return {"trace_id": t.trace_id,
                        "parent_span_id": t.parent_span_id}

        server = Messenger("obs-server")
        server.register_service("obs", Svc())
        client = Messenger("obs-client")
        try:
            with Trace("obs-root") as root:
                ret = client.call(server.address, "obs", "probe")
            assert ret["trace_id"] == root.trace_id
            assert ret["parent_span_id"] == root.span_id
            # untraced caller: handler starts a fresh root
            ret2 = client.call(server.address, "obs", "probe")
            assert ret2["trace_id"] != root.trace_id
            assert ret2["parent_span_id"] is None
        finally:
            client.shutdown()
            server.shutdown()


# ---------------------------------------------------------------------------
# Webserver: 404 only for missing routes; handler bugs are 500
# ---------------------------------------------------------------------------

def test_webserver_handler_keyerror_is_500():
    from yugabyte_tpu.server.webserver import Webserver

    ws = Webserver(MetricRegistry())
    ws.register("/boom", lambda: {}["missing"])  # handler raises KeyError
    try:
        base = f"http://{ws.address}"
        with pytest.raises(urllib.error.HTTPError) as e500:
            urllib.request.urlopen(base + "/boom", timeout=5)
        assert e500.value.code == 500
        with pytest.raises(urllib.error.HTTPError) as e404:
            urllib.request.urlopen(base + "/no-such-route", timeout=5)
        assert e404.value.code == 404
    finally:
        ws.shutdown()


# ---------------------------------------------------------------------------
# /compactionz source stats at the DB level
# ---------------------------------------------------------------------------

def test_compaction_stats_versions_gcd(tmp_path):
    from yugabyte_tpu.storage.db import DB, DBOptions

    db = DB(str(tmp_path / "db"),
            DBOptions(auto_compact=False,
                      retention_policy=lambda: 1 << 62))
    key = SubDocKey(DocKey(range_components=("row",)),
                    (("col", 0),)).encode(include_ht=False)
    for v in range(4):
        db.write_batch([(key, DocHybridTime(HybridTime((v + 1) << 12), 0),
                         Value(primitive=v).encode())])
        db.flush()
    db.compact_all()
    stats = db.compaction_stats.to_dict()
    db.close()
    assert stats["flushes"] == 4
    assert stats["flush_bytes_written"] > 0
    assert stats["compactions"] == 1
    assert stats["compaction_bytes_read"] > 0
    assert stats["compaction_bytes_written"] > 0
    # 4 versions of one key at a cutoff above all of them: only the
    # visible version survives a major compaction
    assert stats["compaction_rows_in"] == 4
    assert stats["compaction_rows_out"] == 1
    assert stats["versions_gcd"] == 3
    assert stats["write_amplification"] > 1.0


# ---------------------------------------------------------------------------
# Live mini-cluster: endpoint smoke + /compactionz + kernel histograms
# ---------------------------------------------------------------------------

def _get(addr: str, path: str) -> bytes:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return r.read()


def test_endpoint_smoke_and_compactionz(tmp_path):
    from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
    from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                       MiniClusterOptions)

    import yugabyte_tpu.storage.offload_policy  # defines the mode flag
    old_rf = flags.get_flag("replication_factor")
    old_mode = flags.get_flag("device_offload_mode")
    flags.set_flag("replication_factor", 1)
    # route the compaction through the device kernel so kernel-dispatch
    # histograms demonstrably exist in this server's exposition
    flags.set_flag("device_offload_mode", "device")
    mc = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path / "fs"))).start()
    try:
        client = mc.new_client()
        client.create_namespace("obs")
        t = client.create_table("obs", "t", SCHEMA, num_tablets=1)
        ts = mc.tservers[0]
        # several flushed runs of overlapping keys -> a real compaction
        for rnd in range(3):
            for i in range(20):
                client.write(t, [QLWriteOp(
                    WriteOpKind.INSERT, DocKey(hash_components=(f"k{i}",)),
                    {"v": i + rnd})])
            for tid in ts.tablet_manager.tablet_ids():
                ts.tablet_manager.get_tablet(tid).tablet.flush()
        for tid in ts.tablet_manager.tablet_ids():
            ts.tablet_manager.get_tablet(tid).tablet.compact()

        addr = ts.webserver.address
        # tserver /healthz: liveness status + the bucket-health board
        hz = json.loads(_get(addr, "/healthz"))
        assert hz["status"] == "ok"
        bh = hz["bucket_health"]
        assert set(bh["states"]) == {"cold", "warming", "healthy",
                                     "degraded", "quarantined",
                                     "probation"}
        assert isinstance(bh["keys"], list)
        assert isinstance(bh["quarantine"], list)
        for path in ("/metrics", "/rpcz", "/tracez", "/threadz",
                     "/compactionz", "/integrityz"):
            payload = json.loads(_get(addr, path))
            assert payload is not None, path

        iz = json.loads(_get(addr, "/integrityz"))
        assert iz["shadow_verify"]["sample"] == flags.get_flag(
            "shadow_verify_sample")
        assert iz["scrub"]["interval_s"] == flags.get_flag(
            "scrub_interval_s")
        assert isinstance(iz["quarantined_files"], list)
        assert all("scrub" in t and "failed_corrupt" in t
                   for t in iz["tablets"])

        cz = json.loads(_get(addr, "/compactionz"))
        totals = cz["totals"]
        assert totals["flush_bytes_written"] > 0
        assert totals["compaction_bytes_read"] > 0
        assert totals["compaction_bytes_written"] > 0
        assert totals["write_amplification"] > 1.0

        prom = _get(addr, "/prometheus-metrics").decode()
        errs = validate_prometheus_text(prom)
        assert not errs, "\n".join(errs[:20])
        # kernel-dispatch instrumentation made it into the exposition
        assert "kernel_run_merge_dispatch_total" in prom \
            or "kernel_merge_gc_dispatch_total" in prom
        assert "kernel_run_merge_batch_rows" in prom \
            or "kernel_merge_gc_batch_rows" in prom
        # per-method inbound RPC histograms (service entity carries method)
        assert "rpc_inbound_call_duration_ms" in prom
        # WAL tier histograms
        assert "wal_fsync_duration_ms" in prom
        client.close()
    finally:
        mc.shutdown()
        flags.set_flag("replication_factor", old_rf)
        flags.set_flag("device_offload_mode", old_mode)


# ---------------------------------------------------------------------------
# Cross-node trace propagation on a replicated write
# ---------------------------------------------------------------------------

def test_write_trace_stitches_across_cluster(tmp_path):
    from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
    from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                       MiniClusterOptions)

    old_rf = flags.get_flag("replication_factor")
    flags.set_flag("replication_factor", 3)
    mc = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path / "fs"))).start()
    try:
        client = mc.new_client()
        client.create_namespace("tr")
        t = client.create_table("tr", "t", SCHEMA, num_tablets=1)
        mc.wait_all_replicas_running(t.table_id)
        mc.wait_for_table_leaders("tr", "t")  # don't race the election
        with Trace("test-write-root") as root:
            client.write(t, [QLWriteOp(
                WriteOpKind.INSERT, DocKey(hash_components=("kx",)),
                {"v": 7})])
        tid = root.trace_id

        def spans_for(trace_id):
            return [s for s in trace_mod.tracez()
                    if s["trace_id"] == trace_id]

        # replicate acks from the majority land before write() returns;
        # give the slowest peer's span a moment to be recorded too
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            names = {s["name"] for s in spans_for(tid)}
            if ("tserver.write" in names
                    and any(n.startswith("raft.append_entries:")
                            for n in names)
                    and "consensus.update_consensus" in names):
                break
            time.sleep(0.05)
        spans = spans_for(tid)
        names = {s["name"] for s in spans}
        # hop 1: the client root span itself
        assert "client.write" in names, names
        # hop 2: the coordinating tserver's write handler (adopted ctx)
        assert "tserver.write" in names, names
        # hop 3: the leader's per-peer replication spans
        assert any(n.startswith("raft.append_entries:") for n in names), names
        # hop 4: the raft peers' inbound AppendEntries handler spans
        assert "consensus.update_consensus" in names, names

        # parent/child stitching: the tserver.write handler is a child of
        # the client.write span
        by_name = {s["name"]: s for s in spans}
        client_span = by_name["client.write"]
        assert by_name["tserver.write"]["parent_span_id"] == \
            client_span["span_id"]

        # the grouped /tracez view on the coordinating tserver shows the
        # whole multi-hop trace under one trace_id with per-hop timings
        leader_addr = None
        for ts in mc.tservers:
            for tb in ts.tablet_manager.tablet_ids():
                peer = ts.tablet_manager.get_tablet(tb)
                if peer.raft.is_leader():
                    leader_addr = ts.webserver.address
        assert leader_addr is not None
        tz = json.loads(_get(leader_addr, "/tracez"))
        groups = [g for g in tz["traces"] if g["trace_id"] == tid]
        assert groups and groups[0]["n_spans"] >= 4
        assert all(sp["duration_ms"] >= 0 for sp in groups[0]["spans"])
        client.close()
    finally:
        mc.shutdown()
        flags.set_flag("replication_factor", old_rf)


# ---------------------------------------------------------------------------
# CI wiring for tools/lint_metric_names.py (like lint_swallowed_errors)
# ---------------------------------------------------------------------------

def test_metric_names_conform():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import lint_metric_names as lint
    finally:
        sys.path.pop(0)
    offenses = lint.check_paths(REPO_ROOT)
    assert not offenses, "\n".join(
        f"{p}:{ln}: {msg}" for p, ln, msg in offenses)


def test_metric_name_lint_catches_offenses(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import lint_metric_names as lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "e.counter('CamelCase')\n"
        "e.counter('missing_suffix')\n"
        "e.histogram('latency')\n"
        "e.gauge('depth_ok_depth')\n"
        "e.counter('waived')  # lint: metric-name-ok\n"
        "e.counter(dynamic_name)\n")
    offenses = lint.check_file(str(bad))
    msgs = [m for _p, _l, m in offenses]
    assert len(offenses) == 3, msgs
    assert any("not snake_case" in m for m in msgs)
    assert any("'missing_suffix'" in m for m in msgs)
    assert any("'latency'" in m for m in msgs)
