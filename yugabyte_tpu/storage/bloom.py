"""DocDB-aware bloom filter: one probe per *document* key.

Capability parity with the reference's DocDbAwareFilterPolicy (ref:
src/yb/docdb/doc_key.h:811-866): the filter key is a prefix of the encoded
key, so one filter probe serves every subkey/version of a row. Divergence:
the reference filters on the hashed-components prefix; we filter on the full
DocKey prefix (doc_key_len), which is strictly more selective for point gets
and equally computable from slabs (doc_key_len is a slab column).

Build is vectorized over entries (byte-position loop is bounded by the key
stride); probes use FNV-64 split into two 32-bit halves, double-hashed.
This module is the CPU path of the batched device probe: the TPU twin
(ops/point_read.py `_fnv64_fused` + `_bloom_probe_fused`) reproduces the
same uint64 arithmetic in two uint32 limbs and probes one SST's bit words
for a whole key batch in one dispatch — the two paths must stay
bit-identical (differential-tested in tests/test_point_read_batch.py).
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import numpy as np

_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def fnv64_masked(key_bytes_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a over the first lengths[i] bytes of each row."""
    n, stride = key_bytes_u8.shape
    h = np.full(n, _FNV_OFFSET, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for j in range(stride):
            active = lengths > j
            hj = (h ^ key_bytes_u8[:, j].astype(np.uint64)) * _FNV_PRIME
            h = np.where(active, hj, h)
    return h


class BloomFilterBuilder:
    def __init__(self, n_keys_estimate: int, bits_per_key: int = 10):
        self.m_bits = max(64, n_keys_estimate * bits_per_key)
        self.m_bits = ((self.m_bits + 63) // 64) * 64
        self.k = max(1, min(12, int(round(bits_per_key * 0.69))))
        self.bits = np.zeros(self.m_bits // 8, dtype=np.uint8)

    def add_hashes(self, h: np.ndarray) -> None:
        try:
            # the numpy scatter below is an unbuffered ufunc.at (~100ns
            # per OR); the native path is the same schedule in C++
            from yugabyte_tpu.storage import native_engine
            if native_engine.available():
                native_engine.bloom_build(h, self.bits, self.m_bits, self.k)
                return
        except Exception as e:  # pragma: no cover — numpy fallback is exact
            from yugabyte_tpu.utils.trace import TRACE
            TRACE("bloom: native build failed, using numpy fallback: %s", e)
        h1 = (h & np.uint64(0xFFFFFFFF)).astype(np.uint64)
        h2 = (h >> np.uint64(32)).astype(np.uint64) | np.uint64(1)
        with np.errstate(over="ignore"):
            for i in range(self.k):
                pos = (h1 + np.uint64(i) * h2) % np.uint64(self.m_bits)
                byte_idx = (pos >> np.uint64(3)).astype(np.int64)
                bit = (np.uint8(1) << (pos & np.uint64(7)).astype(np.uint8))
                np.bitwise_or.at(self.bits, byte_idx, bit)

    def finish(self) -> bytes:
        return struct.pack("<IQ", self.k, self.m_bits) + self.bits.tobytes()


class BloomFilter:
    def __init__(self, data: bytes):
        self.k, self.m_bits = struct.unpack_from("<IQ", data, 0)
        self.bits = np.frombuffer(data, dtype=np.uint8, offset=12)

    def may_contain_hash(self, h: int) -> bool:
        h1 = h & 0xFFFFFFFF
        h2 = (h >> 32) | 1
        for i in range(self.k):
            pos = (h1 + i * h2) % self.m_bits
            if not (self.bits[pos >> 3] >> (pos & 7)) & 1:
                return False
        return True

    def may_contain(self, filter_key: bytes) -> bool:
        arr = np.frombuffer(filter_key, dtype=np.uint8).reshape(1, -1)
        h = int(fnv64_masked(arr, np.array([len(filter_key)]))[0])
        return self.may_contain_hash(h)

    def may_contain_batch(self, h: np.ndarray) -> np.ndarray:
        """Vectorized probe for a batch of hashes — the CPU path of
        ops/point_read._bloom_probe_fused (bit-identical positions)."""
        h1 = (h & np.uint64(0xFFFFFFFF)).astype(np.uint64)
        h2 = (h >> np.uint64(32)).astype(np.uint64) | np.uint64(1)
        ok = np.ones(h.shape[0], dtype=bool)
        with np.errstate(over="ignore"):
            for i in range(self.k):
                pos = (h1 + np.uint64(i) * h2) % np.uint64(self.m_bits)
                byte_idx = (pos >> np.uint64(3)).astype(np.int64)
                ok &= ((self.bits[byte_idx] >> (pos & np.uint64(7)).astype(np.uint8)) & 1).astype(bool)
        return ok
