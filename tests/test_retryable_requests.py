"""Exactly-once writes: retryable-request dedup (tablet/retryable_requests).

The load-bearing scenario (round-2 Weak #6): a write whose first attempt
replicated but whose ack was lost (OperationOutcomeUnknown) is retried by
the client — it must apply exactly once, across leader changes and WAL
replay (ref: src/yb/consensus/retryable_requests.cc).
"""

import pytest

from yugabyte_tpu.consensus.raft import OperationOutcomeUnknown
from yugabyte_tpu.tablet.tablet_peer import TabletPeer
from yugabyte_tpu.utils.status import StatusError

import sys
import os
sys.path.insert(0, os.path.dirname(__file__))
from test_consensus import (  # noqa: E402
    LocalTransport, PeerHarness, make_schema, wait_for, write_op)

CID = b"client-0123456789"[:16]


def _entry_count(peer):
    """Exact count of raw KV entries (every version) in the regular DB."""
    return sum(1 for _ in peer.tablet.regular_db.iter_from(b""))


def test_duplicate_request_returns_original_result(tmp_path):
    h = PeerHarness(tmp_path)
    try:
        leader = h.elect("ts0")
        ht1 = leader.write([write_op(h.schema, "k1", 1)],
                           request=(CID, 7))
        n = _entry_count(leader)
        ht2 = leader.write([write_op(h.schema, "k1", 1)],
                           request=(CID, 7))
        assert ht2.value == ht1.value
        assert _entry_count(leader) == n  # nothing re-applied
        # a different request id applies normally
        ht3 = leader.write([write_op(h.schema, "k1", 2)],
                           request=(CID, 8))
        assert ht3.value != ht1.value
        assert _entry_count(leader) == n + 2  # liveness + column
    finally:
        h.shutdown()


def test_unknown_outcome_retry_applies_once(tmp_path):
    """Replicate succeeds but the ack is lost: the retry must dedup."""
    h = PeerHarness(tmp_path)
    try:
        leader = h.elect("ts0")
        real_submit = leader.tablet.consensus.submit

        def flaky_submit(*a, **kw):
            real_submit(*a, **kw)
            raise OperationOutcomeUnknown("ack lost after replication")

        leader.tablet.consensus.submit = flaky_submit
        with pytest.raises(OperationOutcomeUnknown):
            leader.write([write_op(h.schema, "kx", 5)], request=(CID, 20))
        leader.tablet.consensus.submit = real_submit
        n = _entry_count(leader)
        # the client's retry loop re-sends the SAME request id
        ht = leader.write([write_op(h.schema, "kx", 5)], request=(CID, 20))
        assert ht.value > 0
        assert _entry_count(leader) == n  # zero additional application
    finally:
        h.shutdown()


def test_in_flight_duplicate_is_pushed_back(tmp_path):
    h = PeerHarness(tmp_path)
    try:
        leader = h.elect("ts0")
        reg = leader.tablet.retryable
        assert reg.check_or_track(CID, 33)[0] == "new"
        assert reg.check_or_track(CID, 33)[0] == "in_flight"
        with pytest.raises(StatusError):
            leader.write([write_op(h.schema, "ky", 1)], request=(CID, 33))
        reg.failed(CID, 33)
        leader.write([write_op(h.schema, "ky", 1)], request=(CID, 33))
    finally:
        h.shutdown()


def test_dedup_survives_leader_change(tmp_path):
    h = PeerHarness(tmp_path)
    try:
        leader = h.elect("ts0")
        ht1 = leader.write([write_op(h.schema, "kz", 9)], request=(CID, 40))
        # every follower applied the batch (and its request tag)
        wait_for(lambda: all(
            len(p.tablet.retryable) == 1 for p in h.peers.values()),
            msg="registry replicated everywhere")
        new_leader = h.elect("ts1")
        n = _entry_count(new_leader)
        ht2 = new_leader.write([write_op(h.schema, "kz", 9)],
                               request=(CID, 40))
        assert ht2.value == ht1.value
        assert _entry_count(new_leader) == n
    finally:
        h.shutdown()


def test_dedup_survives_restart_replay(tmp_path):
    transport = LocalTransport()
    schema = make_schema()
    peer = TabletPeer("t1", str(tmp_path / "solo"), schema, "ts0", ("ts0",),
                      transport).start(election_timer=False)
    peer.raft.start_election(ignore_lease=True)
    wait_for(lambda: peer.raft.is_leader(), msg="leader")
    ht1 = peer.write([write_op(schema, "kr", 3)], request=(CID, 55))
    peer.shutdown()

    peer2 = TabletPeer("t1", str(tmp_path / "solo"), schema, "ts0",
                       ("ts0",), transport).start(election_timer=False)
    try:
        peer2.raft.start_election(ignore_lease=True)
        wait_for(lambda: peer2.raft.is_leader(), msg="leader after restart")
        assert len(peer2.tablet.retryable) == 1  # rebuilt from WAL replay
        n = _entry_count(peer2)
        ht2 = peer2.write([write_op(schema, "kr", 3)], request=(CID, 55))
        assert ht2.value == ht1.value
        assert _entry_count(peer2) == n
    finally:
        peer2.shutdown()
