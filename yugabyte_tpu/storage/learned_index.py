"""Learned per-SST index: fit, (de)serialization, base-file attachment.

The "Pragmatic Learned Indexing in RocksDB" recipe (PAPERS.md): one tiny
targeted model per SST, minimal system modification, exact-search fallback
on bounded misprediction. The model is a piecewise-linear map from a key
coordinate (first 8 key bytes as float32 — monotone in memcmp order) to
entry position, stored as S+1 anchor coordinates plus a measured max-error
bound. It is ADVISORY ONLY: the batched locate kernel
(ops/point_read._locate_gather_fused) uses it to narrow the binary-seek
window and verifies the answer against the search invariant; any
misprediction beyond the bound is detected and the key re-resolves
exactly, so correctness never depends on the model.

Fit sites:
  - device: ops/point_read._index_fit_fused over staged cols already in
    HBM (the compaction write-through path — sorted keys are there for
    free);
  - host (this module): the numpy twin over sorted key words, used by the
    Python SST writer and the native flush encoder. The twin mirrors the
    inference arithmetic so the recorded bound is self-consistent.

Persistence: an optional ``lindex`` field in the SST properties block
(storage/sst.py). Format-compatible both ways: pre-PR readers ignore the
extra JSON key; post-PR readers treat its absence as "no model".
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

MODEL_VERSION = 1

# Model lattice — the CANONICAL definitions (ops/point_read.py imports
# them so its static search window stays in lock-step; this module must
# stay jax-free because every flush imports it for the host fit).
LINDEX_SEGMENTS = 16
# bound must fit the locate kernel's fixed window search: 2*err+1
# candidates resolved in point_read._LG_WINDOW halvings
LINDEX_MAX_ERR = (1 << 14) - 2
LINDEX_MIN_ENTRIES = 256   # below this a binary seek is already ~8 steps
# Prefix skip is capped at 2 words so the coordinate is a pure function
# of the first 16 KEY BYTES — independent of slab/staged padding width,
# which keeps fits byte-identical across every writer path (python,
# native-packed, device) for the same key set.
LINDEX_MAX_P = 2


def _anchor_positions(n: int, s: int = LINDEX_SEGMENTS) -> np.ndarray:
    """Deterministic anchor positions for an n-entry SST — recomputed at
    read time instead of persisted (the device fit uses the identical
    integer formula)."""
    return (np.arange(s + 1, dtype=np.int64) * (n - 1) // s
            ).astype(np.int32)


def _predict_host(x_hi: np.ndarray, x_lo: np.ndarray,
                  a_hi: np.ndarray, a_lo: np.ndarray,
                  anchor_pos: np.ndarray) -> np.ndarray:
    """Numpy twin of ops/point_read._predict_pos: exact two-limb segment
    selection and differences, float32 only for the interpolation."""
    s = len(a_hi) - 1
    seg = np.zeros(x_hi.shape, dtype=np.int32)
    for i in range(1, s):
        seg += ((x_hi > a_hi[i])
                | ((x_hi == a_hi[i]) & (x_lo >= a_lo[i]))
                ).astype(np.int32)
    a0h, a0l = a_hi[seg], a_lo[seg]
    a1h, a1l = a_hi[seg + 1], a_lo[seg + 1]
    p0 = anchor_pos[seg].astype(np.float32)
    p1 = anchor_pos[seg + 1].astype(np.float32)
    ge0 = (x_hi > a0h) | ((x_hi == a0h) & (x_lo >= a0l))
    x64 = (x_hi.astype(np.uint64) << np.uint64(32)) | x_lo
    a0 = (a0h.astype(np.uint64) << np.uint64(32)) | a0l
    a1 = (a1h.astype(np.uint64) << np.uint64(32)) | a1l
    dx64 = np.where(ge0, x64 - a0, 0)
    da64 = a1 - a0
    dx = (np.float32(4294967296.0)
          * (dx64 >> np.uint64(32)).astype(np.float32)
          + (dx64 & np.uint64(0xFFFFFFFF)).astype(np.float32))
    da = (np.float32(4294967296.0)
          * (da64 >> np.uint64(32)).astype(np.float32)
          + (da64 & np.uint64(0xFFFFFFFF)).astype(np.float32))
    t = np.where(ge0 & (da > 0), dx / np.where(da > 0, da,
                                               np.float32(1.0)),
                 np.float32(0.0))
    t = np.clip(t, np.float32(0.0), np.float32(1.0))
    return p0 + t * (p1 - p0)


def finish_model(a_hi: np.ndarray, a_lo: np.ndarray, p: int,
                 max_err: int, n: int) -> Optional[dict]:
    """Assemble the persistable dict from fitted anchors + measured
    bound; None when the bound is too loose for the fixed search window
    (the model would narrow nothing). All-integer: JSON round-trips the
    model exactly."""
    if max_err > LINDEX_MAX_ERR:
        return None
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    ROOT_REGISTRY.entity("server", "point_read").gauge(
        "learned_index_max_error_rows",
        "recorded max-error bound (entry positions) of the most "
        "recently fitted learned per-SST index").set(int(max_err))
    return {
        "v": MODEL_VERSION,
        "s": LINDEX_SEGMENTS,
        "n": int(n),
        "p": int(p),
        "max_err": int(max_err),
        "a_hi": [int(a) for a in np.asarray(a_hi, dtype=np.uint32)],
        "a_lo": [int(a) for a in np.asarray(a_lo, dtype=np.uint32)],
    }


def fit_from_sorted_words(key_words: np.ndarray) -> Optional[dict]:
    """Host fit over SORTED key words (big-endian uint32 [n, w], entry
    order == key order). The numpy twin of _index_fit_fused: the same
    word-aligned prefix skip, exact anchors, and inference arithmetic
    for the measured bound."""
    n = int(key_words.shape[0])
    if n < LINDEX_MIN_ENTRIES:
        return None
    w = int(key_words.shape[1])
    if w != LINDEX_MAX_P + 2:
        # normalize to the first 16 key bytes (4 words): the model must
        # not depend on how wide a particular writer padded its slab
        fixed = np.zeros((n, LINDEX_MAX_P + 2), dtype=np.uint32)
        fixed[:, :min(w, LINDEX_MAX_P + 2)] = \
            key_words[:, :LINDEX_MAX_P + 2]
        key_words = fixed
    p = 0
    while p < LINDEX_MAX_P and key_words[0, p] == key_words[n - 1, p]:
        p += 1
    x_hi = np.ascontiguousarray(key_words[:, p], dtype=np.uint32)
    x_lo = np.ascontiguousarray(key_words[:, p + 1], dtype=np.uint32)
    pos = _anchor_positions(n)
    a_hi = x_hi[pos]
    a_lo = x_lo[pos]
    pred = _predict_host(x_hi, x_lo, a_hi, a_lo, pos)
    err = np.abs(np.round(pred).astype(np.int64)
                 - np.arange(n, dtype=np.int64))
    return finish_model(a_hi, a_lo, p, int(err.max(initial=0)), n)


def fit_from_packed_keys(keys_blob: bytes, key_offs) -> Optional[dict]:
    """Host fit from a packed key run in ANY order (the native flush /
    bulk-ingest path). The coordinate words are a monotone (non-strict)
    transform of memcmp order among keys sharing the prefix, so sorting
    the 16-byte prefixes reproduces the key-sorted coordinate sequence
    exactly — no need to sort the keys themselves."""
    offs = np.asarray(key_offs, dtype=np.int64)
    n = len(offs) - 1
    if n < LINDEX_MIN_ENTRIES:
        return None
    data = np.frombuffer(keys_blob, dtype=np.uint8)
    if not len(data):
        return None
    lens = offs[1:] - offs[:-1]
    pos16 = offs[:-1, None] + np.arange(16, dtype=np.int64)[None, :]
    valid = np.arange(16, dtype=np.int64)[None, :] < lens[:, None]
    b16 = np.where(valid, data[np.clip(pos16, 0, len(data) - 1)],
                   0).astype(np.uint32)
    words = np.zeros((n, 4), dtype=np.uint32)
    for j in range(4):
        words[:, j] = ((b16[:, 4 * j] << 24) | (b16[:, 4 * j + 1] << 16)
                       | (b16[:, 4 * j + 2] << 8) | b16[:, 4 * j + 3])
    # sort the 16-byte prefixes into key order (lexicographic over the
    # four words == memcmp over the first 16 bytes; ties beyond that
    # produce equal coordinates, so the sequence is still exact)
    order = np.lexsort((words[:, 3], words[:, 2], words[:, 1],
                        words[:, 0]))
    return fit_from_sorted_words(words[order])


def fit_from_slab(slab) -> Optional[dict]:
    """Host fit from an already-sorted slab (the Python SST writer)."""
    if slab.n < LINDEX_MIN_ENTRIES:
        return None
    return fit_from_sorted_words(np.asarray(slab.key_words,
                                            dtype=np.uint32))


def model_operands(lindex: Optional[dict], n_entries: int):
    """Validate a persisted model against the file it claims to index
    and return the kernel operands (a_hi, a_lo, anchor_pos, p, max_err),
    or None when the model is absent/stale/oversized — the locate kernel
    then runs the exact full seek (advisory-only contract)."""
    if not lindex or not isinstance(lindex, dict):
        return None
    try:
        if (int(lindex.get("v", 0)) != MODEL_VERSION
                or int(lindex.get("s", 0)) != LINDEX_SEGMENTS
                or int(lindex.get("n", -1)) != int(n_entries)
                or int(lindex["max_err"]) > LINDEX_MAX_ERR
                or int(lindex.get("p", -1)) < 0):
            return None
        a_hi = np.asarray(lindex["a_hi"], dtype=np.uint32)
        a_lo = np.asarray(lindex["a_lo"], dtype=np.uint32)
        if a_hi.shape != (LINDEX_SEGMENTS + 1,) \
                or a_lo.shape != (LINDEX_SEGMENTS + 1,):
            return None
    except (KeyError, TypeError, ValueError):  # yblint: contained(a malformed persisted model is advisory data — ignored, the exact seek serves)
        return None
    return a_hi, a_lo, _anchor_positions(int(n_entries)), \
        int(lindex["p"]), int(lindex["max_err"])


def attach_learned_index(base_path: str, lindex: dict) -> int:
    """Rewrite an SST base file with the model added to its properties
    block (CRC + footer recomputed). Used by the device-native compaction
    path, which fits AFTER the streaming writer produced the file but
    BEFORE the output installs/serves. Returns the new base-file size."""
    import json
    import zlib
    from yugabyte_tpu.storage.sst import _FOOTER, SST_MAGIC
    from yugabyte_tpu.utils.env import get_env
    raw = get_env().read_file(base_path)
    (index_off, index_len, bloom_off, bloom_len, props_off, props_len,
     data_size, _crc, magic) = _FOOTER.unpack_from(raw,
                                                   len(raw) - _FOOTER.size)
    if magic != SST_MAGIC:
        raise ValueError(f"not an SST base file: {base_path}")
    index_bytes = raw[index_off: index_off + index_len]
    bloom_bytes = raw[bloom_off: bloom_off + bloom_len]
    props = json.loads(raw[props_off: props_off + props_len])
    props["lindex"] = lindex
    props_bytes = json.dumps(props).encode()
    crc = (zlib.crc32(index_bytes) ^ zlib.crc32(bloom_bytes)
           ^ zlib.crc32(props_bytes))
    blob = (index_bytes + bloom_bytes + props_bytes
            + _FOOTER.pack(0, len(index_bytes), len(index_bytes),
                           len(bloom_bytes),
                           len(index_bytes) + len(bloom_bytes),
                           len(props_bytes), data_size, crc, SST_MAGIC))
    get_env().write_file(base_path, blob)
    return len(blob)
