"""Offload policy (VERDICT r3 #2): production compactions route device vs
native from MEASURED calibration, never into a known pessimization."""

import json

import numpy as np
import pytest

from yugabyte_tpu.storage.offload_policy import (CalibrationPoint,
                                                 OffloadPolicy)
from yugabyte_tpu.utils import flags


def pt(n, cached, dev, nat, plat="cpu"):
    return CalibrationPoint(n, cached, dev, nat, plat)


def test_uncalibrated_is_native():
    """VERDICT r4 #4: without same-platform proof the device never wins —
    the old >=1M-cached default offloaded into a measured pessimization."""
    p = OffloadPolicy([])
    assert not p.use_device(100_000, cached=False)
    assert not p.use_device(100_000, cached=True)
    assert not p.use_device(10 << 20, cached=False)
    assert not p.use_device(10 << 20, cached=True)


def test_calibrated_pessimization_stays_native():
    # r3's measured reality: device e2e 0.088x native
    p = OffloadPolicy([pt(1 << 22, True, 128_000, 1_450_000)],
                      platform="cpu")
    assert not p.use_device(1 << 22, cached=True)
    assert not p.use_device(1 << 24, cached=True)


def test_calibrated_win_offloads():
    p = OffloadPolicy([pt(1 << 22, True, 5_000_000, 1_450_000)],
                      platform="cpu")
    assert p.use_device(1 << 22, cached=True)
    # nearest-size rule: a small job measured slow stays native
    p2 = OffloadPolicy([pt(1 << 14, True, 100_000, 1_000_000),
                        pt(1 << 22, True, 5_000_000, 1_450_000)],
                       platform="cpu")
    assert not p2.use_device(1 << 14, cached=True)
    assert p2.use_device(1 << 22, cached=True)


def test_platform_mismatch_routes_native():
    # a TPU-platform server with CPU-only calibration must route native:
    # foreign-platform records prove nothing about this device
    p = OffloadPolicy([pt(1 << 22, True, 100_000, 1_450_000, "cpu")],
                      platform="tpu")
    assert not p.use_device(1 << 22, cached=False)
    assert not p.use_device(10 << 20, cached=True)
    # even a cpu record where the device WON does not gate a tpu server
    p2 = OffloadPolicy([pt(1 << 22, True, 9_000_000, 1_450_000, "cpu")],
                       platform="tpu")
    assert not p2.use_device(1 << 22, cached=True)
    # same-platform winning record does offload
    p3 = OffloadPolicy([pt(1 << 22, True, 9_000_000, 1_450_000, "tpu")],
                       platform="tpu")
    assert p3.use_device(1 << 22, cached=True)


def test_mode_flags_force():
    p = OffloadPolicy([pt(1 << 22, True, 1, 10, "cpu")], platform="cpu")
    flags.set_flag("device_offload_mode", "device")
    try:
        assert p.use_device(10, cached=False)
    finally:
        flags.set_flag("device_offload_mode", "auto")
    flags.set_flag("device_offload_mode", "native")
    try:
        assert not p.use_device(10 << 20, cached=True)
    finally:
        flags.set_flag("device_offload_mode", "auto")


def test_load_and_append_roundtrip(tmp_path):
    path = str(tmp_path / "cal.json")
    OffloadPolicy.append_calibration(path, 1 << 20, True, 2e6, 1e6, "cpu")
    OffloadPolicy.append_calibration(path, 1 << 20, False, 5e5, 1e6, "cpu")
    p = OffloadPolicy.load(platform="cpu", path=path)
    assert p.use_device(1 << 20, cached=True)
    assert not p.use_device(1 << 20, cached=False)
    # corrupt lines are skipped
    with open(path, "a") as f:
        f.write("not json\n")
    assert len(OffloadPolicy.load(platform="cpu", path=path).points) == 2


def test_compaction_job_respects_policy(tmp_path, monkeypatch):
    """run_compaction_job with a native-wins policy must not touch the
    device kernel at all."""
    import jax

    from bench import _attach_values, _split_runs, synth_ycsb_runs
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.storage.compaction import run_compaction_job
    from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter

    n = 4096
    slab, offsets = synth_ycsb_runs(n, 4, n // 2, seed=3)
    _attach_values(slab, 16)
    paths = []
    for i, sub in enumerate(_split_runs(slab, offsets)):
        p = str(tmp_path / f"{i:06d}.sst")
        SSTWriter(p).write(sub, Frontier())
        paths.append(p)

    def boom(*a, **k):
        raise AssertionError("device kernel invoked despite native policy")
    monkeypatch.setattr(run_merge, "merge_and_gc_runs", boom)
    monkeypatch.setattr(run_merge, "launch_merge_gc", boom)

    policy = OffloadPolicy([pt(n, False, 1.0, 100.0, "cpu")],
                           platform="cpu")
    readers = [SSTReader(p) for p in paths]
    ids = iter(range(1, 100))
    out = tmp_path / "out"
    out.mkdir()
    res = run_compaction_job(readers, str(out), lambda: next(ids),
                             (10_000_000 << 12), True,
                             device=jax.devices()[0],
                             offload_policy=policy)
    for r in readers:
        r.close()
    assert res.rows_out > 0


def test_server_context_loads_policy(tmp_path, monkeypatch):
    cal = tmp_path / "cal.json"
    OffloadPolicy.append_calibration(str(cal), 1 << 20, True, 2e6, 1e6,
                                     "cpu")
    flags.set_flag("offload_calibration_path", str(cal))
    try:
        from yugabyte_tpu.tserver.server_context import (
            ServerExecutionContext)
        import jax
        ctx = ServerExecutionContext(device=jax.devices()[0])
        try:
            opts = ctx.tablet_options()
            assert opts.offload_policy is not None
            assert opts.offload_policy.use_device(1 << 20, cached=True)
        finally:
            ctx.shutdown()
    finally:
        flags.set_flag("offload_calibration_path", "")


def test_recalibration_supersedes_stale_records(tmp_path):
    """A re-measured (n_rows, cached) class must WIN over the old line in
    the file — the nearest-size tie-break must never resurrect a stale
    measurement (the whole point of appending new calibration)."""
    path = str(tmp_path / "cal.json")
    OffloadPolicy.append_calibration(path, 1 << 18, True, 1e5, 1e6, "cpu")
    p = OffloadPolicy.load(platform="cpu", path=path)
    assert not p.use_device(1 << 18, cached=True)   # device loses
    OffloadPolicy.append_calibration(path, 1 << 18, True, 5e6, 1e6, "cpu")
    p2 = OffloadPolicy.load(platform="cpu", path=path)
    assert p2.use_device(1 << 18, cached=True)      # new record wins
    assert len(p2.points) == 1                      # deduped on load
