"""MaintenanceManager: scored flush / log-GC / compact scheduling.

Policy parity with the reference's FindBestOp
(tablet/maintenance_manager.cc): memory pressure prefers the op anchoring
the most RAM; WAL debt above log_target_replay_size prefers the op
releasing the most log bytes; otherwise the highest perf_improvement
runs. Integration: a real TabletPeer's WAL segments are GC'd
automatically once flushed.
"""

import os

import pytest

from yugabyte_tpu.tserver.maintenance_manager import (
    MaintenanceManager, MaintenanceOp, MaintenanceOpStats)
from yugabyte_tpu.utils import flags


class _ScriptedOp(MaintenanceOp):
    def __init__(self, name, ram=0, logs=0, perf=0.0, runnable=True):
        super().__init__(name)
        self.ram, self.logs, self.perf = ram, logs, perf
        self.runnable = runnable
        self.performed = 0

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        stats.runnable = self.runnable
        stats.ram_anchored = self.ram
        stats.logs_retained_bytes = self.logs
        stats.perf_improvement = self.perf

    def perform(self) -> None:
        self.performed += 1


def _mgr(ops, pressure=False):
    m = MaintenanceManager(peers_fn=lambda: [],
                           memory_pressure_fn=lambda: pressure)
    for op in ops:
        m.register_op(op)
    return m


def test_memory_pressure_prefers_ram_anchored():
    small = _ScriptedOp("small", ram=10, perf=100.0)
    big = _ScriptedOp("big", ram=1000, perf=0.1)
    m = _mgr([small, big], pressure=True)
    assert m.run_once() == "big"
    assert big.performed == 1 and small.performed == 0


def test_log_debt_prefers_log_releasing_op():
    old = flags.get_flag("log_target_replay_size_mb")
    flags.set_flag("log_target_replay_size_mb", 1)
    try:
        loggy = _ScriptedOp("loggy", logs=2 << 20)
        perfy = _ScriptedOp("perfy", perf=50.0)
        m = _mgr([loggy, perfy])
        assert m.run_once() == "loggy"
    finally:
        flags.set_flag("log_target_replay_size_mb", old)


def test_perf_improvement_otherwise():
    a = _ScriptedOp("a", perf=1.0)
    b = _ScriptedOp("b", perf=9.0)
    idle = _ScriptedOp("idle", runnable=False, perf=99.0)
    m = _mgr([a, b, idle])
    assert m.run_once() == "b"
    assert idle.performed == 0


def test_small_log_debt_still_collected():
    """Below-target log bytes are cheap housekeeping, not ignored."""
    loggy = _ScriptedOp("loggy", logs=1024)
    m = _mgr([loggy])
    assert m.run_once() == "loggy"


def test_nothing_runnable():
    m = _mgr([_ScriptedOp("x", runnable=False)])
    assert m.run_once() is None


def test_unregister():
    op = _ScriptedOp("x", perf=1.0)
    m = _mgr([op])
    m.unregister_op(op)
    assert m.run_once() is None


def test_wal_gc_end_to_end(tmp_path):
    """Real peer: write -> roll segments -> maintenance flushes + GCs WAL."""
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_consensus import PeerHarness, write_op

    h = PeerHarness(tmp_path, n=3)
    try:
        leader = h.elect("ts0")
        # enough writes to roll several WAL segments
        for batch in range(6):
            leader.write([write_op(h.schema, f"r{batch:02d}{i:03d}", i)
                          for i in range(50)])
        segs_before = len(os.listdir(os.path.join(leader.data_dir, "wal")))
        m = MaintenanceManager(peers_fn=lambda: [leader],
                               memory_pressure_fn=lambda: True)
        # under pressure: FlushOp runs (flush + WAL GC)
        name = m.run_once()
        assert name == "flush:t1"
        assert leader.tablet.memstore_bytes() == 0
        # after the flush the anchor has advanced; log-gc op reports clean
        # (flush_and_gc_wal already dropped the flushed segments)
        left = leader.log.gc_candidate_bytes(leader.wal_anchor())
        assert left == 0
        if segs_before > 1:
            segs_after = len(os.listdir(os.path.join(leader.data_dir, "wal")))
            assert segs_after <= segs_before
    finally:
        h.shutdown()


def test_tablet_server_owns_maintenance_manager(tmp_path):
    from yugabyte_tpu.tserver.tablet_server import (
        TabletServer, TabletServerOptions)
    ts = TabletServer(TabletServerOptions(
        server_id="ts-maint", fs_root=str(tmp_path / "fs"), port=0,
        master_addrs=[], tablet_options_factory=lambda: None))
    try:
        assert ts.maintenance_manager is not None
        assert ts.maintenance_manager.run_once() is None  # no tablets
    finally:
        ts.shutdown()
