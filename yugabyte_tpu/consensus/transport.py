"""Consensus transport seam.

The reference sends consensus traffic through its custom RPC framework
(ref: src/yb/consensus/consensus_peers.h:131 `Peer::SendNextRequest` over a
`PeerProxy`). Here the seam is `PeerProxyIf` with two calls — UpdateConsensus
(AppendEntries) and RequestVote — so the same RaftConsensus runs over:

- `LocalTransport`: in-process dispatch between peers in one interpreter
  (the MiniCluster path, ref rpc/local_call.h bypass), with fault injection
  (partitions, drops) for failure tests, and
- the host RPC layer (yugabyte_tpu/rpc) for real multi-process clusters.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, Optional, Set, Tuple


class PeerUnreachable(Exception):
    pass


class LocalTransport:
    """In-process message fabric between named consensus instances."""

    def __init__(self, seed: int = 0):
        from yugabyte_tpu.utils import lock_rank
        self._peers: Dict[str, object] = {}        # guarded-by: _lock
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "local_transport._lock")
        self._partitions: Set[Tuple[str, str]] = set()  # guarded-by: _lock
        self._down: Set[str] = set()               # guarded-by: _lock
        self._drop_probability = 0.0               # guarded-by: _lock
        self._rng = random.Random(seed)            # guarded-by: _lock

    def register(self, peer_id: str, consensus: object) -> None:
        with self._lock:
            self._peers[peer_id] = consensus

    def unregister(self, peer_id: str) -> None:
        with self._lock:
            self._peers.pop(peer_id, None)

    # ------------------------------------------------------ fault injection
    def _known(self, name: str) -> bool:  # guarded-by: _lock
        return name in self._peers or \
            any(p.startswith(name + "/") for p in self._peers)

    def partition(self, a: str, b: str) -> None:
        with self._lock:
            # a silent no-op partition (name not matching any registered
            # peer id) makes fault tests pass vacuously — fail loudly
            for name in (a, b):
                if self._peers and not self._known(name):
                    raise ValueError(
                        f"partition({name!r}): no such peer; registered: "
                        f"{sorted(self._peers)}")
            self._partitions.add((a, b))
            self._partitions.add((b, a))

    def isolate(self, peer_id: str) -> None:
        """Cut peer_id off from everyone (crash-failure emulation)."""
        with self._lock:
            if self._peers and not self._known(peer_id):
                raise ValueError(
                    f"isolate({peer_id!r}): no such peer; registered: "
                    f"{sorted(self._peers)}")
            self._down.add(peer_id)

    def heal(self) -> None:
        with self._lock:
            self._partitions.clear()
            self._down.clear()

    def set_drop_probability(self, p: float) -> None:
        with self._lock:
            self._drop_probability = p

    def _check_link(self, src: str, dst: str) -> object:
        # Faults match the full consensus id ("ts0/t1") OR the server part
        # ("ts0"): a network partition cuts SERVERS, so tests express it
        # per-server and it applies to every tablet channel between them.
        src_srv = src.split("/", 1)[0]
        dst_srv = dst.split("/", 1)[0]
        with self._lock:
            down = self._down
            if (src in down or dst in down
                    or src_srv in down or dst_srv in down):
                raise PeerUnreachable(f"{src}->{dst}: peer down")
            parts = self._partitions
            if ((src, dst) in parts or (src_srv, dst_srv) in parts
                    or (src, dst_srv) in parts or (src_srv, dst) in parts):
                # mixed-form entries (one bare server, one full id) match
                # too — a stored pair that can never fire would silently
                # un-partition the link
                raise PeerUnreachable(f"{src}->{dst}: partitioned")
            if self._drop_probability and \
                    self._rng.random() < self._drop_probability:
                raise PeerUnreachable(f"{src}->{dst}: dropped")
            peer = self._peers.get(dst)
        if peer is None:
            raise PeerUnreachable(f"{src}->{dst}: unknown peer")
        return peer

    # ------------------------------------------------------------ dispatch
    def update_consensus(self, src: str, dst: str, request):
        peer = self._check_link(src, dst)
        ctx = getattr(request, "trace_ctx", None)
        if ctx is not None:
            # mirror the RPC path's inbound adoption: the in-process hop
            # still produces a per-peer handler span under the same
            # trace_id, so LocalTransport clusters trace like real ones
            from yugabyte_tpu.utils.trace import Trace
            with Trace.from_wire_context(ctx, f"consensus.update:{dst}"):
                return peer.handle_update(request)
        return peer.handle_update(request)

    def request_vote(self, src: str, dst: str, request):
        return self._check_link(src, dst).handle_vote_request(request)
