"""yblint core: single-parse file contexts, the pass API, the parallel
runner, and the baseline/suppression machinery.

Design:

- Each file is parsed ONCE and walked ONCE (`FileContext`): the walk
  builds a parent map and a by-node-type index that every pass shares, so
  adding a pass costs an index scan, not another parse of the tree.
- A pass is a `AnalysisPass` subclass with `run(ctx) -> [Finding]`.
  Passes self-gate via `applies_to(relpath)` (e.g. the swallowed-errors
  pass only covers the storage-critical layers).
- Findings are identified for baseline purposes by a line-number-free
  fingerprint (path + pass + code + enclosing symbol + normalized source
  line), so unrelated edits that shift line numbers do not invalidate the
  committed baseline.
- Suppression: `# yblint: disable=<pass-name>` on the offending line
  waives a single finding; the committed baseline (tools/analysis/
  baseline.txt) carries justified legacy findings — the runner fails only
  on findings NOT in the baseline, and reports stale baseline entries so
  the file shrinks over time.
"""

from __future__ import annotations

import ast
import concurrent.futures
import json
import os
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_TARGETS = ("yugabyte_tpu",)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.txt")

_DISABLE_RE = re.compile(r"#\s*yblint:\s*disable=([\w,-]+)")


@dataclass(frozen=True)
class Finding:
    """One defect report. `symbol` is the enclosing def/class qualname
    (or '<module>') — part of the fingerprint so baselines survive line
    drift."""

    path: str          # repo-relative, forward slashes
    line: int
    pass_name: str
    code: str          # short kebab-case defect class, e.g. "host-sync"
    message: str
    symbol: str = "<module>"
    src: str = ""      # stripped source line (fingerprint component)

    @property
    def fingerprint(self) -> str:
        return "|".join((self.path, self.pass_name, self.code, self.symbol,
                         " ".join(self.src.split())))

    def render(self, root: str = REPO_ROOT) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.code}] "
                f"{self.message}")

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line,
                "pass": self.pass_name, "code": self.code,
                "message": self.message, "symbol": self.symbol,
                "fingerprint": self.fingerprint}


class FileContext:
    """Parse-once, walk-once view of one source file shared by all passes."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # --- the single walk: parent links + per-type index -------------
        self.parents: Dict[int, ast.AST] = {}
        self.by_type: Dict[type, List[ast.AST]] = {}
        stack = [self.tree]
        while stack:
            node = stack.pop()
            self.by_type.setdefault(type(node), []).append(node)
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
                stack.append(child)

    # ------------------------------------------------------------- helpers
    def nodes_of(self, *types: type) -> List[ast.AST]:
        out: List[ast.AST] = []
        for t in types:
            out.extend(self.by_type.get(t, []))
        return out

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def qualname(self, node: ast.AST) -> str:
        parts = []
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                parts.append(a.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts)) or "<module>"

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def line_comment_has(self, lineno: int, token: str) -> bool:
        return token in self.line_text(lineno)

    def finding(self, pass_name: str, code: str, node: ast.AST,
                message: str) -> Finding:
        lineno = getattr(node, "lineno", 0)
        fn = self.enclosing_function(node)
        symbol = self.qualname(fn) if fn is not None else (
            self.qualname(node) if isinstance(node, ast.ClassDef)
            else "<module>")
        return Finding(self.relpath, lineno, pass_name, code, message,
                       symbol=symbol, src=self.line_text(lineno).strip())


class AnalysisPass:
    """Plugin pass API: subclass, set `name`, implement run(ctx).

    A whole-program pass sets `needs_index = True` and implements
    `run(ctx, index)` — the runner then hands it the ProjectIndex built
    once per run (passes invoked standalone, e.g. from test fixtures,
    get a single-file index synthesized on the spot)."""

    name = "base"
    needs_index = False

    def applies_to(self, relpath: str) -> bool:
        return True

    def run(self, ctx: FileContext, index=None) -> List[Finding]:
        raise NotImplementedError


def _is_suppressed(ctx: FileContext, f: Finding) -> bool:
    m = _DISABLE_RE.search(ctx.line_text(f.line))
    if not m:
        return False
    names = {n.strip() for n in m.group(1).split(",")}
    return f.pass_name in names or "all" in names


def _parse_context(path: str, relpath: str):
    """(ctx, findings): a FileContext, or parse-stage findings."""
    try:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        return FileContext(path, relpath, src), []
    except SyntaxError as e:
        return None, [Finding(relpath, e.lineno or 0, "parse",
                              "syntax-error", f"unparseable: {e.msg}")]
    except OSError as e:
        return None, [Finding(relpath, 0, "parse", "io-error", str(e))]


def _run_passes(ctx: FileContext, passes: Sequence[AnalysisPass],
                index) -> List[Finding]:
    out: List[Finding] = []
    for p in passes:
        if not p.applies_to(ctx.relpath):
            continue
        fs = p.run(ctx, index) if p.needs_index else p.run(ctx)
        out.extend(f for f in fs if not _is_suppressed(ctx, f))
    return out


def analyze_file(path: str, relpath: str,
                 passes: Sequence[AnalysisPass]) -> List[Finding]:
    """Standalone single-file entry point (whole-program passes see a
    one-file index); the batch runner below shares one index instead."""
    ctx, errs = _parse_context(path, relpath)
    if ctx is None:
        return errs
    index = None
    if any(p.needs_index for p in passes):
        from tools.analysis.project_index import ProjectIndex
        index = ProjectIndex([ctx])
    return _run_passes(ctx, passes, index)


def _collect_files(root: str, targets: Sequence[str]) -> List[Tuple[str, str]]:
    seen = set()
    out: List[Tuple[str, str]] = []

    def add(path: str) -> None:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        if rel not in seen:
            seen.add(rel)
            out.append((path, rel))

    for t in targets:
        path = t if os.path.isabs(t) else os.path.join(root, t)
        if os.path.isfile(path) and path.endswith(".py"):
            add(path)
            continue
        for dirpath, dirnames, files in os.walk(path):
            dirnames.sort()
            for fn in sorted(files):
                if fn.endswith(".py"):
                    add(os.path.join(dirpath, fn))
    return out


def analyze_paths(root: str = REPO_ROOT,
                  targets: Sequence[str] = DEFAULT_TARGETS,
                  passes: Optional[Sequence[AnalysisPass]] = None,
                  jobs: Optional[int] = None,
                  report_only: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    """Run the passes over every .py file under the targets.

    Two phases: (1) parse every file into a FileContext (parallel, one
    parse per file) and build the whole-program ProjectIndex EXACTLY ONCE
    over all of them; (2) run the passes per file (parallel — contexts
    are independent, the index is shared read-only).

    report_only: when given (the `--changed` pre-commit path), findings
    are only emitted for those relpaths — but the index still covers the
    full target set, so cross-file passes see the whole program."""
    if passes is None:
        from tools.analysis.passes import ALL_PASSES
        passes = ALL_PASSES
    files = _collect_files(root, targets)
    jobs = jobs or min(8, (os.cpu_count() or 2))
    findings: List[Finding] = []
    ctxs: List[FileContext] = []
    if jobs <= 1 or len(files) <= 1:
        parsed = [_parse_context(p, r) for p, r in files]
    else:
        with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as ex:
            parsed = list(ex.map(lambda a: _parse_context(a[0], a[1]),
                                 files))
    for ctx, errs in parsed:
        findings.extend(errs)
        if ctx is not None:
            ctxs.append(ctx)
    index = None
    if any(p.needs_index for p in passes):
        from tools.analysis.project_index import ProjectIndex
        index = ProjectIndex(ctxs)
    if jobs <= 1 or len(ctxs) <= 1:
        for ctx in ctxs:
            findings.extend(_run_passes(ctx, passes, index))
    else:
        with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as ex:
            for fs in ex.map(lambda c: _run_passes(c, passes, index),
                             ctxs):
                findings.extend(fs)
    if report_only is not None:
        keep = set(report_only)
        findings = [f for f in findings if f.path in keep]
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name, f.code))
    return findings


class Baseline:
    """Committed multiset of justified finding fingerprints.

    File format: one fingerprint per line; `  # justification` after two
    spaces is kept on rewrite; blank lines and full-line comments are
    ignored. A fingerprint occurring N times accepts N matching findings
    (the same defect class can legitimately appear twice in one symbol).
    """

    def __init__(self, entries: Optional[Counter] = None,
                 notes: Optional[Dict[str, str]] = None):
        self.entries: Counter = entries or Counter()
        self.notes: Dict[str, str] = notes or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        entries: Counter = Counter()
        notes: Dict[str, str] = {}
        if not os.path.exists(path):
            return cls(entries, notes)
        with open(path, encoding="utf-8") as fh:
            for raw in fh:
                line = raw.rstrip("\n")
                if not line.strip() or line.lstrip().startswith("#"):
                    continue
                fp, _, note = line.partition("  #")
                fp = fp.strip()
                entries[fp] += 1
                if note.strip():
                    notes[fp] = note.strip()
        return cls(entries, notes)

    def save(self, path: str, findings: Sequence[Finding]) -> None:
        """Rewrite the baseline from `findings`, sectioned per pass so
        suppressions are auditable pass by pass; notes survive for
        unchanged fingerprints."""
        by_pass: Dict[str, List[Finding]] = {}
        for f in findings:
            by_pass.setdefault(f.pass_name, []).append(f)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("# yblint baseline: justified findings, one "
                     "fingerprint per line, sectioned per pass.\n"
                     "# Regenerate with `python -m tools.analysis "
                     "--update-baseline`; every entry must carry a\n"
                     "# justification as `  # why this is acceptable` — "
                     "it survives regeneration for unchanged entries.\n")
            for pass_name in sorted(by_pass):
                fh.write(f"\n# --- pass: {pass_name} ---\n")
                for fp in sorted(f.fingerprint for f in by_pass[pass_name]):
                    note = self.notes.get(fp)
                    fh.write(f"{fp}  # {note}\n" if note else fp + "\n")

    def update(self, path: str,
               findings: Sequence[Finding]) -> List[str]:
        """`--update-baseline`: regenerate from the current findings, but
        REFUSE to add entries lacking a `#` justification. Returns the
        unjustified fingerprints — empty means the file was written;
        non-empty means nothing was touched (add a justification for each
        listed fingerprint, or fix the finding)."""
        unjustified = sorted({f.fingerprint for f in findings
                              if not self.notes.get(f.fingerprint)})
        if unjustified:
            return unjustified
        self.save(path, findings)
        return []

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
        """(new, known, stale): findings not covered by the baseline,
        findings it covers, and baseline entries nothing matched."""
        budget = Counter(self.entries)
        new, known = [], []
        for f in findings:
            if budget.get(f.fingerprint, 0) > 0:
                budget[f.fingerprint] -= 1
                known.append(f)
            else:
                new.append(f)
        stale = sorted(fp for fp, n in budget.items() if n > 0
                       for _ in range(n))
        return new, known, stale


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    new: List[Finding] = field(default_factory=list)
    known: List[Finding] = field(default_factory=list)
    stale: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0

    def to_json(self) -> dict:
        return {
            "new": [f.to_json() for f in self.new],
            "baselined": [f.to_json() for f in self.known],
            "stale_baseline_entries": self.stale,
            "counts": {"new": len(self.new), "baselined": len(self.known),
                       "stale": len(self.stale)},
        }


def run_analysis(root: str = REPO_ROOT,
                 targets: Sequence[str] = DEFAULT_TARGETS,
                 passes: Optional[Sequence[AnalysisPass]] = None,
                 baseline_path: Optional[str] = DEFAULT_BASELINE,
                 jobs: Optional[int] = None,
                 report_only: Optional[Sequence[str]] = None
                 ) -> AnalysisResult:
    findings = analyze_paths(root, targets, passes, jobs,
                             report_only=report_only)
    if baseline_path is None:
        return AnalysisResult(findings, list(findings), [], [])
    bl = Baseline.load(baseline_path)
    new, known, stale = bl.split(findings)
    if report_only is not None:
        # a scoped run can't see findings outside the file set, so
        # baseline entries it didn't match are not evidence of staleness
        stale = []
    else:
        # staleness is per-pass evidence: entries for passes this run
        # did not execute — a `--passes` subset, or dynamic-only passes
        # like ybsan whose findings exist only in armed pytest runs —
        # cannot be judged by it
        if passes is None:
            from tools.analysis.passes import ALL_PASSES
            passes = ALL_PASSES
        ran = {p.name for p in passes}
        stale = [fp for fp in stale
                 if len(parts := fp.split("|", 2)) > 1 and parts[1] in ran]
    return AnalysisResult(findings, new, known, stale)


def format_human(result: AnalysisResult, verbose: bool = False) -> str:
    out: List[str] = []
    for f in result.new:
        out.append(f.render())
    if verbose:
        for f in result.known:
            out.append(f"{f.render()}  [baselined]")
    for fp in result.stale:
        out.append(f"stale baseline entry (no longer found): {fp}")
    n_new, n_known = len(result.new), len(result.known)
    out.append(f"yblint: {n_new} new finding(s), {n_known} baselined, "
               f"{len(result.stale)} stale baseline entr"
               f"{'y' if len(result.stale) == 1 else 'ies'}")
    return "\n".join(out)


def format_json(result: AnalysisResult) -> str:
    return json.dumps(result.to_json(), indent=1, sort_keys=True)
