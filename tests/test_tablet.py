"""Tablet layer tests: MVCC, locks, write pipeline, rowwise reads.

Modeled on the reference's tablet/docdb unit tests (ref:
src/yb/tablet/tablet-test.cc, src/yb/docdb/docdb-test.cc,
src/yb/tablet/mvcc-test.cc).
"""

import threading
import time

import pytest

from yugabyte_tpu.common.hybrid_time import HybridClock, HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.docdb.lock_manager import (
    IntentType, LockBatch, SharedLockManager, intents_conflict)
from yugabyte_tpu.tablet.mvcc import MvccManager
from yugabyte_tpu.tablet.tablet import Tablet, TabletOptions


SCHEMA = Schema(
    columns=[
        ColumnSchema("h", DataType.STRING),
        ColumnSchema("r", DataType.INT64),
        ColumnSchema("v1", DataType.STRING),
        ColumnSchema("v2", DataType.INT64),
    ],
    num_hash_key_columns=1,
    num_range_key_columns=1,
)


def make_tablet(tmp_path, **kw):
    opts = TabletOptions(auto_compact=False, **kw)
    return Tablet("t-test", str(tmp_path), SCHEMA, options=opts)


def dk(h, r):
    return DocKey(hash_components=(h,), range_components=(r,))


def insert(tablet, h, r, v1=None, v2=None, ttl_ms=None):
    vals = {}
    if v1 is not None:
        vals["v1"] = v1
    if v2 is not None:
        vals["v2"] = v2
    return tablet.write([QLWriteOp(WriteOpKind.INSERT, dk(h, r), vals,
                                   ttl_ms=ttl_ms)])


# ---------------------------------------------------------------------- mvcc
class TestMvcc:
    def test_safe_time_advances_with_clock_when_idle(self):
        clock = HybridClock()
        m = MvccManager(clock)
        st1 = m.safe_time()
        st2 = m.safe_time()
        assert st2.value >= st1.value

    def test_pending_write_holds_back_safe_time(self):
        clock = HybridClock()
        m = MvccManager(clock)
        ht = clock.now()
        m.add_pending(ht)
        assert m._safe_time_unlocked().value == ht.value - 1
        m.replicated(ht)
        assert m.safe_time().value >= ht.value

    def test_out_of_order_registration_rejected(self):
        clock = HybridClock()
        m = MvccManager(clock)
        ht = clock.now()
        m.add_pending(ht)
        with pytest.raises(ValueError):
            m.add_pending(HybridTime(ht.value - 5))
        m.replicated(ht)

    def test_safe_time_blocks_until_replicated(self):
        clock = HybridClock()
        m = MvccManager(clock)
        ht = clock.now()
        m.add_pending(ht)
        result = {}

        def reader():
            result["st"] = m.safe_time(min_allowed=ht)

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.05)
        assert "st" not in result
        m.replicated(ht)
        t.join(timeout=5)
        assert result["st"].value >= ht.value

    def test_follower_uses_propagated_safe_time(self):
        clock = HybridClock()
        m = MvccManager(clock)
        m.set_leader_mode(False)
        ht = HybridTime.from_micros(12345)
        m.set_propagated_safe_time(ht)
        assert m.safe_time_for_follower().value == ht.value
        # propagated safe time never regresses
        m.set_propagated_safe_time(HybridTime.from_micros(12))
        assert m.safe_time_for_follower().value == ht.value


# --------------------------------------------------------------------- locks
class TestLockManager:
    def test_conflict_matrix(self):
        W, S = IntentType, IntentType
        # read/read never conflicts
        assert not intents_conflict(S.kStrongRead, S.kStrongRead)
        assert not intents_conflict(S.kWeakRead, S.kStrongRead)
        # weak/weak never conflicts
        assert not intents_conflict(W.kWeakWrite, W.kWeakWrite)
        # strong + write conflicts
        assert intents_conflict(S.kStrongWrite, S.kStrongWrite)
        assert intents_conflict(S.kStrongRead, S.kStrongWrite)
        assert intents_conflict(W.kWeakRead, S.kStrongWrite)
        assert intents_conflict(W.kWeakWrite, S.kStrongRead)

    def test_weak_locks_share_prefix(self):
        lm = SharedLockManager()
        b1 = lm.lock(LockBatch([(b"doc", IntentType.kWeakWrite),
                                (b"doc/c1", IntentType.kStrongWrite)]))
        # disjoint column of the same doc: weak+weak on the prefix is fine
        b2 = lm.lock(LockBatch([(b"doc", IntentType.kWeakWrite),
                                (b"doc/c2", IntentType.kStrongWrite)]))
        b1.release()
        b2.release()
        assert lm.held_count() == 0

    def test_strong_blocks_weak(self):
        lm = SharedLockManager()
        b1 = lm.lock(LockBatch([(b"doc", IntentType.kStrongWrite)]))
        assert not lm.try_lock(LockBatch([(b"doc", IntentType.kWeakWrite)]))
        b1.release()
        assert lm.try_lock(LockBatch([(b"doc", IntentType.kWeakWrite)]))

    def test_blocked_lock_acquires_after_release(self):
        lm = SharedLockManager()
        b1 = lm.lock(LockBatch([(b"k", IntentType.kStrongWrite)]))
        acquired = threading.Event()

        def taker():
            b = lm.lock(LockBatch([(b"k", IntentType.kStrongWrite)]))
            acquired.set()
            b.release()

        t = threading.Thread(target=taker)
        t.start()
        time.sleep(0.05)
        assert not acquired.is_set()
        b1.release()
        t.join(timeout=5)
        assert acquired.is_set()


# -------------------------------------------------------------------- tablet
class TestTabletWrites:
    def test_insert_and_point_read(self, tmp_path):
        t = make_tablet(tmp_path)
        insert(t, "alice", 1, v1="hello", v2=42)
        row = t.read_row(dk("alice", 1))
        assert row is not None
        d = row.to_dict(SCHEMA)
        assert d == {"h": "alice", "r": 1, "v1": "hello", "v2": 42}
        assert t.read_row(dk("bob", 1)) is None
        t.close()

    def test_update_overwrites_only_touched_columns(self, tmp_path):
        t = make_tablet(tmp_path)
        insert(t, "a", 1, v1="x", v2=1)
        t.write([QLWriteOp(WriteOpKind.UPDATE, dk("a", 1), {"v2": 2})])
        d = t.read_row(dk("a", 1)).to_dict(SCHEMA)
        assert d["v1"] == "x" and d["v2"] == 2
        t.close()

    def test_update_to_null_deletes_column(self, tmp_path):
        t = make_tablet(tmp_path)
        insert(t, "a", 1, v1="x", v2=1)
        t.write([QLWriteOp(WriteOpKind.UPDATE, dk("a", 1), {"v1": None})])
        d = t.read_row(dk("a", 1)).to_dict(SCHEMA)
        assert d["v1"] is None and d["v2"] == 1
        t.close()

    def test_delete_row_then_reinsert(self, tmp_path):
        t = make_tablet(tmp_path)
        insert(t, "a", 1, v1="x")
        t.write([QLWriteOp(WriteOpKind.DELETE_ROW, dk("a", 1))])
        assert t.read_row(dk("a", 1)) is None
        insert(t, "a", 1, v2=7)
        d = t.read_row(dk("a", 1)).to_dict(SCHEMA)
        # v1 from before the row tombstone must NOT resurface
        assert d["v1"] is None and d["v2"] == 7
        t.close()

    def test_update_alone_does_not_create_row(self, tmp_path):
        # CQL semantics: UPDATE writes columns without liveness; the row is
        # visible because a column exists — but after deleting that column
        # the row vanishes (no liveness marker).
        t = make_tablet(tmp_path)
        t.write([QLWriteOp(WriteOpKind.UPDATE, dk("u", 1), {"v1": "only"})])
        assert t.read_row(dk("u", 1)) is not None
        t.write([QLWriteOp(WriteOpKind.DELETE_COLS, dk("u", 1),
                           columns_to_delete=("v1",))])
        assert t.read_row(dk("u", 1)) is None
        t.close()

    def test_insert_survives_deleting_all_columns(self, tmp_path):
        # INSERT writes liveness: row exists even with all columns deleted.
        t = make_tablet(tmp_path)
        insert(t, "a", 1, v1="x")
        t.write([QLWriteOp(WriteOpKind.DELETE_COLS, dk("a", 1),
                           columns_to_delete=("v1",))])
        row = t.read_row(dk("a", 1))
        assert row is not None
        assert row.to_dict(SCHEMA)["v1"] is None
        t.close()

    def test_snapshot_read_at_past_ht(self, tmp_path):
        t = make_tablet(tmp_path)
        ht1 = insert(t, "a", 1, v1="old")
        t.write([QLWriteOp(WriteOpKind.UPDATE, dk("a", 1), {"v1": "new"})])
        assert t.read_row(dk("a", 1)).to_dict(SCHEMA)["v1"] == "new"
        assert t.read_row(dk("a", 1), read_ht=ht1).to_dict(SCHEMA)["v1"] == "old"
        t.close()

    def test_read_after_flush_and_compact(self, tmp_path):
        t = make_tablet(tmp_path)
        for i in range(20):
            insert(t, "u", i, v1=f"val{i}", v2=i)
        t.flush()
        for i in range(0, 20, 2):
            t.write([QLWriteOp(WriteOpKind.UPDATE, dk("u", i),
                               {"v1": f"upd{i}"})])
        t.flush()
        t.compact()
        for i in range(20):
            d = t.read_row(dk("u", i)).to_dict(SCHEMA)
            expect = f"upd{i}" if i % 2 == 0 else f"val{i}"
            assert d["v1"] == expect, (i, d)
        t.close()

    def test_ttl_expiry(self, tmp_path):
        t = make_tablet(tmp_path)
        insert(t, "a", 1, v1="ephemeral", ttl_ms=1)
        insert(t, "a", 2, v1="persistent")
        time.sleep(0.01)
        assert t.read_row(dk("a", 1)) is None
        assert t.read_row(dk("a", 2)) is not None
        t.close()

    def test_scan_returns_rows_in_key_order(self, tmp_path):
        t = make_tablet(tmp_path)
        for i in range(10):
            insert(t, "scan", i, v2=i * 10)
        rows = [r.to_dict(SCHEMA) for r in t.scan()]
        assert [r["r"] for r in rows] == sorted(r["r"] for r in rows)
        assert len(rows) == 10
        assert all(r["v2"] == r["r"] * 10 for r in rows)
        t.close()

    def test_scan_with_limit_pages(self, tmp_path):
        t = make_tablet(tmp_path)
        for i in range(10):
            insert(t, "p", i, v2=i)
        it = t.scan()
        first = [r.to_dict(SCHEMA)["r"] for r in it.rows(limit=4)]
        assert len(first) == 4
        resume = it.next_doc_key
        assert resume is not None
        it2 = t.scan(lower_doc_key=resume)
        rest = [r.to_dict(SCHEMA)["r"] for r in it2]
        assert sorted(first + rest) == list(range(10))
        t.close()

    def test_concurrent_writers_same_row_serialize(self, tmp_path):
        t = make_tablet(tmp_path)
        n_threads, n_iters = 4, 25
        errors = []

        def writer(tid):
            try:
                for i in range(n_iters):
                    t.write([QLWriteOp(WriteOpKind.UPDATE, dk("hot", 0),
                                       {"v2": tid * 1000 + i})])
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors
        row = t.read_row(dk("hot", 0))
        assert row is not None and row.to_dict(SCHEMA)["v2"] is not None
        t.close()

    def test_concurrent_disjoint_writers(self, tmp_path):
        # regression: MVCC requires FIFO completion in HT order; disjoint-key
        # writers used to complete out of order and crash replicated()
        t = make_tablet(tmp_path)
        errors = []

        def writer(tid):
            try:
                for i in range(30):
                    insert(t, f"w{tid}", i, v2=i)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors, errors
        assert len(list(t.scan())) == 240
        t.close()

    def test_concurrent_reads_during_writes(self, tmp_path):
        # regression: safe_time() between a writer's clock read and its MVCC
        # registration used to fence the writer's hybrid time out
        t = make_tablet(tmp_path)
        errors = []
        stop = threading.Event()

        def reader():
            try:
                while not stop.is_set():
                    list(t.scan())
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def writer():
            try:
                for i in range(200):
                    insert(t, "rw", i, v2=i)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        rt = threading.Thread(target=reader)
        wts = [threading.Thread(target=writer) for _ in range(2)]
        rt.start()
        for w in wts:
            w.start()
        for w in wts:
            w.join()
        stop.set()
        rt.join(timeout=10)
        assert not errors, errors
        t.close()

    def test_projection_read_of_updateonly_row(self, tmp_path):
        # regression: projection used to hide row existence when the only
        # visible column was outside the projection
        t = make_tablet(tmp_path)
        t.write([QLWriteOp(WriteOpKind.UPDATE, dk("pj", 1), {"v1": "only"})])
        cid_v2 = SCHEMA.column_id("v2")
        row = t.read_row(dk("pj", 1), projection=[cid_v2])
        assert row is not None
        assert row.columns == {}
        t.close()

    def test_write_visible_at_returned_ht(self, tmp_path):
        t = make_tablet(tmp_path)
        ht = insert(t, "vis", 1, v1="x")
        assert t.read_row(dk("vis", 1), read_ht=ht) is not None
        assert t.read_row(dk("vis", 1),
                          read_ht=HybridTime(ht.value - 1)) is None
        t.close()

    def test_split_key_is_median_doc(self, tmp_path):
        t = make_tablet(tmp_path)
        for i in range(9):
            insert(t, "s", i, v2=i)
        sk = t.split_key()
        assert sk is not None
        lower = [r.to_dict(SCHEMA)["r"] for r in t.scan(upper_doc_key=sk)]
        upper = [r.to_dict(SCHEMA)["r"] for r in t.scan(lower_doc_key=sk)]
        assert sorted(lower + upper) == list(range(9))
        assert 3 <= len(lower) <= 6
        t.close()

    def test_checkpoint_restores(self, tmp_path):
        t = make_tablet(tmp_path / "src")
        for i in range(5):
            insert(t, "c", i, v1=f"v{i}")
        t.checkpoint(str(tmp_path / "ckpt"))
        t.close()
        t2 = Tablet("t-restored", str(tmp_path / "ckpt"), SCHEMA,
                    options=TabletOptions(auto_compact=False))
        rows = [r.to_dict(SCHEMA) for r in t2.scan()]
        assert len(rows) == 5
        t2.close()
