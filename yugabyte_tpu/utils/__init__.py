from yugabyte_tpu.utils.status import Status, StatusError, Result
from yugabyte_tpu.utils.flags import define_flag, get_flag, set_flag, FlagTag
from yugabyte_tpu.utils.metrics import MetricRegistry, Counter, Gauge, Histogram
