"""YCQL lightweight transactions: INSERT ... IF NOT EXISTS, UPDATE/
DELETE ... IF EXISTS / IF <conditions>, returning the CQL [applied]
row (current values on CAS failure).

ref: the reference's conditional DML — ql/ptree/pt_dml.h if-clause
analysis; conditional QLWriteRequest if_expr evaluated in
docdb/ql_operations; executed here as read-check-write distributed
transactions with conflict retry.
"""

import threading

import pytest

from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.yql.cql.executor import QLProcessor


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path_factory.mktemp("lwtcluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture()
def ql(cluster):
    p = QLProcessor(cluster.new_client())
    p.execute("CREATE KEYSPACE IF NOT EXISTS lwt")
    p.execute("USE lwt")
    p.execute("DROP TABLE IF EXISTS accounts")
    p.execute("CREATE TABLE accounts (id TEXT PRIMARY KEY, "
              "balance BIGINT, owner TEXT)")
    return p


def test_insert_if_not_exists(ql):
    rs = ql.execute("INSERT INTO accounts (id, balance) VALUES ('a', 100) "
                    "IF NOT EXISTS")
    assert rs.columns[0] == "[applied]" and rs.rows == [[True]]
    # second attempt fails and reports the existing row
    rs = ql.execute("INSERT INTO accounts (id, balance) VALUES ('a', 999) "
                    "IF NOT EXISTS")
    assert rs.rows[0][0] is False
    d = dict(zip(rs.columns, rs.rows[0]))
    assert d["balance"] == 100
    rs = ql.execute("SELECT balance FROM accounts WHERE id = 'a'")
    assert rs.rows == [[100]]


def test_update_if_condition(ql):
    ql.execute("INSERT INTO accounts (id, balance, owner) "
               "VALUES ('b', 50, 'bob')")
    rs = ql.execute("UPDATE accounts SET balance = 40 WHERE id = 'b' "
                    "IF balance = 50")
    assert rs.rows == [[True]]
    # CAS failure reports the condition column's current value
    rs = ql.execute("UPDATE accounts SET balance = 0 WHERE id = 'b' "
                    "IF balance = 50")
    assert rs.rows[0][0] is False
    d = dict(zip(rs.columns, rs.rows[0]))
    assert d["balance"] == 40
    # multi-condition
    rs = ql.execute("UPDATE accounts SET balance = 35 WHERE id = 'b' "
                    "IF balance = 40 AND owner = 'bob'")
    assert rs.rows == [[True]]


def test_update_if_exists(ql):
    rs = ql.execute("UPDATE accounts SET balance = 1 WHERE id = 'ghost' "
                    "IF EXISTS")
    assert rs.rows == [[False]]
    assert ql.execute("SELECT * FROM accounts WHERE id = 'ghost'").rows \
        == []
    ql.execute("INSERT INTO accounts (id, balance) VALUES ('c', 5)")
    rs = ql.execute("UPDATE accounts SET balance = 6 WHERE id = 'c' "
                    "IF EXISTS")
    assert rs.rows == [[True]]


def test_delete_if(ql):
    ql.execute("INSERT INTO accounts (id, balance) VALUES ('d', 10)")
    rs = ql.execute("DELETE FROM accounts WHERE id = 'd' IF balance = 99")
    assert rs.rows[0][0] is False
    assert ql.execute("SELECT id FROM accounts WHERE id = 'd'").rows \
        == [["d"]]
    rs = ql.execute("DELETE FROM accounts WHERE id = 'd' IF balance = 10")
    assert rs.rows == [[True]]
    assert ql.execute("SELECT id FROM accounts WHERE id = 'd'").rows == []
    rs = ql.execute("DELETE FROM accounts WHERE id = 'd' IF EXISTS")
    assert rs.rows == [[False]]


def test_insert_if_not_exists_with_ttl_order(ql):
    rs = ql.execute("INSERT INTO accounts (id, balance) VALUES ('t', 1) "
                    "IF NOT EXISTS USING TTL 100")
    assert rs.rows == [[True]]
    rs = ql.execute("INSERT INTO accounts (id, balance) VALUES ('t2', 1) "
                    "USING TTL 100 IF NOT EXISTS")
    assert rs.rows == [[True]]


def test_concurrent_cas_single_winner(ql, cluster):
    ql.execute("INSERT INTO accounts (id, balance) VALUES ('race', 0)")
    wins = []

    def cas(i):
        p = QLProcessor(cluster.new_client())
        p.execute("USE lwt")
        rs = p.execute("UPDATE accounts SET balance = %d "
                       "WHERE id = 'race' IF balance = 0" % (i + 1))
        wins.append(rs.rows[0][0])

    ts = [threading.Thread(target=cas, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sum(1 for w in wins if w) == 1, wins


def test_bind_markers_in_conditions(ql):
    ql.execute("INSERT INTO accounts (id, balance) VALUES ('m', 7)")
    rs = ql.execute("UPDATE accounts SET balance = ? WHERE id = ? "
                    "IF balance = ?", [8, "m", 7])
    assert rs.rows == [[True]]
    rs = ql.execute("SELECT balance FROM accounts WHERE id = 'm'")
    assert rs.rows == [[8]]


def test_lwt_rejected_in_transaction_block(ql):
    from yugabyte_tpu.utils.status import StatusError
    with pytest.raises(StatusError, match="IF"):
        ql.execute("BEGIN TRANSACTION "
                   "INSERT INTO accounts (id, balance) VALUES ('x', 1) "
                   "IF NOT EXISTS; "
                   "END TRANSACTION")
    assert ql.execute("SELECT id FROM accounts WHERE id = 'x'").rows == []


def test_lwt_on_indexed_table(ql):
    ql.execute("DROP TABLE IF EXISTS iacc")
    ql.execute("CREATE TABLE iacc (id TEXT PRIMARY KEY, owner TEXT)")
    ql.execute("CREATE INDEX iown ON iacc (owner)")
    rs = ql.execute("INSERT INTO iacc (id, owner) VALUES ('1', 'ann') "
                    "IF NOT EXISTS")
    assert rs.rows == [[True]]
    rs = ql.execute("UPDATE iacc SET owner = 'ben' WHERE id = '1' "
                    "IF owner = 'ann'")
    assert rs.rows == [[True]]
    # index maintained through the conditional path
    assert ql.execute("SELECT id FROM iacc WHERE owner = 'ben'").rows \
        == [["1"]]
    assert ql.execute("SELECT id FROM iacc WHERE owner = 'ann'").rows \
        == []
